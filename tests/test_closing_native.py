"""Closing-native evolution: named coverage for the closed-state
fixpoint (ops/fast_kernels.py closing_native).

reference: the closed gate at src/state_machine.zig:3837, the set at
:3941-3944, the void exception at :4184-4189 and the reopen at
:4254-4261. Closing transfers (and voids of closing pendings) run on
the device fixpoint tiers — the plain/imported tiers escalate instead
of hard-falling-back, so eligibility is uniform across tiers and the
SPMD driver. Every scenario here is diffed against the oracle; the
fallback counters make "native" a measured claim.
"""

import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

import numpy as np

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import Account, AccountFlags, Transfer, TransferFlags

LINKED = int(TransferFlags.linked)
PENDING = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
VOID = int(TransferFlags.void_pending_transfer)
BAL_DR = int(TransferFlags.balancing_debit)
CLOSE_DR = int(TransferFlags.closing_debit)
CLOSE_CR = int(TransferFlags.closing_credit)
IMPORTED = int(TransferFlags.imported)
AMOUNT_MAX = (1 << 128) - 1


def _pair():
    led = DeviceLedger(a_cap=1 << 12, t_cap=1 << 14)
    sm = StateMachineOracle()
    accts = [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
    for eng in (led, sm):
        res = eng.create_accounts(accts, 100)
        assert all(r.status.name == "created" for r in res)
    return led, sm


def _both(led, sm, events, ts):
    got = led.create_transfers(events, ts)
    want = sm.create_transfers(events, ts)
    assert ([(r.timestamp, r.status) for r in got]
            == [(r.timestamp, r.status) for r in want]), (
        [r.status.name for r in got], [r.status.name for r in want])
    return [r.status.name for r in got]


def _check_state(led, sm):
    host = led.to_host()
    assert host.accounts == sm.accounts
    assert host.transfers == sm.transfers
    assert host.pending_status == sm.pending_status


class TestClosingNative:
    def test_closing_chain_rollback_oscillation_falls_back(self):
        """A closing member APPLIES mid-chain, closes its account, makes
        a later member fail (already_closed), and the chain rollback
        then reopens the account — the closed->status->applied->closed
        circularity oscillates instead of converging prefix-stable, so
        the fixpoint must FALL BACK to the exact host path and the
        results must still match the oracle bit for bit."""
        led, sm = _pair()
        ts = 10**12
        evs = [
            # Chain: closing pending on account 2, then a member that
            # debits the now-closed account 2 -> fails -> rollback
            # reopens 2 -> re-evaluating the failed member would now
            # succeed: a 2-cycle oscillation.
            Transfer(id=1, debit_account_id=2, credit_account_id=3,
                     amount=1, ledger=1, code=1,
                     flags=LINKED | PENDING | CLOSE_DR, timeout=60),
            Transfer(id=2, debit_account_id=2, credit_account_id=4,
                     amount=1, ledger=1, code=1),
        ]
        st = _both(led, sm, evs, ts)
        # Sequential truth: the chain member 1 applies, closes 2, member
        # 2 fails on the closed account — but member 2 is NOT in the
        # chain (member 1 is the chain via LINKED on itself + next), so
        # chain semantics: evs[0] linked means evs[0]+evs[1] are one
        # chain; evs[1] fails -> whole chain rolls back.
        assert st == ["linked_event_failed", "debit_account_already_closed"]
        assert led.fallbacks >= 1, "oscillation must fall back to exact"
        _check_state(led, sm)

    def test_void_reopen_via_inwindow_pending_substitution(self):
        """pending+closing and its VOID in ONE batch: the void resolves
        through the in-window pending substitution (the definition's
        event lanes), the reopen clears the closed bit in the same
        fixpoint, and a later lane in the batch sees the account OPEN —
        all native (fallbacks == 0)."""
        led, sm = _pair()
        ts = 10**12
        evs = [
            Transfer(id=10, debit_account_id=2, credit_account_id=3,
                     amount=1, ledger=1, code=1,
                     flags=PENDING | CLOSE_DR, timeout=60),
            # Account 2 is closed here (between def and void).
            Transfer(id=11, debit_account_id=2, credit_account_id=4,
                     amount=1, ledger=1, code=1),
            # Void the in-window closing pending: reopens account 2.
            Transfer(id=12, pending_id=10, amount=0, flags=VOID),
            # After the reopen this lane must see account 2 OPEN.
            Transfer(id=13, debit_account_id=2, credit_account_id=5,
                     amount=2, ledger=1, code=1),
        ]
        st = _both(led, sm, evs, ts)
        assert st == ["created", "debit_account_already_closed",
                      "created", "created"]
        assert led.fallbacks == 0, "void-reopen must run native"
        _check_state(led, sm)

    def test_closing_and_balancing_one_batch(self):
        """closing_credit and balancing_debit interleaved in ONE batch:
        the clamp fixpoint and the closed-state evolution share rounds —
        a balancing clamp reads balances produced by the closing pending,
        and a post-close balancing lane dies on the closed account. All
        native (fallbacks == 0), oracle-exact."""
        led, sm = _pair()
        ts = 10**12
        # Fund: 6 credits 2 with 50 (headroom for balancing debits of 2).
        _both(led, sm, [Transfer(id=20, debit_account_id=6,
                                 credit_account_id=2, amount=50,
                                 ledger=1, code=1)], ts)
        ts += 10**6
        evs = [
            # Balancing debit from 2: clamps to 50.
            Transfer(id=21, debit_account_id=2, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
            # Closing pending: closes account 3 (credit side).
            Transfer(id=22, debit_account_id=4, credit_account_id=3,
                     amount=1, ledger=1, code=1,
                     flags=PENDING | CLOSE_CR, timeout=60),
            # Balancing debit INTO the now-closed 3: must die closed.
            Transfer(id=23, debit_account_id=5, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
            # Balancing debit from 3's sibling path stays alive.
            Transfer(id=24, debit_account_id=3, credit_account_id=5,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
        ]
        st = _both(led, sm, evs, ts)
        assert st[0] == "created"
        assert st[1] == "created"
        assert st[2] == "credit_account_already_closed"
        assert st[3] == "debit_account_already_closed"
        assert led.fallbacks == 0, "closing x balancing must run native"
        _check_state(led, sm)

    def test_imported_closing_uniform_eligibility(self):
        """imported + closing in one batch runs on the imported fixpoint
        tier (closing-native there too): the closed evolution, the
        imported regress maxima chain and the void-reopen all compose,
        with zero host fallbacks."""
        led, sm = _pair()
        ts = 10**12
        evs = [
            Transfer(id=30, debit_account_id=1, credit_account_id=2,
                     amount=5, ledger=1, code=1,
                     flags=IMPORTED | PENDING | CLOSE_DR, timestamp=500),
            # Dies on the closed account 1 — and therefore must NOT
            # advance the imported running max.
            Transfer(id=31, debit_account_id=1, credit_account_id=3,
                     amount=1, ledger=1, code=1, flags=IMPORTED,
                     timestamp=600),
            # 550 < 600, but 600 never applied: this one is CREATED.
            Transfer(id=32, debit_account_id=3, credit_account_id=4,
                     amount=1, ledger=1, code=1, flags=IMPORTED,
                     timestamp=550),
        ]
        st = _both(led, sm, evs, ts)
        assert st == ["created", "debit_account_already_closed", "created"]
        assert led.fallbacks == 0
        ts += 10**6
        # Void the imported closing pending in a later batch: reopen.
        st2 = _both(led, sm, [Transfer(id=33, pending_id=30, amount=0,
                                       flags=VOID)], ts)
        assert st2 == ["created"]
        assert led.fallbacks == 0
        _check_state(led, sm)

    def test_fallback_causes_counted(self):
        """The per-cause fallback counters are a real record: a batch
        with a genuine duplicate-id collision (hard e2) increments
        exactly that cause."""
        led, sm = _pair()
        ts = 10**12
        evs = [
            Transfer(id=40, debit_account_id=1, credit_account_id=2,
                     amount=1, ledger=1, code=1),
            Transfer(id=40, debit_account_id=1, credit_account_id=2,
                     amount=1, ledger=1, code=1),  # duplicate id
        ]
        st = _both(led, sm, evs, ts)
        assert st == ["created", "exists"]
        assert led.fallbacks == 1
        assert led.fallback_causes.get("e2_collision", 0) == 1, \
            led.fallback_causes
        stats = led.fallback_stats()
        assert stats["host_fallbacks"] == 1
        assert stats["causes"]["e2_collision"] == 1
        _check_state(led, sm)
