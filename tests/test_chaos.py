"""Chaos-hardened serving: verified state epochs, seeded device-fault
injection, and bounded replay recovery (tigerbeetle_tpu/serving.py,
ops/state_epoch.py, testing/chaos.py).

Quick tier: the pure-host pieces (digest fold, fault-plan determinism,
retry policy) plus supervisor recovery on single-batch windows (only
the fast kernel compiles, which the quick tier already pays for).
Slow tier: the 20-seed chaos sweep over superbatch windows and the
sharded-router shard-loss differential.
"""

import random

import numpy as np
import pytest

from tigerbeetle_tpu import constants
from tigerbeetle_tpu.ops import state_epoch
from tigerbeetle_tpu.ops.ev_layout import XF_P32_POS, XF_U64_IDX
from tigerbeetle_tpu.oracle.state_machine import StateMachineOracle
from tigerbeetle_tpu.serving import (DispatchTimeout, RecoveryNeeded,
                                     RetryPolicy, ServingSupervisor,
                                     TransientDispatchError,
                                     call_with_retries)
from tigerbeetle_tpu.testing.chaos import (CORRUPTION_KINDS, FAULT_KINDS,
                                           FaultPlan, inject_state_bitflip,
                                           run_chaos_seed,
                                           shard_loss_scenario)
from tigerbeetle_tpu.types import Account, Transfer

A_CAP = 1 << 8


def _small_oracle(n_transfers=12):
    sm = StateMachineOracle()
    sm.create_accounts([Account(id=i, ledger=1, code=1)
                        for i in range(1, 9)], 1_000)
    evs = [Transfer(id=100 + i, debit_account_id=1 + i % 7,
                    credit_account_id=2 + i % 6, amount=5 + i,
                    ledger=1, code=1) for i in range(n_transfers)]
    for e in evs:
        if e.debit_account_id == e.credit_account_id:
            e.credit_account_id = e.debit_account_id % 8 + 1
    sm.create_transfers(evs, 10_000)
    return sm


# ------------------------------------------------------- digest (host)

class TestStateDigest:
    def test_identical_states_digest_equal(self):
        a = state_epoch.oracle_state_digest(_small_oracle(), A_CAP)
        b = state_epoch.oracle_state_digest(_small_oracle(), A_CAP)
        assert a == b
        assert state_epoch.combine(a) == state_epoch.combine(b)

    def test_any_semantic_change_changes_digest(self):
        base = state_epoch.oracle_state_digest(_small_oracle(), A_CAP)
        changed = _small_oracle()
        t = changed.transfers[100]
        import dataclasses

        changed.transfers[100] = dataclasses.replace(t, amount=t.amount + 1)
        got = state_epoch.oracle_state_digest(changed, A_CAP)
        assert got != base
        assert state_epoch.diverging_components(got, base) \
            == ["transfers_u64"]

    def test_single_bit_in_pack_is_detected(self):
        sm = _small_oracle()
        pack = state_epoch.pack_oracle_state(sm, A_CAP)
        base = {k: int(v) for k, v in
                state_epoch._digest_components(pack, np).items()}
        rng = random.Random(7)
        for _ in range(20):
            comp = rng.choice(("accounts", "transfers"))
            mat = pack[comp]["u64"]
            covered = [j for j in range(mat.shape[1])
                       if comp == "accounts"
                       or state_epoch.XF_COL_MASKS[j]]
            r = rng.randrange(mat.shape[0])
            c = rng.choice(covered)
            bit = np.uint64(1 << rng.randrange(64))
            mat[r, c] ^= bit
            got = {k: int(v) for k, v in
                   state_epoch._digest_components(pack, np).items()}
            assert got != base, (comp, r, c)
            mat[r, c] ^= bit  # restore

    def test_excluded_columns_do_not_digest(self):
        # expires and the dr_row/cr_row cache column are deliberately
        # outside the digest (non-canonical across write paths).
        sm = _small_oracle()
        pack = state_epoch.pack_oracle_state(sm, A_CAP)
        base = state_epoch._digest_components(pack, np)
        mat = pack["transfers"]["u64"]
        mat[0, XF_U64_IDX["expires"]] ^= np.uint64(1 << 17)
        mat[1, XF_P32_POS["dr_row"][0]] ^= np.uint64(1 << 3)
        got = state_epoch._digest_components(pack, np)
        assert {k: int(v) for k, v in got.items()} \
            == {k: int(v) for k, v in base.items()}

    def test_device_digest_matches_oracle_digest(self):
        from tigerbeetle_tpu.ops.ledger import DeviceLedger

        sm = StateMachineOracle()
        led = DeviceLedger(a_cap=A_CAP, t_cap=1 << 10)
        accounts = [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
        led.create_accounts(accounts, 1_000)
        sm.create_accounts(accounts, 1_000)
        evs = [Transfer(id=500 + i, debit_account_id=1 + i % 7,
                        credit_account_id=2 + i % 6, amount=3,
                        ledger=1, code=1) for i in range(16)]
        for e in evs:
            if e.debit_account_id == e.credit_account_id:
                e.credit_account_id = e.debit_account_id % 8 + 1
        led.create_transfers(evs, 10_000)
        sm.create_transfers(evs, 10_000)
        assert state_epoch.device_state_digest(led.state) \
            == state_epoch.oracle_state_digest(sm, A_CAP)


# -------------------------------------------------------- fault plans

class TestFaultPlan:
    def test_deterministic_per_seed(self):
        for seed in range(20):
            a = FaultPlan(seed, 10)
            b = FaultPlan(seed, 10)
            assert a.schedule == b.schedule

    def test_seeds_differ_and_always_inject(self):
        schedules = [tuple(sorted(
            (w, f["kind"]) for w, f in FaultPlan(s, 10).schedule.items()))
            for s in range(30)]
        assert len(set(schedules)) > 1
        for s in schedules:
            assert s  # at least one fault per run

    def test_every_kind_appears_across_seeds(self):
        seen = set()
        for s in range(40):
            seen.update(f["kind"]
                        for f in FaultPlan(s, 10).schedule.values())
        assert seen == set(FAULT_KINDS)


# ------------------------------------------------------- retry policy

class TestRetryPolicy:
    def _counters(self):
        from tigerbeetle_tpu.ops.ledger import default_recovery_stats

        return default_recovery_stats()

    def test_transient_faults_retry_then_succeed(self):
        calls = {"n": 0}
        sleeps = []

        def fn():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise TransientDispatchError("flaky")
            return "ok"

        counters = self._counters()
        out = call_with_retries(fn, RetryPolicy(max_retries=3),
                                random.Random(0), counters,
                                sleep=sleeps.append)
        assert out == "ok"
        assert counters["retries"] == 2
        assert len(sleeps) == 2
        assert sleeps[1] > sleeps[0]  # exponential
        # the counter rounds to microseconds as it accumulates
        assert counters["backoff_s"] == pytest.approx(sum(sleeps), abs=1e-5)

    def test_backoff_jitter_is_seeded(self):
        def delays(seed):
            out = []
            calls = {"n": 0}

            def fn():
                calls["n"] += 1
                if calls["n"] <= 3:
                    raise TransientDispatchError("flaky")
                return None

            call_with_retries(fn, RetryPolicy(max_retries=3),
                              random.Random(seed), self._counters(),
                              sleep=out.append)
            return out

        assert delays(1) == delays(1)
        assert delays(1) != delays(2)

    def test_exhaustion_escalates_to_recovery(self):
        def fn():
            raise DispatchTimeout("wedged")

        with pytest.raises(RecoveryNeeded) as ei:
            call_with_retries(fn, RetryPolicy(max_retries=2),
                              random.Random(0), self._counters(),
                              sleep=lambda s: None)
        assert ei.value.cause == "dispatch_exhausted"

    def test_deadline_escalates_to_recovery(self):
        clock = {"t": 0.0}

        def fn():
            clock["t"] += 100.0
            raise TransientDispatchError("slow")

        with pytest.raises(RecoveryNeeded) as ei:
            call_with_retries(fn, RetryPolicy(max_retries=99,
                                              deadline_s=50.0),
                              random.Random(0), self._counters(),
                              sleep=lambda s: None,
                              clock=lambda: clock["t"])
        assert ei.value.cause == "dispatch_deadline"

    def test_clamped_deadline_bounds_total_retry_budget(self):
        # ISSUE 18 satellite: under saturation the whole retry sequence
        # — attempts AND backoff sleeps — is bounded by the request's
        # remaining admission deadline (RetryPolicy.clamped), so a
        # saturated pipeline degrades into a fast typed escalation
        # instead of every admitted request burning the policy's full
        # static 30s deadline.
        t = {"t": 0.0}

        def fn():
            t["t"] += 0.010  # each attempt costs 10ms of wall
            raise TransientDispatchError("saturated")

        policy = RetryPolicy(max_retries=99, base_delay_s=0.050,
                             max_delay_s=10.0, deadline_s=30.0,
                             jitter=0.0).clamped(0.080)
        assert policy.deadline_s == pytest.approx(0.080)
        with pytest.raises(RecoveryNeeded) as ei:
            call_with_retries(fn, policy, random.Random(0),
                              self._counters(),
                              sleep=lambda s: t.__setitem__(
                                  "t", t["t"] + s),
                              clock=lambda: t["t"])
        assert ei.value.cause == "dispatch_deadline"
        # Total elapsed <= clamped budget + one attempt's own cost (an
        # in-flight attempt cannot be preempted, only not retried) —
        # nowhere near the policy's static 30s.
        assert t["t"] <= 0.080 + 0.010 + 1e-9

    def test_clamped_tightens_never_loosens(self):
        p = RetryPolicy(deadline_s=0.5)
        assert p.clamped(30.0) is p
        assert p.clamped(None) is p
        assert p.clamped(0.1).deadline_s == pytest.approx(0.1)
        assert p.clamped(-1.0).deadline_s == 0.0

    def test_mirror_divergence_goes_straight_to_recovery(self):
        from tigerbeetle_tpu.ops.ledger import MirrorDivergence

        def fn():
            raise MirrorDivergence("verify: device/mirror divergence")

        counters = self._counters()
        with pytest.raises(RecoveryNeeded) as ei:
            call_with_retries(fn, RetryPolicy(), random.Random(0),
                              counters, sleep=lambda s: None)
        assert ei.value.cause == "mirror_divergence"
        assert counters["retries"] == 0


# ------------------------------------------- supervisor (fast kernel)

def _mk_supervisor(seed=0, epoch_interval=2, fault_hook=None):
    sup = ServingSupervisor(
        a_cap=A_CAP, t_cap=1 << 11, epoch_interval=epoch_interval,
        retry=RetryPolicy(max_retries=2, base_delay_s=1e-4,
                          max_delay_s=1e-3),
        seed=seed, fault_hook=fault_hook, sleep=lambda s: None)
    sup.create_accounts([Account(id=i, ledger=1, code=1)
                         for i in range(1, 9)], 1_000)
    return sup


def _simple_window(next_id, ts, n=24):
    rng = random.Random(next_id)
    evs = []
    for i in range(n):
        dr = rng.randrange(1, 9)
        evs.append(Transfer(id=next_id + i, debit_account_id=dr,
                            credit_account_id=dr % 8 + 1,
                            amount=rng.randrange(1, 50), ledger=1, code=1))
    return [evs], [ts]


def _audit(sup, script):
    audit = StateMachineOracle()
    expected = []
    for kind, payload, when in script:
        if kind == "accounts":
            expected.append([(r.timestamp, int(r.status))
                             for r in audit.create_accounts(payload, when)])
        else:
            expected.append([
                [(r.timestamp, int(r.status))
                 for r in audit.create_transfers(b, bts)]
                for b, bts in zip(payload, when)])
    assert sup.history == expected
    host = sup.led.to_host()
    for field in ("accounts", "transfers", "pending_status", "orphaned",
                  "expiry", "account_events"):
        assert getattr(host, field) == getattr(audit, field), field


class TestSupervisorRecovery:
    def _run(self, sup, windows, corrupt_at=None):
        script = [("accounts",
                   [Account(id=i, ledger=1, code=1) for i in range(1, 9)],
                   1_000)]
        ts = 10 ** 9
        next_id = 1_000
        for w in range(windows):
            if corrupt_at is not None and w == corrupt_at:
                f = {"target": "accounts_bal", "row_pick": 3,
                     "col_pick": 5, "bit": 11}
                assert inject_state_bitflip(sup.led, f), f
            ts += 40
            batches, tss = _simple_window(next_id, ts)
            next_id += 24
            sup.create_transfers_window(batches, tss)
            script.append(("window", batches, tss))
        sup.verify_epoch()
        return script

    def test_clean_run_verifies_epochs_and_never_recovers(self):
        sup = _mk_supervisor()
        script = self._run(sup, windows=4)
        _audit(sup, script)
        assert sup.counters["epochs_verified"] >= 2
        assert sup.counters["recoveries"] == {}
        assert sup.counters["replayed_windows"] == 0

    def test_bitflip_detected_and_recovered_to_parity(self):
        sup = _mk_supervisor(epoch_interval=2)
        script = self._run(sup, windows=4, corrupt_at=1)
        _audit(sup, script)
        recs = sup.counters["recoveries"]
        assert sum(recs.values()) >= 1, recs
        # Detected as a checksum/state divergence (digest or mirror),
        # never silently absorbed.
        assert set(recs) <= {"state_digest", "mirror_divergence",
                             "result_divergence", "drain_fault"}

    def test_replay_is_bounded_by_epoch_interval(self):
        sup = _mk_supervisor(epoch_interval=3)
        self._run(sup, windows=6, corrupt_at=1)
        assert sup.last_recovery is not None
        assert sup.last_recovery["replayed_windows"] <= 3
        assert sup.counters["replayed_windows"] <= 3

    def test_dispatch_faults_within_budget_just_retry(self):
        fails = {"left": 2}

        def hook(win, what):
            if what == "window" and fails["left"]:
                fails["left"] -= 1
                raise TransientDispatchError("injected")

        sup = _mk_supervisor(fault_hook=hook)
        script = self._run(sup, windows=2)
        _audit(sup, script)
        assert sup.counters["retries"] == 2
        assert sup.counters["recoveries"] == {}

    def test_dispatch_exhaustion_recovers_and_reserves(self):
        fails = {"left": 5}

        def hook(win, what):
            if what == "window" and fails["left"]:
                fails["left"] -= 1
                raise DispatchTimeout("injected")

        sup = _mk_supervisor(fault_hook=hook)
        script = self._run(sup, windows=3)
        _audit(sup, script)
        assert sup.counters["recoveries"].get("dispatch_exhausted", 0) >= 1

    def test_recovery_counters_surface_through_fallback_stats(self):
        sup = _mk_supervisor(epoch_interval=2)
        self._run(sup, windows=4, corrupt_at=1)
        rec = sup.led.fallback_stats()["recovery"]
        assert rec["replayed_windows"] == \
            sup.counters["replayed_windows"] > 0
        assert rec["recoveries"] == sup.counters["recoveries"]


# ------------------------------------------- chaos x causal tracing

class TestChaosTracing:
    """ISSUE 15 satellite: chaos and tracing compose. A seeded fault
    that lands mid-request must leave the affected requests' traces
    tail-kept (reason = the recovery cause) and cross-referenced from
    the flight-recorder artifact by trace id."""

    def _traced_supervisor(self, tmp_path):
        from tigerbeetle_tpu.trace import FlightRecorder, Tracer

        tracer = Tracer(pid=0)
        flight = FlightRecorder(tracer=tracer, out_dir=str(tmp_path))
        sup = ServingSupervisor(
            a_cap=A_CAP, t_cap=1 << 11, epoch_interval=2,
            retry=RetryPolicy(max_retries=2, base_delay_s=1e-4,
                              max_delay_s=1e-3),
            seed=0, sleep=lambda s: None, tracer=tracer,
            flight_recorder=flight)
        sup.create_accounts([Account(id=i, ledger=1, code=1)
                             for i in range(1, 9)], 1_000)
        return sup, tracer, flight

    def _run_traced(self, sup, windows, corrupt_at):
        from tigerbeetle_tpu.trace.context import (fmt_trace_id,
                                                   mint_context)

        trace_ids = []
        ts = 10 ** 9
        next_id = 1_000
        for w in range(windows):
            if w == corrupt_at:
                f = {"target": "accounts_bal", "row_pick": 3,
                     "col_pick": 5, "bit": 11}
                assert inject_state_bitflip(sup.led, f), f
            ts += 40
            batches, tss = _simple_window(next_id, ts)
            next_id += 24
            ctx = mint_context(3, w + 1, head_rate=1.0)
            trace_ids.append(fmt_trace_id(ctx.trace_id))
            sup.create_transfers_window(batches, tss, trace_ctxs=[ctx])
        sup.verify_epoch()
        return trace_ids

    def test_recovery_tail_keeps_affected_traces(self, tmp_path):
        sup, tracer, _ = self._traced_supervisor(tmp_path)
        trace_ids = self._run_traced(sup, windows=4, corrupt_at=1)
        recs = sup.counters["recoveries"]
        assert sum(recs.values()) >= 1, recs
        # Every tail-kept trace names the recovery cause as its reason
        # and is one of the requests in flight since the last epoch.
        assert tracer.kept_traces, "recovery kept no traces"
        assert set(tracer.kept_traces.values()) <= set(recs)
        assert set(tracer.kept_traces) <= set(trace_ids)
        assert tracer.counters["trace_tail_keep"] \
            == len(tracer.kept_traces)
        # The verified-epoch boundary clears the at-risk set: a later
        # clean run keeps nothing new.
        before = dict(tracer.kept_traces)
        self._run_traced(sup, windows=2, corrupt_at=None)
        assert tracer.kept_traces == before

    def test_flight_artifact_names_affected_trace_ids(self, tmp_path):
        import json

        sup, tracer, flight = self._traced_supervisor(tmp_path)
        trace_ids = self._run_traced(sup, windows=4, corrupt_at=1)
        assert flight.dumps >= 1 and flight.last_dump_path
        with open(flight.last_dump_path) as f:
            doc = json.load(f)
        named = set()
        for rec in doc["records"]:
            named.update((rec.get("detail") or {}).get("trace_ids", ()))
        # The artifact cross-references BOTH planes: the per-window
        # records carry each window's constituent trace ids (up to the
        # dump — the ring freezes AT recovery, later windows are not in
        # it), and the recovery record names the tail-kept set.
        assert named and named <= set(trace_ids)
        assert set(tracer.kept_traces) <= named
        recovery = [rec for rec in doc["records"]
                    if rec.get("route") == "recovery"]
        assert recovery, "recovery never reached the flight ring"
        assert set((recovery[-1].get("detail") or {})["trace_ids"]) \
            == set(tracer.kept_traces)

    def test_window_spans_link_constituent_traces(self, tmp_path):
        sup, tracer, _ = self._traced_supervisor(tmp_path)
        trace_ids = self._run_traced(sup, windows=2, corrupt_at=None)
        spans = [e for e in tracer.events
                 if e.get("name") == "window_commit"
                 and (e.get("args") or {}).get("links")]
        assert spans, "no window span carried fan-in links"
        linked = set()
        for s in spans:
            linked.update(s["args"]["links"])
        assert linked == set(trace_ids)


class TestSpotCheckDiagnostics:
    def test_divergence_names_op_and_fields(self, monkeypatch):
        import dataclasses

        from tigerbeetle_tpu.ops.ledger import MirrorDivergence
        from tigerbeetle_tpu.state_machine import StateMachine

        monkeypatch.setenv("TB_VERIFY_SPOT_RATE", "1.0")
        was = constants.VERIFY
        constants.set_verify(True)
        try:
            sm = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 12)
            sm.create_accounts([Account(id=i, ledger=1, code=1)
                                for i in range(1, 9)], 100)
            evs = [Transfer(id=100 + i, debit_account_id=1 + i % 7,
                            credit_account_id=2 + i % 6, amount=1,
                            ledger=1, code=1) for i in range(8)]
            for e in evs:
                if e.debit_account_id == e.credit_account_id:
                    e.credit_account_id = e.debit_account_id % 8 + 1
            sm.create_transfers(evs, 10_000)
            _ = sm.state.transfers  # clean drain
            tid = next(iter(sm.state.transfers))
            sm.state.transfers[tid] = dataclasses.replace(
                sm.state.transfers[tid], amount=424242)
            sm.create_transfers(
                [Transfer(id=900, debit_account_id=1, credit_account_id=2,
                          amount=1, ledger=1, code=1)], 20_000)
            with pytest.raises(MirrorDivergence) as ei:
                _ = sm.state.transfers
            msg = str(ei.value)
            assert "device/mirror divergence" in msg
            assert "op " in msg           # which prepare produced it
            assert "amount" in msg        # the differing field, named
            assert "424242" in msg        # ... with both values
        finally:
            constants.set_verify(was)


# ------------------------------------------------------- chaos sweeps

@pytest.mark.slow
class TestChaosSweep:
    def test_twenty_seeds_zero_silent_corruption(self):
        """The acceptance sweep: >= 20 deterministic seeds across every
        fault class; each run either recovers to bit-exact oracle
        parity or fails loudly (run_chaos_seed asserts both, plus that
        every applied corruption produced a counted recovery)."""
        kinds_seen = set()
        recovered = 0
        for seed in range(1, 21):
            s = run_chaos_seed(seed, windows=6, batches_per_window=2,
                               events_per_batch=32, mesh_scenario=False)
            kinds_seen.update(k for k in s["faults"]
                              if not k.endswith("_skipped"))
            recovered += sum(s["recoveries"].values())
            assert s["replayed_windows"] <= \
                s["epoch_interval"] * (sum(s["recoveries"].values()) or 1)
        assert kinds_seen == set(FAULT_KINDS)
        assert recovered >= 5  # the sweep genuinely exercises recovery

    def test_chaos_seed_is_reproducible(self):
        a = run_chaos_seed(11, windows=4, batches_per_window=2,
                           events_per_batch=24, mesh_scenario=False)
        b = run_chaos_seed(11, windows=4, batches_per_window=2,
                           events_per_batch=24, mesh_scenario=False)
        assert a == b


# ------------------------------------- adversarial traffic shapes (18)

class TestTrafficShapes:
    @pytest.mark.slow
    def test_every_shape_runs_clean_and_reproducibly(self):
        from tigerbeetle_tpu.testing.chaos import TRAFFIC_SHAPES

        for shape in TRAFFIC_SHAPES:
            a = run_chaos_seed(9, windows=4, batches_per_window=2,
                               events_per_batch=24, mesh_scenario=False,
                               kinds=("dispatch_fail",), traffic=shape)
            b = run_chaos_seed(9, windows=4, batches_per_window=2,
                               events_per_batch=24, mesh_scenario=False,
                               kinds=("dispatch_fail",), traffic=shape)
            assert a == b, shape
            assert a["traffic"] == shape

    def test_shapes_generate_distinct_workloads(self):
        from tigerbeetle_tpu.testing.chaos import TrafficShape

        batches = {}
        for shape in ("hot_skew", "pending_storm", "open_close_burst"):
            s = TrafficShape(shape, seed=5, n_accounts=32, n_windows=4)
            evs, _nid = s.batch(0, random.Random(0), 1_000, 24, [])
            batches[shape] = [(e.debit_account_id, e.credit_account_id,
                               int(e.flags)) for e in evs]
        assert len({tuple(v) for v in batches.values()}) == 3


# ------------------------------- admission x saturation (ISSUE 18 #2)

class TestAdmissionSaturation:
    @pytest.mark.slow
    def test_saturated_pipeline_sheds_instead_of_timing_out(self):
        """Offered load ~6x the pump's service capacity: the plane must
        degrade into TYPED sheds (shed_line/deadline/no_credit) with
        every ADMITTED request's queue wait inside its class deadline —
        and the supervisor below must see zero dispatch_deadline
        recoveries, because shedding (not per-request retry timeouts)
        is how saturation is absorbed."""
        from tigerbeetle_tpu.admission import (AdmissionClass,
                                               AdmissionPlane,
                                               ShedResult, VirtualClock)

        clock = VirtualClock()
        sup = ServingSupervisor(
            a_cap=A_CAP, t_cap=1 << 11, epoch_interval=4,
            retry=RetryPolicy(max_retries=2, base_delay_s=1e-4,
                              max_delay_s=1e-3),
            seed=11, sleep=lambda s: None)
        classes = (
            AdmissionClass("critical", 0, slo_ms=60.0, deadline_ms=240.0),
            AdmissionClass("batch", 1, slo_ms=120.0, deadline_ms=240.0),
        )
        plane = AdmissionPlane(
            sup, classes=classes, prepare_max=8, window_prepares=1,
            max_windows_per_pump=1, session_credits=3, max_queue=64,
            burn_window_ticks=4, burn_budget=0.25, cool_ticks=2,
            clock=clock, seed=11)
        plane.open_accounts([Account(id=i, ledger=1, code=1)
                             for i in range(1, 9)], 1_000)
        nid = 10 ** 5
        reqs = []
        for tick in range(15):
            for sid in range(1, 13):  # 48 events offered vs 8 served
                cls = "critical" if sid == 1 else "batch"
                evs = [Transfer(id=nid + i, debit_account_id=1 + i % 7,
                                credit_account_id=2 + i % 6, amount=1,
                                ledger=1, code=1) for i in range(4)]
                nid += 4
                reqs.append(plane.submit(sid, evs, cls=cls))
            plane.pump()
            clock.advance(0.05)
        plane.drain()
        cons = plane.conservation()
        assert cons["ok"] and cons["queued"] == 0
        assert cons["shed"] > 0, "saturation produced no sheds"
        for r in reqs:
            assert r.state in ("admitted", "shed")
            if r.state == "shed":
                assert isinstance(r.shed, ShedResult), r.shed
            else:
                assert r.admit_wait_ms <= r.cls.deadline_ms + 1e-6
        # The pipeline below never escalated a retry-deadline recovery:
        # saturation was absorbed at the admission line, not burned in
        # per-request retry budgets.
        assert sup.last_recovery is None
        assert sup.counters["recoveries"] == {}
        assert sup.verify_epoch()
        hist, _ = plane.oracle_history()
        assert hist == sup.history
        sup.led.shutdown_staging()


@pytest.mark.slow
class TestShardLoss:
    def test_drop_and_restore_bit_exact(self):
        s = shard_loss_scenario(0)
        assert s["reroutes"] == 2
        assert s["devices"] >= 1

    def test_partitioned_loss_requires_resync(self):
        from tigerbeetle_tpu.testing.chaos import shard_resync_scenario

        s = shard_resync_scenario(0)
        assert s["resyncs"] == 1
        assert s["devices"] >= 1

    def test_corruption_kinds_is_subset(self):
        assert CORRUPTION_KINDS < set(FAULT_KINDS)
