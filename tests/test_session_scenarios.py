"""Client-session and asymmetric-partition scenarios (reference:
src/vsr/replica_test.zig "Cluster: eviction: ...", "Cluster: network:
partition client-primary (asymmetric, drop requests/replies)",
"Cluster: network: partition flexible quorum", "Cluster: prepare beyond
checkpoint trigger"). Session semantics under faults are where
at-most-once either holds or silently double-executes — scripted here
because randomized simulation rarely lines the faults up."""

import struct

import pytest

from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Account, Operation, Transfer
from tigerbeetle_tpu import multi_batch


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=t, debit_account_id=d, credit_account_id=c,
                 ledger=1, code=1, amount=a).pack()
        for t, d, c, a in specs)
    return multi_batch.encode([payload], 128)


def _drive(cluster, client, requests, ticks=3000):
    replies = []
    for op, body in requests:
        client.request(op, body)
        ok = cluster.run(ticks, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        replies.append(client.replies[-1])
    return replies


def _result_statuses(reply):
    """Decode a create_* reply body to status ints."""
    out = []
    for batch in multi_batch.decode(reply.body, 16):
        for off in range(0, len(batch), 16):
            _ts, status, _r = struct.unpack_from("<QII", batch, off)
            out.append(status)
    return out


class TestSessionScenarios:
    def test_session_eviction_on_overflow(self):
        """clients_max sessions are live; one more client evicts the
        lowest-request session (reference client_sessions.zig eviction
        order) and the table stays at capacity."""
        cluster = Cluster(seed=21, replica_count=3)
        cap = cluster.replicas[0].storage.layout.clients_max
        boot = cluster.client(1)
        _drive(cluster, boot, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        # boot client has request=1; newer clients get higher numbers.
        for k in range(2, cap + 1):
            c = cluster.client(100 + k)
            _drive(cluster, c, [
                (Operation.create_transfers,
                 _transfers_body([(1000 + k, 1, 2, 1)]))])
            _drive(cluster, c, [
                (Operation.create_transfers,
                 _transfers_body([(2000 + k, 1, 2, 1)]))])
        primary = cluster.replicas[cluster.replicas[0].primary_index()]
        assert len(primary.sessions.entries) == cap
        assert 1 in primary.sessions.entries
        # One more client: the boot session (lowest request number) is
        # evicted; the table stays at capacity.
        extra = cluster.client(999)
        _drive(cluster, extra, [
            (Operation.create_transfers, _transfers_body([(3000, 1, 2, 1)]))])
        assert len(primary.sessions.entries) == cap
        assert 1 not in primary.sessions.entries
        assert 999 in primary.sessions.entries

    def test_evicted_client_retry_is_idempotent_by_id(self):
        """After eviction the session's dedupe memory is gone; the
        DATA-MODEL idempotency (transfer id exists) is what still
        prevents double-effects (reference doctrine: eviction tells the
        client to re-register; replays surface as .exists)."""
        cluster = Cluster(seed=22, replica_count=3)
        a = cluster.client(1)
        _drive(cluster, a, [
            (Operation.create_accounts, _accounts_body([1, 2])),
            (Operation.create_transfers, _transfers_body([(77, 1, 2, 9)])),
        ])
        primary = cluster.replicas[cluster.replicas[0].primary_index()]
        # Force-evict client 1's session (as a full table would).
        del primary.sessions.entries[1]
        # The client retries the SAME logical transfer (fresh request
        # number — its session is gone): the id check reports exists,
        # balances move exactly once.
        replies = _drive(cluster, a, [
            (Operation.create_transfers, _transfers_body([(77, 1, 2, 9)]))])
        from tigerbeetle_tpu.types import CreateTransferStatus

        assert _result_statuses(replies[0]) == [
            int(CreateTransferStatus.exists)]
        cluster.settle()
        acct = cluster.replicas[0].state_machine.state.accounts[2]
        assert acct.credits_posted == 9  # once, not twice

    def test_drop_replies_no_double_execution(self):
        """Asymmetric client-primary partition, reply direction only:
        the request commits, the reply is lost, the client's retry is
        answered from the session table WITHOUT re-execution
        (reference: partition client-primary asymmetric drop replies)."""
        cluster = Cluster(seed=23, replica_count=3)
        c = cluster.client(7)
        _drive(cluster, c, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        primary_id = cluster.replicas[0].primary_index()
        # Cut ONLY primary -> client replies.
        cluster.cut(("replica", primary_id), ("client", 7))
        c.request(Operation.create_transfers,
                  _transfers_body([(500, 1, 2, 21)]))
        # The request itself still flows: it commits cluster-wide.
        ok = cluster.run(
            4000,
            until=lambda: 500 in cluster.replicas[primary_id]
            .state_machine.state.transfers)
        assert ok, cluster.debug_status()
        assert not c.idle  # reply was dropped
        cluster.heal()
        # The client's periodic resend hits the session table: the
        # recorded reply is returned, nothing re-executes.
        ok = cluster.run(5000, until=lambda: c.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        acct = cluster.replicas[0].state_machine.state.accounts[2]
        assert acct.credits_posted == 21
        assert sum(
            1 for t in cluster.replicas[0]
            .state_machine.state.transfers.values() if t.id == 500) == 1

    def test_drop_requests_retry_after_heal(self):
        """Asymmetric partition, request direction only: nothing commits
        while cut; the retry after heal executes exactly once."""
        cluster = Cluster(seed=24, replica_count=3)
        c = cluster.client(8)
        _drive(cluster, c, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        for r in range(3):
            cluster.cut(("client", 8), ("replica", r))
        c.request(Operation.create_transfers,
                  _transfers_body([(600, 1, 2, 5)]))
        cluster.run(1500, until=lambda: False)  # let the cut soak
        assert not c.idle
        assert all(600 not in r.state_machine.state.transfers
                   for r in cluster.replicas)
        cluster.heal()
        ok = cluster.run(5000, until=lambda: c.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        acct = cluster.replicas[0].state_machine.state.accounts[2]
        assert acct.credits_posted == 5

    def test_flexible_quorum_commits_with_backup_cut(self):
        """R=3 keeps committing with one backup fully cut from its peers
        (replication quorum 2/3); the backup catches up after heal
        (reference: partition flexible quorum)."""
        cluster = Cluster(seed=25, replica_count=3)
        c = cluster.client(3)
        _drive(cluster, c, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        primary_id = cluster.replicas[0].primary_index()
        backup = (primary_id + 1) % 3
        for peer in range(3):
            if peer != backup:
                cluster.cut_links.add(frozenset((backup, peer)))
        _drive(cluster, c, [
            (Operation.create_transfers, _transfers_body(
                [(700 + k, 1, 2, 1) for k in range(5)]))])
        assert 700 in cluster.replicas[primary_id] \
            .state_machine.state.transfers
        assert 700 not in cluster.replicas[backup] \
            .state_machine.state.transfers
        cluster.heal()
        cluster.settle()
        assert 704 in cluster.replicas[backup] \
            .state_machine.state.transfers

    def test_primary_no_clock_sync_makes_no_progress(self):
        """A primary whose peers' clocks disagree beyond any common
        interval has no Marzullo quorum: it must NOT stamp prepares, so
        the cluster makes no progress until clocks re-agree (reference:
        "Cluster: network: primary no clock sync"; consensus drives
        time, src/vsr/clock.zig:1-45)."""
        cluster = Cluster(seed=27, replica_count=3)
        c = cluster.client(6)
        _drive(cluster, c, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        # Split the peers' wall clocks beyond any overlap: one far
        # future, one far past. The primary's own interval is [0,0];
        # best coverage = 1 < quorum 2. (The default cluster shares one
        # TimeSim, so give each peer its own DriftedTime view — both the
        # replica and its Clock read it.)
        from tigerbeetle_tpu.testing.cluster import DriftedTime

        primary_id = cluster.replicas[0].primary_index()
        peers = [i for i in range(3) if i != primary_id]
        drifted = []
        for p, off in ((peers[0], 10**15), (peers[1], -(10**15))):
            t = DriftedTime(cluster.time, offset_ns=off)
            cluster.replicas[p].time = t
            cluster.replicas[p].clock.time = t
            drifted.append(t)
        # Old agreeing samples must expire (the clock window), then the
        # request goes unanswered.
        cluster.run(1500, until=lambda: False)
        c.request(Operation.create_transfers,
                  _transfers_body([(950, 1, 2, 3)]))
        progressed = cluster.run(1500, until=lambda: c.idle)
        assert not progressed, "prepared without clock agreement"
        assert all(950 not in r.state_machine.state.transfers
                   for r in cluster.replicas)
        # Clocks re-agree: the retried request commits.
        for t in drifted:
            t.offset_ns = 0
        ok = cluster.run(8000, until=lambda: c.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        assert 950 in cluster.replicas[0].state_machine.state.transfers

    def test_recovering_head_outdated_view(self):
        """A replica crashes holding a view-0 WAL head, misses a view
        change AND further commits, then restarts: it must not trust its
        own head — it adopts the live view, repairs the divergent
        suffix, and converges (reference: "recovery: recovering_head,
        outdated View")."""
        cluster = Cluster(seed=28, replica_count=3)
        c = cluster.client(9)
        _drive(cluster, c, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        old_primary = cluster.replicas[0].primary_index()
        victim = (old_primary + 1) % 3
        _drive(cluster, c, [
            (Operation.create_transfers, _transfers_body([(10, 1, 2, 1)]))])
        cluster.crash(victim)
        # Depose the view-0 primary: the survivors elect a new view.
        cluster.crash(old_primary)
        cluster.run(1200, until=lambda: False)
        cluster.restart(old_primary)
        c.request(Operation.create_transfers,
                  _transfers_body([(11, 1, 2, 2)]))
        ok = cluster.run(8000, until=lambda: c.idle)
        assert ok, cluster.debug_status()
        live = [r for i, r in enumerate(cluster.replicas)
                if i not in cluster.crashed]
        assert any(r.view > 0 for r in live)
        # More commits in the new view while the victim is still down.
        _drive(cluster, c, [
            (Operation.create_transfers, _transfers_body([(12, 1, 2, 4)]))],
            ticks=8000)
        # The victim restarts with a view-0 head and an outdated view.
        cluster.restart(victim)
        cluster.settle()
        r = cluster.replicas[victim]
        assert r.view >= max(x.view for x in live) - 0  # adopted the view
        acct = r.state_machine.state.accounts[2]
        assert acct.credits_posted == 1 + 2 + 4
        cluster.check_storage()

    def test_prepare_beyond_checkpoint_trigger(self):
        """Commits straddle the checkpoint trigger while more prepares
        queue behind it; a post-checkpoint crash+restart replays the WAL
        suffix on top of the checkpoint and converges byte-identically
        (reference: prepare beyond checkpoint trigger)."""
        cluster = Cluster(seed=26, replica_count=3)
        interval = cluster.replicas[0].options.checkpoint_interval
        c = cluster.client(4)
        _drive(cluster, c, [
            (Operation.create_accounts, _accounts_body([1, 2]))])
        # Drive well past one checkpoint boundary.
        n = interval + 3
        for k in range(n):
            _drive(cluster, c, [
                (Operation.create_transfers,
                 _transfers_body([(800 + k, 1, 2, 1)]))])
        assert any(r.superblock.op_checkpoint > 0
                   for r in cluster.replicas)
        victim = (cluster.replicas[0].primary_index() + 2) % 3
        cluster.crash(victim)
        _drive(cluster, c, [
            (Operation.create_transfers, _transfers_body([(900, 1, 2, 2)]))])
        cluster.restart(victim)
        cluster.settle()
        acct = cluster.replicas[victim].state_machine.state.accounts[2]
        assert acct.credits_posted == n + 2
        cluster.check_storage()
