"""Imported events on the device fast path — differential vs the oracle.

reference: execute_create :3052-3063 (batch homogeneity + timestamp
wrapper rules) and create_transfer :3800-3833 (regress/postdate/timeout
rules). The kernel's in-batch regress uses a closed-form left-to-right
maxima chain (ops/fast_kernels.py imported_mode docstring); every
scenario here pins (status, timestamp) bit-equality against the
sequential oracle, including the maxima chain's alternating
apply/regress patterns and the precedence override for checks that sit
after regress in the reference's order.
"""

import numpy as np
import pytest

from tigerbeetle_tpu.oracle.state_machine import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

IMP = int(TransferFlags.imported)
PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
LINKED = int(TransferFlags.linked)


def _pair():
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
    ora = StateMachineOracle()
    accs = [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
    led.create_accounts(accs, 100)
    ora.create_accounts(accs, 100)
    return led, ora


def _diff(led, ora, transfers, ts):
    got = led.create_transfers(list(transfers), ts)
    want = ora.create_transfers(list(transfers), ts)
    mism = [(i, g.status.name, w.status.name, g.timestamp, w.timestamp)
            for i, (g, w) in enumerate(zip(got, want))
            if g.status != w.status or g.timestamp != w.timestamp]
    assert not mism, mism[:6]
    return [w.status.name for w in want]


def _imp(id_, dr, cr, amt, uts, flags=IMP, timeout=0, pid=0):
    return Transfer(id=id_, debit_account_id=dr, credit_account_id=cr,
                    amount=amt, ledger=1, code=1, flags=flags,
                    timeout=timeout, pending_id=pid, timestamp=uts)


class TestImportedFastPath:
    def test_monotone_batch_all_created_with_user_timestamps(self):
        led, ora = _pair()
        xs = [_imp(1000 + i, 1 + i % 4, 5 + i % 4, 10, 5000 + i * 10)
              for i in range(64)]
        names = _diff(led, ora, xs, 10**9)
        assert names == ["created"] * 64
        assert led.fallbacks == 0  # stayed on device
        # Stored rows carry the USER timestamps.
        got = led.lookup_transfers([1000, 1063])
        assert got[0].timestamp == 5000 and got[1].timestamp == 5630

    def test_in_batch_regress_maxima_chain(self):
        """Alternating apply/regress: the applied set is the strict
        left-to-right maxima; a failed timestamp never advances it."""
        led, ora = _pair()
        uts = [5000, 4900, 5100, 5050, 5200, 5200, 5300]
        xs = [_imp(2000 + i, 1, 2, 1, t) for i, t in enumerate(uts)]
        names = _diff(led, ora, xs, 10**9)
        assert names == [
            "created", "imported_event_timestamp_must_not_regress",
            "created", "imported_event_timestamp_must_not_regress",
            "created", "imported_event_timestamp_must_not_regress",
            "created"]
        assert led.fallbacks == 0

    def test_regress_vs_state_key_max(self):
        led, ora = _pair()
        _diff(led, ora, [_imp(3000, 1, 2, 1, 7000)], 10**9)
        names = _diff(led, ora,
                      [_imp(3001, 1, 2, 1, 6999),
                       _imp(3002, 1, 2, 1, 7000),
                       _imp(3003, 1, 2, 1, 7001)], 2 * 10**9)
        assert names == ["imported_event_timestamp_must_not_regress",
                         "imported_event_timestamp_must_not_regress",
                         "created"]

    def test_postdate_accounts_and_collision(self):
        led, ora = _pair()
        # Accounts were created at timestamp 100-ish (sequential
        # ts_event); an imported ts at/below them must postdate-fail,
        # and an exact collision with an account timestamp regresses.
        acct_ts = ora.accounts[1].timestamp
        names = _diff(led, ora,
                      [_imp(4000, 1, 2, 1, acct_ts),
                       _imp(4001, 1, 2, 1, acct_ts - 1, flags=IMP),
                       _imp(4002, 1, 2, 1, ora.accounts[8].timestamp + 1)],
                      10**9)
        assert names[0] == "imported_event_timestamp_must_not_regress"
        assert names[1].startswith("imported_event_timestamp_must")
        assert names[2] == "created"

    def test_wrapper_rules(self):
        led, ora = _pair()
        batch_ts = 10**9
        xs = [
            _imp(5000, 1, 2, 1, batch_ts),       # must_not_advance
            _imp(5001, 1, 2, 1, 0),              # out_of_range
            _imp(5002, 1, 2, 1, 1 << 63),        # out_of_range
            Transfer(id=5003, debit_account_id=1, credit_account_id=2,
                     amount=1, ledger=1, code=1),  # expected (batch imp)
            _imp(5004, 1, 2, 1, 8000),           # created
        ]
        names = _diff(led, ora, xs, batch_ts)
        assert names == [
            "imported_event_timestamp_must_not_advance",
            "imported_event_timestamp_out_of_range",
            "imported_event_timestamp_out_of_range",
            "imported_event_expected",
            "created"]

    def test_not_expected_in_plain_batch(self):
        led, ora = _pair()
        xs = [Transfer(id=6000, debit_account_id=1, credit_account_id=2,
                       amount=1, ledger=1, code=1),
              _imp(6001, 1, 2, 1, 9000)]
        names = _diff(led, ora, xs, 10**9)
        assert names == ["created", "imported_event_not_expected"]

    def test_imported_pending_and_post(self):
        led, ora = _pair()
        names = _diff(led, ora,
                      [_imp(7000, 1, 2, 50, 9100, flags=IMP | PEND),
                       _imp(7001, 1, 2, 1, 9200, flags=IMP | PEND,
                            timeout=5)], 10**9)
        assert names == ["created", "imported_event_timeout_must_be_zero"]
        # Post the imported pending in a later imported batch: the post
        # carries its own user timestamp.
        names = _diff(led, ora,
                      [_imp(7002, 0, 0, (1 << 128) - 1, 9300,
                            flags=IMP | POST, pid=7000)], 2 * 10**9)
        assert names == ["created"]
        got = led.lookup_transfers([7002])
        assert got[0].timestamp == 9300

    def test_after_regress_precedence_override(self):
        """An event failing a check AFTER regress in the reference's
        order (postdate) that ALSO regresses in-batch must report
        regress — the sequential key_max was already advanced."""
        led, ora = _pair()
        acct_ts = ora.accounts[3].timestamp
        xs = [_imp(8000, 1, 2, 1, 6000),
              # <= in-batch max (6000) AND <= account 3's creation ts
              # is impossible (acct ts ~100); instead: > key_max,
              # <= chain max, postdate-ok=false vs account ts? Use a
              # ts below BOTH the chain max and above state max but
              # below account ts — accounts are ancient, so craft the
              # other way: ts below chain max and colliding postdate
              # is covered by the oracle diff itself.
              _imp(8001, 3, 4, 1, 5999)]
        names = _diff(led, ora, xs, 10**9)
        assert names == ["created",
                         "imported_event_timestamp_must_not_regress"]

    def test_chains_fall_back_exactly(self):
        led, ora = _pair()
        xs = [_imp(9000, 1, 2, 1, 12000, flags=IMP | LINKED),
              _imp(9001, 1, 99, 1, 12100)]  # breaks the chain
        names = _diff(led, ora, xs, 10**9)
        assert names == ["linked_event_failed", "credit_account_not_found"]
        assert led.fallbacks >= 1  # exact path took it

    def test_duplicate_imported_id_and_orphan(self):
        led, ora = _pair()
        _diff(led, ora, [_imp(9100, 1, 2, 7, 13000)], 10**9)
        names = _diff(led, ora,
                      [_imp(9100, 1, 2, 7, 13500),   # exists
                       _imp(9101, 1, 2, 7, 13000)],  # regress (orphaned)
                      2 * 10**9)
        assert names[0] == "exists"
        assert names[1] == "imported_event_timestamp_must_not_regress"
        # Regress is NOT transient (reference transient()
        # classification): the id is reusable with a conforming
        # timestamp.
        names = _diff(led, ora, [_imp(9101, 1, 2, 7, 14000)], 3 * 10**9)
        assert names == ["created"]


class TestImportedWindows:
    def test_sync_window_mixed_subbatches(self):
        """Homogeneity is PER SUB-BATCH; the maxima chain spans the
        whole window in commit order (key_max carries across
        prepares)."""
        from tigerbeetle_tpu.ops.batch import transfers_to_arrays

        led, ora = _pair()
        b1 = [_imp(11000 + i, 1, 2, 1, 20000 + i * 5) for i in range(8)]
        b2 = [Transfer(id=11100 + i, debit_account_id=2,
                       credit_account_id=3, amount=1, ledger=1, code=1)
              for i in range(8)]
        # The non-imported prepare advanced key_max to ~tss[1] (its
        # ts_event stream), so the third prepare's maxima reference is
        # the SECOND prepare's commit timestamps — regress below them,
        # create above (but still behind tss[2] for must_not_advance).
        b3 = [_imp(11200, 1, 2, 1, 10**9 + 900),   # <= b2 max -> regress
              _imp(11201, 1, 2, 1, 10**9 + 1500)]  # created
        tss = [10**9, 10**9 + 1000, 10**9 + 2000]
        evs = [transfers_to_arrays(b) for b in (b1, b2, b3)]
        results = led.create_transfers_window(evs, tss)
        assert results is not None
        want = [ora.create_transfers(b, t)
                for b, t in zip((b1, b2, b3), tss)]
        for (st, ts), wb in zip(results, want):
            for g_st, g_ts, w in zip(st.tolist(), ts.tolist(), wb):
                assert g_st == int(w.status) and g_ts == w.timestamp, (
                    g_st, w.status.name)
        names3 = [w.status.name for w in want[2]]
        assert names3 == ["imported_event_timestamp_must_not_regress",
                          "created"]

    def test_pipelined_submit_refuses_imported(self):
        from tigerbeetle_tpu.ops.batch import transfers_to_arrays

        led, _ = _pair()
        led._wt = False
        b = [_imp(12000 + i, 1, 2, 1, 30000 + i) for i in range(4)]
        evs = [transfers_to_arrays(b),
               transfers_to_arrays(
                   [Transfer(id=12100, debit_account_id=1,
                             credit_account_id=2, amount=1, ledger=1,
                             code=1)])]
        assert led.submit_window(evs, [10**9, 10**9 + 500]) is None


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_imported_fuzz_differential(seed):
    """Randomized imported batches (edge-biased timestamps around the
    running maxima, mixed flags, duplicates) — bit-exact vs oracle."""
    rng = np.random.default_rng(seed)
    led, ora = _pair()
    ts = 10**9
    base_uts = 50_000
    nid = 20_000
    for _ in range(6):
        n = int(rng.integers(4, 48))
        batch_imported = bool(rng.integers(0, 2))
        xs = []
        for i in range(n):
            imp = batch_imported if rng.random() > 0.1 \
                else not batch_imported
            dr = int(rng.integers(1, 9))
            cr = int(rng.integers(1, 9))
            if dr == cr:
                cr = dr % 8 + 1
            flags = IMP if imp else 0
            if rng.random() < 0.15:
                flags |= PEND
            # Edge-biased user timestamps: hover around the running max
            # so regress boundaries are exercised densely.
            uts = base_uts + int(rng.integers(-30, 30))
            base_uts += int(rng.integers(0, 12))
            xs.append(_imp(nid, dr, cr, int(rng.integers(1, 100)),
                           uts, flags=flags,
                           timeout=int(rng.integers(0, 2))
                           if (flags & PEND and not imp) else 0))
            nid += 1
        _diff(led, ora, xs, ts)
        ts += 10**6


AIMP = 1 << 7  # AccountFlags.imported


class TestImportedAccounts:
    """Imported account creation on the device fast path (reference
    :3648-3667): regress vs acct_key_max + collision with TRANSFER
    timestamps, maxima chain in-batch, user timestamps stored."""

    def test_monotone_imported_accounts(self):
        from tigerbeetle_tpu.types import AccountFlags

        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        ora = StateMachineOracle()
        accs = [Account(id=100 + i, ledger=1, code=1,
                        flags=int(AccountFlags.imported),
                        timestamp=5000 + i * 10) for i in range(16)]
        g = led.create_accounts(accs, 10**9)
        w = ora.create_accounts(accs, 10**9)
        assert [(x.status.name, x.timestamp) for x in g] == \
            [(x.status.name, x.timestamp) for x in w]
        assert all(x.status.name == "created" for x in w)
        assert led.fallbacks == 0
        got = led.lookup_accounts([100, 115])
        assert got[0].timestamp == 5000 and got[1].timestamp == 5150

    def test_maxima_chain_and_wrapper_rules(self):
        from tigerbeetle_tpu.types import AccountFlags

        imp = int(AccountFlags.imported)
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        ora = StateMachineOracle()
        accs = [
            Account(id=200, ledger=1, code=1, flags=imp, timestamp=7000),
            Account(id=201, ledger=1, code=1, flags=imp, timestamp=6500),
            Account(id=202, ledger=1, code=1, flags=imp, timestamp=7200),
            Account(id=203, ledger=1, code=1),            # expected
            Account(id=204, ledger=1, code=1, flags=imp, timestamp=0),
            Account(id=205, ledger=1, code=1, flags=imp,
                    timestamp=10**9 + 5),                  # not_advance
        ]
        g = led.create_accounts(accs, 10**9)
        w = ora.create_accounts(accs, 10**9)
        assert [(x.status.name, x.timestamp) for x in g] == \
            [(x.status.name, x.timestamp) for x in w]
        assert [x.status.name for x in w] == [
            "created", "imported_event_timestamp_must_not_regress",
            "created", "imported_event_expected",
            "imported_event_timestamp_out_of_range",
            "imported_event_timestamp_must_not_advance"]

    def test_collision_with_transfer_timestamp(self):
        from tigerbeetle_tpu.types import AccountFlags

        imp = int(AccountFlags.imported)
        led, ora = _pair()
        # One imported transfer at uts 40000 (device path).
        _diff(led, ora, [_imp(30000, 1, 2, 1, 40000)], 10**9)
        accs = [Account(id=300, ledger=1, code=1, flags=imp,
                        timestamp=40000),   # collides with the transfer
                Account(id=301, ledger=1, code=1, flags=imp,
                        timestamp=40001)]
        g = led.create_accounts(accs, 2 * 10**9)
        w = ora.create_accounts(accs, 2 * 10**9)
        assert [(x.status.name, x.timestamp) for x in g] == \
            [(x.status.name, x.timestamp) for x in w]
        assert [x.status.name for x in w] == [
            "imported_event_timestamp_must_not_regress", "created"]

    def test_postdate_uses_imported_account_ts(self):
        """A later NON-imported transfer on imported accounts: the
        postdate reference is the stored (user) account timestamp."""
        from tigerbeetle_tpu.types import AccountFlags

        imp = int(AccountFlags.imported)
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        ora = StateMachineOracle()
        accs = [Account(id=1, ledger=1, code=1, flags=imp, timestamp=5000),
                Account(id=2, ledger=1, code=1, flags=imp, timestamp=5001)]
        led.create_accounts(accs, 10**9)
        ora.create_accounts(accs, 10**9)
        # An imported transfer BELOW the accounts' user ts postdate-fails;
        # above, it creates.
        xs = [_imp(31000, 1, 2, 1, 4999), _imp(31001, 1, 2, 1, 6000)]
        names = _diff(led, ora, xs, 2 * 10**9)
        assert names == [
            "imported_event_timestamp_must_postdate_debit_account",
            "created"]
