"""Extra-check mode (reference: constants.verify compiled into fuzz/VOPR
builds, src/fuzz_tests.zig:11-16, docs/internals/vopr.md:48-57): expensive
cross-structure invariants that stay off on the serving path and must
actually FIRE on seeded divergence when enabled.
"""

import dataclasses

import pytest

from tigerbeetle_tpu import constants
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Account, Transfer


@pytest.fixture
def verify_on():
    was = constants.VERIFY
    constants.set_verify(True)
    yield
    constants.set_verify(was)


def _device_sm(n=50):
    sm = StateMachine(engine="device", a_cap=1 << 12, t_cap=1 << 14)
    sm.create_accounts([Account(id=i, ledger=1, code=1)
                        for i in range(1, 11)], 100)
    evs = [Transfer(id=1000 + i, debit_account_id=1 + i % 9,
                    credit_account_id=2 + i % 8, amount=1, ledger=1, code=1)
           for i in range(n)]
    for e in evs:
        if e.debit_account_id == e.credit_account_id:
            e.credit_account_id = e.debit_account_id % 10 + 1
    sm.create_transfers(evs, 10_000)
    return sm


def test_mirror_spot_audit_passes_clean(verify_on):
    sm = _device_sm()
    _ = sm.state.transfers  # drain triggers the device/mirror spot audit
    assert sm.led.fallbacks == 0


def test_mirror_spot_audit_fires_on_divergence(verify_on):
    sm = _device_sm()
    _ = sm.state.transfers  # drain cleanly first
    # Seed a divergence: corrupt the OLDEST mirror transfer (a row no
    # later batch rewrites), then run another batch and drain — the
    # stable-anchor audit must catch it.
    tid = next(iter(sm.state.transfers))
    sm.state.transfers[tid] = dataclasses.replace(
        sm.state.transfers[tid], amount=999_999)
    evs = [Transfer(id=5000 + i, debit_account_id=1, credit_account_id=2,
                    amount=1, ledger=1, code=1) for i in range(4)]
    sm.create_transfers(evs, 20_000)
    with pytest.raises(AssertionError, match="device/mirror divergence"):
        _ = sm.state.transfers


def test_cache_tree_coherence_fires_on_poisoned_cache(verify_on):
    import numpy as np

    from tests.test_lsm_serving import _mk_attached

    attached, _detached, _durable = _mk_attached()
    ids = list(range(1, 20))
    attached.lookup_accounts(ids)  # fill cache (checks pass clean)
    # Poison one STILL-CACHED object (the cache is tiny and evicts);
    # the next verified lookup must catch it.
    victim = next(i for i in ids
                  if attached._acct_cache.get(i) is not None)
    obj = attached._acct_cache.get(victim)
    attached._acct_cache.put(victim, dataclasses.replace(obj, code=99))
    with pytest.raises(AssertionError, match="cache/tree divergence"):
        attached.lookup_accounts([victim])


def test_verify_off_skips_checks():
    constants.set_verify(False)
    sm = _device_sm()
    sm.state.accounts[1] = dataclasses.replace(
        sm.state.accounts[1], debits_posted=12345)
    evs = [Transfer(id=7000, debit_account_id=2, credit_account_id=3,
                    amount=1, ledger=1, code=1)]
    sm.create_transfers(evs, 30_000)
    _ = sm.state.transfers  # no audit, no raise
