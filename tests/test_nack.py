"""NACK / protocol-aware recovery scenarios.

VERDICT r1 #6 — reference: quorum_nack_prepare (src/vsr/replica.zig:254,
:825), docs/ARCHITECTURE.md:540-563, and the scripted-scenario style of
src/vsr/replica_test.zig. Message-level tests drive a single sans-io
replica through exact fault sequences; cluster tests orchestrate the
crash timing the protocol exists for: a replica advertises a prepare in
its do_view_change, then dies before serving the body.
"""

import numpy as np
import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.cluster import MS, Cluster
from tigerbeetle_tpu.types import Account, Operation, Transfer
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.replica import Replica, ReplicaOptions
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

CLUSTER = 0xABCD01


class _CaptureBus:
    def __init__(self):
        self.sent: list[tuple[int, Message]] = []

    def send_to_replica(self, dst: int, msg: Message) -> None:
        self.sent.append((dst, msg))

    def send_to_client(self, client_id: int, msg: Message) -> None:
        pass

    def of(self, command: Command) -> list[tuple[int, Message]]:
        return [(d, m) for d, m in self.sent if m.header.command == command]


class _FakeTime:
    def __init__(self):
        self.now = 1_700_000_000 * 10**9

    def monotonic(self) -> int:
        return self.now

    def realtime(self) -> int:
        return self.now

    def advance(self, dt: int) -> None:
        self.now += dt


def _mk_replica(replica_id: int, replica_count: int = 6):
    storage = MemoryStorage(TEST_LAYOUT)
    Replica.format(storage, cluster=CLUSTER, replica_id=replica_id,
                   replica_count=replica_count)
    bus = _CaptureBus()
    time = _FakeTime()
    r = Replica(cluster=CLUSTER, replica_id=replica_id,
                replica_count=replica_count, storage=storage, bus=bus,
                time=time,
                state_machine_factory=lambda: StateMachine(engine="oracle"))
    r.open()
    return r, bus, time


def _prepare_msg(op: int, *, view: int = 0, parent: int = 0) -> Message:
    body = b"x" * 16
    header = Header(command=Command.prepare, cluster=CLUSTER, view=view,
                    op=op, operation=int(Operation.pulse), parent=parent)
    return Message(header.finalize(body), body=body)


def _dvc(replica: int, view: int, op: int, commit: int, log_view: int,
         suffix: list[Header]) -> Message:
    body = b"".join(h.pack() for h in suffix)
    header = Header(command=Command.do_view_change, cluster=CLUSTER,
                    replica=replica, view=view, op=op, commit=commit,
                    context=log_view)
    return Message(header.finalize(body), body=body)


def _svc(replica: int, view: int) -> Message:
    header = Header(command=Command.start_view_change, cluster=CLUSTER,
                    replica=replica, view=view)
    return Message(header.finalize())


def _nack(replica: int, view: int, op: int, wanted: int) -> Message:
    header = Header(command=Command.nack_prepare, cluster=CLUSTER,
                    replica=replica, view=view, op=op, parent=wanted)
    return Message(header.finalize())


def _enter_pending_view(r, bus, *, lost_op: int, committed_below: int):
    """Drive replica 2 (of 6) into pending view 2 whose canonical log ends
    with `lost_op`, advertised by peer 3's DVC but journaled nowhere
    reachable. Returns the canonical checksum of the lost op."""
    # Prepares below lost_op exist everywhere (feed them to our journal).
    parent = 0
    headers = []
    for op in range(1, lost_op):
        m = _prepare_msg(op, parent=parent)
        r.journal.append(m)
        headers.append(m.header)
        parent = m.header.checksum
    r.op = lost_op - 1
    r.commit_min = r.commit_max = committed_below
    lost = _prepare_msg(lost_op, parent=parent)

    # View change to view 2 (primary index 2 == r.replica_id).
    r.on_message(_svc(3, 2))
    r.on_message(_svc(4, 2))
    r.on_message(_svc(5, 2))
    assert r.status == "view_change" and r.view == 2
    # DVCs: peer 3 advertises the lost op (it held the prepare when it
    # sent the DVC); peers 4 and 5 do not.
    r.on_message(_dvc(3, 2, lost_op, committed_below, 0,
                      headers + [lost.header]))
    r.on_message(_dvc(4, 2, lost_op - 1, committed_below, 0, headers))
    r.on_message(_dvc(5, 2, lost_op - 1, committed_below, 0, headers))
    assert r._pending_view == 2, "primary must be repairing, not live"
    assert r.op == lost_op
    assert r.canonical[lost_op].checksum == lost.header.checksum
    return lost.header.checksum


class TestNackScripted:
    def test_nack_quorum_truncates_lost_uncommitted_suffix(self):
        """The headline scenario: op 5 advertised in a DVC, body
        unobtainable, 3 peer nacks + the primary's own clean slot = the
        nack quorum (4 of 6) -> truncate, view starts."""
        r, bus, _ = _mk_replica(2)
        wanted = _enter_pending_view(r, bus, lost_op=5, committed_below=3)
        r.on_message(_nack(3, 2, 5, wanted))
        assert r._pending_view == 2  # 1 peer + self = 2 < 4
        r.on_message(_nack(4, 2, 5, wanted))
        assert r._pending_view == 2  # 3 < 4
        r.on_message(_nack(5, 2, 5, wanted))
        # 3 peers + self-nack (own slot empty and clean) = 4 = quorum.
        assert r._pending_view is None and r.status == "normal"
        assert r.op == 4 and 5 not in r.canonical
        assert bus.of(Command.start_view), "view must have started"

    def test_committed_op_is_never_truncated(self):
        """Nacks for an op at or below commit_max are ignored: the
        view-change quorum proved it committed."""
        r, bus, _ = _mk_replica(2)
        wanted = _enter_pending_view(r, bus, lost_op=5, committed_below=3)
        r.commit_max = 5  # a (late) DVC proved op 5 committed
        for peer in (3, 4, 5):
            r.on_message(_nack(peer, 2, 5, wanted))
        assert r._pending_view == 2, "must keep repairing, not truncate"
        assert r.op == 5 and 5 in r.canonical

    def test_stale_checksum_nacks_do_not_count(self):
        r, bus, _ = _mk_replica(2)
        _enter_pending_view(r, bus, lost_op=5, committed_below=3)
        for peer in (3, 4, 5):
            r.on_message(_nack(peer, 2, 5, wanted=0xDEAD))
        assert r._pending_view == 2 and r.op == 5

    def test_standby_nacks_do_not_count(self):
        r, bus, _ = _mk_replica(2)
        wanted = _enter_pending_view(r, bus, lost_op=5, committed_below=3)
        for peer in (6, 7, 8):  # standby ids >= replica_count
            r.on_message(_nack(peer, 2, 5, wanted))
        assert r._pending_view == 2 and r.op == 5


class TestNackResponder:
    def test_clean_empty_slot_nacks(self):
        r, bus, _ = _mk_replica(1)
        r.commit_min = 2
        req = Header(command=Command.request_prepare, cluster=CLUSTER,
                     replica=2, view=0, op=7, parent=0xBEEF)
        r.on_message(Message(req.finalize()))
        nacks = bus.of(Command.nack_prepare)
        assert len(nacks) == 1
        dst, m = nacks[0]
        assert dst == 2 and m.header.op == 7 and m.header.parent == 0xBEEF

    def test_faulty_slot_abstains(self):
        """A torn slot may BE the prepare in question: no nack."""
        r, bus, _ = _mk_replica(1)
        r.commit_min = 2
        r.journal.faulty.add(r.journal.slot_for_op(7))
        req = Header(command=Command.request_prepare, cluster=CLUSTER,
                     replica=2, view=0, op=7)
        r.on_message(Message(req.finalize()))
        assert not bus.of(Command.nack_prepare)
        assert not bus.of(Command.prepare)

    def test_committed_op_not_nacked(self):
        """We executed the op: it is committed, never nackable (the
        requester recovers via repair or state sync instead)."""
        r, bus, _ = _mk_replica(1)
        r.commit_min = 9
        req = Header(command=Command.request_prepare, cluster=CLUSTER,
                     replica=2, view=0, op=7)
        r.on_message(Message(req.finalize()))
        assert not bus.of(Command.nack_prepare)

    def test_different_checksum_holder_serves_and_nacks(self):
        """Holding a different prepare for the op proves we never prepared
        the canonical one: serve what we have AND nack the wanted one."""
        r, bus, _ = _mk_replica(1)
        held = _prepare_msg(7, view=0)
        r.journal.append(held)
        r.op = 7
        req = Header(command=Command.request_prepare, cluster=CLUSTER,
                     replica=2, view=0, op=7, parent=0xF00D)
        r.on_message(Message(req.finalize()))
        served = bus.of(Command.prepare)
        nacks = bus.of(Command.nack_prepare)
        assert len(served) == 1
        assert served[0][1].header.checksum == held.header.checksum
        assert len(nacks) == 1 and nacks[0][1].header.parent == 0xF00D
        # Without a wanted checksum there is nothing to nack.
        bus.sent.clear()
        req2 = Header(command=Command.request_prepare, cluster=CLUSTER,
                      replica=2, view=0, op=7, parent=0)
        r.on_message(Message(req2.finalize()))
        assert bus.of(Command.prepare) and not bus.of(Command.nack_prepare)


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr,
                 amount=amt, ledger=1, code=1).pack()
        for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


class TestNackCluster:
    def test_advertised_then_lost_prepare_is_truncated(self):
        """Full-cluster liveness: P0 prepares an op that reaches only P1,
        then crashes; P1's copy is TORN (storage corruption), so P1
        advertises the op's header in its do_view_change but cannot serve
        the body, and must itself abstain from nacking (it prepared it).
        The four clean peers' nacks prove the op uncommitted: the new
        primary truncates it and the cluster keeps serving. Without NACK
        this view change would wedge forever."""
        cluster = Cluster(seed=21, replica_count=6)
        client = cluster.client(900)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        ok = cluster.run(4000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        base_op = cluster.replicas[2].op

        # P0 talks only to P1: the next prepare reaches P1 alone and can
        # never reach its replication quorum of 3.
        for peer in (2, 3, 4, 5):
            cluster.cut_links.add(frozenset((0, peer)))
        client.request(Operation.create_transfers,
                       _transfers_body([(100, 1, 2, 7)]))
        lost_op = base_op + 1
        assert cluster.run(300, until=lambda: cluster.replicas[1].op
                           >= lost_op), cluster.debug_status()
        held = cluster.replicas[1].journal.read_prepare(lost_op)
        assert held is not None
        assert cluster.replicas[2].op < lost_op

        # Tear P1's prepare body on disk (the header ring stays valid, so
        # P1 still advertises the op but can neither serve nor nack it).
        storage = cluster.storages[1]
        psm = storage.layout.message_size_max
        slot = lost_op % storage.layout.slot_count
        raw = storage.read("wal_prepares", slot * psm + 300, 8)
        storage.write("wal_prepares", slot * psm + 300,
                      bytes(b ^ 0xFF for b in raw))
        assert cluster.replicas[1].journal.read_prepare(lost_op) is None

        cluster.crash(0)
        cluster.heal()

        def truncated_and_live():
            live = [r for i, r in enumerate(cluster.replicas)
                    if i not in cluster.crashed]
            return all(r.status == "normal" and r.view >= 1
                       and r.op < lost_op for r in live)

        assert cluster.run(60000, until=truncated_and_live), \
            cluster.debug_status()
        # The cluster keeps serving (liveness regained), the op is gone.
        client2 = cluster.client(901)
        client2.request(Operation.create_transfers,
                        _transfers_body([(200, 2, 1, 3)]))
        assert cluster.run(20000, until=lambda: client2.idle), \
            cluster.debug_status()
        cluster.settle()
        # The truncated PREPARE is gone; the client's still-pending request
        # may legitimately have been retried and re-committed as a NEW op
        # in the new view (exactly-once is per request, not per attempt).
        live = [r for i, r in enumerate(cluster.replicas)
                if i not in cluster.crashed]
        states = [(dict(r.state_machine.state.accounts),
                   dict(r.state_machine.state.transfers)) for r in live]
        for st in states[1:]:
            assert st == states[0], "live replicas must converge"
        accounts, transfers = states[0]
        assert accounts[1].credits_posted == 3
        if 100 in transfers:
            # Re-committed via retry: must postdate the truncation (a new
            # timestamp in the new view), not the torn original.
            assert transfers[100].timestamp > transfers[200].timestamp - \
                10**10
            assert accounts[1].debits_posted == 7
        else:
            assert accounts[1].debits_posted == 0

    def test_possibly_committed_op_repaired_not_truncated(self):
        """Same shape, but the holder stays alive: the new primary must
        REPAIR the advertised op from it (and re-replicate), never
        truncate it."""
        cluster = Cluster(seed=22, replica_count=6)
        client = cluster.client(910)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        ok = cluster.run(4000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        for peer in (2, 3, 4, 5):
            cluster.cut_links.add(frozenset((0, peer)))
        base_op = cluster.replicas[2].op
        client.request(Operation.create_transfers,
                       _transfers_body([(300, 1, 2, 9)]))
        lost_op = base_op + 1
        assert cluster.run(300, until=lambda: cluster.replicas[1].op
                           >= lost_op), cluster.debug_status()
        assert cluster.replicas[2].op < lost_op
        cluster.crash(0)
        cluster.heal()
        # P1 alive and connected: whether it wins the election or serves
        # repair, the op must survive and commit in the new view.
        cluster.settle()

        def op_committed():
            return all(r.commit_min >= lost_op
                       for i, r in enumerate(cluster.replicas)
                       if i not in cluster.crashed)

        assert cluster.run(40000, until=op_committed), cluster.debug_status()
        for i, r in enumerate(cluster.replicas):
            if i not in cluster.crashed:
                assert 300 in r.state_machine.state.transfers
                assert r.state_machine.state.accounts[2].credits_posted == 9

    def test_rejoining_stale_suffix_truncates(self):
        """A restarted replica holding an uncommitted suffix from an old
        view truncates it on learning the new canonical log."""
        cluster = Cluster(seed=23, replica_count=3)
        client = cluster.client(920)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        ok = cluster.run(4000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        # P0 (primary) prepares an op nobody receives.
        for peer in (1, 2):
            cluster.cut_links.add(frozenset((0, peer)))
        client.request(Operation.create_transfers,
                       _transfers_body([(400, 1, 2, 5)]))
        cluster.run(60)
        stale_op = cluster.replicas[0].op
        assert cluster.replicas[1].op < stale_op
        cluster.crash(0)
        cluster.heal()
        cluster.settle()
        # The survivors elected a new view and moved on; commit new work.
        client2 = cluster.client(921)
        client2.request(Operation.create_transfers,
                        _transfers_body([(401, 2, 1, 4)]))
        assert cluster.run(20000, until=lambda: client2.idle), \
            cluster.debug_status()
        cluster.restart(0)
        cluster.settle()
        r0 = cluster.replicas[0]
        assert 401 in r0.state_machine.state.transfers
        # The stale PREPARE was truncated; the client's pending request may
        # have been retried into the new view as a fresh op. If so, every
        # replica agrees on it (it went through consensus, not through
        # P0's stale journal).
        if 400 in r0.state_machine.state.transfers:
            t = r0.state_machine.state.transfers[400]
            for r in cluster.replicas[1:]:
                assert r.state_machine.state.transfers[400] == t
        cluster.check_convergence()
