"""The device serving engine: StateMachine(engine="device").

VERDICT r1 #2: the consensus serving path and the benched path must be the
same code — creates execute on the DeviceLedger via the vectorized fast
kernels (ops/fast_kernels.py), with a write-through host mirror for
queries and durability. These tests pin (a) bit-exact parity of the
serving path against the oracle across fast batches, hard-regime
fallbacks, and probe recovery; (b) the mirror staying value-identical to
the device ground truth; (c) restart recovery re-attaching the device
state; (d) a full consensus cluster running on the device engine.

reference: src/lsm/groove.zig:885 (object cache get),
src/state_machine.zig:2564 (commit), -Dvopr-state-machine differential
switch (src/vopr.zig:25-29).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags,
    Operation,
    QueryFilter,
    Transfer,
    TransferFlags,
)

PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
VOID = int(TransferFlags.void_pending_transfer)
LINKED = int(TransferFlags.linked)


def _mk_pair(a_cap=1 << 10, t_cap=1 << 12):
    dev = StateMachine(engine="device", a_cap=a_cap, t_cap=t_cap)
    orc = StateMachine(engine="oracle")
    accts = [Account(id=i, ledger=1, code=1) for i in range(1, 101)]
    for sm in (dev, orc):
        res = sm.create_accounts(accts, 120)
        assert all(r.status.name == "created" for r in res)
    return dev, orc


def _assert_state_equal(s1, s2):
    assert s1.accounts == s2.accounts
    assert s1.transfers == s2.transfers
    assert s1.pending_status == s2.pending_status
    assert s1.expiry == s2.expiry
    assert s1.orphaned == s2.orphaned
    assert s1.account_events == s2.account_events
    assert s1.commit_timestamp == s2.commit_timestamp
    assert s1.pulse_next_timestamp == s2.pulse_next_timestamp
    assert s1.accounts_key_max == s2.accounts_key_max
    assert s1.transfers_key_max == s2.transfers_key_max


def _batch(rng, nid, n, hard_mix=False):
    evs = []
    nid_start = nid  # post/void target only pre-batch pendings (E2)
    pids_used = set()  # E2 also bans duplicate pending_ids per batch
    for i in range(n):
        roll = rng.random()
        tid = nid
        nid += 1
        if roll < 0.6:
            evs.append(Transfer(
                id=tid, debit_account_id=int(rng.integers(0, 105)),
                credit_account_id=int(rng.integers(1, 105)),
                amount=int(rng.integers(0, 500)), ledger=1,
                code=int(rng.integers(0, 2)),
                flags=LINKED if i % 11 == 0 else 0))
        elif roll < 0.8 and hard_mix:
            # Same-id duplicate pair: a same-kind id collision (E2) is
            # a hard fallback — the exact host path must serve it.
            # (Balancing, the previous trigger here, now runs natively
            # on the balancing fixpoint tier.)
            dup = Transfer(
                id=tid, debit_account_id=int(rng.integers(1, 101)),
                credit_account_id=1 + int(rng.integers(1, 100)),
                amount=int(rng.integers(1, 50)), ledger=1, code=1)
            evs.append(dup)
            evs.append(Transfer(
                id=tid, debit_account_id=dup.debit_account_id,
                credit_account_id=dup.credit_account_id,
                amount=dup.amount, ledger=1, code=1))
        elif roll < 0.9:
            evs.append(Transfer(
                id=tid, debit_account_id=int(rng.integers(1, 101)),
                credit_account_id=1 + int(rng.integers(1, 100)),
                amount=int(rng.integers(1, 50)), ledger=1, code=1,
                flags=PEND))
        else:
            f = POST if rng.random() < 0.5 else VOID
            pid = (int(rng.integers(10**6, nid_start))
                   if nid_start > 10**6  # pre-batch pendings only (E2)
                   else int(rng.integers(10**5, 10**6)))  # not-found probe
            if pid in pids_used:  # E2 bans duplicate pending_ids
                evs.append(Transfer(
                    id=tid, debit_account_id=int(rng.integers(1, 101)),
                    credit_account_id=1 + int(rng.integers(1, 100)),
                    amount=1, ledger=1, code=1))
                continue
            pids_used.add(pid)
            evs.append(Transfer(
                id=tid, pending_id=pid,
                amount=(2**128 - 1) if f == POST else 0, flags=f))
    for e in evs:
        if (e.flags & (POST | VOID)) == 0 \
                and e.debit_account_id == e.credit_account_id:
            e.credit_account_id = e.debit_account_id % 100 + 1
    if evs[-1].flags & LINKED:
        evs[-1].flags &= ~LINKED
    return evs, nid


class TestDeviceEngineParity:
    def test_fast_path_dominates_plain_workload(self):
        dev, orc = _mk_pair()
        rng = np.random.default_rng(31)
        ts, nid = 10**9, 10**6
        for b in range(4):
            evs, nid = _batch(rng, nid, 300)
            ts += 400
            got = dev.create_transfers(evs, ts)
            want = orc.create_transfers(evs, ts)
            assert [(r.timestamp, r.status) for r in got] == \
                   [(r.timestamp, r.status) for r in want], b
        assert dev.led.fast_batches >= 4  # accounts batch + transfer batches
        _assert_state_equal(dev.state, orc.state)

    def test_hard_regime_and_probe_recovery(self):
        """Hard batches (E2: same-kind duplicate ids) push
        the ledger into the mirror regime; after MIRROR_PROBE_INTERVAL
        easy batches the probe returns it to the fast path — with the
        write-through mirror exact throughout."""
        dev, orc = _mk_pair()
        rng = np.random.default_rng(32)
        ts, nid = 10**9, 10**6
        # 2 hard batches, then 12 easy ones (probe interval is 8).
        for b in range(14):
            evs, nid = _batch(rng, nid, 200, hard_mix=(b < 2))
            ts += 300
            got = dev.create_transfers(evs, ts)
            want = orc.create_transfers(evs, ts)
            assert [(r.timestamp, r.status) for r in got] == \
                   [(r.timestamp, r.status) for r in want], b
        assert dev.led.fallbacks > 0
        assert not dev.led._hard_regime  # probe recovered
        _assert_state_equal(dev.state, orc.state)
        # Device ground truth == mirror.
        host = dev.led.to_host()
        assert host.accounts == dev.state.accounts
        assert host.transfers == dev.state.transfers
        assert host.account_events == dev.state.account_events

    def test_expiry_pulse(self):
        dev, orc = _mk_pair()
        ts = 10**9
        evs = [Transfer(id=10**6 + i, debit_account_id=1 + i,
                        credit_account_id=2 + i, amount=10, ledger=1, code=1,
                        flags=PEND, timeout=1) for i in range(5)]
        ts += 10
        for sm in (dev, orc):
            res = sm.create_transfers(evs, ts)
            assert all(r.status.name == "created" for r in res)
        later = ts + 5 * 10**9
        assert dev.pulse_needed(later) and orc.pulse_needed(later)
        body_ts = later
        dev.commit(Operation.pulse, b"", body_ts)
        orc.commit(Operation.pulse, b"", body_ts)
        _assert_state_equal(dev.state, orc.state)
        assert all(s.name == "expired"
                   for s in dev.state.pending_status.values())

    def test_queries_served_after_fast_batches(self):
        dev, orc = _mk_pair()
        ts = 10**9
        evs = [Transfer(id=10**6 + i, debit_account_id=7,
                        credit_account_id=8 + (i % 3), amount=5 + i,
                        ledger=1, code=1, user_data_64=i % 2)
               for i in range(50)]
        ts += 60
        for sm in (dev, orc):
            sm.create_transfers(evs, ts)
        f = AccountFilter(
            account_id=7,
            flags=int(AccountFilterFlags.debits | AccountFilterFlags.credits),
            limit=100)
        assert [t.id for t in dev.get_account_transfers(f)] == \
               [t.id for t in orc.get_account_transfers(f)]
        q = QueryFilter(user_data_64=1, limit=50)
        assert [t.id for t in dev.query_transfers(q)] == \
               [t.id for t in orc.query_transfers(q)]

    def test_commit_wire_path_uses_device(self):
        """The replica-facing commit() boundary routes through the ledger."""
        dev = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 12)
        body = multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128)
        dev.commit(Operation.create_accounts, body, 100)
        body = multi_batch.encode(
            [Transfer(id=9, debit_account_id=1, credit_account_id=2,
                      amount=50, ledger=1, code=1).pack()], 128)
        dev.commit(Operation.create_transfers, body, 200)
        assert dev.led.fast_batches == 2 and dev.led.fallbacks == 0
        assert dev.state.accounts[2].credits_posted == 50


class TestDirtyChannels:
    def test_fast_orphans_not_repushed_by_hard_batch(self):
        """Fast-batch transient failures insert orphan ids on device; the
        next hard batch's push must not re-insert them (ht_insert claims
        empty slots, so a re-insert would be a permanent duplicate). The
        durable channel (.dirty) must still carry them for the flusher."""
        dev, orc = _mk_pair()
        ts = 10**9
        # Fast batch with transient failures (missing debit accounts).
        evs = [Transfer(id=10**6 + i, debit_account_id=500 + i,
                        credit_account_id=1, amount=1, ledger=1, code=1)
               for i in range(10)]
        ts += 20
        got = dev.create_transfers(evs, ts)
        orc.create_transfers(evs, ts)
        assert all(r.status.name == "debit_account_not_found" for r in got)
        assert len(dev.state.orphaned) == 10
        # Device-push channel drained; durable channel retained.
        assert not dev.state.orphaned.dirty_dev
        assert dev.state.orphaned.dirty == set(dev.state.orphaned)
        # Hard batch (E2: same-kind duplicate id) -> mirror apply +
        # push; must not re-insert the fast-path orphans.
        hard = [
            Transfer(id=10**6 + 100, debit_account_id=1,
                     credit_account_id=2, amount=1, ledger=1, code=1),
            Transfer(id=10**6 + 100, debit_account_id=1,
                     credit_account_id=2, amount=1, ledger=1, code=1),
        ]
        ts += 20
        got = dev.create_transfers(hard, ts)
        want = orc.create_transfers(hard, ts)
        assert [(r.timestamp, r.status) for r in got] == \
               [(r.timestamp, r.status) for r in want]
        assert dev.led.fallbacks == 1
        # Retrying a poisoned id still reports id_already_failed via the
        # device path (orphan_ht consistent, no duplicate entries).
        ts += 20
        retry = [Transfer(id=10**6, debit_account_id=1, credit_account_id=2,
                          amount=1, ledger=1, code=1)]
        got = dev.create_transfers(retry, ts)
        want = orc.create_transfers(retry, ts)
        assert got[0].status.name == "id_already_failed"
        assert [(r.timestamp, r.status) for r in got] == \
               [(r.timestamp, r.status) for r in want]
        # Ground truth: device rebuild matches the mirror exactly.
        host = dev.led.to_host()
        assert host.orphaned == dev.state.orphaned


class TestDeviceEngineRestart:
    def test_state_reattach_rebuilds_device(self):
        """Assigning .state (restart recovery / state sync) rebuilds the
        device tables from the restored host state."""
        dev, orc = _mk_pair()
        rng = np.random.default_rng(33)
        ts, nid = 10**9, 10**6
        evs, nid = _batch(rng, nid, 100)
        ts += 150
        dev.create_transfers(evs, ts)
        orc.create_transfers(evs, ts)
        # "Restart": move a copy of the oracle state into a fresh device
        # engine (replica recovery materializes a fresh oracle from the
        # forest, so no aliasing there).
        import copy

        dev2 = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 12)
        dev2.state = copy.deepcopy(orc.state)
        evs2, nid = _batch(rng, nid, 100)
        ts += 150
        got = dev2.create_transfers(evs2, ts)
        want = orc.create_transfers(evs2, ts)
        assert [(r.timestamp, r.status) for r in got] == \
               [(r.timestamp, r.status) for r in want]
        _assert_state_equal(dev2.state, orc.state)


class TestDeviceEngineCluster:
    def test_cluster_consensus_on_device_engine(self):
        """A 3-replica cluster serving through the device engine: normal
        path + crash/restart recovery (the round-1 gap: the database
        never ran the benched engine)."""
        from tigerbeetle_tpu.testing.cluster import Cluster

        cluster = Cluster(
            seed=7, replica_count=3,
            state_machine_factory=lambda: StateMachine(
                engine="device", a_cap=1 << 10, t_cap=1 << 12))
        client = cluster.client(55)
        ops = [
            (Operation.create_accounts, multi_batch.encode(
                [b"".join(Account(id=i, ledger=1, code=1).pack()
                          for i in (1, 2, 3))], 128)),
            (Operation.create_transfers, multi_batch.encode(
                [b"".join(Transfer(id=100 + k, debit_account_id=1,
                                   credit_account_id=2, amount=k + 1,
                                   ledger=1, code=1).pack()
                          for k in range(10))], 128)),
        ]
        for op, body in ops:
            client.request(op, body)
            ok = cluster.run(3000, until=lambda: client.idle)
            assert ok, cluster.debug_status()
        cluster.settle()
        for r in cluster.replicas:
            assert r.state_machine.engine == "device"
            assert r.state_machine.led.fast_batches >= 2
            a2 = r.state_machine.state.accounts[2]
            assert a2.credits_posted == sum(range(1, 11))
        # Crash + restart one backup: recovery must reattach the device.
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        client.request(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=500, debit_account_id=2, credit_account_id=3,
                      amount=5, ledger=1, code=1).pack()], 128))
        ok = cluster.run(5000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster.restart(victim)
        cluster.settle()
        r = cluster.replicas[victim]
        assert r.state_machine.state.accounts[3].credits_posted == 5
        # And the restarted replica keeps serving on the fast path.
        client.request(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=501, debit_account_id=3, credit_account_id=1,
                      amount=2, ledger=1, code=1).pack()], 128))
        ok = cluster.run(5000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        for r in cluster.replicas:
            assert r.state_machine.state.accounts[1].credits_posted == 2
