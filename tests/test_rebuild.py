"""Rebuild-from-cluster (ISSUE 4): blank-replica recovery over state
sync. A replica whose data file was lost or zeroed solicits a peer
checkpoint, installs it staged (superblock sync_op record), repairs the
WAL suffix through normal VSR repair, certifies the grid with a full
scrub tour, and only then votes again. Deterministic in-process
coverage; the real-process acceptance scenario lives in test_vortex.py.
"""

import pytest

from tests.test_vsr import (
    _create_accounts_body,
    _create_transfers_body,
    _drive,
)
from tigerbeetle_tpu.ops.state_epoch import combine, oracle_state_digest
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import Command
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage
from tigerbeetle_tpu.vsr.superblock import SuperBlock


def _setup(seed, n_transfers):
    cluster = Cluster(seed=seed, replica_count=3)
    client = cluster.client(60 + seed)
    _drive(cluster, client, [
        (Operation.create_accounts, _create_accounts_body([1, 2]))])
    for k in range(n_transfers):
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(100 + k, 1, 2, 1)]))])
    cluster.settle()
    return cluster, client


def _digests(cluster):
    return [combine(oracle_state_digest(r.state_machine.state, 1 << 8))
            for i, r in enumerate(cluster.replicas)
            if i not in cluster.crashed]


class TestRebuildFromCluster:
    def test_blank_rebuild_state_syncs_and_matches(self):
        """Past a WAL wrap (>32 ops) the rebuild MUST take the state-sync
        path; the rebuilt replica's state-epoch digest is bit-identical
        to its peers' and the storage checker passes."""
        cluster, client = _setup(31, 40)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.destroy_data_file(victim)
        for k in range(5):  # live traffic while the data file is gone
            _drive(cluster, client, [
                (Operation.create_transfers,
                 _create_transfers_body([(300 + k, 1, 2, 1)]))])
        rebuilt = cluster.rebuild(victim)
        assert rebuilt._rebuild_synced, \
            "rebuild converged without exercising state sync"
        assert rebuilt._rebuild_certified
        cluster.settle()
        digests = _digests(cluster)
        assert len(set(digests)) == 1, digests

    def test_rebuild_without_peer_checkpoint_repairs_wal(self):
        """A young cluster (no checkpoint yet) has nothing to offer over
        state sync: the rebuild catches up through ordinary WAL repair
        under the primary's start_view and still converges."""
        cluster, client = _setup(32, 5)  # 6 ops < checkpoint_interval
        victim = (cluster.replicas[0].primary_index() + 2) % 3
        assert all(r.superblock.op_checkpoint == 0
                   for r in cluster.replicas)
        cluster.destroy_data_file(victim)
        rebuilt = cluster.rebuild(victim)
        assert not rebuilt._rebuild_synced  # WAL-only path
        cluster.settle()
        digests = _digests(cluster)
        assert len(set(digests)) == 1, digests

    def test_rebuilding_replica_never_votes(self):
        """No half-installed state ever votes: while rebuilding, the
        replica sends no prepare_ok, no nack, and joins no view change —
        its lost promise history must not weigh in any quorum."""
        cluster, client = _setup(33, 40)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.destroy_data_file(victim)
        rebuilt = cluster.begin_rebuild(victim)
        sent = []
        orig = rebuilt.bus.send_to_replica

        def spy(dst, msg):
            if rebuilt.rebuilding:
                sent.append(msg.header.command)
            orig(dst, msg)

        rebuilt.bus.send_to_replica = spy
        ok = cluster.run(12000, until=lambda: rebuilt.rebuild_complete)
        assert ok, rebuilt.rebuild_progress()
        forbidden = {Command.prepare_ok, Command.nack_prepare,
                     Command.start_view_change, Command.do_view_change}
        assert not (set(sent) & forbidden), set(sent) & forbidden
        assert not rebuilt.is_primary
        rebuilt.finish_rebuild()
        cluster.settle()

    def test_crash_mid_install_refuses_normal_open(self):
        """A crash between the staged sync_op record and the final
        superblock flip leaves a half-installed grid: a normal open must
        REFUSE the file (RuntimeError naming recover --from-cluster) and
        a re-run of the rebuild must complete cleanly."""
        cluster, client = _setup(34, 40)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.destroy_data_file(victim)
        rebuilt = cluster.begin_rebuild(victim)
        ok = cluster.run(8000, until=lambda: rebuilt.syncing is not None)
        assert ok, "rebuild never began syncing"

        class _Crash(Exception):
            pass

        class _CrashAfter:
            """Write-through until the budget runs out, then crash — the
            4 superblock copies (sync_op record) land, grid writes tear."""

            def __init__(self, inner, writes_left):
                self.inner = inner
                self.layout = inner.layout
                self.writes_left = writes_left

            def read(self, zone, off, size):
                return self.inner.read(zone, off, size)

            def write(self, zone, off, data):
                if self.writes_left <= 0:
                    raise _Crash()
                self.writes_left -= 1
                self.inner.write(zone, off, data)

            def sync(self):
                self.inner.sync()

            def write_pair_async(self, *a):
                return None

            def io_poll(self):
                return []

            def read_batch(self, zone, reqs):
                return [self.read(zone, o, s) for o, s in reqs]

        storage = cluster.storages[victim]
        rebuilt.storage = _CrashAfter(storage, writes_left=5)
        with pytest.raises(_Crash):
            cluster.run(8000, until=lambda: rebuilt.rebuild_complete)
        cluster.crash(victim)
        sb = SuperBlock.load(storage)
        assert sb is not None and sb.sync_op > 0, \
            "torn install left no sync-progress record"
        # The half-installed file must never serve reads or vote.
        doomed = cluster._make_replica(victim)
        with pytest.raises(RuntimeError, match="mid-rebuild"):
            doomed.open()
        # The rebuild path restarts cleanly on the same bytes.
        rebuilt = cluster.rebuild(victim)
        assert rebuilt._rebuild_synced
        cluster.settle()
        digests = _digests(cluster)
        assert len(set(digests)) == 1, digests

    def test_rebuild_under_live_traffic(self):
        """Client load keeps committing through the whole rebuild; the
        rebuilt replica converges to the moving cluster state."""
        cluster, client = _setup(35, 36)
        victim = (cluster.replicas[0].primary_index() + 2) % 3
        cluster.destroy_data_file(victim)
        rebuilt = cluster.begin_rebuild(victim)
        for k in range(8):  # interleave traffic with rebuild progress
            _drive(cluster, client, [
                (Operation.create_transfers,
                 _create_transfers_body([(500 + k, 1, 2, 1)]))])
        ok = cluster.run(12000, until=lambda: rebuilt.rebuild_complete)
        assert ok, rebuilt.rebuild_progress()
        rebuilt.finish_rebuild()
        cluster.settle()
        digests = _digests(cluster)
        assert len(set(digests)) == 1, digests


class TestSuperBlockSyncOp:
    def test_sync_op_roundtrips(self):
        storage = MemoryStorage(TEST_LAYOUT)
        sb = SuperBlock(cluster=3, replica_id=1, replica_count=3,
                        sync_op=77)
        sb.store(storage)
        got = SuperBlock.load(storage)
        assert got.sync_op == 77
        sb.sync_op = 0
        sb.store(storage)
        assert SuperBlock.load(storage).sync_op == 0
