"""Spec tables for status codes no other test exercised: the remaining
exists_with_* comparisons, the imported debit-account timestamp rule,
and the four per-field u128 overflow variants. Expected codes are
written out explicitly (the state_machine_tests.zig table style,
src/state_machine_tests.zig:1) and asserted on BOTH the sequential
oracle and the device serving engine.

Reference: create_transfer_exists (src/state_machine.zig:3988-4050),
imported timestamp rules (:3795-3812), overflow checks (:3856-3884)."""

import pytest

from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (Account, AccountFlags, Transfer,
                                   TransferFlags)

PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
IMPORTED = int(TransferFlags.imported)
IMPORTED_A = int(AccountFlags.imported)
U128MAX = (1 << 128) - 1
HUGE = 1 << 127


@pytest.fixture(params=["oracle", "device"])
def sm(request):
    m = StateMachine(engine=request.param, a_cap=1 << 10, t_cap=1 << 12)
    m.create_accounts([Account(id=i, ledger=1, code=1)
                       for i in range(1, 9)], 100)
    return m


def _one(sm, t, ts):
    return sm.create_transfers([t], ts)[0].status.name


class TestExistsComparisons:
    def test_exists_with_different_credit_account_id(self, sm):
        ts = 10**12
        base = dict(debit_account_id=1, credit_account_id=2, amount=5,
                    ledger=1, code=1)
        assert _one(sm, Transfer(id=50, **base), ts) == "created"
        dup = dict(base, credit_account_id=3)
        assert _one(sm, Transfer(id=50, **dup), ts + 100) == \
            "exists_with_different_credit_account_id"

    def test_exists_with_different_timeout(self, sm):
        ts = 10**12
        base = dict(debit_account_id=1, credit_account_id=2, amount=5,
                    ledger=1, code=1, flags=PEND, timeout=10)
        assert _one(sm, Transfer(id=51, **base), ts) == "created"
        dup = dict(base, timeout=20)
        assert _one(sm, Transfer(id=51, **dup), ts + 100) == \
            "exists_with_different_timeout"

    def test_exists_with_different_pending_id(self, sm):
        ts = 10**12
        for i, tid in enumerate((52, 53)):
            assert _one(sm, Transfer(
                id=tid, debit_account_id=1, credit_account_id=2,
                amount=5, ledger=1, code=1, flags=PEND),
                ts + i * 100) == "created"
        post = dict(amount=U128MAX, ledger=1, code=1, flags=POST)
        assert _one(sm, Transfer(id=54, pending_id=52, **post),
                    ts + 300) == "created"
        assert _one(sm, Transfer(id=54, pending_id=53, **post),
                    ts + 400) == "exists_with_different_pending_id"


class TestImportedTimestampRules:
    def test_imported_transfer_must_postdate_debit_account(self, sm):
        ts = 10**12
        r = sm.create_accounts([
            Account(id=21, ledger=1, code=1, flags=IMPORTED_A,
                    timestamp=4000),
            Account(id=20, ledger=1, code=1, flags=IMPORTED_A,
                    timestamp=5000),
        ], ts)
        assert [x.status.name for x in r] == ["created", "created"]
        # Imported transfer at ts 4500: postdates credit (4000) but NOT
        # debit (5000) -> the debit-account variant, checked first.
        got = _one(sm, Transfer(
            id=60, debit_account_id=20, credit_account_id=21, amount=1,
            ledger=1, code=1, flags=IMPORTED, timestamp=4500), ts + 100)
        assert got == "imported_event_timestamp_must_postdate_debit_account"
        # And at 3500 it predates BOTH: debit account still reported
        # first (precedence, reference :3795-3812).
        got = _one(sm, Transfer(
            id=61, debit_account_id=20, credit_account_id=21, amount=1,
            ledger=1, code=1, flags=IMPORTED, timestamp=3500), ts + 200)
        assert got == "imported_event_timestamp_must_postdate_debit_account"


class TestOverflowVariants:
    def test_overflows_debits_pending(self, sm):
        ts = 10**12
        assert _one(sm, Transfer(
            id=70, debit_account_id=1, credit_account_id=2, amount=HUGE,
            ledger=1, code=1, flags=PEND), ts) == "created"
        assert _one(sm, Transfer(
            id=71, debit_account_id=1, credit_account_id=3, amount=HUGE,
            ledger=1, code=1, flags=PEND), ts + 100) == \
            "overflows_debits_pending"

    def test_overflows_credits_pending(self, sm):
        ts = 10**12
        assert _one(sm, Transfer(
            id=72, debit_account_id=1, credit_account_id=2, amount=HUGE,
            ledger=1, code=1, flags=PEND), ts) == "created"
        assert _one(sm, Transfer(
            id=73, debit_account_id=3, credit_account_id=2, amount=HUGE,
            ledger=1, code=1, flags=PEND), ts + 100) == \
            "overflows_credits_pending"

    def test_overflows_credits_posted(self, sm):
        ts = 10**12
        assert _one(sm, Transfer(
            id=74, debit_account_id=1, credit_account_id=2, amount=HUGE,
            ledger=1, code=1), ts) == "created"
        assert _one(sm, Transfer(
            id=75, debit_account_id=3, credit_account_id=2, amount=HUGE,
            ledger=1, code=1), ts + 100) == "overflows_credits_posted"

    def test_overflows_credits_total(self, sm):
        """credits_pending + credits_posted + amount > u128 while
        NEITHER single-field sum overflows — only then does the
        combined-total variant fire (the posted-field check runs
        unconditionally first, reference :3864-3884)."""
        ts = 10**12
        q = 1 << 126  # quarter of 2^128
        # credits_posted = 2q, credits_pending = q on account 2.
        assert _one(sm, Transfer(
            id=76, debit_account_id=1, credit_account_id=2,
            amount=2 * q, ledger=1, code=1), ts) == "created"
        assert _one(sm, Transfer(
            id=77, debit_account_id=4, credit_account_id=2,
            amount=q, ledger=1, code=1, flags=PEND),
            ts + 100) == "created"
        # amount q+1: posted-sum 3q+1 fits, pending not checked
        # (non-pending), but the total 4q+1 = 2^128 + 1 overflows.
        assert _one(sm, Transfer(
            id=78, debit_account_id=5, credit_account_id=2,
            amount=q + 1, ledger=1, code=1),
            ts + 200) == "overflows_credits"
