"""Where do the bench's 84ms/batch go? Time the real kernel dispatch
at several state sizes and stack factors, solo on the chip."""
import sys; sys.path.insert(0, "/root/repo")
import json, time
import numpy as np
import jax
from tigerbeetle_tpu.benchmark import _make_ledger, _soa, N
from tigerbeetle_tpu.ops.fast_kernels import (
    create_transfers_fast_jit, create_transfers_super_jit, _accum_jit)
from tigerbeetle_tpu.ops.ledger import pad_transfer_events, stack_superbatch

out = {}
rng = np.random.default_rng(2)

def mk(b, account_count=10_000):
    base = 10**7 + b * N
    ids = np.arange(base, base + N)
    dr = rng.integers(1, account_count + 1, N, dtype=np.uint64)
    cr = rng.integers(1, account_count + 1, N, dtype=np.uint64)
    clash = dr == cr
    cr[clash] = dr[clash] % account_count + 1
    return _soa(ids, dr, cr, rng.integers(1, 10**6, N))

for t_cap_log in (18, 21):
    led = _make_ledger(10_000, a_cap=1 << 15, t_cap=1 << t_cap_log)
    # single-batch timing, 12 batches, first 4 = warmup
    evs = [mk(b) for b in range(12)]
    padded = [{k: jax.device_put(v) for k, v in pad_transfer_events(e).items()}
              for e in evs]
    ts0 = 10**12
    times = []
    poisoned = jax.device_put(np.bool_(False))
    for i, ev in enumerate(padded):
        t0 = time.perf_counter()
        led.state, outs = create_transfers_fast_jit(
            led.state, ev, np.uint64(ts0 + i * (N + 10)), np.int32(N),
            force_fallback=poisoned)
        poisoned = outs["fallback"]
        jax.block_until_ready(poisoned)   # force full sync per batch
        times.append(time.perf_counter() - t0)
    out[f"tcap{t_cap_log}_single_ms"] = [round(t*1e3, 1) for t in times]

    # superbatch (8) timing, 3 groups after 1 warmup
    led2 = _make_ledger(10_000, a_cap=1 << 15, t_cap=1 << t_cap_log)
    groups = []
    for g in range(4):
        evs = [mk(100 + g * 8 + i) for i in range(8)]
        tss = [10**13 + (g * 8 + i) * (N + 10) for i in range(8)]
        ev_s, seg = stack_superbatch(evs, tss)
        groups.append(({k: jax.device_put(v) for k, v in ev_s.items()},
                       {k: jax.device_put(v) for k, v in seg.items()}))
    poisoned = jax.device_put(np.bool_(False))
    times = []
    for ev_s, seg in groups:
        t0 = time.perf_counter()
        led2.state, outs = create_transfers_super_jit(
            led2.state, ev_s, seg, force_fallback=poisoned)
        poisoned = outs["fallback"]
        jax.block_until_ready(poisoned)
        times.append(time.perf_counter() - t0)
    out[f"tcap{t_cap_log}_super8_ms"] = [round(t*1e3, 1) for t in times]

print(json.dumps(out, indent=1))
json.dump(out, open("/root/repo/onchip/kernel_probe_result.json", "w"), indent=2)
