"""Per-op-class cost scaling on the real chip: which ops break the
size-independence the superbatch relies on?"""
import json, time
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp

def timeit(fn, *a, warm=2, iters=4):
    for _ in range(warm):
        jax.block_until_ready(fn(*a))
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn(*a))
    return (time.perf_counter() - t0) / iters

out = {}
for n in (16384, 65536, 262144):
    key = jnp.arange(n, dtype=jnp.int64)[::-1] ^ jnp.int64(0x5A5A5A)
    u = (jnp.arange(n, dtype=jnp.uint64) * jnp.uint64(2654435761)) % jnp.uint64(n)
    idx = (jnp.arange(n, dtype=jnp.int32) * 7) % n
    seg = idx // 8

    probes = {
        "argsort_i64": jax.jit(lambda k: jnp.argsort(k)),
        "sort_u64": jax.jit(lambda k: jnp.sort(k)),
        "gather_u64": jax.jit(lambda x, i: x[i]),
        "scatter_set_u64": jax.jit(lambda x, i: x.at[i].set(x)),
        "segsum_u64": jax.jit(lambda x, s: jax.ops.segment_sum(x, s, num_segments=n)),
        "ascan_u64": jax.jit(lambda x: jax.lax.associative_scan(jnp.add, x)),
        "where_u64": jax.jit(lambda x: jnp.where(x > 5, x, x + 1)),
    }
    for name, f in probes.items():
        if name == "argsort_i64" or name == "sort_u64":
            t = timeit(f, key)
        elif name in ("gather_u64", "scatter_set_u64"):
            t = timeit(f, u, idx)
        elif name == "segsum_u64":
            t = timeit(f, u, seg)
        else:
            t = timeit(f, u)
        out[f"{name}_n{n}_ms"] = round(t * 1e3, 2)
print(json.dumps(out, indent=1))
json.dump(out, open("/root/repo/onchip/opclass_probe_result.json", "w"), indent=2)
