"""Shared incremental-banking harness for on-chip probes.

Doctrine (learned 20260802, the hard way): a probe's artifact must land
no matter how the measurement dies, and the watcher's SIGKILL backstop
must never fire mid-RPC — killing an axon client mid-call coincided
with losing the whole tunnel relay. Every probe therefore

  * dumps its result dict atomically after every completed arm,
  * resumes from the existing artifact instead of re-measuring arms,
  * self-deadlines via a watchdog THREAD (a SIGALRM handler cannot
    preempt a main thread blocked inside a PJRT C call) that banks a
    snapshot and exits hard, strictly before the watcher's timeout.
"""

from __future__ import annotations

import json
import os
import threading
import time


def make_dumper(res: dict, out_path: str):
    """Atomic, thread-safe-enough artifact writer.

    Per-writer tmp names keep the watchdog thread and the main thread
    from interleaving into one file; the watchdog always dumps a
    SNAPSHOT so the main thread's json.dump never races a mutation.
    """

    def dump(snapshot: dict | None = None) -> None:
        snapshot = dict(res) if snapshot is None else snapshot
        tmp = f"{out_path}.tmp{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "w") as f:
                json.dump(snapshot, f, indent=2)
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            # A failed dump (e.g. a non-serializable value) must not
            # leak the tmp file or clobber the last good artifact.
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, out_path)

    return dump


def resume_from(out_path: str, res: dict, keep=lambda k: True) -> None:
    """Seed `res` with previously banked arms so a re-run resumes
    instead of regressing the artifact (keys chosen by `keep`; control
    keys like complete/alarm/error/verdict are never carried over)."""
    drop = {"complete", "alarm", "error", "verdict", "deadline_hit"}
    try:
        with open(out_path) as f:
            old = json.load(f)
    except (OSError, json.JSONDecodeError):
        return
    if not isinstance(old, dict):
        return
    for k, v in old.items():
        if k not in drop and k not in res and keep(k):
            res[k] = v


def start_watchdog(deadline_env: str, default_s: float, on_deadline,
                   grace_s: float = 0.0) -> float:
    """Start the hard-exit watchdog; returns the monotonic deadline.

    `on_deadline()` runs in the watchdog thread at deadline+grace: it
    must bank a snapshot itself; then the process exits(4). `grace_s`
    gives a probe's own in-loop deadline checks first shot at a clean
    between-arms exit.
    """
    deadline = time.monotonic() + float(
        os.environ.get(deadline_env, str(default_s)))

    def _watchdog():
        while time.monotonic() < deadline + grace_s:
            time.sleep(5.0)
        try:
            on_deadline()
        finally:
            # The hard exit must survive a failing callback (e.g. a
            # dict-changed-size race while snapshotting): blocking past
            # the deadline reinstates the SIGKILL-mid-RPC hazard.
            os._exit(4)

    threading.Thread(target=_watchdog, daemon=True).start()
    return deadline
