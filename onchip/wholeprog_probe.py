"""Whole-program execution probe (round 4, VERDICT item 1).

Question: does ONE compiled program that chains K iterations of a
gather/scatter body on device amortize the tunnel's ~0.5-1 ms per-op
cost (PERF.md), or does the tunnel op-stream *executed* ops so a
K-iteration program costs K times one iteration?

Four variants over the same ~10-heavy-op body at 8k rows:
  A  one body, one dispatch                  -> per-op baseline
  B  K back-to-back dispatches of A          -> current (tunnel) regime
  C  one jit with K bodies UNROLLED          -> program op count ~ K*10
  D  one jit with lax.scan over K iterations -> program op count ~ 10,
                                                executed op count K*10
plus a trailing 1-op dispatch after D (round-2 found executed
while_loops degrade later dispatches; scan lowers to While HLO).

If D(K=32) ~= A + epsilon: whole-program execution is real ->
build the K-window scan kernel (4-16M model holds).
If D(K=32) ~= K * A: the tunnel op-streams inside a single jit ->
the 4-16M whole-program claim is FALSIFIED for this environment.
"""
import json
import os
import time

import jax

# The real kernels are uint64 end-to-end (tigerbeetle_tpu enables x64 at
# package import); without this the probe would silently benchmark a
# 32-bit body — half the memory traffic of the regime under test.
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np

N = 8192
KS = (8, 32)


def body(carry):
    table, idx, vals = carry
    perm = jnp.argsort(idx)                      # sort (heavy)
    g1 = table[idx]                              # gather
    g2 = table[perm]                             # gather
    s = jax.lax.associative_scan(jnp.add, vals)  # log-step scan
    t2 = table.at[idx].add(vals)                 # scatter-add
    mix = (g1 ^ s) + g2
    seg = jax.lax.associative_scan(jnp.maximum, mix)
    new_idx = ((idx.astype(jnp.uint32) * jnp.uint32(2654435761))
               % jnp.uint32(N)).astype(jnp.int32)
    new_vals = (mix + seg) | jnp.uint64(1)
    new_table = t2.at[new_idx].max(new_vals)     # scatter-max
    return (new_table, new_idx, new_vals)


@jax.jit
def one(carry):
    return body(carry)


def unrolled(k):
    @jax.jit
    def f(carry):
        for _ in range(k):
            carry = body(carry)
        return carry
    return f


def scanned(k):
    @jax.jit
    def f(carry):
        def step(c, _):
            return body(c), None
        c, _ = jax.lax.scan(step, carry, None, length=k)
        return c
    return f


@jax.jit
def tiny(x):
    return x * jnp.uint64(2) + jnp.uint64(1)


def fresh():
    rng = np.random.default_rng(7)
    return (jax.device_put(rng.integers(0, 1 << 62, N, dtype=np.uint64)),
            jax.device_put(rng.integers(0, N, N, dtype=np.int32).astype(np.int32)),
            jax.device_put(rng.integers(0, 1 << 62, N, dtype=np.uint64)))


def timed(fn, carry, reps=3):
    out = fn(carry)
    jax.block_until_ready(out)                    # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(carry)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) * 1e3)
    return ts, out


def main():
    res = {"platform": jax.devices()[0].platform,
           "device": str(jax.devices()[0]), "n_rows": N}
    carry = fresh()

    ts_a, _ = timed(one, carry)
    res["A_one_body_ms"] = [round(t, 2) for t in ts_a]
    a = min(ts_a)

    for k in KS:
        c = fresh()
        t0 = time.perf_counter()
        for _ in range(k):
            c = one(c)
        jax.block_until_ready(c)
        res[f"B_seq_k{k}_ms"] = round((time.perf_counter() - t0) * 1e3, 2)

    for k in KS:
        ts, _ = timed(unrolled(k), fresh())
        res[f"C_unroll_k{k}_ms"] = [round(t, 2) for t in ts]
        res[f"C_unroll_k{k}_vs_kA"] = round(min(ts) / (k * a), 3)

    for k in KS + (128,):
        ts, _ = timed(scanned(k), fresh())
        res[f"D_scan_k{k}_ms"] = [round(t, 2) for t in ts]
        res[f"D_scan_k{k}_vs_kA"] = round(min(ts) / (k * a), 3)

    # post-scan poison check (round-2: executed While degrades dispatches)
    x = jax.device_put(np.arange(N, dtype=np.uint64))
    jax.block_until_ready(tiny(x))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(tiny(x))
        ts.append((time.perf_counter() - t0) * 1e3)
    res["post_scan_tiny_dispatch_ms"] = [round(t, 3) for t in ts]

    k = 32
    scan_ok = min(res[f"D_scan_k{k}_ms"]) < 0.35 * k * a
    unroll_ok = min(res[f"C_unroll_k{k}_ms"]) < 0.35 * k * a
    if scan_ok:
        res["verdict"] = ("WHOLE-PROGRAM AMORTIZES (scan form): build "
                          "the K-window lax.scan kernel")
    elif unroll_ok:
        res["verdict"] = ("WHOLE-PROGRAM AMORTIZES (unrolled form ONLY; "
                          "scan op-streams): build the K-window kernel "
                          "UNROLLED, not as lax.scan")
    else:
        res["verdict"] = ("TUNNEL OP-STREAMS INSIDE A SINGLE JIT (both "
                          "forms): whole-program claim falsified for "
                          "this environment")
    print(json.dumps(res, indent=1))
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "wholeprog_probe_result.json")
    json.dump(res, open(out_path, "w"), indent=2)


if __name__ == "__main__":
    main()
