"""Whole-program execution probe (round 4, VERDICT item 1).

Question: does ONE compiled program that chains K iterations of a
gather/scatter body on device amortize the tunnel's ~0.5-1 ms per-op
cost (PERF.md), or does the tunnel op-stream *executed* ops so a
K-iteration program costs K times one iteration?

Four variants over the same ~10-heavy-op body at 8k rows:
  A  one body, one dispatch                  -> per-op baseline
  B  K back-to-back dispatches of A          -> current (tunnel) regime
  C  one jit with K bodies UNROLLED          -> program op count ~ K*10
  D  one jit with lax.scan over K iterations -> program op count ~ 10,
                                                executed op count K*10
plus a trailing 1-op dispatch after D (round-2 found executed
while_loops degrade later dispatches; scan lowers to While HLO).

If D(K=32) ~= A + epsilon: whole-program execution is real ->
build the K-window scan kernel (4-16M model holds).
If D(K=32) ~= K * A: the tunnel op-streams inside a single jit ->
the 4-16M whole-program claim is FALSIFIED for this environment.

Watchdog doctrine (ADVICE r4): the self-deadline arms BEFORE the first
jax import / backend touch — a wedged PJRT_Client_Create must hit the
in-process deadline (which banks a marker artifact) and never the
watcher's SIGKILL-mid-RPC backstop.
"""
import json
import os
import sys
import time

N = 8192
KS = (8, 32)


def _run(res, dump):
    # First backend touch strictly after the watchdog is armed.
    import jax

    # The real kernels are uint64 end-to-end (tigerbeetle_tpu enables
    # x64 at package import); without this the probe would silently
    # benchmark a 32-bit body — half the memory traffic of the regime
    # under test.
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    res["platform"] = jax.devices()[0].platform
    res["device"] = str(jax.devices()[0])
    dump()

    def body(carry):
        table, idx, vals = carry
        perm = jnp.argsort(idx)                      # sort (heavy)
        g1 = table[idx]                              # gather
        g2 = table[perm]                             # gather
        s = jax.lax.associative_scan(jnp.add, vals)  # log-step scan
        t2 = table.at[idx].add(vals)                 # scatter-add
        mix = (g1 ^ s) + g2
        seg = jax.lax.associative_scan(jnp.maximum, mix)
        new_idx = ((idx.astype(jnp.uint32) * jnp.uint32(2654435761))
                   % jnp.uint32(N)).astype(jnp.int32)
        new_vals = (mix + seg) | jnp.uint64(1)
        new_table = t2.at[new_idx].max(new_vals)     # scatter-max
        return (new_table, new_idx, new_vals)

    one = jax.jit(body)

    def unrolled(k):
        @jax.jit
        def f(carry):
            for _ in range(k):
                carry = body(carry)
            return carry
        return f

    def scanned(k):
        @jax.jit
        def f(carry):
            def step(c, _):
                return body(c), None
            c, _ = jax.lax.scan(step, carry, None, length=k)
            return c
        return f

    tiny = jax.jit(lambda x: x * jnp.uint64(2) + jnp.uint64(1))

    def fresh():
        rng = np.random.default_rng(7)
        return (jax.device_put(rng.integers(0, 1 << 62, N, dtype=np.uint64)),
                jax.device_put(
                    rng.integers(0, N, N, dtype=np.int32).astype(np.int32)),
                jax.device_put(rng.integers(0, 1 << 62, N, dtype=np.uint64)))

    def timed(fn, carry, reps=3):
        out = fn(carry)
        jax.block_until_ready(out)                    # compile + warm
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn(carry)
            jax.block_until_ready(out)
            ts.append((time.perf_counter() - t0) * 1e3)
        return ts, out

    carry = fresh()

    # Incremental banking after each arm (same doctrine as chain_probe):
    # an exception or deadline mid-probe must not lose measured arms.
    try:
        if "A_one_body_ms" not in res:
            ts_a, _ = timed(one, carry)
            res["A_one_body_ms"] = [round(t, 2) for t in ts_a]
            dump()
        a = min(res["A_one_body_ms"])

        for k in KS:
            if f"B_seq_k{k}_ms" in res:
                continue
            c = fresh()
            t0 = time.perf_counter()
            for _ in range(k):
                c = one(c)
            jax.block_until_ready(c)
            res[f"B_seq_k{k}_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 2)
        dump()

        for k in KS:
            if f"C_unroll_k{k}_ms" in res:
                continue
            ts, _ = timed(unrolled(k), fresh())
            res[f"C_unroll_k{k}_ms"] = [round(t, 2) for t in ts]
            res[f"C_unroll_k{k}_vs_kA"] = round(min(ts) / (k * a), 3)
            dump()

        for k in KS + (128,):
            if f"D_scan_k{k}_ms" in res:
                continue
            ts, _ = timed(scanned(k), fresh())
            res[f"D_scan_k{k}_ms"] = [round(t, 2) for t in ts]
            res[f"D_scan_k{k}_vs_kA"] = round(min(ts) / (k * a), 3)
            dump()

        # post-scan poison check (round-2: executed While degrades
        # dispatches)
        if "post_scan_tiny_dispatch_ms" not in res:
            x = jax.device_put(np.arange(N, dtype=np.uint64))
            jax.block_until_ready(tiny(x))
            ts = []
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(tiny(x))
                ts.append((time.perf_counter() - t0) * 1e3)
            res["post_scan_tiny_dispatch_ms"] = [round(t, 3) for t in ts]
    except Exception as e:  # noqa: BLE001 — bank what was measured
        res["error"] = repr(e)[:300]
        dump()
        raise

    k = 32
    scan_ok = min(res[f"D_scan_k{k}_ms"]) < 0.35 * k * a
    unroll_ok = min(res[f"C_unroll_k{k}_ms"]) < 0.35 * k * a
    if scan_ok:
        res["verdict"] = ("WHOLE-PROGRAM AMORTIZES (scan form): build "
                          "the K-window lax.scan kernel")
    elif unroll_ok:
        res["verdict"] = ("WHOLE-PROGRAM AMORTIZES (unrolled form ONLY; "
                          "scan op-streams): build the K-window kernel "
                          "UNROLLED, not as lax.scan")
    else:
        res["verdict"] = ("TUNNEL OP-STREAMS INSIDE A SINGLE JIT (both "
                          "forms): whole-program claim falsified for "
                          "this environment")
    res["complete"] = True
    print(json.dumps(res, indent=1))
    dump()


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _banking import make_dumper, resume_from, start_watchdog

    res = {"n_rows": N}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "wholeprog_probe_result.json")
    # Resume: banked arms survive a re-run (an error-only re-run must
    # never regress a COMPLETE verdict artifact).
    resume_from(out_path, res,
                keep=lambda k: k[:1] in "ABCD" or k.startswith("post_"))
    dump = make_dumper(res, out_path)

    def _on_deadline():
        snap = dict(res)
        snap["alarm"] = ("watchdog: deadline exceeded mid-call" +
                         ("" if "platform" in res
                          else " (wedged during PJRT init)"))
        dump(snap)

    # See onchip/_banking.py for the watchdog/banking doctrine. Armed
    # BEFORE the first jax import (ADVICE r4 medium).
    start_watchdog("PROBE_DEADLINE_S", 840.0, _on_deadline)
    _run(res, dump)


if __name__ == "__main__":
    main()
