"""Does tunnel per-op cost depend on array size? Decides superbatching."""
import json, time
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp

def timeit(fn, *a, warm=2, iters=6):
    for _ in range(warm):
        jax.block_until_ready(fn(*a))
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn(*a))
    return (time.perf_counter() - t0) / iters

OPS = 16
out = {}
for n in (8192, 65536, 524288):
    tab = jnp.arange(n, dtype=jnp.uint64)
    @jax.jit
    def f(x, tab=tab, n=n):
        for _ in range(OPS):
            x = tab[((x + jnp.uint64(1)) & jnp.uint64(n - 1)).astype(jnp.int32)]
        return x
    x = jnp.arange(n, dtype=jnp.uint64)
    t = timeit(f, x)
    out[f"chain{OPS}_n{n}_ms"] = round(t * 1e3, 2)
    out[f"per_op_us_n{n}"] = round(t / OPS * 1e6, 1)
print(json.dumps(out))
json.dump(out, open("/root/repo/onchip/size_probe_result.json", "w"), indent=2)
