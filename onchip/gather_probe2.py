"""Round 2 of Mosaic gather formulations: SMEM scalar loop, int32 casts,
explicit int32 take_along_axis, blocked grid."""
import json

import jax

jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, W, N = 4097, 48, 8192
table = (jnp.arange(B * W, dtype=jnp.uint32)).reshape(B, W)
rows = (jnp.arange(N, dtype=jnp.int32) * 7) % B
out = {}


def attempt(name, fn, ref_fn=None):
    try:
        r = jax.jit(fn)(table, rows)
        jax.block_until_ready(r)
        ref = (ref_fn or (lambda: jnp.take(table, rows, axis=0)))()
        out[name] = {"ok": True, "match": bool((r == ref).all())}
    except Exception as e:
        out[name] = {"ok": False,
                     "err": f"{type(e).__name__}: {e}".splitlines()[0][:300]}
    print(name, out[name], flush=True)


# --- A: scalar-prefetch rows in SMEM, serial fori_loop over queries ----
def k_smem_loop(r_smem, t_ref, o_ref):
    def body(i, _):
        o_ref[i, :] = t_ref[r_smem[i], :]
        return 0
    jax.lax.fori_loop(0, N, body, 0)


def f_smem_loop(t, r):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        k_smem_loop,
        out_shape=jax.ShapeDtypeStruct((N, W), jnp.uint32),
        grid_spec=grid_spec,
    )(r, t)


attempt("pl_smem_loop", f_smem_loop)


# --- B: take_along_axis with strictly-int32 index math ---------------
def k_taa32(t_ref, r_ref, o_ref):
    idx = jnp.broadcast_to(
        r_ref[:].astype(jnp.int32)[:, None], (N, W)).astype(jnp.int32)
    o_ref[:] = jnp.take_along_axis(
        t_ref[:], idx, axis=0, mode="promise_in_bounds")


def f_taa32(t, r):
    return pl.pallas_call(
        k_taa32,
        out_shape=jax.ShapeDtypeStruct((N, W), jnp.uint32),
    )(t, r)


attempt("pl_taa_int32", f_taa32)


# --- C: one-hot matmul with int32->f32 casts -------------------------
def k_onehot32(t_ref, r_ref, o_ref):
    limb = (t_ref[:] & jnp.uint32(0xFFFF)).astype(jnp.int32).astype(
        jnp.float32)
    oh = (r_ref[:][:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (N, B), 1)).astype(jnp.float32)
    acc = jax.lax.dot_general(
        oh, limb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[:] = acc.astype(jnp.int32).astype(jnp.uint32)


def f_onehot32(t, r):
    return pl.pallas_call(
        k_onehot32,
        out_shape=jax.ShapeDtypeStruct((N, W), jnp.uint32),
    )(t, r)


attempt("pl_onehot_int32",
        f_onehot32,
        lambda: jnp.take(table & jnp.uint32(0xFFFF), rows, axis=0))


# --- D: grid over query blocks, SMEM scalars, serial inner loop -------
BLK = 1024


def k_blk(r_smem, t_ref, o_ref):
    blk = pl.program_id(0)

    def body(i, _):
        o_ref[i, :] = t_ref[r_smem[blk * BLK + i], :]
        return 0
    jax.lax.fori_loop(0, BLK, body, 0)


def f_blk(t, r):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N // BLK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(
            (BLK, W), lambda b, r_smem: (b, 0),
            memory_space=pltpu.VMEM),
    )
    return pl.pallas_call(
        k_blk,
        out_shape=jax.ShapeDtypeStruct((N, W), jnp.uint32),
        grid_spec=grid_spec,
    )(r, t)


attempt("pl_blocked_smem_loop", f_blk)

json.dump(out, open("/root/repo/onchip/gather_probe2_result.json", "w"),
          indent=2)
