"""Solo per-op slope: K-gather chains at n=65536, K=1..64."""
import json, time
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp

n = 65536
tab = jnp.arange(n, dtype=jnp.uint64)
def timeit(fn, *a, warm=2, iters=5):
    for _ in range(warm):
        jax.block_until_ready(fn(*a))
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn(*a))
    return (time.perf_counter() - t0) / iters

out = {}
for K in (1, 2, 4, 8, 16, 32, 64):
    @jax.jit
    def f(x, K=K):
        for _ in range(K):
            x = tab[((x + jnp.uint64(1)) & jnp.uint64(n - 1)).astype(jnp.int32)]
        return x
    t = timeit(f, jnp.arange(n, dtype=jnp.uint64))
    out[f"chain{K}_ms"] = round(t * 1e3, 2)
print(json.dumps(out))
json.dump(out, open("/root/repo/onchip/slope_probe_result.json", "w"), indent=2)
