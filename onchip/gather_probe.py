"""Which gather formulations lower in Mosaic on this chip?
Mosaic has no 64-bit types in-kernel, so everything tests u32
(the real kernel will view its u64 table as u32 pairs)."""
import json
import sys

import jax

jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from jax.experimental import pallas as pl

B, W, N = 4097, 48, 8192
table = (jnp.arange(B * W, dtype=jnp.uint32)).reshape(B, W)
rows = (jnp.arange(N, dtype=jnp.int32) * 7) % B
out = {}


def attempt(name, fn):
    try:
        r = jax.jit(fn)(table, rows)
        jax.block_until_ready(r)
        ref = jnp.take(table, rows, axis=0)
        out[name] = {"ok": True, "match": bool((r == ref).all())}
    except Exception as e:
        out[name] = {"ok": False,
                     "err": f"{type(e).__name__}: {e}".splitlines()[0][:300]}
    print(name, out[name], flush=True)


def k_take(t_ref, r_ref, o_ref):
    o_ref[:] = jnp.take(t_ref[:], r_ref[:], axis=0)


def k_taa(t_ref, r_ref, o_ref):
    idx = jnp.broadcast_to(r_ref[:][:, None], (N, W))
    o_ref[:] = jnp.take_along_axis(t_ref[:], idx, axis=0)


def k_loop(t_ref, r_ref, o_ref):
    def body(i, _):
        o_ref[i, :] = t_ref[r_ref[i], :]
        return 0
    jax.lax.fori_loop(0, N, body, 0)


def k_onehot(t_ref, r_ref, o_ref):
    limb = (t_ref[:] & jnp.uint32(0xFFFF)).astype(jnp.float32)
    oh = (r_ref[:][:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (N, B), 1)).astype(jnp.float32)
    acc = jax.lax.dot_general(
        oh, limb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[:] = acc.astype(jnp.uint32)


def mk(kernel):
    def f(t, r):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((N, W), jnp.uint32),
        )(t, r)
    return f


attempt("xla_take_baseline", lambda t, r: jnp.take(t, r, axis=0))
attempt("pl_take", mk(k_take))
attempt("pl_take_along_axis", mk(k_taa))
attempt("pl_loop_dynslice", mk(k_loop))


def attempt_onehot():
    name = "pl_onehot_limb"
    try:
        r = jax.jit(mk(k_onehot))(table, rows)
        jax.block_until_ready(r)
        ref = jnp.take(table & jnp.uint32(0xFFFF), rows, axis=0)
        out[name] = {"ok": True, "match": bool((r == ref).all())}
    except Exception as e:
        out[name] = {"ok": False,
                     "err": f"{type(e).__name__}: {e}".splitlines()[0][:300]}
    print(name, out[name], flush=True)


attempt_onehot()
json.dump(out, open(sys.argv[1] if len(sys.argv) > 1 else
                    "/root/repo/onchip/gather_probe_result.json", "w"),
          indent=2)
