"""Bisect the superbatch dispatch cost: per_event_status vs full kernel,
plus a no-application variant (statuses only), at stack=8."""
import json, time
import sys; sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from tigerbeetle_tpu.benchmark import _make_ledger, _soa, N
from tigerbeetle_tpu.ops import fast_kernels as fk
from tigerbeetle_tpu.ops.ledger import stack_superbatch

rng = np.random.default_rng(2)
AC = 10_000
def mk(b):
    base = 10**7 + b * N
    ids = np.arange(base, base + N)
    dr = rng.integers(1, AC + 1, N, dtype=np.uint64)
    cr = rng.integers(1, AC + 1, N, dtype=np.uint64)
    clash = dr == cr
    cr[clash] = dr[clash] % AC + 1
    return _soa(ids, dr, cr, rng.integers(1, 10**6, N))

led = _make_ledger(AC, a_cap=1 << 15, t_cap=1 << 21)
state = led.state
bi = 0
def group():
    global bi
    evs, tss = [], []
    for i in range(8):
        evs.append(mk(bi)); tss.append(10**13 + bi * (N + 10)); bi += 1
    ev_s, seg = stack_superbatch(evs, tss)
    return ({k: jax.device_put(v) for k, v in ev_s.items()},
            {k: jax.device_put(v) for k, v in seg.items()})

groups = [group() for _ in range(4)]

pe_jit = jax.jit(lambda st, ev, seg: fk.per_event_status(
    st, ev, seg["ts_event"]))

out = {}
def timeit(name, fn):
    ts = []
    for ev_s, seg in groups:
        t0 = time.perf_counter()
        r = fn(ev_s, seg)
        jax.block_until_ready(r)
        ts.append(round((time.perf_counter() - t0) * 1e3, 1))
    out[name] = ts
    print(name, ts, flush=True)

timeit("per_event_status_ms", lambda ev, seg: pe_jit(state, ev, seg))

# Full kernel WITHOUT state mutation visible: still runs application, so
# time the real thing against a copy each call (undonated timing control).
full = jax.jit(lambda st, ev, seg: fk.create_transfers_fast(
    st, ev, jnp.uint64(0), jnp.int32(0), seg=seg)[1]["r_status"])
timeit("full_kernel_ms", lambda ev, seg: full(state, ev, seg))
json.dump(out, open("/root/repo/onchip/stage_probe_result.json", "w"), indent=2)
