"""Find the superbatch knee: steady-state dispatch time per stack factor."""
import json, time
import sys; sys.path.insert(0, "/root/repo")
import numpy as np
import jax
from tigerbeetle_tpu.benchmark import _make_ledger, _soa, N
from tigerbeetle_tpu.ops.fast_kernels import create_transfers_super_jit
from tigerbeetle_tpu.ops.ledger import stack_superbatch

out = {}
rng = np.random.default_rng(2)
AC = 10_000

def mk(b):
    base = 10**7 + b * N
    ids = np.arange(base, base + N)
    dr = rng.integers(1, AC + 1, N, dtype=np.uint64)
    cr = rng.integers(1, AC + 1, N, dtype=np.uint64)
    clash = dr == cr
    cr[clash] = dr[clash] % AC + 1
    return _soa(ids, dr, cr, rng.integers(1, 10**6, N))

bi = 0
for stack in (32, 64):
    led = _make_ledger(AC, a_cap=1 << 15, t_cap=1 << 21)
    groups = []
    for g in range(3):
        evs = []
        tss = []
        for i in range(stack):
            evs.append(mk(bi)); tss.append(10**13 + bi * (N + 10)); bi += 1
        ev_s, seg = stack_superbatch(evs, tss)
        groups.append(({k: jax.device_put(v) for k, v in ev_s.items()},
                       {k: jax.device_put(v) for k, v in seg.items()}))
    poisoned = jax.device_put(np.bool_(False))
    times = []
    for ev_s, seg in groups:
        t0 = time.perf_counter()
        led.state, outs = create_transfers_super_jit(
            led.state, ev_s, seg, force_fallback=poisoned)
        poisoned = outs["fallback"]
        jax.block_until_ready(poisoned)
        times.append(time.perf_counter() - t0)
    assert not bool(jax.device_get(poisoned))
    out[f"stack{stack}_ms"] = [round(t*1e3, 1) for t in times]
    out[f"stack{stack}_tps_steady"] = round(stack * N / (times[-1]), 1)
print(json.dumps(out, indent=1))
json.dump(out, open("/root/repo/onchip/stack_probe_result.json", "w"), indent=2)
