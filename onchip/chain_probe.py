"""Whole-program window-chain probe on the REAL kernel — now through
the REAL serving route (round 7: the chain is the default dispatch
mode, so the banked numbers must be serving-path numbers, not
synthetic kernel calls).

Measures config2-shaped commit windows (stack x 8190-event prepares per
window) on the chip:

  seq      W separate super dispatches (the round-3 regime, anchor)
  chain    ONE compiled program: raw lax.scan over W windows (the
           round-4/5 synthetic arm, kept for series continuity)
  route    DeviceLedger.submit_window/resolve_windows with depth-2
           pipelining AND double-buffered window staging (stage_window
           packs window k+1 on the background stager while window k's
           blocking resolve waits on the chip) — the ACTUAL serving
           dispatch (scan-form chain kernel per window, W prepares per
           dispatch), so the banked verdict prices the route clients
           hit, overlap included.
  proute   the same pipelined+staged submit_window loop in attach mode:
           the FUSED partitioned-chain route (one shard_map+scan per
           window over account-range-sharded state) on whatever mesh
           exists — best_route_tps is the max over route/proute arms.

If the chain amortizes (per PERF.md's whole-program model), transfers/s
at W prepares per dispatch should approach W x the per-dispatch rate;
if the tunnel op-streams inside a single jit, it won't. Writes
onchip/chain_probe_result.json either way: the artifact that validates
or falsifies the 4-16M whole-program claim for this environment.

Watchdog doctrine (ADVICE r4): the self-deadline arms BEFORE the first
jax import / backend touch — a wedged PJRT_Client_Create must hit the
in-process deadline (which banks a marker artifact) and never the
watcher's SIGKILL-mid-RPC backstop.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # _banking

STACK = 32
AC = 10_000


def _run(res, dump, deadline):
    # First backend touch strictly after the watchdog is armed.
    import numpy as np

    import jax

    jax.config.update("jax_enable_x64", True)

    from tigerbeetle_tpu.benchmark import N, _make_ledger, _soa
    from tigerbeetle_tpu.ops import fast_kernels as fk
    from tigerbeetle_tpu.ops.ledger import stack_superbatch

    res["platform"] = jax.devices()[0].platform
    res["n_per_batch"] = N
    dump()
    evs_per_window = STACK * N

    def mk_windows(n_windows, bi0=0):
        rng = np.random.default_rng(2)
        windows = []
        bi = bi0
        for _ in range(n_windows):
            evs, tss = [], []
            for _ in range(STACK):
                base = 10 ** 7 + bi * N
                ids = np.arange(base, base + N)
                dr = rng.integers(1, AC + 1, N, dtype=np.uint64)
                cr = rng.integers(1, AC + 1, N, dtype=np.uint64)
                clash = dr == cr
                cr[clash] = dr[clash] % AC + 1
                evs.append(_soa(ids, dr, cr, rng.integers(1, 10 ** 6, N)))
                tss.append(10 ** 13 + bi * (N + 10))
                bi += 1
            ev_s, seg = stack_superbatch(evs, tss)
            windows.append((ev_s, seg))
        return windows, bi

    def stack_windows(windows):
        ev_stack = {k: jax.device_put(
            np.stack([np.asarray(w[0][k]) for w in windows]))
            for k in windows[0][0]}
        seg_stack = {k: jax.device_put(
            np.stack([np.asarray(w[1][k]) for w in windows]))
            for k in windows[0][1]}
        return ev_stack, seg_stack

    def run_seq(state, windows):
        poisoned = jax.device_put(np.bool_(False))
        t0 = time.perf_counter()
        for ev_s, seg in windows:
            ev_d = {k: jax.device_put(v) for k, v in ev_s.items()}
            seg_d = {k: jax.device_put(v) for k, v in seg.items()}
            state, out = fk.create_transfers_super_jit(
                state, ev_d, seg_d, poisoned)
            poisoned = out["fallback"]
        jax.block_until_ready(poisoned)
        dt = time.perf_counter() - t0
        assert not bool(jax.device_get(poisoned))
        return state, dt

    def run_chain(state, windows, fn):
        ev_stack, seg_stack = stack_windows(windows)
        t0 = time.perf_counter()
        state, outs = fn(state, ev_stack, seg_stack)
        jax.block_until_ready(outs["fallback"])
        dt = time.perf_counter() - t0
        assert not bool(jax.device_get(outs["fallback"]).any())
        return state, dt

    bi = 0
    # Sequential baseline FIRST (it reuses the bench's already-proven
    # kernel shape and anchors every later ratio even if the window
    # closes mid-probe). Resumed runs skip it.
    if "seq_w1_tps" not in res:
        try:
            led = _make_ledger(AC, a_cap=1 << 15, t_cap=1 << 22)
            warm, bi = mk_windows(1, bi)
            t_c0 = time.perf_counter()
            led.state, _ = run_seq(led.state, warm)
            res["seq_w1_compile_s"] = round(
                time.perf_counter() - t_c0, 1)
            runs = []
            for _ in range(3):
                ws, bi = mk_windows(1, bi)
                led.state, dt = run_seq(led.state, ws)
                runs.append(dt)
            res["seq_w1_ms"] = [round(r * 1e3, 1) for r in runs]
            res["seq_w1_tps"] = round(evs_per_window / min(runs), 1)
        except Exception as e:  # noqa: BLE001
            res["seq_w1_error"] = repr(e)[:300]
        dump()
    # Fresh ledger per measured run: W=8 appends 2.1M rows per run,
    # so a shared ledger would fill its transfer store mid-probe and
    # every later dispatch would hard-fallback (capacity, not the
    # kernel, would be measured). id streams never repeat across
    # ledgers (bi keeps advancing), so dup checks stay cold.
    # Scan-form only: wholeprog_probe's banked verdict (20260802)
    # says the scan form amortizes, and the unrolled programs are
    # what blew the first run's compile budget.
    for fname, fn in (
            ("chain", fk.create_transfers_chain_jit),):
        for W in (2, 4, 8):
            key = f"{fname}_w{W}"
            if key + "_tps" in res:
                continue  # banked by an earlier run
            if time.monotonic() > deadline:
                res["deadline_hit"] = f"before {key}"
                break
            try:
                led = _make_ledger(AC, a_cap=1 << 15, t_cap=1 << 22)
                warmw, bi = mk_windows(W, bi)
                t_c0 = time.perf_counter()
                led.state, _ = run_chain(led.state, warmw, fn)
                res[key + "_compile_s"] = round(
                    time.perf_counter() - t_c0, 1)
                runs = []
                for _ in range(2):
                    led = _make_ledger(AC, a_cap=1 << 15,
                                       t_cap=1 << 22)
                    ws, bi = mk_windows(W, bi)
                    led.state, dt = run_chain(led.state, ws, fn)
                    runs.append(dt)
                best = min(runs)
                res[key + "_ms"] = [round(r * 1e3, 1) for r in runs]
                res[key + "_tps"] = round(
                    W * evs_per_window / best, 1)
            except Exception as e:  # noqa: BLE001 — record, go on
                res[key + "_error"] = repr(e)[:300]
            dump()

    # ---- the REAL serving route: submit_window/resolve_windows with
    # depth-2 pipelining, W prepares per chain dispatch (the default
    # dispatch mode since round 7). These are the numbers the serving
    # path actually delivers — route_wN_tps is the banked verdict's
    # primary arm now.
    def mk_prepares(n_windows, w, bi0):
        rng = np.random.default_rng(3)
        out = []
        bi = bi0
        for _ in range(n_windows):
            evs, tss = [], []
            for _ in range(w):
                base = 2 * 10 ** 8 + bi * N
                ids = np.arange(base, base + N)
                dr = rng.integers(1, AC + 1, N, dtype=np.uint64)
                cr = rng.integers(1, AC + 1, N, dtype=np.uint64)
                clash = dr == cr
                cr[clash] = dr[clash] % AC + 1
                evs.append(_soa(ids, dr, cr,
                                rng.integers(1, 10 ** 6, N)))
                tss.append(2 * 10 ** 13 + bi * (N + 10))
                bi += 1
            out.append((evs, tss))
        return out, bi

    def run_route(led, windows, route="chain"):
        pending = []
        t0 = time.perf_counter()
        for i, (evs, tss) in enumerate(windows):
            tk = led.submit_window(evs, tss)
            assert tk is not None, "route arm fell off the pipeline"
            pending.append(tk)
            # Double-buffered staging: window k+1's pack + transfer
            # runs on the background stager while the resolve below
            # blocks on window k-1's device execution (ISSUE 16 — the
            # submit above consumes the previous iteration's stage).
            if i + 1 < len(windows):
                led.stage_window(*windows[i + 1])
            if len(pending) > 1:
                led.resolve_windows(count=1)
                pending.pop(0)
        led.resolve_windows()
        dt = time.perf_counter() - t0
        stats = led.fallback_stats()
        assert stats["routes"]["windows"].get(route, 0) >= 1, stats
        assert stats["host_fallbacks"] == 0, stats
        if len(windows) > 1:
            assert stats["staging"]["staged"] >= 1, stats["staging"]
        led.shutdown_staging()
        return dt

    bi_r = 0
    for W in (8, 32):
        key = f"route_w{W}"
        if key + "_tps" in res:
            continue
        if time.monotonic() > deadline:
            res.setdefault("deadline_hit", f"before {key}")
            break
        try:
            led = _make_ledger(AC, a_cap=1 << 15, t_cap=1 << 22)
            warm, bi_r = mk_prepares(2, W, bi_r)
            t_c0 = time.perf_counter()
            run_route(led, warm)
            res[key + "_compile_s"] = round(time.perf_counter() - t_c0, 1)
            runs = []
            for _ in range(2):
                led = _make_ledger(AC, a_cap=1 << 15, t_cap=1 << 22)
                ws, bi_r = mk_prepares(2, W, bi_r)
                runs.append(run_route(led, ws))
            best = min(runs)
            res[key + "_ms"] = [round(r * 1e3, 1) for r in runs]
            res[key + "_tps"] = round(2 * W * N / best, 1)
        except Exception as e:  # noqa: BLE001 — record, go on
            res[key + "_error"] = repr(e)[:300]
        dump()

    # ---- the FUSED partitioned-chain route through the same pipelined
    # + staged submit_window loop, in attach mode on whatever mesh
    # exists (1 chip degenerates gracefully; the chip pod is the real
    # target): one shard_map+lax.scan dispatch per W-prepare window
    # over account-range-sharded state. proute_wN_tps extends the
    # serving-route record with the partitioned tier's own number.
    from jax.sharding import Mesh

    from tigerbeetle_tpu.oracle import StateMachineOracle
    from tigerbeetle_tpu.ops.ledger import DeviceLedger
    from tigerbeetle_tpu.parallel.partitioned import PartitionedRouter
    from tigerbeetle_tpu.types import Account

    def mk_partitioned():
        mesh = Mesh(np.array(jax.devices()), ("batch",))
        router = PartitionedRouter(mesh, a_cap=1 << 15, t_cap=1 << 19)
        orc = StateMachineOracle()
        orc.create_accounts([Account(id=i, ledger=1, code=1)
                             for i in range(1, AC + 1)], AC + 10)
        led = DeviceLedger(a_cap=1 << 12, t_cap=1 << 14)
        led.attach_partitioned(router, router.from_oracle(orc))
        return led

    res["proute_n_shards"] = len(jax.devices())
    bi_p = 0
    for W in (2, 8):
        key = f"proute_w{W}"
        if key + "_tps" in res:
            continue
        if time.monotonic() > deadline:
            res.setdefault("deadline_hit", f"before {key}")
            break
        try:
            led = mk_partitioned()
            warm, bi_p = mk_prepares(2, W, bi_p)
            t_c0 = time.perf_counter()
            run_route(led, warm, route="partitioned_chain")
            res[key + "_compile_s"] = round(
                time.perf_counter() - t_c0, 1)
            runs = []
            for _ in range(2):
                led = mk_partitioned()
                ws, bi_p = mk_prepares(2, W, bi_p)
                runs.append(run_route(led, ws,
                                      route="partitioned_chain"))
            best = min(runs)
            res[key + "_ms"] = [round(r * 1e3, 1) for r in runs]
            res[key + "_tps"] = round(2 * W * N / best, 1)
        except Exception as e:  # noqa: BLE001 — record, go on
            res[key + "_error"] = repr(e)[:300]
        dump()

    if "deadline_hit" not in res and "alarm" not in res:
        # The watcher re-runs this probe in later windows until a
        # COMPLETE artifact lands (partial ones bank data but must
        # not suppress the remaining arms).
        res["complete"] = True


def main():
    res = {"stack": STACK}
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "chain_probe_result.json")

    from _banking import make_dumper, resume_from, start_watchdog

    # Resume: arms banked by an earlier (deadline-cut) run are carried
    # over and skipped, so a re-run extends the artifact instead of
    # regressing it.
    resume_from(out_path, res,
                keep=lambda k: k.startswith(("seq_w1_", "chain_w",
                                             "route_w", "proute_w")))
    dump = make_dumper(res, out_path)

    def verdict(target=None):
        target = res if target is None else target
        # Only the measured arms (chain_wN_tps / route_wN_tps) — NOT
        # best_chain_tps, which an earlier verdict() call may have
        # written (the watchdog can re-enter verdict() on a snapshot
        # taken after finally).
        chain_arms = [v for k, v in target.items()
                      if k.startswith(("chain_w", "route_w"))
                      and k.endswith("_tps") and v is not None]
        route_arms = [v for k, v in target.items()
                      if k.startswith(("route_w", "proute_w"))
                      and k.endswith("_tps") and v is not None]
        seq = target.get("seq_w1_tps", 0)
        if not chain_arms:
            # A deadline-cut run with zero chain arms must not bank a
            # definitive negative for the round's central claim.
            target["verdict"] = "INSUFFICIENT DATA: no chain arm completed"
            target["best_chain_tps"] = None
            return
        chain_tps = max(chain_arms)
        target["verdict"] = (
            "WHOLE-PROGRAM AMORTIZES on the real kernel"
            if seq and chain_tps > 1.5 * seq else
            "whole-program chain does NOT beat sequential dispatch here")
        target["best_chain_tps"] = chain_tps
        # Serving-route record: the best number the overlapped
        # submit_window pipeline delivered across the single-chip chain
        # (route_wN) and fused partitioned-chain (proute_wN) arms — the
        # rate clients actually see.
        target["best_route_tps"] = max(route_arms) if route_arms else None

    def _on_deadline():
        # Work on a snapshot: mutating res while the main thread is
        # mid-json.dump would corrupt BOTH writers' output.
        snap = dict(res)
        snap["alarm"] = ("watchdog: deadline exceeded mid-call" +
                         ("" if "platform" in res
                          else " (wedged during PJRT init)"))
        verdict(snap)
        dump(snap)

    # Self-deadline (see onchip/_banking.py doctrine): armed BEFORE the
    # first jax import (ADVICE r4 medium); the in-loop deadline ends
    # the probe between arms; the watchdog thread is the backstop for a
    # single over-budget blocking compile.
    deadline = start_watchdog("PROBE_DEADLINE_S", 2700.0, _on_deadline,
                              grace_s=60.0)

    try:
        _run(res, dump, deadline)
    finally:
        # The artifact lands no matter how the measurement dies
        # (docstring contract: "writes chain_probe_result.json either
        # way").
        verdict()
        print(json.dumps(res, indent=1))
        dump()


if __name__ == "__main__":
    main()
