"""Per-kernel op-budget ledger: heavy-op counts + operand bytes, gated.

The tunnel regime bills ~0.5-1 ms per *executed op* inside large
programs, with a bytes-dependent term (PERF.md dispatch model), so the
one portable lever is fewer, fatter ops. This module makes that lever
un-regressable:

  - census: jaxpr-level heavy-op counts by class (sort / gather /
    scatter / segment_sum / scan — tigerbeetle_tpu.jaxhound.heavy_census)
    plus the operand bytes those ops read, for every create_transfers
    kernel tier INCLUDING the SPMD lowerings (8-device CPU mesh).
  - budgets: perf/opbudget_r09.json commits a per-tier budget. A kernel
    change that raises any tier's heavy-op count or operand bytes past
    its budget fails `--check` (wired into scripts/gate.py) — raising a
    budget is an explicit, reviewed edit of the JSON (see
    ARCHITECTURE.md "Op-budget workflow"). Round 7 added the CHAIN
    entries: the scan-form whole-window route's whole-program census
    (chain_w{2,8,32} — ~constant in window depth, the route's whole
    point) and its per-iteration BODY census (chain_body_w8, via
    jaxhound.scan_body_census — pinned <= the per-batch plain tier).
    Round 8 adds the PARTITIONED tiers (sharded state, on-device
    exchange): cross-device collectives are a counted class
    ('collective'), so the budget pins the exchange's op count, and
    the lints additionally reject any collective moving a whole-state
    operand (jaxhound.state_gathers). Round 9 fuses the two: the
    PARTITIONED CHAIN tiers census the whole-window scan dispatch over
    sharded state (partitioned_chain_w{2,8,32} — whole-program, flat
    in W) and its per-iteration body (partitioned_chain_body, via
    scan_body_census — pinned == the per-batch partitioned_plain tier,
    collectives INSIDE the scan body included, with their ICI byte
    mass broken out as collective_operand_bytes).
  - lints: `--lint` runs the jaxhound static checks over the serving-
    path jit entries: no closure constant > 4 KiB (the measured
    ~64 ms/call tunnel intercept), no while/fori loop in any serving
    lowering (the measured 5-8 ms process-wide degradation) beyond an
    entry's declared allowance (the chain entries' ONE deliberate scan
    lowers to one stablehlo.while; everything else allows zero), and
    every state-carrying entry donates its ledger buffers
    (donated-input count == state leaf count in the lowered artifact —
    the chain entries are audited too, incl. the unrolled form).

CLI:
    python perf/opbudget.py             # print the census table
    python perf/opbudget.py --check    # fail (rc=1) on budget excess
    python perf/opbudget.py --lint     # fail (rc=1) on lint violations
    python perf/opbudget.py --write    # refresh the 'post' column of
                                       # the budget file IN PLACE
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The sharded tiers trace against an 8-device CPU mesh in-process.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tigerbeetle_tpu import jaxhound  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# The budget trail is append-oriented (a new opbudget_r<N>.json per
# round that moves a pinned census); always check/write the head.
BUDGET_PATH = jaxhound.newest_budget_path(os.path.join(REPO, "perf"))

STACK = 4
N_SUPER = 1024
# Chain-route census depths: the whole-program census must be
# ~constant across these (the scan body lowers once).
CHAIN_DEPTHS = (2, 8, 32)


def _mk_prepares(n_prepares, n=N_SUPER, nid0=10 ** 6, seed=0):
    from tigerbeetle_tpu.benchmark import _soa

    rng = np.random.default_rng(seed)
    evs, tss = [], []
    nid = nid0
    for b in range(n_prepares):
        dr = rng.integers(1, 64, n, dtype=np.uint64)
        cr = (dr % 63) + 1
        evs.append(_soa(np.arange(nid, nid + n), dr, cr,
                        rng.integers(1, 100, n)))
        nid += n
        tss.append(10 ** 12 + b * (n + 10))
    return evs, tss


def _fixtures():
    from tigerbeetle_tpu.ops.batch import transfers_to_arrays
    from tigerbeetle_tpu.ops.ledger import (
        init_state, pad_transfer_events, stack_superbatch)
    from tigerbeetle_tpu.types import Transfer

    state = init_state(1 << 10, 1 << 12)
    ev = pad_transfer_events(transfers_to_arrays(
        [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                  amount=1, ledger=1, code=1)]))
    evs, tss = _mk_prepares(STACK)
    ev_s, seg = stack_superbatch(evs, tss)
    return state, ev, ev_s, seg


def _chain_fixture(depth):
    from tigerbeetle_tpu.ops.ledger import stack_chain_window

    evs, tss = _mk_prepares(depth)
    return stack_chain_window(evs, tss, N_SUPER)


def _partitioned_chain_fixture(depth):
    from tigerbeetle_tpu.parallel.partitioned import (
        stack_partitioned_window)

    evs, tss = _mk_prepares(depth)
    return stack_partitioned_window(evs, tss, N_SUPER)


def _partitioned_fixture(mesh, axis="batch"):
    """Stacked empty partitioned state over `mesh` (per-shard caps =
    the replicated fixture caps / n_shards; the census and lints only
    need shapes, not contents)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tigerbeetle_tpu.ops.ledger import init_state

    n = mesh.shape[axis]
    sub = jax.tree.map(np.asarray, init_state(
        (1 << 10) // n, (1 << 12) // n, orphan_cap=(1 << 16) // n))
    stacked = jax.tree.map(lambda x: np.stack([x] * n), sub)
    return jax.device_put(stacked, NamedSharding(mesh, P(axis)))


def census_tiers(include_sharded: bool = True,
                 only: tuple | None = None) -> dict:
    """tier name -> heavy_census dict for every kernel tier. `only`
    restricts to a named subset (bench.py's light ##opbudget line)."""
    from tigerbeetle_tpu.ops import fast_kernels as fk

    state, ev, ev_s, seg = _fixtures()
    N = ev["id_lo"].shape[0]
    ts = np.uint64(1000)
    n = np.int32(1)
    ts_vec = jnp.full((N,), 1000, jnp.uint64)
    idxs = jnp.arange(N, dtype=jnp.int32)

    def pe_plain(state, ev, ts_vec):
        return fk.per_event_status(state, ev, ts_vec)

    def pe_imported(state, ev, ts_vec):
        ctx = fk.imported_batch_ctx(state, ev, ts_vec, ev["valid"], idxs)
        return fk.per_event_status(state, ev, ts_vec, imported_ctx=ctx)

    def super_(limit_rounds):
        def f(state, ev_s, seg):
            return fk.create_transfers_fast(
                state, ev_s, jnp.uint64(0), jnp.int32(0), seg=seg,
                limit_rounds=limit_rounds)
        return f

    tiers = {
        "per_event_plain": (pe_plain, (state, ev, ts_vec)),
        "per_event_imported": (pe_imported, (state, ev, ts_vec)),
        "plain": (fk.create_transfers_fast, (state, ev, ts, n)),
        "imported": (functools.partial(
            fk.create_transfers_fast, imported_mode=True),
            (state, ev, ts, n)),
        "fixpoint_8": (functools.partial(
            fk.create_transfers_fast, limit_rounds=8), (state, ev, ts, n)),
        "fixpoint_deep_32": (functools.partial(
            fk.create_transfers_fast, limit_rounds=32),
            (state, ev, ts, n)),
        "balancing_8": (functools.partial(
            fk.create_transfers_fast, limit_rounds=8,
            balancing_mode=True), (state, ev, ts, n)),
        "imported_fixpoint_8": (functools.partial(
            fk.create_transfers_fast, imported_mode=True, limit_rounds=8),
            (state, ev, ts, n)),
        "super_plain_s4": (super_(1), (state, ev_s, seg)),
        "super_deep24_s4": (super_(
            fk.LIMIT_FIXPOINT_ROUNDS_WINDOW_DEEP), (state, ev_s, seg)),
    }
    out = {}
    for name, (fn, args) in tiers.items():
        if only is not None and name not in only:
            continue
        out[name] = jaxhound.heavy_census(jax.make_jaxpr(fn)(*args))
    # Chain route (the default whole-window scan dispatch): the
    # whole-program census at three depths — ~constant heavy totals
    # prove the scan body lowers once — plus the per-iteration BODY
    # census the gate pins against the per-batch plain tier.
    chain_names = tuple(f"chain_w{w}" for w in CHAIN_DEPTHS) + (
        "chain_body_w8",)
    if only is None or any(n in only for n in chain_names):
        for w in CHAIN_DEPTHS:
            name = f"chain_w{w}"
            if only is not None and name not in only and not (
                    w == 8 and "chain_body_w8" in only):
                continue
            ev_c, seg_c = _chain_fixture(w)
            cj = jax.make_jaxpr(fk._create_transfers_chain)(
                state, ev_c, seg_c)
            if only is None or name in only:
                out[name] = jaxhound.heavy_census(cj)
            if w == 8 and (only is None or "chain_body_w8" in only):
                out["chain_body_w8"] = jaxhound.scan_body_census(cj)
    if only is not None:
        include_sharded = False
    if include_sharded and len(jax.devices()) >= 8:
        from jax.sharding import Mesh
        from tigerbeetle_tpu.parallel.full_sharded import (
            make_sharded_create_transfers)

        mesh = Mesh(np.array(jax.devices()[:8]), ("batch",))
        for mode in ("plain", "fixpoint"):
            step = make_sharded_create_transfers(mesh, mode=mode)
            with mesh:
                cj = jax.make_jaxpr(
                    lambda st, e: step.__wrapped__(
                        st, e, jnp.uint64(1000), jnp.int32(1)))(state, ev)
            out[f"sharded_{mode}"] = jaxhound.heavy_census(cj)
        # Partitioned tiers (sharded STATE + on-device exchange): the
        # 'collective' class pins the exchange's ICI round trips.
        from tigerbeetle_tpu.parallel.partitioned import (
            make_partitioned_create_transfers)

        pstate = _partitioned_fixture(mesh)
        for mode in ("plain", "fixpoint"):
            pstep = make_partitioned_create_transfers(mesh, mode=mode)
            with mesh:
                cj = jax.make_jaxpr(
                    lambda st, e: pstep.__wrapped__(
                        st, e, jnp.uint64(1000), jnp.int32(1)))(pstate, ev)
            out[f"partitioned_{mode}"] = jaxhound.heavy_census(cj)
        # Partitioned CHAIN (the fused default window route): the
        # whole-program census must be flat across depths (the scan
        # body — exchange collectives included — lowers ONCE), and the
        # per-iteration BODY census is pinned == the per-batch
        # partitioned_plain tier: the window amortizes dispatch, it
        # must not add op mass per prepare.
        from tigerbeetle_tpu.parallel.partitioned import (
            make_partitioned_chain_create_transfers)

        cstep = make_partitioned_chain_create_transfers(mesh, mode="plain")
        for w in CHAIN_DEPTHS:
            ev_p, ts_p, n_p = _partitioned_chain_fixture(w)
            with mesh:
                cj = jax.make_jaxpr(
                    lambda st, e, t, nn: cstep.__wrapped__(
                        st, e, t, nn, None))(pstate, ev_p, ts_p, n_p)
            out[f"partitioned_chain_w{w}"] = jaxhound.heavy_census(cj)
            if w == 8:
                out["partitioned_chain_body"] = \
                    jaxhound.scan_body_census(cj)
    return out


def serving_entries() -> dict:
    """name -> (lowered artifact, expected donated-input count, allowed
    while count) for the state-carrying jit entries on the serving/scan
    paths. The chain entries allow exactly ONE stablehlo.while (their
    deliberate lax.scan); everything else allows zero."""
    from tigerbeetle_tpu.ops import fast_kernels as fk

    state, ev, ev_s, seg = _fixtures()
    n_leaves = len(jax.tree_util.tree_leaves(state))
    ts = np.uint64(1000)
    n = np.int32(1)
    entries = {}

    def add(name, jitfn, *args, max_while=0):
        entries[name] = (jitfn.lower(*args), n_leaves, max_while)

    add("create_transfers_fast_jit", fk.create_transfers_fast_jit,
        state, ev, ts, n)
    add("create_transfers_fixpoint_jit", fk.create_transfers_fixpoint_jit,
        state, ev, ts, n)
    add("create_transfers_fixpoint_deep_jit",
        fk.create_transfers_fixpoint_deep_jit, state, ev, ts, n)
    add("create_transfers_balancing_jit",
        fk.create_transfers_balancing_jit, state, ev, ts, n)
    add("create_transfers_imported_jit",
        fk.create_transfers_imported_jit, state, ev, ts, n)
    add("create_transfers_imported_fixpoint_jit",
        fk.create_transfers_imported_fixpoint_jit, state, ev, ts, n)
    add("create_transfers_super_jit", fk.create_transfers_super_jit,
        state, ev_s, seg)
    add("create_transfers_super_deep_jit",
        fk.create_transfers_super_deep_jit, state, ev_s, seg)
    add("create_transfers_super_ring_jit",
        fk.create_transfers_super_ring_jit, state, ev_s, seg)
    add("create_transfers_super_deep_ring_jit",
        fk.create_transfers_super_deep_ring_jit, state, ev_s, seg)
    add("create_transfers_super_balancing_jit",
        fk.create_transfers_super_balancing_jit, state, ev_s, seg)
    # Chain entries (the default whole-window route): the scan form's
    # one deliberate while is allowed; the unrolled fallback form must
    # stay straight-line — and BOTH must donate the state carry
    # (create_transfers_chain_unrolled_jit used to escape this audit
    # because only per-batch tiers were enumerated here).
    ev_c, seg_c = _chain_fixture(4)
    add("create_transfers_chain_jit", fk.create_transfers_chain_jit,
        state, ev_c, seg_c, max_while=1)
    add("create_transfers_chain_ring_jit",
        fk.create_transfers_chain_ring_jit, state, ev_c, seg_c,
        max_while=1)
    add("create_transfers_chain_unrolled_jit",
        fk.create_transfers_chain_unrolled_jit, state, ev_c, seg_c)
    # Sharded steps (8-device CPU mesh): same donation contract.
    if len(jax.devices()) >= 8:
        from jax.sharding import Mesh
        from tigerbeetle_tpu.parallel.full_sharded import (
            make_sharded_create_transfers)

        mesh = Mesh(np.array(jax.devices()[:8]), ("batch",))
        for mode in ("plain", "fixpoint"):
            step = make_sharded_create_transfers(mesh, mode=mode)
            with mesh:
                entries[f"sharded_{mode}_step"] = (
                    step.lower(state, ev, np.uint64(1000), np.int32(1)),
                    n_leaves, 0)
        # Partitioned steps: same donation contract over the stacked
        # (device-sharded) state pytree.
        from tigerbeetle_tpu.parallel.partitioned import (
            make_partitioned_create_transfers)

        pstate = _partitioned_fixture(mesh)
        for mode in ("plain", "fixpoint"):
            pstep = make_partitioned_create_transfers(mesh, mode=mode)
            with mesh:
                entries[f"partitioned_{mode}_step"] = (
                    pstep.lower(pstate, ev, np.uint64(1000), np.int32(1)),
                    n_leaves, 0)
        # Partitioned chain step: one deliberate scan (max_while=1),
        # donated sharded state carry.
        from tigerbeetle_tpu.parallel.partitioned import (
            make_partitioned_chain_create_transfers)

        cstep = make_partitioned_chain_create_transfers(mesh, mode="plain")
        ev_p, ts_p, n_p = _partitioned_chain_fixture(4)
        with mesh:
            entries["partitioned_chain_step"] = (
                cstep.lower(pstate, ev_p, ts_p, n_p, None),
                n_leaves, 1)
    return entries


def run_lints() -> list[str]:
    """Serving-path static lints (jaxhound): closure constants, while
    loops, donation. Returns human-readable failure strings."""
    fails = []
    for name, (lowered, n_donate, max_while) in serving_entries().items():
        # The serving path must stay straight-line: lax.scan/while both
        # lower to stablehlo.while. The chain entries declare their ONE
        # deliberate scan (max_while=1); anything beyond an entry's
        # allowance — e.g. a searchsorted left on the default scan
        # method — is a red.
        text = lowered.as_text()
        n_while = text.count("stablehlo.while")
        if n_while > max_while:
            fails.append(
                f"{name}: {n_while} while loop(s) in the lowering "
                f"(> allowed {max_while}; one executed while degrades "
                "every later dispatch to 5-8 ms — PERF.md)")
        donated = jaxhound.donated_inputs(lowered)
        if donated < n_donate:
            fails.append(
                f"{name}: {donated} donated inputs < {n_donate} state "
                "leaves (missing donate_argnums => every dispatch pays "
                "a full state copy)")
    # Closure constants are a trace-level property: re-trace the raw fns.
    from tigerbeetle_tpu.ops import fast_kernels as fk

    state, ev, ev_s, seg = _fixtures()
    ev_c, seg_c = _chain_fixture(4)
    for name, fn, args in (
            ("create_transfers_fast", fk.create_transfers_fast,
             (state, ev, np.uint64(1000), np.int32(1))),
            ("create_transfers_super",
             lambda st, e, s: fk.create_transfers_fast(
                 st, e, jnp.uint64(0), jnp.int32(0), seg=s),
             (state, ev_s, seg)),
            ("create_transfers_chain", fk._create_transfers_chain,
             (state, ev_c, seg_c)),
    ):
        big = jaxhound.closure_constants(jax.make_jaxpr(fn)(*args))
        for label, size in big:
            fails.append(
                f"{name}: closure constant {label} = {size} B > "
                f"{jaxhound.CLOSURE_CONST_LIMIT} B (the tunnel re-ships "
                "baked constants every call: ~64 ms at 0.5 MB — PERF.md)")
    # Partitioned entries: the exchange must never regress into moving
    # whole-state operands through a collective — that would rebuild
    # the replicated route inside the partitioned one.
    if len(jax.devices()) >= 8:
        from jax.sharding import Mesh

        from tigerbeetle_tpu.parallel.partitioned import (
            make_partitioned_create_transfers)

        mesh = Mesh(np.array(jax.devices()[:8]), ("batch",))
        pstate = _partitioned_fixture(mesh)
        for mode in ("plain", "fixpoint"):
            pstep = make_partitioned_create_transfers(mesh, mode=mode)
            with mesh:
                cj = jax.make_jaxpr(
                    lambda st, e: pstep.__wrapped__(
                        st, e, jnp.uint64(1000), jnp.int32(1)))(pstate, ev)
            for prim, nbytes in jaxhound.state_gathers(cj):
                fails.append(
                    f"partitioned_{mode}_step: {prim} moves {nbytes} B "
                    f"per device (> {jaxhound.STATE_GATHER_LIMIT} B — "
                    "the exchange regressed into a whole-state gather)")
            for label, size in jaxhound.closure_constants(cj):
                fails.append(
                    f"partitioned_{mode}_step: closure constant {label} "
                    f"= {size} B > {jaxhound.CLOSURE_CONST_LIMIT} B")
        # The fused chain runs the exchange INSIDE its scan body;
        # state_gathers recurses into scan bodies, so a whole-state
        # collective can't hide behind the scan either.
        from tigerbeetle_tpu.parallel.partitioned import (
            make_partitioned_chain_create_transfers)

        cstep = make_partitioned_chain_create_transfers(mesh, mode="plain")
        ev_p, ts_p, n_p = _partitioned_chain_fixture(4)
        with mesh:
            cj = jax.make_jaxpr(
                lambda st, e, t, nn: cstep.__wrapped__(
                    st, e, t, nn, None))(pstate, ev_p, ts_p, n_p)
        for prim, nbytes in jaxhound.state_gathers(cj):
            fails.append(
                f"partitioned_chain_step: {prim} moves {nbytes} B "
                f"per device (> {jaxhound.STATE_GATHER_LIMIT} B — "
                "the scanned exchange regressed into a whole-state "
                "gather)")
        for label, size in jaxhound.closure_constants(cj):
            fails.append(
                f"partitioned_chain_step: closure constant {label} "
                f"= {size} B > {jaxhound.CLOSURE_CONST_LIMIT} B")
    return fails


def telemetry_report() -> dict:
    """Census the device-telemetry plane of the fused partitioned
    chain (round 10): the pack's lane count (jaxhound.telemetry_census
    — the telemetry block cannot grow a word silently), and the
    telemetry-on vs telemetry-off DELTA of the scan body's heavy
    census (the pack is elementwise + a named stack, so the pinned
    allowance is zero heavy ops — observability must ride the existing
    op mass, not add to it). Returns {} on < 8 devices (the
    partitioned tiers need the mesh)."""
    if len(jax.devices()) < 8:
        return {}
    from jax.sharding import Mesh
    from tigerbeetle_tpu.parallel.partitioned import (
        make_partitioned_chain_create_transfers)

    mesh = Mesh(np.array(jax.devices()[:8]), ("batch",))
    pstate = _partitioned_fixture(mesh)
    ev_p, ts_p, n_p = _partitioned_chain_fixture(8)
    bodies = {}
    tel = None
    for on in (True, False):
        cstep = make_partitioned_chain_create_transfers(
            mesh, mode="plain", telemetry=on)
        with mesh:
            cj = jax.make_jaxpr(
                lambda st, e, t, nn: cstep.__wrapped__(
                    st, e, t, nn, None))(pstate, ev_p, ts_p, n_p)
        bodies[on] = jaxhound.scan_body_census(cj)["heavy_total"]
        if on:
            tel = jaxhound.telemetry_census(cj)
    return {
        "lanes": tel["lanes"],
        "pack_sites": tel["sites"],
        "pack_ops": tel["ops"],
        "chain_body_heavy_on": bodies[True],
        "chain_body_heavy_off": bodies[False],
        "chain_body_heavy_delta": bodies[True] - bodies[False],
    }


def check_telemetry(report: dict | None = None) -> list[str]:
    """Gate leg: the telemetry-lane census vs the committed budget's
    `telemetry` section. Reds when the pack grows lanes/ops past the
    committed words, when the pack disappeared from the fused route
    (dead telemetry plane), or when the scan body's heavy-op delta
    exceeds the pinned allowance."""
    with open(BUDGET_PATH) as f:
        committed = json.load(f)
    budget = committed.get("telemetry")
    if budget is None:
        return [f"{os.path.basename(BUDGET_PATH)} has no 'telemetry' "
                "section (run --write on >= 8 devices)"]
    if report is None:
        report = telemetry_report()
    if not report:
        return []  # no mesh: the partitioned tiers are not censusable
    fails = []
    if report["lanes"] != budget["lanes"]:
        fails.append(
            f"telemetry lanes {report['lanes']} != committed "
            f"{budget['lanes']} (TEL_LAYOUT changed without a budget "
            "bump — commit a new opbudget round)")
    if report["pack_sites"] < 1:
        fails.append("telemetry pack missing from the fused chain "
                     "route (dead telemetry plane)")
    if report["pack_ops"] > budget["pack_ops"]:
        fails.append(
            f"telemetry pack ops {report['pack_ops']} > committed "
            f"{budget['pack_ops']} (compute smuggled into the "
            "observability plane)")
    delta_max = budget.get("chain_body_heavy_delta_max", 0)
    if report["chain_body_heavy_delta"] > delta_max:
        fails.append(
            f"telemetry heavy-op delta "
            f"{report['chain_body_heavy_delta']} > allowed {delta_max} "
            "(the telemetry block added heavy ops to the scan body)")
    return fails


def check_budgets(current: dict | None = None) -> list[str]:
    """Compare the current census against the committed budgets.
    Returns failure strings (empty = within budget)."""
    with open(BUDGET_PATH) as f:
        committed = json.load(f)
    budgets = committed.get("budget", {})
    if current is None:
        current = census_tiers()
    fails = []
    for tier, budget in budgets.items():
        cur = current.get(tier)
        if cur is None:
            fails.append(f"{tier}: no current census (tier removed? "
                         "update the committed budget JSON)")
            continue
        if cur["heavy_total"] > budget["heavy_total"]:
            fails.append(
                f"{tier}: heavy_total {cur['heavy_total']} > budget "
                f"{budget['heavy_total']}")
        for cls, limit in budget.get("heavy", {}).items():
            if cur["heavy"].get(cls, 0) > limit:
                fails.append(
                    f"{tier}: {cls} count {cur['heavy'].get(cls, 0)} > "
                    f"budget {limit}")
        limit_b = budget.get("heavy_operand_bytes")
        if limit_b is not None and cur["heavy_operand_bytes"] > limit_b:
            fails.append(
                f"{tier}: heavy operand bytes "
                f"{cur['heavy_operand_bytes']} > budget {limit_b}")
    return fails


# Light subset for bench.py's per-run ##opbudget line (the full table
# incl. deep/sharded tiers is the gate's job; tracing them every bench
# run would eat the bench budget). chain_body_w8 is the serving route's
# per-iteration op mass — the number the whole-window dispatch bills W
# times per window.
BENCH_TIERS = ("per_event_plain", "plain", "fixpoint_8",
               "super_plain_s4", "chain_body_w8")


def summary_line(current: dict | None = None) -> dict:
    """Compact per-tier summary for bench.py's ##opbudget line and the
    devhub table."""
    if current is None:
        current = census_tiers(only=BENCH_TIERS)
    return {
        tier: {
            "heavy_total": c["heavy_total"],
            "heavy": c["heavy"],
            "operand_mb": round(c["heavy_operand_bytes"] / 1e6, 2),
        } for tier, c in current.items()
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="fail when any tier exceeds its budget")
    ap.add_argument("--lint", action="store_true",
                    help="run the jaxhound serving-path lints")
    ap.add_argument("--write", action="store_true",
                    help="refresh the budget file's 'post'+'budget' "
                         "columns from the current census")
    args = ap.parse_args()

    current = census_tiers()
    for tier, c in current.items():
        print(f"{tier:24s} heavy={c['heavy_total']:4d} "
              + " ".join(f"{k}={v}" for k, v in c["heavy"].items())
              + f" operand_MB={c['heavy_operand_bytes'] / 1e6:.2f}")

    rc = 0
    tel_report = telemetry_report()
    if tel_report:
        print(f"telemetry                lanes={tel_report['lanes']} "
              f"pack_ops={tel_report['pack_ops']} "
              f"body_delta={tel_report['chain_body_heavy_delta']}")
    if args.write:
        with open(BUDGET_PATH) as f:
            committed = json.load(f)
        committed["post"] = current
        committed["budget"] = {
            t: {"heavy_total": c["heavy_total"], "heavy": c["heavy"],
                "heavy_operand_bytes": c["heavy_operand_bytes"]}
            for t, c in current.items()}
        if tel_report:
            committed["telemetry"] = {
                "lanes": tel_report["lanes"],
                "pack_ops": tel_report["pack_ops"],
                "chain_body_heavy_delta_max": 0,
                # Measured wall-clock bound, enforced by the gate's
                # telemetry leg (testing/telemetry_smoke.py): fused
                # dispatch ms/window with telemetry on vs off.
                "overhead_ratio_max": committed.get(
                    "telemetry", {}).get("overhead_ratio_max", 1.10),
            }
        with open(BUDGET_PATH, "w") as f:
            json.dump(committed, f, indent=1)
        print(f"[opbudget] wrote {BUDGET_PATH}")
    if args.check:
        fails = check_budgets(current) + check_telemetry(tel_report)
        for f_ in fails:
            print(f"[opbudget] OVER BUDGET: {f_}")
        if fails:
            rc = 1
        else:
            print("[opbudget] within budget")
    if args.lint:
        fails = run_lints()
        for f_ in fails:
            print(f"[opbudget] LINT: {f_}")
        if fails:
            rc = 1
        else:
            print("[opbudget] lints clean")
    return rc


if __name__ == "__main__":
    sys.exit(main())
