"""Op-budget table: post-optimization HLO op counts for the kernel tiers.

The tunnel regime bills ~0.5-1 ms per *executed top-level HLO op* inside
large programs (PERF.md); this counts them per kernel tier so the round-4
op-cut work has a before/after table. Fusions count as one op (one
dispatch); the table also splits out the op kinds that dominate.
"""
import collections
import functools
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, "/root/repo")

import jax
import numpy as np

import tigerbeetle_tpu  # noqa: F401
from tigerbeetle_tpu.benchmark import _soa
from tigerbeetle_tpu.ops import fast_kernels as fk
from tigerbeetle_tpu.ops.ledger import init_state, stack_superbatch

STACK = 8
N = 1024


def hlo_opcount(lowered):
    mod = lowered.compile()
    txts = mod.as_text() if isinstance(mod.as_text(), str) else ""
    counts = collections.Counter()
    total = 0
    entry = False
    for line in txts.splitlines():
        s = line.strip()
        if s.startswith("ENTRY "):
            entry = True
            continue
        if entry:
            if s.startswith("}"):
                break
            if "=" in s and not s.startswith("//"):
                rhs = s.split("=", 1)[-1].strip()
                # 'f32[...]{...} opname(' — opname after the type
                parts = rhs.split()
                if len(parts) >= 2:
                    op = parts[1].split("(")[0]
                    counts[op] += 1
                    total += 1
    return total, counts


def shape_args():
    state = init_state(1 << 12, 1 << 16)
    rng = np.random.default_rng(0)
    evs, tss = [], []
    nid = 10 ** 6
    for b in range(STACK):
        dr = rng.integers(1, 64, N, dtype=np.uint64)
        cr = (dr % 63) + 1
        ev = _soa(np.arange(nid, nid + N), dr, cr,
                  rng.integers(1, 100, N))
        nid += N
        evs.append(ev)
        tss.append(10 ** 12 + b * (N + 10))
    ev_s, seg = stack_superbatch(evs, tss)
    return state, ev_s, seg


def main():
    import jax.numpy as jnp
    state, ev_s, seg = shape_args()
    tiers = {
        "plain_super (limit_rounds=1)": dict(limit_rounds=1),
        "fixpoint_8": dict(limit_rounds=8),
        "fixpoint_deep_32": dict(limit_rounds=32),
        "balancing_8": dict(limit_rounds=8, balancing_mode=True),
    }
    rows = []
    for name, kw in tiers.items():
        fn = functools.partial(fk.create_transfers_fast, **kw)
        low = jax.jit(fn, donate_argnums=0).lower(
            state, ev_s, jnp.uint64(0), jnp.int32(0), seg=seg)
        total, counts = hlo_opcount(low)
        heavy = {k: v for k, v in counts.items()
                 if k.split(".")[0] in
                 ("fusion", "scatter", "gather", "sort", "while",
                  "reduce", "reduce-window", "all-reduce", "copy",
                  "dynamic-slice", "dynamic-update-slice", "select-and-scatter")}
        rows.append((name, total, sum(heavy.values()),
                     counts.most_common(10)))
    for name, total, heavy, top in rows:
        print(f"{name:32s} total={total:5d} heavy={heavy:5d} top={top}")
    base = rows[0][2]
    for name, total, heavy, _ in rows[1:]:
        print(f"{name}: heavy-op multiple of plain = {heavy / base:.2f}x")


if __name__ == "__main__":
    main()
