"""Convergence depth at BENCH scale (n=8190, W_PAIRS=4 windows) for the
folded fixpoint — decides whether LIMIT_FIXPOINT_ROUNDS_DEEP can drop."""
import functools
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, "/root/repo")

import importlib

import perf.fixpoint_rounds_probe as P

P.N = 8190
P.W_PAIRS = 4
P.WINDOWS = 6
P.T_CAP = 1 << 19

if __name__ == "__main__":
    for rounds in (24,):
        unconv, fb = P.run(rounds)
        print(f"BENCHSCALE rounds={rounds:2d} "
              f"unconverged={sum(unconv)}/{len(unconv)} {unconv}",
              flush=True)
        if not any(unconv):
            break
