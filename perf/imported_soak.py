"""Extended imported-differential soak: many seeds, bigger batches,
sync windows mixing imported/non-imported prepares — kernel vs oracle
bit-exact or die."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, "/root/repo")

import numpy as np

import tigerbeetle_tpu  # noqa: F401
from tigerbeetle_tpu.oracle.state_machine import StateMachineOracle
from tigerbeetle_tpu.ops.batch import transfers_to_arrays
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import Account, AccountFlags, Transfer, TransferFlags

IMP = int(TransferFlags.imported)
PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
VOID = int(TransferFlags.void_pending_transfer)
AIMP = int(AccountFlags.imported)


def run_seed(seed: int) -> int:
    rng = np.random.default_rng(seed)
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 15)
    ora = StateMachineOracle()
    # Mix imported and regular accounts.
    accs = []
    uts_a = 500
    for i in range(1, 33):
        if rng.random() < 0.4:
            uts_a += int(rng.integers(1, 9))
            accs.append(Account(id=i, ledger=1, code=1, flags=AIMP,
                                timestamp=uts_a))
        else:
            accs.append(Account(id=i, ledger=1, code=1))
    # Homogeneity: oracle requires per-batch; split by kind.
    imp_accs = [a for a in accs if a.flags & AIMP]
    reg_accs = [a for a in accs if not a.flags & AIMP]
    ts = 10 ** 9
    for group in (imp_accs, reg_accs):
        if group:
            g = led.create_accounts(group, ts)
            w = ora.create_accounts(group, ts)
            assert [(x.status, x.timestamp) for x in g] == \
                [(x.status, x.timestamp) for x in w], f"seed {seed} accounts"
            ts += 10 ** 6
    checked = 0
    nid = 10 ** 5
    base_uts = 100_000
    pend_ids: list = []
    for step in range(10):
        use_window = rng.random() < 0.4
        n_batches = int(rng.integers(2, 5)) if use_window else 1
        evs, tss, wants = [], [], []
        for _ in range(n_batches):
            n = int(rng.integers(8, 96))
            batch_imp = bool(rng.integers(0, 2))
            xs = []
            for _ in range(n):
                imp = batch_imp if rng.random() > 0.08 else not batch_imp
                dr = int(rng.integers(1, 33))
                cr = int(rng.integers(1, 33))
                if dr == cr:
                    cr = dr % 32 + 1
                flags = IMP if imp else 0
                kind = rng.random()
                pid = 0
                amt = int(rng.integers(1, 500))
                if kind < 0.12 and pend_ids:
                    flags |= POST if rng.random() < 0.5 else VOID
                    pid = int(rng.choice(pend_ids))
                    if rng.random() < 0.5:
                        amt = (1 << 128) - 1 if flags & POST else 0
                elif kind < 0.3:
                    flags |= PEND
                uts = base_uts + int(rng.integers(-25, 25))
                base_uts += int(rng.integers(0, 10))
                t = Transfer(id=nid, debit_account_id=dr,
                             credit_account_id=cr, amount=amt, ledger=1,
                             code=1, flags=flags, pending_id=pid,
                             timestamp=uts if imp else 0,
                             timeout=int(rng.integers(0, 3))
                             if (flags & PEND and not imp) else 0)
                if flags & (POST | VOID):
                    t.debit_account_id = 0
                    t.credit_account_id = 0
                    t.ledger = 0
                    t.code = 0
                xs.append(t)
                nid += 1
            evs.append(xs)
            tss.append(ts)
            ts += 10 ** 6
        if use_window and n_batches > 1:
            arrays = [transfers_to_arrays(b) for b in evs]
            results = led.create_transfers_window(arrays, tss)
            wants = [ora.create_transfers(b, t)
                     for b, t in zip(evs, tss)]
            assert results is not None  # sync window always returns
            for (st, rts), w in zip(results, wants):
                got = list(zip(st.tolist(), rts.tolist()))
                want = [(int(x.status), x.timestamp) for x in w]
                assert got == want, f"seed {seed} step {step} window"
                checked += len(w)
        else:
            for b, t in zip(evs, tss):
                g = led.create_transfers(b, t)
                w = ora.create_transfers(b, t)
                assert [(x.status, x.timestamp) for x in g] == \
                    [(x.status, x.timestamp) for x in w], \
                    f"seed {seed} step {step}"
                checked += len(w)
                wants.append(w)
        for b, w in zip(evs, wants):
            for t, r in zip(b, w):
                if r.status.name == "created" and t.flags & PEND:
                    pend_ids.append(t.id)
        pend_ids = pend_ids[-64:]
    return checked


if __name__ == "__main__":
    total = 0
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    seeds = range(100, 100 + n_seeds)
    for seed in seeds:
        total += run_seed(seed)
        print(f"seed {seed} ok (cum {total})", flush=True)
    print(f"SOAK CLEAN: {len(list(seeds))} seeds, {total} events diffed")
