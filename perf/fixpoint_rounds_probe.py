"""How many fixpoint rounds does the config4 workload actually need?

Runs the config4 two-phase-under-limits workload shape through the deep
superbatch kernel at several static round budgets and reports, per
window, whether the fixpoint converged (out["fix_unconverged"]) — the
data that decides between adaptive tiering (cheap rounds + escalation)
and a round-body op cut.
"""
import functools
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, "/root/repo")

import jax
import numpy as np

import tigerbeetle_tpu  # noqa: F401  (enables x64)
from tigerbeetle_tpu.benchmark import _soa
from tigerbeetle_tpu.ops import fast_kernels as fk
from tigerbeetle_tpu.ops.ledger import DeviceLedger, stack_superbatch
from tigerbeetle_tpu.types import Account, AccountFlags, TransferFlags

U128_MAX = (1 << 128) - 1
N = 1024
ACCOUNTS = 64
W_PAIRS = 4
WINDOWS = 6
T_CAP = 1 << 18


def mk_workload():
    rng = np.random.default_rng(4)
    limit = int(AccountFlags.debits_must_not_exceed_credits)
    accounts = [Account(id=i, ledger=1, code=1,
                        flags=limit if i % 2 == 0 else 0)
                for i in range(1, ACCOUNTS + 1)]
    pend = int(TransferFlags.pending)
    post = int(TransferFlags.post_pending_transfer)
    void = int(TransferFlags.void_pending_transfer)
    next_id = 10 ** 7
    ts = 10 ** 12
    windows = []
    for _ in range(WINDOWS):
        evs, tss = [], []
        for _ in range(W_PAIRS):
            pend_base = next_id
            next_id += N
            dr = rng.integers(1, ACCOUNTS + 1, N, dtype=np.uint64)
            cr = rng.integers(1, ACCOUNTS + 1, N, dtype=np.uint64)
            clash = dr == cr
            cr[clash] = dr[clash] % ACCOUNTS + 1
            ev = _soa(np.arange(pend_base, pend_base + N), dr, cr,
                      rng.integers(1, 100, N),
                      flags=np.full(N, pend, dtype=np.uint32))
            evs.append(ev); tss.append(ts + N + 10)
            even = np.arange(N) % 2 == 0
            rev = _soa(np.arange(next_id, next_id + N),
                       np.zeros(N, dtype=np.uint64),
                       np.zeros(N, dtype=np.uint64),
                       np.where(even, np.uint64(U128_MAX & ((1 << 64) - 1)),
                                np.uint64(0)),
                       flags=np.where(even, post, void).astype(np.uint32),
                       pid=np.arange(pend_base, pend_base + N))
            rev["amt_hi"] = np.where(even, np.uint64(U128_MAX >> 64),
                                     np.uint64(0))
            rev["ledger"] = np.zeros(N, dtype=np.uint32)
            rev["code"] = np.zeros(N, dtype=np.uint32)
            next_id += N
            evs.append(rev); tss.append(ts + 2 * (N + 10))
            ts += 2 * (N + 10)
        windows.append((evs, tss))
    return accounts, windows


def run(rounds: int):
    accounts, windows = mk_workload()
    led = DeviceLedger(a_cap=1 << 12, t_cap=T_CAP)
    led.create_accounts(accounts, timestamp=ACCOUNTS)
    kern = jax.jit(functools.partial(
        fk.create_transfers_fast, limit_rounds=rounds),
        static_argnames=(), donate_argnums=0)

    unconv = []
    fellback = []
    for evs, tss in windows:
        ev_s, seg = stack_superbatch(evs, tss)
        ev_s = {k: jax.device_put(v) for k, v in ev_s.items()}
        seg = {k: jax.device_put(v) for k, v in seg.items()}
        import jax.numpy as jnp
        new_state, out = kern(led.state, ev_s,
                              jnp.uint64(0), jnp.int32(0), seg=seg)
        led.state = new_state
        unconv.append(bool(jax.device_get(out["fix_unconverged"])))
        fellback.append(bool(jax.device_get(out["fallback"])))
    return unconv, fellback


if __name__ == "__main__":
    for rounds in (14, 16, 20, 24):
        unconv, fb = run(rounds)
        print(f"rounds={rounds:2d} unconverged_windows={sum(unconv)}/"
              f"{len(unconv)} fallback={sum(fb)} per-window={unconv}",
              flush=True)
