"""Cluster-critical constants.

TPU-native rebuild of the reference's two-level comptime config
(reference: src/config.zig:153-163, src/constants.zig). These are the
consensus-critical values that must match across a cluster; they are plain
Python ints here, frozen at import time, and baked into jitted kernels as
static shapes (the TPU analog of comptime).
"""

# --- Wire / message plane (reference: src/config.zig:159, src/vsr/message_header.zig:72) ---
MESSAGE_SIZE_MAX = 1024 * 1024  # 1 MiB
HEADER_SIZE = 256
MESSAGE_BODY_SIZE_MAX = MESSAGE_SIZE_MAX - HEADER_SIZE

# --- Data model (reference: src/tigerbeetle.zig:10-43,85-116) ---
ACCOUNT_SIZE = 128
TRANSFER_SIZE = 128
RESULT_SIZE = 16  # CreateAccountResult / CreateTransferResult (tigerbeetle.zig:471-493)

# Maximum events in one create_accounts/create_transfers batch:
# (1 MiB - 256 B header) / 128 B = 8190 (reference: src/state_machine.zig:336-380,
# docs/concepts/performance.md:27).  This is the static batch shape of the TPU kernel.
BATCH_MAX = MESSAGE_BODY_SIZE_MAX // TRANSFER_SIZE
assert BATCH_MAX == 8190

# --- Integer domains ---
U128_MAX = (1 << 128) - 1
U64_MAX = (1 << 64) - 1
U63_MAX = (1 << 63) - 1
U32_MAX = (1 << 32) - 1
U16_MAX = (1 << 16) - 1

# Timestamps are u63; the MSB of the u64 is reserved as the LSM tombstone flag
# (reference: src/lsm/timestamp_range.zig:5-10).
TIMESTAMP_MIN = 1
TIMESTAMP_MAX = U63_MAX

NS_PER_S = 1_000_000_000

# --- VSR (reference: src/config.zig:153-163) ---
JOURNAL_SLOT_COUNT = 1024
PIPELINE_PREPARE_QUEUE_MAX = 8
CLIENTS_MAX = 64
SUPERBLOCK_COPIES = 4
VSR_OPERATIONS_RESERVED = 128

# --- LSM (reference: src/config.zig:162-163) ---
LSM_LEVELS = 7
LSM_GROWTH_FACTOR = 8
LSM_COMPACTION_OPS = 32  # ops per compaction "bar"
BLOCK_SIZE = 512 * 1024  # grid block size


def timestamp_valid(timestamp: int) -> bool:
    """reference: src/lsm/timestamp_range.zig:36-39"""
    return TIMESTAMP_MIN <= timestamp <= TIMESTAMP_MAX



# --- Extra-check mode (reference: constants.verify, src/fuzz_tests.zig:11-16,
# docs/internals/vopr.md:48-57): expensive cross-structure invariant checks
# kept OFF on the serving path and switched ON under fuzz / VOPR / the
# deterministic simulator. Call sites read `constants.VERIFY` through the
# module (never `from ... import VERIFY` — that would freeze the value).
import os as _os

VERIFY = _os.environ.get("TB_VERIFY", "") == "1"


def set_verify(on: bool) -> None:
    global VERIFY
    VERIFY = bool(on)


def config_fingerprint(extra: tuple = ()) -> int:
    """Fingerprint of the CLUSTER-critical configuration (the reference's
    ConfigCluster, src/config.zig:153-163: parameters that must match
    across every replica of a cluster). Covers the protocol constants
    here plus `extra` — the replica passes its storage-layout geometry
    (WAL slot count, message size, grid block size), which lives on the
    layout rather than in this module. Replicas exchange the fingerprint
    on pings and refuse a mismatched peer's traffic: a mixed-config
    cluster would corrupt journals and quorum math silently."""
    import hashlib

    material = ",".join(str(x) for x in (
        MESSAGE_SIZE_MAX, MESSAGE_BODY_SIZE_MAX, BATCH_MAX,
        PIPELINE_PREPARE_QUEUE_MAX, TIMESTAMP_MAX, *extra))
    return int.from_bytes(
        hashlib.blake2b(material.encode(), digest_size=8).digest(), "little")
