"""StateMachine shell: operation dispatch, queries, and the wire boundary.

The host-side counterpart of the reference StateMachine
(src/state_machine.zig:222 StateMachineType): owns the authoritative state
store, routes create batches through the TPU validation kernels
(ops/create_kernels.py — bit-exact vs the oracle), serves lookups and
queries, schedules the expiry pulse, and encodes/decodes operation bodies
(including the multi-batch trailer, src/vsr/multi_batch.zig).

Queries are served from incrementally-maintained secondary indexes — the
host analog of the reference's 33 LSM index trees (tree ids at
src/state_machine.zig:45-90). Index lists are keyed by field value and hold
timestamps in ascending commit order (imported-timestamp regression checks
guarantee inserts are timestamp-monotonic per groove).
"""

from __future__ import annotations

import dataclasses
import struct
import time as _time
from typing import Optional

from . import multi_batch
from .constants import (
    MESSAGE_BODY_SIZE_MAX,
    TIMESTAMP_MAX,
    U128_MAX,
)
from .oracle.state_machine import AccountEventRecord, StateMachineOracle
from .types import (
    Account,
    AccountBalance,
    AccountFilter,
    AccountFilterFlags,
    AccountFlags,
    ChangeEvent,
    ChangeEventType,
    ChangeEventsFilter,
    CreateAccountResult,
    CreateTransferResult,
    CreateTransferStatus,
    Operation,
    QueryFilter,
    QueryFilterFlags,
    Transfer,
    TransferFlags,
    TransferPendingStatus,
)

__all__ = ["StateMachine", "OperationSpec", "OPERATION_SPECS", "ProtocolError"]


class ProtocolError(ValueError):
    """Malformed operation body (the replica rejects the request;
    reference: input_valid / batch en/decode errors)."""


@dataclasses.dataclass(frozen=True)
class OperationSpec:
    """Wire shape of one operation (reference: src/tigerbeetle.zig:717-785
    EventType/ResultType per operation)."""

    event_size: int
    result_size: int
    sparse_results: bool = False  # deprecated {index, result} encoding

    def event_max(self, body_max: int = MESSAGE_BODY_SIZE_MAX) -> int:
        return body_max // self.event_size if self.event_size else 0

    def result_max(self, body_max: int = MESSAGE_BODY_SIZE_MAX) -> int:
        return body_max // self.result_size if self.result_size else 0


OPERATION_SPECS: dict[Operation, OperationSpec] = {
    Operation.pulse: OperationSpec(0, 0),
    Operation.create_accounts: OperationSpec(128, 16),
    Operation.create_transfers: OperationSpec(128, 16),
    Operation.lookup_accounts: OperationSpec(16, 128),
    Operation.lookup_transfers: OperationSpec(16, 128),
    Operation.get_account_transfers: OperationSpec(128, 128),
    Operation.get_account_balances: OperationSpec(128, 128),
    Operation.query_accounts: OperationSpec(64, 128),
    Operation.query_transfers: OperationSpec(64, 128),
    Operation.get_change_events: OperationSpec(64, 384),
    Operation.deprecated_create_accounts_unbatched: OperationSpec(128, 8, True),
    Operation.deprecated_create_transfers_unbatched: OperationSpec(128, 8, True),
    Operation.deprecated_create_accounts_sparse: OperationSpec(128, 8, True),
    Operation.deprecated_create_transfers_sparse: OperationSpec(128, 8, True),
    Operation.deprecated_lookup_accounts_unbatched: OperationSpec(16, 128),
    Operation.deprecated_lookup_transfers_unbatched: OperationSpec(16, 128),
    Operation.deprecated_get_account_transfers_unbatched: OperationSpec(128, 128),
    Operation.deprecated_get_account_balances_unbatched: OperationSpec(128, 128),
    Operation.deprecated_query_accounts_unbatched: OperationSpec(64, 128),
    Operation.deprecated_query_transfers_unbatched: OperationSpec(64, 128),
}


class _Index:
    """Per-field secondary index: value -> ascending timestamp list."""

    def __init__(self):
        self.by_value: dict[int, list[int]] = {}

    def add(self, value: int, timestamp: int) -> None:
        self.by_value.setdefault(value, []).append(timestamp)

    def get(self, value: int) -> list[int]:
        return self.by_value.get(value, [])


class StateMachine:
    """Engine selection mirrors the reference's `-Dvopr-state-machine=`
    differential-testing switch: 'device' serves batches from the
    device-resident DeviceLedger via the vectorized fast kernels
    (ops/fast_kernels.py) with a write-through host mirror for queries and
    durability — the database serving path; 'kernel' runs batches on the
    sequential device kernel; 'oracle' runs the pure-Python reference
    implementation."""

    def __init__(self, engine: str = "kernel",
                 a_cap: int = 1 << 14, t_cap: int = 1 << 16):
        assert engine in ("kernel", "oracle", "device")
        self.engine = engine
        self._a_cap = a_cap
        self._t_cap = t_cap
        self._state = StateMachineOracle()
        self.led = None
        if engine == "device":
            from .ops.ledger import DeviceLedger

            self.led = DeviceLedger(a_cap=a_cap, t_cap=t_cap,
                                    write_through=self._state)
        # Secondary indexes (host analog of the LSM index trees).
        self._xfer_ts: list[int] = []  # all transfer timestamps ascending
        self._xfer_by: dict[str, _Index] = {
            f: _Index() for f in (
                "debit_account_id", "credit_account_id",
                "user_data_128", "user_data_64", "user_data_32",
                "ledger", "code")}
        self._xfer_indexed = 0
        self._acct_ts: list[int] = []
        self._acct_by: dict[str, _Index] = {
            f: _Index() for f in (
                "user_data_128", "user_data_64", "user_data_32",
                "ledger", "code")}
        self._acct_indexed = 0
        self._events_by_ts: dict[int, AccountEventRecord] = {}
        self._events_indexed = 0
        # LSM-serving read path (attach_durable): ForestQuery + bounded
        # object caches. None = standalone mode (host dict indexes).
        self._fq = None
        self._acct_cache = None
        self._xfer_cache = None
        # Per-operation commit timing table (op name -> count/total/max).
        self.metrics: dict[str, dict] = {}
        # Pipelined commit windows awaiting resolution (submit_commit_window).
        self._pending_windows: list = []
        # stage_commit_window's decode cache: the staged window's exact
        # SoA dicts, reused by the matching submit_commit_window so the
        # ledger's staged pack can be consumed by identity.
        self._staged_window = None

    def fallback_stats(self) -> dict:
        """Device-engine routing/fallback counters (per-cause host
        fallbacks + on-device escalations); empty for host engines.
        Surfaced by bench.py per-config diagnostics and devhub.py."""
        if self.led is None:
            return {}
        return self.led.fallback_stats()

    # -------------------------------------------------------- LSM serving

    def attach_durable(self, durable, *, cache_sets: int = 1024,
                       ways: int = 8) -> None:
        """Serve reads from the LSM forest with a bounded object cache
        (VERDICT r1 #4; reference: groove object cache + prefetch,
        src/lsm/groove.zig:885,996,1339 + set_associative_cache.zig:1).
        Queries route through ForestQuery; lookups hit the cache first and
        fall through to the object trees on miss. The caches are written
        through after every durable flush (cache_upsert), so entries are
        always current. Memory on the read path is bounded by
        construction: 2 * cache_sets * ways objects."""
        from .lsm.cache_map import ObjectCache
        from .lsm.query import ForestQuery

        self._fq = ForestQuery(durable.forest)
        self._acct_cache = ObjectCache(sets=cache_sets, ways=ways)
        self._xfer_cache = ObjectCache(sets=cache_sets, ways=ways)
        if self.led is not None:
            # Serving mode: the device event ring becomes per-batch
            # transport (recycled after consumption) — history lives in
            # the forest, so ring capacity can never wedge the fast path.
            self.led.recycle_events = True
            # The durable flusher consumes drained transfer columns
            # through the vectorized path (durable._flush_transfer_columns).
            self.led.retain_flush_columns = True

    def cache_upsert(self, acct_ids, xfer_ids) -> None:
        """Cache coherence after a durable flush. Device engine: the
        flush consumed device delta COLUMNS (no mirror objects exist
        yet), so drop the flushed ids — the next read misses into the
        just-written trees, and the mirror drain stays deferred. Other
        engines: refresh cached copies from the state (the groove
        cache-update-at-commit discipline)."""
        if self._fq is None:
            return
        if self.led is not None:
            for aid in acct_ids:
                self._acct_cache.remove(aid)
            for tid in xfer_ids:
                self._xfer_cache.remove(tid)
            return
        for aid in acct_ids:
            a = self.state.accounts.get(aid)
            if a is not None:
                self._acct_cache.put(aid, a)
        for tid in xfer_ids:
            t = self.state.transfers.get(tid)
            if t is not None:
                self._xfer_cache.put(tid, t)

    # ------------------------------------------------------------- state

    @property
    def state(self) -> StateMachineOracle:
        # The device engine defers write-through materialization (columnar
        # chunks); every object-level read goes through this property, so
        # draining here keeps the mirror exact at every read boundary.
        if self.led is not None:
            self.led.drain_mirror()
        return self._state

    @property
    def raw_state(self) -> StateMachineOracle:
        """The state WITHOUT draining the deferred device mirror. For the
        durable flush only: it consumes the device delta columns directly
        (durable._flush_*_columns), so forcing a per-commit object
        materialization here would throw the deferral away. Any
        object-level READER must use `state`."""
        return self._state

    @state.setter
    def state(self, new_state: StateMachineOracle) -> None:
        """Replace the authoritative state (restart recovery / state sync,
        vsr/replica.py). For the device engine this rebuilds the device
        tables from the restored host state."""
        self._state = new_state
        if self.engine == "device":
            from .ops.ledger import DeviceLedger

            self.led = DeviceLedger(a_cap=self._a_cap, t_cap=self._t_cap,
                                    write_through=new_state)
        # Derived query indexes must be rebuilt from scratch.
        self._xfer_ts = []
        for idx in self._xfer_by.values():
            idx.by_value = {}
        self._xfer_indexed = 0
        self._acct_ts = []
        for idx in self._acct_by.values():
            idx.by_value = {}
        self._acct_indexed = 0
        self._events_by_ts = {}
        self._events_indexed = 0
        if self._acct_cache is not None:
            self._acct_cache.clear()
            self._xfer_cache.clear()

    # ------------------------------------------------------------- creates

    def create_accounts(self, events: list[Account], timestamp: int):
        if self.engine == "device":
            return self.led.create_accounts(events, timestamp)
        if self.engine == "kernel":
            from .ops.create_kernels import run_create_accounts

            return run_create_accounts(self.state, events, timestamp)
        return self.state.create_accounts(events, timestamp)

    def create_transfers(self, events: list[Transfer], timestamp: int):
        if self.engine == "device":
            return self.led.create_transfers(events, timestamp)
        if self.engine == "kernel":
            from .ops.create_kernels import run_create_transfers

            return run_create_transfers(self.state, events, timestamp)
        return self.state.create_transfers(events, timestamp)

    # ------------------------------------------------------------- lookups

    def lookup_accounts(self, ids: list[int]) -> list[Account]:
        if self._fq is not None:
            return self._lookup_batched(
                ids, self._acct_cache, "accounts", Account)
        return [self.state.accounts[i] for i in ids if i in self.state.accounts]

    def lookup_transfers(self, ids: list[int]) -> list[Transfer]:
        if self._fq is not None:
            return self._lookup_batched(
                ids, self._xfer_cache, "transfers", Transfer)
        return [self.state.transfers[i] for i in ids if i in self.state.transfers]

    def _lookup_batched(self, ids, cache, tree_name, cls) -> list:
        """Cache hits first; ALL misses go to the object tree as one
        batched fan-out (Tree.get_many), then refill the cache — a cold
        batch costs one concurrent read round per LSM level, not one
        synchronous read per id (VERDICT r2 weak #5; reference:
        src/lsm/groove.zig:996,1339)."""
        hit: dict = {}
        misses = []
        for i in ids:
            obj = cache.get(i)
            if obj is not None:
                hit[i] = obj
            elif i not in hit:
                misses.append(i)
        if misses:
            tree = self._fq.forest.trees[tree_name]
            unique = list(dict.fromkeys(misses))
            got = tree.get_many([i.to_bytes(16, "big") for i in unique])
            for i in unique:
                raw = got.get(i.to_bytes(16, "big"))
                if raw is not None:
                    obj = cls.unpack(raw)
                    cache.put(i, obj)
                    hit[i] = obj
        from . import constants

        if constants.VERIFY and hit:
            # Extra-check mode: cached objects must match their tree-
            # resident copies (cache-vs-tree coherence; both are updated
            # at the durable flush boundary).
            tree = self._fq.forest.trees[tree_name]
            for i, obj in list(hit.items())[:4]:
                raw = tree.get(i.to_bytes(16, "big"))
                assert raw is not None and cls.unpack(raw) == obj, \
                    f"verify: cache/tree divergence on {tree_name} {i}"
        return [hit[i] for i in ids if i in hit]

    # ------------------------------------------------------------- indexes

    def _refresh_indexes(self) -> None:
        import itertools

        # Walk the by-timestamp maps, not the object dicts: they are the
        # commit-ordered spine (1:1 with the stores — scope rollbacks pop
        # both), and stay ordered under the lazy mirror, where a point
        # read moves a transfer out of dict insertion position
        # (ops/lazy_mirror.py).
        transfers = self.state.transfers
        by_ts_t = self.state.transfer_by_timestamp
        if len(by_ts_t) > self._xfer_indexed:
            for ts, tid in itertools.islice(by_ts_t.items(),
                                            self._xfer_indexed, None):
                t = transfers[tid]
                self._xfer_ts.append(ts)
                for field, idx in self._xfer_by.items():
                    idx.add(getattr(t, field), ts)
            self._xfer_indexed = len(by_ts_t)
        accounts = self.state.accounts
        by_ts_a = self.state.account_by_timestamp
        if len(by_ts_a) > self._acct_indexed:
            for ts, aid in itertools.islice(by_ts_a.items(),
                                            self._acct_indexed, None):
                a = accounts[aid]
                self._acct_ts.append(ts)
                for field, idx in self._acct_by.items():
                    idx.add(getattr(a, field), ts)
            self._acct_indexed = len(by_ts_a)
        events = self.state.account_events
        if len(events) > self._events_indexed:
            for rec in events[self._events_indexed:]:
                self._events_by_ts[rec.timestamp] = rec
            self._events_indexed = len(events)

    # ------------------------------------------------------------- queries

    @staticmethod
    def _account_filter_valid(f: AccountFilter) -> bool:
        """reference: src/state_machine.zig:1737-1752"""
        ts_ok = (
            (f.timestamp_min == 0 or 1 <= f.timestamp_min <= TIMESTAMP_MAX)
            and (f.timestamp_max == 0 or 1 <= f.timestamp_max <= TIMESTAMP_MAX)
            and (f.timestamp_max == 0 or f.timestamp_min <= f.timestamp_max)
        )
        flags_ok = (
            (f.flags & (AccountFilterFlags.credits | AccountFilterFlags.debits))
            and not (f.flags & ~0x7)
        )
        return bool(
            f.account_id not in (0, U128_MAX) and ts_ok and f.limit != 0
            and flags_ok
        )

    def _filtered_account_transfer_ts(self, f: AccountFilter) -> list[int]:
        """Candidate timestamps matching an AccountFilter, in scan order."""
        self._refresh_indexes()
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        cands: list[int] = []
        if f.flags & AccountFilterFlags.debits:
            cands += self._xfer_by["debit_account_id"].get(f.account_id)
        if f.flags & AccountFilterFlags.credits:
            cands += self._xfer_by["credit_account_id"].get(f.account_id)
        cands = sorted(set(cands))
        out = []
        for ts in cands:
            if not (ts_min <= ts <= ts_max):
                continue
            t = self.state.transfers[self.state.transfer_by_timestamp[ts]]
            if f.user_data_128 and t.user_data_128 != f.user_data_128:
                continue
            if f.user_data_64 and t.user_data_64 != f.user_data_64:
                continue
            if f.user_data_32 and t.user_data_32 != f.user_data_32:
                continue
            if f.code and t.code != f.code:
                continue
            out.append(ts)
        if f.flags & AccountFilterFlags.reversed:
            out.reverse()
        return out

    def get_account_transfers(self, f: AccountFilter) -> list[Transfer]:
        """reference: src/state_machine.zig:3294-3310 + scan construction
        :1737-1831 (debits OR credits, AND user_data/code, range, limit)."""
        if self._fq is not None:
            return self._fq.get_account_transfers(f)
        if not self._account_filter_valid(f):
            return []
        limit = min(f.limit,
                    OPERATION_SPECS[Operation.get_account_transfers].result_max())
        ts_list = self._filtered_account_transfer_ts(f)[:limit]
        return [self.state.transfers[self.state.transfer_by_timestamp[ts]]
                for ts in ts_list]

    def get_account_balances(self, f: AccountFilter) -> list[AccountBalance]:
        """reference: src/state_machine.zig:1568-1666, 3312-3357 — the same
        transfer scan, mapped through account_events history rows; only for
        accounts with flags.history."""
        if self._fq is not None:
            return self._fq.get_account_balances(f)
        if not self._account_filter_valid(f):
            return []
        account = self.state.accounts.get(f.account_id)
        if account is None or not (account.flags & AccountFlags.history):
            return []
        limit = min(f.limit,
                    OPERATION_SPECS[Operation.get_account_balances].result_max())
        out: list[AccountBalance] = []
        for ts in self._filtered_account_transfer_ts(f):
            rec = self._events_by_ts.get(ts)
            if rec is None:
                continue
            if rec.dr_account.id == f.account_id:
                side = rec.dr_account
            elif rec.cr_account.id == f.account_id:
                side = rec.cr_account
            else:
                continue
            out.append(AccountBalance(
                debits_pending=side.debits_pending,
                debits_posted=side.debits_posted,
                credits_pending=side.credits_pending,
                credits_posted=side.credits_posted,
                timestamp=ts,
            ))
            if len(out) >= limit:
                break
        return out

    @staticmethod
    def _query_filter_valid(f: QueryFilter) -> bool:
        """reference: src/state_machine.zig:2054-2070"""
        ts_ok = (
            (f.timestamp_min == 0 or 1 <= f.timestamp_min <= TIMESTAMP_MAX)
            and (f.timestamp_max == 0 or 1 <= f.timestamp_max <= TIMESTAMP_MAX)
            and (f.timestamp_max == 0 or f.timestamp_min <= f.timestamp_max)
        )
        return bool(ts_ok and f.limit != 0 and not (f.flags & ~0x1))

    def _query(self, f: QueryFilter, kind: str, limit_cap: int) -> list[int]:
        """Shared query_accounts/query_transfers index walk."""
        self._refresh_indexes()
        indexes = self._acct_by if kind == "accounts" else self._xfer_by
        all_ts = self._acct_ts if kind == "accounts" else self._xfer_ts
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        conds = [(field, getattr(f, field))
                 for field in ("user_data_128", "user_data_64", "user_data_32",
                               "ledger", "code")
                 if getattr(f, field) != 0]
        if conds:
            # Walk the most selective index; verify the rest on the object.
            field0, value0 = min(
                conds, key=lambda fv: len(indexes[fv[0]].get(fv[1])))
            cands = indexes[field0].get(value0)
        else:
            cands = all_ts
        by_ts = (self.state.account_by_timestamp if kind == "accounts"
                 else self.state.transfer_by_timestamp)
        store = (self.state.accounts if kind == "accounts"
                 else self.state.transfers)
        out = []
        it = reversed(cands) if f.flags & QueryFilterFlags.reversed else iter(cands)
        limit = min(f.limit, limit_cap)
        for ts in it:
            if not (ts_min <= ts <= ts_max):
                continue
            obj = store[by_ts[ts]]
            if any(getattr(obj, field) != value for field, value in conds):
                continue
            out.append(ts)
            if len(out) >= limit:
                break
        return out

    def query_accounts(self, f: QueryFilter) -> list[Account]:
        """reference: src/state_machine.zig:3359-3375 + :2054-2124."""
        if self._fq is not None:
            return self._fq.query_accounts(f)
        if not self._query_filter_valid(f):
            return []
        cap = OPERATION_SPECS[Operation.query_accounts].result_max()
        return [self.state.accounts[self.state.account_by_timestamp[ts]]
                for ts in self._query(f, "accounts", cap)]

    def query_transfers(self, f: QueryFilter) -> list[Transfer]:
        if self._fq is not None:
            return self._fq.query_transfers(f)
        if not self._query_filter_valid(f):
            return []
        cap = OPERATION_SPECS[Operation.query_transfers].result_max()
        return [self.state.transfers[self.state.transfer_by_timestamp[ts]]
                for ts in self._query(f, "transfers", cap)]

    def get_change_events(self, f: ChangeEventsFilter) -> list[ChangeEvent]:
        """reference: src/state_machine.zig:3395-3528 — scan account_events
        by timestamp, join the transfer (by event timestamp; by pending id
        for expiries) and both accounts."""
        valid = (
            f.limit != 0
            and (f.timestamp_min == 0 or 1 <= f.timestamp_min <= TIMESTAMP_MAX)
            and (f.timestamp_max == 0 or 1 <= f.timestamp_max <= TIMESTAMP_MAX)
            and (f.timestamp_max == 0 or f.timestamp_min <= f.timestamp_max)
        )
        if not valid:
            return []
        if self._fq is not None:
            return self._fq.get_change_events(f)
        self._refresh_indexes()
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        limit = min(f.limit,
                    OPERATION_SPECS[Operation.get_change_events].result_max())
        out: list[ChangeEvent] = []
        for rec in self.state.account_events:
            if not (ts_min <= rec.timestamp <= ts_max):
                continue
            out.append(self._change_event(rec))
            if len(out) >= limit:
                break
        return out

    def _change_event(self, rec: AccountEventRecord) -> ChangeEvent:
        return build_change_event(
            rec,
            lambda ts: self.state.transfers[
                self.state.transfer_by_timestamp[ts]],
            lambda aid: self.state.accounts[aid])


    # ------------------------------------------------------------- pulse

    def pulse_needed(self, timestamp: int) -> bool:
        """reference: src/state_machine.zig:1138-1144"""
        if self.led is not None:
            # Answered from the device pulse_next scalar: the primary asks
            # this once per prepare, and a drain-on-read here would negate
            # the deferred mirror materialization on the serving path.
            return self.led.pulse_needed(timestamp)
        return self.state.pulse_needed(timestamp)

    # ------------------------------------------------------------- wire

    def input_valid(self, op: Operation, body: bytes) -> bool:
        """Cheap wire-shape validation before a request is accepted
        (reference: input_valid, src/state_machine.zig:~1000)."""
        spec = OPERATION_SPECS.get(op)
        if spec is None:
            return False
        if op == Operation.pulse:
            return body == b""
        if len(body) > MESSAGE_BODY_SIZE_MAX:
            return False  # would not fit a prepare (journal slot bound)
        try:
            batches = (multi_batch.decode(body, spec.event_size)
                       if op.is_multi_batch() else [body])
        except ValueError:
            return False
        base = _base_operation(op)
        single = base in (
            Operation.get_account_transfers, Operation.get_account_balances,
            Operation.query_accounts, Operation.query_transfers,
            Operation.get_change_events)
        for b in batches:
            if spec.event_size and len(b) % spec.event_size != 0:
                return False
            if single and len(b) != spec.event_size:
                return False
        return True

    def commit(self, op: Operation, body: bytes, timestamp: int) -> bytes:
        """Execute one operation body (reference StateMachine.commit,
        src/state_machine.zig:2564-2669): decode (multi-batch aware),
        dispatch, encode results. Raises ProtocolError on malformed input
        (callers validate first via input_valid). Per-op timings aggregate
        into `metrics` (reference: the commit Metrics table,
        src/state_machine.zig:729-780, :2637-2667)."""
        # Metrics-only timing, never committed state.
        t0 = _time.perf_counter_ns()  # jaxhound: allow(wall_clock)
        try:
            return self._commit_timed(op, body, timestamp)
        finally:
            m = self.metrics.setdefault(
                op.name, {"count": 0, "total_ns": 0, "max_ns": 0})
            dt = _time.perf_counter_ns() - t0  # jaxhound: allow(wall_clock)
            m["count"] += 1
            m["total_ns"] += dt
            if dt > m["max_ns"]:
                m["max_ns"] = dt

    def commit_window(self, op: Operation, bodies: list[bytes],
                      timestamps: list[int],
                      all_or_nothing: bool = False):
        """Commit a contiguous run of already-ordered prepares in one
        device dispatch (commit-window aggregation). Replicas may call
        this whenever several committed prepares are queued behind the
        execute stage — the analog of the reference pipelining 8
        prepares (src/config.zig:155). Results are bit-identical to
        committing one body at a time: any cross-prepare dependency
        falls back to the sequential path inside the ledger.

        Only device-engine create_transfers windows aggregate; anything
        else (mixed ops, pulse, host engine) commits per body.

        all_or_nothing=True (the replica commit loop): never executes
        per body on any obstacle — returns None with state untouched
        (the caller re-commits op by op through its normal path), and
        on success returns (replies, chunks_per_body) so the caller can
        attribute flush chunks to prepares."""
        O = Operation
        can_window = (
            self.engine == "device" and len(bodies) > 1
            and _base_operation(op) == O.create_transfers
            and op.is_multi_batch()
            and all(self.input_valid(op, b) for b in bodies))
        if not can_window:
            if all_or_nothing:
                return None
            return [self.commit(op, b, ts)
                    for b, ts in zip(bodies, timestamps)]

        spec = OPERATION_SPECS[op]
        # Metrics-only timing, never committed state.
        t0 = _time.perf_counter_ns()  # jaxhound: allow(wall_clock)
        evs, tss, shape = self._flatten_window(op, bodies, timestamps)
        outs = self.led.create_transfers_window(
            evs, tss, all_or_nothing=all_or_nothing)
        if outs is None:
            assert all_or_nothing
            return None
        replies = self._encode_window_replies(spec, outs, shape)
        m = self.metrics.setdefault(
            op.name, {"count": 0, "total_ns": 0, "max_ns": 0})
        dt = _time.perf_counter_ns() - t0  # jaxhound: allow(wall_clock)
        m["count"] += len(bodies)
        m["total_ns"] += dt
        if dt > m["max_ns"]:
            m["max_ns"] = dt
        if all_or_nothing:
            return replies, shape
        return replies

    def _flatten_window(self, op: Operation, bodies: list[bytes],
                        timestamps: list[int]):
        """Decode a window's bodies into flat (evs, tss, shape): each
        body may hold several inner batches, each consuming one
        timestamp per event ending at the prepare timestamp (reference:
        execute_multi_batch, src/state_machine.zig:2720-2756). Shared by
        the sync and pipelined window paths so their timestamp
        attribution can never diverge."""
        from .ops.batch import transfers_soa_from_bytes

        spec = OPERATION_SPECS[op]
        evs, tss, shape = [], [], []
        for body, ts in zip(bodies, timestamps):
            batches = multi_batch.decode(body, spec.event_size)
            counts = [len(b) // spec.event_size for b in batches]
            running = ts - sum(counts)
            for b, n in zip(batches, counts):
                running += n
                evs.append(transfers_soa_from_bytes(b))
                tss.append(running)
            shape.append(len(batches))
        return evs, tss, shape

    @staticmethod
    def _encode_window_replies(spec, outs, shape) -> list[bytes]:
        replies = []
        i = 0
        for k in shape:
            parts = [_encode_results_soa(st, t, spec)
                     for st, t in outs[i:i + k]]
            i += k
            replies.append(multi_batch.encode(parts, spec.result_size))
        return replies

    def _window_pipelinable(self, op: Operation,
                            bodies: list[bytes]) -> bool:
        O = Operation
        return (self.engine == "device" and len(bodies) > 1
                and _base_operation(op) == O.create_transfers
                and op.is_multi_batch()
                and all(self.input_valid(op, b) for b in bodies))

    def stage_commit_window(self, op: Operation, bodies: list[bytes],
                            timestamps: list[int]) -> bool:
        """Host↔device overlap: decode window k+1's bodies and hand its
        stacked operands to the ledger's background stager while window
        k's dispatch is in flight (DeviceLedger.stage_window). The
        decode is cached by body identity so the following
        submit_commit_window of the same window reuses the exact SoA
        dicts — which is what lets the ledger match its staged pack.
        Purely an optimization: an unstaged or mismatched submit packs
        inline, bit-identically. Returns True when a stage was
        enqueued."""
        if not self._window_pipelinable(op, bodies):
            self._staged_window = None
            return False
        evs, tss, shape = self._flatten_window(op, bodies, timestamps)
        # Keep the bodies alive in the cache: their ids key the reuse.
        self._staged_window = (op, tuple(map(id, bodies)), bodies,
                               list(timestamps), evs, tss, shape)
        return self.led.stage_window(evs, tss)

    def submit_commit_window(self, op: Operation, bodies: list[bytes],
                             timestamps: list[int]):
        """Pipelined serving: decode + submit one commit window with no
        device synchronization (DeviceLedger.submit_window — the
        reference's 8-deep prepare pipeline analog, src/config.zig:155).
        Returns an opaque pending record, or None when the window cannot
        pipeline (caller takes the synchronous commit_window path).
        Replies materialize at resolve_commit_windows()."""
        if not self._window_pipelinable(op, bodies):
            return None
        staged, self._staged_window = self._staged_window, None
        if (staged is not None and staged[0] == op
                and staged[1] == tuple(map(id, bodies))
                and staged[3] == list(timestamps)):
            evs, tss, shape = staged[4], staged[5], staged[6]
        else:
            evs, tss, shape = self._flatten_window(op, bodies,
                                                   timestamps)
        ticket = self.led.submit_window(evs, tss)
        if ticket is None:
            return None
        rec = {"op": op, "ticket": ticket, "shape": shape,
               "n_bodies": len(bodies)}
        self._pending_windows.append(rec)
        return rec

    def resolve_commit_windows(self, count: int | None = None) -> list:
        """Resolve pending pipelined windows in order — all, or at least
        the oldest `count` (a mid-pipeline fallback resolves everything;
        see DeviceLedger.resolve_windows) — and attach wire replies to
        each completed record under rec['replies']. Returns the
        completed records in order."""
        if not self._pending_windows:
            return []
        self.led.resolve_windows(count)
        done = []
        while (self._pending_windows
               and self._pending_windows[0]["ticket"].results is not None):
            rec = self._pending_windows.pop(0)
            _, outs = rec["ticket"].results
            rec["replies"] = self._encode_window_replies(
                OPERATION_SPECS[rec["op"]], outs, rec["shape"])
            done.append(rec)
        return done

    def _commit_timed(self, op: Operation, body: bytes,
                      timestamp: int) -> bytes:
        if not self.input_valid(op, body):
            raise ProtocolError(f"malformed body for {op!r}")
        spec = OPERATION_SPECS[op]
        if op == Operation.pulse:
            if self.engine == "device":
                self.led.expire_pending_transfers(timestamp)
            else:
                self.state.expire_pending_transfers(timestamp)
            return b""
        if op.is_multi_batch():
            batches = multi_batch.decode(body, spec.event_size)
            results = []
            if _base_operation(op) in (Operation.create_accounts,
                                       Operation.create_transfers):
                # Each inner batch consumes one timestamp per event; the
                # prepare timestamp is the LAST event's
                # (reference: execute_multi_batch advances the execute
                # timestamp per batch, src/state_machine.zig:2720-2756).
                counts = [len(b) // spec.event_size for b in batches]
                running = timestamp - sum(counts)
                for b, n in zip(batches, counts):
                    running += n
                    results.append(self._commit_one(op, spec, b, running))
            else:
                results = [self._commit_one(op, spec, b, timestamp)
                           for b in batches]
            return multi_batch.encode(results, spec.result_size)
        return self._commit_one(op, spec, body, timestamp)

    def _commit_one(self, op: Operation, spec: OperationSpec, body: bytes,
                    timestamp: int) -> bytes:
        O = Operation
        base = _base_operation(op)
        if base == O.create_transfers and self.engine == "device":
            # Vectorized serving path: wire -> SoA -> kernel -> wire with
            # no per-event Python objects (reference: commit is the cheap
            # part, src/state_machine.zig:2564-2669).
            from .ops.batch import transfers_soa_from_bytes

            ev = transfers_soa_from_bytes(body)
            st, ts = self.led.create_transfers_soa(ev, timestamp)
            return _encode_results_soa(st, ts, spec)
        events = [body[i:i + spec.event_size]
                  for i in range(0, len(body), spec.event_size)]
        if base == O.create_accounts:
            accounts = [Account.unpack(e) for e in events]
            results = self.create_accounts(accounts, timestamp)
            return _encode_create_results(results, spec)
        if base == O.create_transfers:
            transfers = [Transfer.unpack(e) for e in events]
            results = self.create_transfers(transfers, timestamp)
            return _encode_create_results(results, spec)
        if base == O.lookup_accounts:
            ids = [int.from_bytes(e, "little") for e in events]
            return b"".join(a.pack() for a in self.lookup_accounts(ids))
        if base == O.lookup_transfers:
            ids = [int.from_bytes(e, "little") for e in events]
            return b"".join(t.pack() for t in self.lookup_transfers(ids))
        if base == O.get_account_transfers:
            assert len(events) == 1
            return b"".join(t.pack() for t in
                            self.get_account_transfers(AccountFilter.unpack(events[0])))
        if base == O.get_account_balances:
            assert len(events) == 1
            return b"".join(b.pack() for b in
                            self.get_account_balances(AccountFilter.unpack(events[0])))
        if base == O.query_accounts:
            assert len(events) == 1
            return b"".join(a.pack() for a in
                            self.query_accounts(QueryFilter.unpack(events[0])))
        if base == O.query_transfers:
            assert len(events) == 1
            return b"".join(t.pack() for t in
                            self.query_transfers(QueryFilter.unpack(events[0])))
        if base == O.get_change_events:
            assert len(events) == 1
            return b"".join(e.pack() for e in
                            self.get_change_events(ChangeEventsFilter.unpack(events[0])))
        raise ValueError(f"unhandled operation {op!r}")


def _base_operation(op: Operation) -> Operation:
    """Map deprecated wire-compat variants onto their modern semantics
    (reference: src/tigerbeetle.zig:685-715)."""
    O = Operation
    return {
        O.deprecated_create_accounts_unbatched: O.create_accounts,
        O.deprecated_create_transfers_unbatched: O.create_transfers,
        O.deprecated_create_accounts_sparse: O.create_accounts,
        O.deprecated_create_transfers_sparse: O.create_transfers,
        O.deprecated_lookup_accounts_unbatched: O.lookup_accounts,
        O.deprecated_lookup_transfers_unbatched: O.lookup_transfers,
        O.deprecated_get_account_transfers_unbatched: O.get_account_transfers,
        O.deprecated_get_account_balances_unbatched: O.get_account_balances,
        O.deprecated_query_accounts_unbatched: O.query_accounts,
        O.deprecated_query_transfers_unbatched: O.query_transfers,
    }.get(op, op)


def _encode_results_soa(st, ts, spec: OperationSpec) -> bytes:
    """Vectorized result encode from (status, timestamp) arrays."""
    import numpy as np

    from .ops.batch import encode_create_results

    if not spec.sparse_results:
        return encode_create_results(st, ts)
    # Deprecated sparse encoding: {index, result} u32 pairs, non-created only.
    created = np.uint32(int(CreateTransferStatus.created))
    idx = np.nonzero(st != created)[0]
    out = np.empty(len(idx), dtype=np.dtype(
        {"names": ["index", "result"], "formats": ["<u4", "<u4"]}))
    out["index"] = idx
    out["result"] = st[idx]
    return out.tobytes()


def _encode_create_results(results, spec: OperationSpec) -> bytes:
    if not spec.sparse_results:
        return b"".join(r.pack() for r in results)
    # Deprecated sparse encoding: {index: u32, result: u32} for non-ok only,
    # where `created` maps to omitted and wire code `ok`=0 is never sent.
    out = b""
    for i, r in enumerate(results):
        if r.status.name == "created":
            continue
        out += struct.pack("<II", i, int(r.status))
    return out


def build_change_event(rec: AccountEventRecord, transfer_by_timestamp,
                       account_by_id) -> ChangeEvent:
    """Join one account_events record with its transfer + accounts
    (reference: src/state_machine.zig:3395-3528). Shared by the host-index
    path and the forest-backed path (lsm/query.py)."""
    status = rec.transfer_pending_status
    if status == TransferPendingStatus.expired:
        transfer = rec.transfer_pending
        assert transfer is not None
        etype = ChangeEventType.two_phase_expired
    else:
        transfer = transfer_by_timestamp(rec.timestamp)
        etype = {
            TransferPendingStatus.none: ChangeEventType.single_phase,
            TransferPendingStatus.pending: ChangeEventType.two_phase_pending,
            TransferPendingStatus.posted: ChangeEventType.two_phase_posted,
            TransferPendingStatus.voided: ChangeEventType.two_phase_voided,
        }[status]
    dr = account_by_id(rec.dr_account.id)
    cr = account_by_id(rec.cr_account.id)
    return ChangeEvent(
        transfer_id=transfer.id,
        transfer_amount=rec.amount,
        transfer_pending_id=transfer.pending_id,
        transfer_user_data_128=transfer.user_data_128,
        transfer_user_data_64=transfer.user_data_64,
        transfer_user_data_32=transfer.user_data_32,
        transfer_timeout=transfer.timeout,
        transfer_code=transfer.code,
        transfer_flags=transfer.flags,
        ledger=transfer.ledger,
        type=etype,
        debit_account_id=dr.id,
        debit_account_debits_pending=rec.dr_account.debits_pending,
        debit_account_debits_posted=rec.dr_account.debits_posted,
        debit_account_credits_pending=rec.dr_account.credits_pending,
        debit_account_credits_posted=rec.dr_account.credits_posted,
        debit_account_user_data_128=dr.user_data_128,
        debit_account_user_data_64=dr.user_data_64,
        debit_account_user_data_32=dr.user_data_32,
        debit_account_code=dr.code,
        debit_account_flags=rec.dr_account.flags,
        credit_account_id=cr.id,
        credit_account_debits_pending=rec.cr_account.debits_pending,
        credit_account_debits_posted=rec.cr_account.debits_posted,
        credit_account_credits_pending=rec.cr_account.credits_pending,
        credit_account_credits_posted=rec.cr_account.credits_posted,
        credit_account_user_data_128=cr.user_data_128,
        credit_account_user_data_64=cr.user_data_64,
        credit_account_user_data_32=cr.user_data_32,
        credit_account_code=cr.code,
        credit_account_flags=rec.cr_account.flags,
        timestamp=rec.timestamp,
        transfer_timestamp=transfer.timestamp,
        debit_account_timestamp=dr.timestamp,
        credit_account_timestamp=cr.timestamp,
    )
