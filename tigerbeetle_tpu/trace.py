"""Tracing and metrics: spans, Chrome-trace export, StatsD emission.

reference: src/trace.zig (span start/stop compiled into the hot path,
Chrome/Perfetto JSON via --trace), src/trace/statsd.zig (StatsD/DogStatsD
metric emission), src/trace/event.zig (event catalog). The tracer is
injected into the replica at construction; the default NullTracer keeps
the hot path free of overhead.
"""

from __future__ import annotations

import json
import socket
import time as _time
from typing import Optional


class NullTracer:
    """No-op tracer (production default unless --trace is set)."""

    def span(self, name: str, **tags):
        return _NULL_SPAN

    def count(self, metric: str, value: int = 1, **tags) -> None:
        pass

    def gauge(self, metric: str, value: float, **tags) -> None:
        pass

    def dump_chrome_trace(self, path: str) -> None:
        pass


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer(NullTracer):
    """Recording tracer: bounded ring of completed spans + counters."""

    def __init__(self, capacity: int = 65536, statsd: "Optional[StatsD]" = None):
        self.capacity = capacity
        self.events: list[dict] = []
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        self.statsd = statsd

    def span(self, name: str, **tags):
        return _Span(self, name, tags)

    def count(self, metric: str, value: int = 1, **tags) -> None:
        self.counters[metric] = self.counters.get(metric, 0) + value
        if self.statsd is not None:
            self.statsd.count(metric, value, **tags)

    def gauge(self, metric: str, value: float, **tags) -> None:
        self.gauges[metric] = value
        if self.statsd is not None:
            self.statsd.gauge(metric, value, **tags)

    def _record(self, name: str, start_us: float, dur_us: float,
                tags: dict) -> None:
        if len(self.events) >= self.capacity:
            del self.events[: self.capacity // 2]
        self.events.append({
            "name": name, "ph": "X", "ts": start_us, "dur": dur_us,
            "pid": 0, "tid": 0, "args": tags,
        })
        if self.statsd is not None:
            self.statsd.timing(name, dur_us / 1000.0, **tags)

    def dump_chrome_trace(self, path: str) -> None:
        """Chrome/Perfetto-loadable trace (reference: --trace=file)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events}, f)


class _Span:
    __slots__ = ("tracer", "name", "tags", "start")

    def __init__(self, tracer: Tracer, name: str, tags: dict):
        self.tracer = tracer
        self.name = name
        self.tags = tags

    def __enter__(self):
        self.start = _time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur = _time.perf_counter_ns() - self.start
        self.tracer._record(self.name, self.start / 1000.0, dur / 1000.0,
                            self.tags)
        return False


class StatsD:
    """DogStatsD-format UDP emitter (reference: src/trace/statsd.zig)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "tb_tpu"):
        self.addr = (host, port)
        self.prefix = prefix
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setblocking(False)

    def _emit(self, metric: str, value, kind: str, tags: dict) -> None:
        line = f"{self.prefix}.{metric}:{value}|{kind}"
        if tags:
            line += "|#" + ",".join(f"{k}:{v}" for k, v in tags.items())
        try:
            self.sock.sendto(line.encode(), self.addr)
        except OSError:
            pass  # metrics are best-effort

    def count(self, metric: str, value: int = 1, **tags) -> None:
        self._emit(metric, value, "c", tags)

    def gauge(self, metric: str, value: float, **tags) -> None:
        self._emit(metric, value, "g", tags)

    def timing(self, metric: str, ms: float, **tags) -> None:
        self._emit(metric, ms, "ms", tags)

    def close(self) -> None:
        self.sock.close()
