"""Device determinism pass: nondeterminism hazards in serving jaxprs.

The system's load-bearing invariant is byte-for-byte determinism
across replicas (ARCHITECTURE.md "Fault model & recovery": corrupted
state is repaired from peers precisely because every replica computes
identical bytes). This pass walks every registered serving entry's
jaxpr — recursing into scan/cond/pjit/shard_map sub-jaxprs — and REDs
on the four hazard classes that can silently break bit-parity:

  rng_no_key      an RNG primitive whose operands are all baked
                  (literals / closed-over constants, never derived
                  from an input): the key is compiled into the
                  program, so a retrace or a different backend mints
                  different bits than the replica that traced first.
                  A key THREADED from an argument is fine — the
                  caller owns reproducibility. The legacy stateful
                  `rng_uniform` is always a RED.
  host_callback   pure_callback / io_callback / debug_callback in a
                  serving lowering: the host round trip escapes the
                  deterministic replay envelope entirely (and breaks
                  the tunnel's dispatch model besides).
  float_collective a cross-device collective on floating-point
                  operands: float psum is summation-order-dependent
                  across mesh topologies, so the same window commits
                  different bytes on a 2x4 vs an 8x1 mesh. The
                  partitioned exchange must stay integer (the PR 8/9
                  bodies do — this pass proves it stays that way).
  float_scatter_dup a scatter-family op on float operands with
                  neither sorted nor unique indices: duplicate index
                  combination order is unspecified, so FP accumulation
                  order — and the committed bytes — can vary.

Findings are strings prefixed with the rule name; an empty list means
the entry is determinism-clean.
"""

from __future__ import annotations

import numpy as np

from .core import HEAVY_CLASSES

# Key-threading RNG primitives (jax.random's functional family): legal
# ONLY when the key/seed operand is derived from an input.
RNG_PRIMS = frozenset({
    "threefry2x32", "rng_bit_generator", "random_seed", "random_wrap",
    "random_unwrap", "random_bits", "random_fold_in", "random_gamma",
    "random_clone",
})
# Legacy stateful RNG: nondeterministic by construction.
RNG_ALWAYS_RED = frozenset({"rng_uniform"})
# Host round trips: never allowed in a serving lowering.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})


def _is_float(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and np.issubdtype(dtype, np.floating)


def _sub_jaxprs(eqn):
    """(inner_jaxpr, inner_invars) for every sub-jaxpr carried by an
    equation's params — ClosedJaxpr (pjit/scan/cond) or raw Jaxpr
    (shard_map/while) alike."""
    out = []
    for sub in eqn.params.values():
        subs = sub if isinstance(sub, (list, tuple)) else (sub,)
        for s in subs:
            inner = getattr(s, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                out.append(inner)  # ClosedJaxpr (pjit/scan/cond)
            elif hasattr(s, "eqns"):
                out.append(s)  # raw Jaxpr (shard_map/while)
    return out


def _check_jaxpr(jaxpr, derived: set, findings: list, where: str) -> None:
    """One jaxpr level: local input-derived dataflow + hazard checks,
    then recursion. `derived` holds the Vars (identity-keyed) known to
    flow from this level's inputs; constvars and literal-fed chains
    stay outside it — an RNG primitive fed ONLY by those is baked."""
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        eqn_derived = any(v in derived for v in eqn.invars
                          if hasattr(v, "aval") and not hasattr(v, "val"))
        if prim in RNG_ALWAYS_RED:
            findings.append(
                f"rng_no_key: stateful `{prim}` in {where} "
                "(nondeterministic by construction)")
        elif prim in RNG_PRIMS and not eqn_derived:
            findings.append(
                f"rng_no_key: `{prim}` in {where} consumes a baked "
                "key/seed (literal or closed-over constant) — thread "
                "the key through an argument")
        if prim in CALLBACK_PRIMS:
            findings.append(
                f"host_callback: `{prim}` in {where} — host round "
                "trips escape the deterministic replay envelope")
        if HEAVY_CLASSES.get(prim) == "collective" and any(
                _is_float(getattr(v, "aval", None)) for v in eqn.invars):
            findings.append(
                f"float_collective: `{prim}` on floating operands in "
                f"{where} — summation order varies across mesh "
                "topologies; the exchange must stay integer")
        if (prim.startswith("scatter") and eqn.invars
                and _is_float(getattr(eqn.invars[0], "aval", None))
                and not eqn.params.get("unique_indices", False)
                and not eqn.params.get("indices_are_sorted", False)):
            findings.append(
                f"float_scatter_dup: `{prim}` on float operands with "
                f"unsorted, non-unique indices in {where} — duplicate "
                "combination order is unspecified")
        if eqn_derived:
            derived.update(eqn.outvars)
        for inner in _sub_jaxprs(eqn):
            # Positional derived-ness transfer, aligned from the END
            # (cond carries a leading predicate the branches don't
            # see); on a count mismatch fall back to all-derived —
            # conservative against false REDs.
            inner_derived = set()
            n_in, n_out = len(eqn.invars), len(inner.invars)
            if n_in >= n_out:
                for ov, iv in zip(eqn.invars[n_in - n_out:],
                                  inner.invars):
                    if not hasattr(ov, "val") and ov in derived:
                        inner_derived.add(iv)
            else:
                inner_derived.update(inner.invars)
            _check_jaxpr(inner, inner_derived, findings,
                         f"{where}/{prim}")


def findings_for(closed_jaxpr, name: str = "entry") -> list[str]:
    """Device-determinism findings for one traced program (empty =
    clean)."""
    findings: list[str] = []
    _check_jaxpr(closed_jaxpr.jaxpr, set(closed_jaxpr.jaxpr.invars),
                 findings, name)
    return findings


def run(jaxprs: dict) -> list[str]:
    """Run the pass over `name -> ClosedJaxpr`; returns RED strings."""
    fails = []
    for name, cj in jaxprs.items():
        fails.extend(f"{name}: {f}" for f in findings_for(cj, name))
    return fails
