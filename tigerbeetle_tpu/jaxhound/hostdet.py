"""Host determinism pass: AST lint over the deterministic-replay plane.

The replay envelope — the oracle, the serving supervisor's epoch
replay, the VSR state machine, the partitioned router's host side —
must recompute IDENTICAL bytes from identical logged inputs, on any
replica, at any later time. Four host-side habits silently break that:

  wall_clock       reading the wall clock (time.time/monotonic/...,
                   datetime.now) inside replay logic: replayed state
                   depends on WHEN it replayed. Injected clocks
                   (`self.time.monotonic()`, a `clock=` parameter) are
                   the sanctioned pattern and are not flagged — only
                   direct module-level reads are.
  unseeded_random  the process-global `random.*` / legacy
                   `np.random.*` generators: unseeded, shared, and
                   order-dependent across interleavings. Seeded
                   instances (`random.Random(seed)`,
                   `np.random.default_rng(seed)`) are fine.
  set_iteration    iterating a set expression directly (for /
                   comprehension over `set(...)`, a set literal, a set
                   comprehension, or a union/difference of those):
                   Python set order is hash-salt- and history-
                   dependent, so any committed ordering fed by it
                   diverges across replicas. `sorted(<set>)` is the
                   sanctioned pattern and is not flagged.
  env_read         os.environ / os.getenv inside replay modules:
                   environment is per-process state, not logged input.

Escape hatch: a flagged line carrying `# jaxhound: allow(<rule>)`
suppresses that rule on that line (tests/test_tidy.py verifies every
pragma in the tree names a real rule, so stale pragmas cannot
accumulate). The scanned scope is SCOPE below — the modules whose
output feeds committed state.
"""

from __future__ import annotations

import ast
import os
import re

RULES = ("wall_clock", "unseeded_random", "set_iteration", "env_read")

# Replay-plane scope, relative to the package root's parent (the repo
# checkout): directories scan recursively.
SCOPE = (
    "tigerbeetle_tpu/oracle",
    "tigerbeetle_tpu/serving.py",
    "tigerbeetle_tpu/state_machine.py",
    "tigerbeetle_tpu/vsr",
    "tigerbeetle_tpu/parallel/partitioned.py",
)

_PRAGMA_RE = re.compile(r"#\s*jaxhound:\s*allow\(([\w,\s]+)\)")

_WALL_CLOCK_TIME_FNS = {
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "process_time", "process_time_ns",
}
_WALL_CLOCK_DATETIME_FNS = {"now", "utcnow", "today"}
# Seeded-constructor names on the random module: instantiating is fine,
# calling the module-level functions is not.
_RANDOM_OK = {"Random", "SystemRandom", "seed"}
_NP_RANDOM_OK = {"default_rng", "Generator", "SeedSequence", "PCG64",
                 "Philox", "RandomState"}
_ENV_FNS = {"getenv"}


def file_pragmas(source: str) -> dict[int, set[str]]:
    """line number -> set of allowed rule names for every
    `# jaxhound: allow(rule[, rule])` pragma in `source`."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",")}
    return out


class _ModuleAliases(ast.NodeVisitor):
    """Top-level import resolution: alias name -> module path, plus
    `from M import f` leaves alias -> 'M.f'."""

    def __init__(self):
        self.aliases: dict[str, str] = {}

    def visit_Import(self, node):
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node):
        if node.module:
            for a in node.names:
                self.aliases[a.asname or a.name] = \
                    f"{node.module}.{a.name}"


def _resolve(node, aliases) -> str | None:
    """Dotted path of a Name/Attribute chain rooted at an imported
    module, e.g. `_time.monotonic` -> 'time.monotonic'. Chains rooted
    at anything else (self.time.monotonic — an injected provider)
    resolve to None and are never flagged."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    root = aliases.get(node.id)
    if root is None:
        return None
    return ".".join([root] + list(reversed(parts)))


def _is_set_expr(node) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _is_set_expr(node.left) or _is_set_expr(node.right)
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, aliases, pragmas):
        self.aliases = aliases
        self.pragmas = pragmas
        self.findings: list[tuple[int, str, str]] = []

    def _flag(self, node, rule: str, detail: str) -> None:
        if rule in self.pragmas.get(node.lineno, ()):
            return
        self.findings.append((node.lineno, rule, detail))

    def visit_Call(self, node):
        path = _resolve(node.func, self.aliases)
        if path:
            mod, _, fn = path.rpartition(".")
            if mod == "time" and fn in _WALL_CLOCK_TIME_FNS:
                self._flag(node, "wall_clock", f"{path}() read")
            elif (mod in ("datetime.datetime", "datetime.date")
                  and fn in _WALL_CLOCK_DATETIME_FNS):
                self._flag(node, "wall_clock", f"{path}() read")
            elif mod == "random" and fn not in _RANDOM_OK:
                self._flag(node, "unseeded_random",
                           f"process-global {path}()")
            elif (mod in ("numpy.random", "np.random")
                  and fn not in _NP_RANDOM_OK):
                self._flag(node, "unseeded_random",
                           f"legacy global {path}()")
            elif mod == "os" and fn in _ENV_FNS:
                self._flag(node, "env_read", f"{path}() in replay scope")
            elif path in ("os.environ.get", "os.environ.setdefault"):
                self._flag(node, "env_read", f"{path}() in replay scope")
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if _resolve(node.value, self.aliases) == "os.environ":
            self._flag(node, "env_read", "os.environ[...] in replay "
                       "scope")
        self.generic_visit(node)

    def _check_iter(self, node, it):
        if _is_set_expr(it):
            self._flag(node, "set_iteration",
                       "iterating a set expression feeds an "
                       "unspecified order — wrap in sorted(...)")

    def visit_For(self, node):
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self._check_iter(node, gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp

    def visit_DictComp(self, node):
        self._visit_comp(node)


def scan_source(source: str, path: str = "<str>") -> list[str]:
    """Host-determinism findings for one module's source text, pragma
    allowlist applied. Each finding: 'path:line: rule: detail'."""
    tree = ast.parse(source, filename=path)
    aliases = _ModuleAliases()
    aliases.visit(tree)
    checker = _Checker(aliases.aliases, file_pragmas(source))
    checker.visit(tree)
    return [f"{path}:{line}: {rule}: {detail}"
            for line, rule, detail in sorted(checker.findings)]


def scope_files(repo_root: str | None = None) -> list[str]:
    """The replay-plane .py files SCOPE resolves to."""
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    files = []
    for rel in SCOPE:
        p = os.path.join(repo_root, rel)
        if os.path.isdir(p):
            for dirpath, _dirs, names in sorted(os.walk(p)):
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names) if n.endswith(".py"))
        elif os.path.isfile(p):
            files.append(p)
    return files


def run(repo_root: str | None = None) -> list[str]:
    """Run the host pass over the replay scope; returns RED strings
    (relative paths)."""
    fails = []
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for path in scope_files(root):
        with open(path) as f:
            src = f.read()
        rel = os.path.relpath(path, root)
        fails.extend(scan_source(src, rel))
    return fails
