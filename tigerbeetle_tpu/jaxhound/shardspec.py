"""Sharding-spec verifier: the partitioned layout, statically proven.

The partitioned tiers exist so that ledger STORE rows never move: each
device owns an account range, state stays resident under
`P("batch")`, and only compact per-event bundles cross the ICI. The
runtime-shape complement (`core.state_gathers`) catches a collective
moving whole-state operands; THIS pass catches the quieter failure —
a donated state leaf whose sharding silently degrades to replicated
(an in_specs/out_specs edit, a lost NamedSharding on the fixture, an
out_shardings default) so every device suddenly holds, copies, and
donates the WHOLE ledger again.

It parses the lowered StableHLO of each partitioned entry and asserts:

  - every `jax.buffer_donor` input (the donated state leaves) carries
    an `mhlo.sharding = "{devices=...}"` attr — present, and not
    `"{replicated}"` / `"{maximal...}"`;
  - the donated-and-sharded input count >= the state leaf count (no
    leaf slipped out of the donated set into replicated-land);
  - the output side round-trips through at least as many
    `@SPMDShardToFullShape` device-sharded custom calls (shard_map's
    exit markers) as there are state leaves — the state comes BACK
    sharded, not gathered;
  - no state-sized operand is silently replicated: any @main input
    without a devices-sharding whose byte size reaches the largest
    sharded state leaf is flagged (a whole-state table passed
    replicated defeats the layout even if the named state is fine).

Findings are strings; empty = the layout holds.
"""

from __future__ import annotations

import re

# MLIR element type -> bytes (i1 stored as a byte for sizing purposes).
_ELEM_BYTES = {
    "i1": 1, "i8": 1, "ui8": 1, "i16": 2, "ui16": 2, "f16": 2,
    "bf16": 2, "i32": 4, "ui32": 4, "f32": 4, "i64": 8, "ui64": 8,
    "f64": 8,
}

_MAIN_RE = re.compile(
    r"func\.func\s+public\s+@main\((.*?)\)\s*->", re.S)
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_DEVICES_RE = re.compile(r'mhlo\.sharding\s*=\s*"\{devices=')


def tensor_nbytes(tensor_body: str) -> int:
    """Byte size of an MLIR `tensor<...>` body like '8x512x6xui64'."""
    parts = tensor_body.split("x")
    elem = parts[-1]
    n = 1
    for p in parts[:-1]:
        n *= int(p)
    return n * _ELEM_BYTES.get(elem, 1)


def split_main_args(text: str) -> list[str]:
    """The @main signature's argument declarations (attrs included),
    split at top-level commas."""
    m = _MAIN_RE.search(text)
    if m is None:
        return []
    body = m.group(1)
    args, depth, cur, in_str = [], 0, [], False
    for ch in body:
        if ch == '"':
            # Sharding attr values are quoted and hold UNBALANCED
            # brackets ("{devices=[8,1]<=[8]}"); bracket depth must
            # ignore string contents entirely.
            in_str = not in_str
        elif not in_str:
            if ch in "<{([":
                depth += 1
            elif ch in ">})]":
                depth -= 1
        if ch == "," and depth == 0 and not in_str:
            args.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur and "".join(cur).strip():
        args.append("".join(cur).strip())
    return args


def verify_lowered(lowered, n_state_leaves: int,
                   name: str = "entry") -> list[str]:
    """Sharding-spec findings for one lowered partitioned entry."""
    text = lowered.as_text()
    args = split_main_args(text)
    fails: list[str] = []
    if not args:
        return [f"{name}: could not locate @main signature in the "
                "lowered artifact"]
    donated_sharded = 0
    sharded_sizes: list[int] = []
    arg_meta = []  # (index, nbytes, donated, devices_sharded)
    for i, a in enumerate(args):
        tm = _TENSOR_RE.search(a)
        nbytes = tensor_nbytes(tm.group(1)) if tm else 0
        # Donation lowers as `jax.buffer_donor = true` (unaliased
        # donor) or `tf.aliasing_output = N` (donor aliased to an
        # output) depending on whether XLA established the alias.
        donated = "jax.buffer_donor" in a or "tf.aliasing_output" in a
        devices = bool(_DEVICES_RE.search(a))
        replicated = "{replicated}" in a or "{maximal" in a
        arg_meta.append((i, nbytes, donated, devices))
        if donated:
            if devices and not replicated:
                donated_sharded += 1
                sharded_sizes.append(nbytes)
            else:
                fails.append(
                    f"{name}: donated input #{i} "
                    f"({tm.group(1) if tm else '?'}) carries no "
                    "devices sharding (replicated donated state — the "
                    "partitioned layout regressed)")
    if donated_sharded < n_state_leaves:
        fails.append(
            f"{name}: {donated_sharded} donated+sharded inputs < "
            f"{n_state_leaves} state leaves (a state leaf left the "
            "donated sharded set)")
    # Output side: shard_map exits through @SPMDShardToFullShape; the
    # state must come back device-sharded, leaf for leaf.
    out_sharded = len(re.findall(
        r'@SPMDShardToFullShape.*?mhlo\.sharding\s*=\s*"\{devices=',
        text))
    if out_sharded < n_state_leaves:
        fails.append(
            f"{name}: {out_sharded} device-sharded "
            f"@SPMDShardToFullShape outputs < {n_state_leaves} state "
            "leaves (state is gathered, not returned sharded)")
    # Silent replication: any input as large as the biggest sharded
    # state leaf but carrying no devices sharding is whole-state mass
    # being re-shipped to every device.
    threshold = max(sharded_sizes, default=0)
    if threshold:
        for i, nbytes, donated, devices in arg_meta:
            if not devices and nbytes >= threshold:
                fails.append(
                    f"{name}: input #{i} ({nbytes} B) is state-sized "
                    "but replicated (no devices sharding) — a "
                    "whole-state operand is shipped to every device")
    return fails


def run(entries: dict) -> list[str]:
    """Run the verifier over the registry's partitioned entries
    (routes 'partitioned' and 'partitioned_chain')."""
    fails = []
    for name, entry in entries.items():
        if entry.route not in ("partitioned", "partitioned_chain"):
            continue
        fails.extend(verify_lowered(entry.lower(), entry.n_state_leaves,
                                    name))
    return fails
