"""jaxhound: whole-stack static verifier for the TPU ledger.

reference: src/copyhound.zig:1-9 — the reference hunts large memcpys
and monomorphization bloat in LLVM IR; jaxhound inspects jax/XLA
compile artifacts and the host control plane. It grew from a census
module into a package of static passes that turn the system's
load-bearing runtime invariant — byte-for-byte determinism across
replicas — into machine-checked artifacts:

  core         heavy-op census, scan-body census, telemetry census,
               closure/while/gather lints, budget-trail resolvers,
               lowered-artifact analysis (the original jaxhound).
  determinism  device determinism pass: RNG without a threaded key,
               host callbacks in serving lowerings, floating-point
               cross-device collectives, unsorted-duplicate-index
               float scatters.
  hostdet      host determinism pass: Python-AST lint over the
               deterministic-replay modules (wall-clock reads,
               unseeded `random`, set-iteration ordering, env reads)
               with a `# jaxhound: allow(<rule>)` pragma allowlist.
  retrace      retrace/recompile auditor: the dispatch-route matrix
               (flat, chain, partitioned, partitioned-chain at
               W∈{1,2,8,32}) under a jit-cache-miss probe, pinned in
               perf/tracebudget_r*.json; plus the weak-type carry
               check.
  shardspec    sharding-spec verifier: every donated state leaf of a
               partitioned entry carries the batch sharding on input
               and output; no state-sized operand silently replicated.
  registry     the serving-entry registry the passes run over.

CLI: ``python -m tigerbeetle_tpu.jaxhound [--kernel K] [--json]
[--pass determinism|host|retrace|sharding|all]``; the gate's `static`
leg (testing/static_smoke.py) runs every pass plus the negative
injected-violation proofs.
"""

from __future__ import annotations

from .core import (  # noqa: F401 — the package's public census/lint API
    CLOSURE_CONST_LIMIT,
    HEAVY_CLASSES,
    HEAVY_CLASS_ORDER,
    STATE_GATHER_LIMIT,
    TELEMETRY_PACK_NAME,
    _aval_bytes,
    _collect_consts,
    _walk_jaxpr,
    analyze_lowered,
    closure_constants,
    donated_inputs,
    heavy_census,
    kernels,
    newest_budget_path,
    newest_membudget_path,
    newest_tracebudget_path,
    report,
    scan_bodies,
    scan_body_census,
    state_gathers,
    telemetry_census,
    while_ops,
)

from . import (  # noqa: F401 — pass modules (jax-import-free at load)
    core,
    determinism,
    hostdet,
    registry,
    retrace,
    shardspec,
)
