"""Serving-entry registry: every compiled entry the static passes audit.

One place enumerates the dispatch surface — the flat per-batch/
superbatch tiers, the scan-form chain route, the replicated sharded
steps, the partitioned (account-range-sharded) steps, and the fused
partitioned chain — so a pass added once runs over ALL of them, and a
new route added to the ledger without a registry entry is a visible
gap, not a silent one. Fixtures mirror perf/opbudget.py's (the
committed censuses are traced from identical shapes); the registry is
self-contained so the analysis plane never imports the perf scripts.

Each Entry carries thunks, not artifacts: nothing traces, lowers, or
compiles until a pass asks. `make_args(depth)` builds the REAL
dispatch-layer inputs (stack_chain_window / stack_partitioned_window /
pad_transfer_events) at a given window depth W — the retrace auditor
drives it across DEPTH_MATRIX; depth-independent entries ignore the
argument.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

# The retrace auditor's window-depth matrix; the representative depth
# is what the jaxpr-level passes trace at (matches opbudget's chain
# fixture depth).
DEPTH_MATRIX = (1, 2, 8, 32)
REP_DEPTH = 4

_N_SUPER = 1024
_STACK = 4


@dataclasses.dataclass
class Entry:
    """One audited serving entry.

    route: flat | chain | sharded | partitioned | partitioned_chain.
    jit_fn: the jit-wrapped dispatch callable (lowerable).
    raw_fn: the traceable function (jax.make_jaxpr target).
    make_args: depth -> concrete args (real stacking/padding drivers).
    depths: the retrace matrix this entry is driven across.
    mesh: the Mesh tracing/lowering must run under (None = none).
    n_state_leaves: donated-state leaf count (sharding verifier).
    """

    name: str
    route: str
    jit_fn: Callable
    raw_fn: Callable
    make_args: Callable[[int], tuple]
    depths: tuple = (1,)
    mesh: object = None
    n_state_leaves: int = 0

    def _ctx(self):
        import contextlib

        return self.mesh if self.mesh is not None \
            else contextlib.nullcontext()

    def trace(self, depth: int = REP_DEPTH):
        """ClosedJaxpr of the entry at `depth` (representative)."""
        import jax

        with self._ctx():
            return jax.make_jaxpr(self.raw_fn)(*self.make_args(depth))

    def lower(self, depth: int = REP_DEPTH):
        """Lowered artifact of the jit entry at `depth`."""
        with self._ctx():
            return self.jit_fn.lower(*self.make_args(depth))


def _mk_prepares(n_prepares, n=_N_SUPER, nid0=10 ** 6, seed=0):
    import numpy as np

    from tigerbeetle_tpu.benchmark import _soa

    rng = np.random.default_rng(seed)
    evs, tss = [], []
    nid = nid0
    for b in range(n_prepares):
        dr = rng.integers(1, 64, n, dtype=np.uint64)
        cr = (dr % 63) + 1
        evs.append(_soa(np.arange(nid, nid + n), dr, cr,
                        rng.integers(1, 100, n)))
        nid += n
        tss.append(10 ** 12 + b * (n + 10))
    return evs, tss


def _flat_fixtures():
    from tigerbeetle_tpu.ops.batch import transfers_to_arrays
    from tigerbeetle_tpu.ops.ledger import (
        init_state, pad_transfer_events, stack_superbatch)
    from tigerbeetle_tpu.types import Transfer

    state = init_state(1 << 10, 1 << 12)
    ev = pad_transfer_events(transfers_to_arrays(
        [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                  amount=1, ledger=1, code=1)]))
    evs, tss = _mk_prepares(_STACK)
    ev_s, seg = stack_superbatch(evs, tss)
    return state, ev, ev_s, seg


def _chain_args_at(depth):
    from tigerbeetle_tpu.ops.ledger import stack_chain_window

    evs, tss = _mk_prepares(depth)
    return stack_chain_window(evs, tss, _N_SUPER)


def _partitioned_state(mesh, axis="batch"):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tigerbeetle_tpu.ops.ledger import init_state

    n = mesh.shape[axis]
    sub = jax.tree.map(np.asarray, init_state(
        (1 << 10) // n, (1 << 12) // n, orphan_cap=(1 << 16) // n))
    stacked = jax.tree.map(lambda x: np.stack([x] * n), sub)
    return jax.device_put(stacked, NamedSharding(mesh, P(axis)))


def entries(include_partitioned: bool | None = None) -> dict[str, Entry]:
    """name -> Entry for the full audited dispatch surface. The mesh
    tiers (sharded/partitioned/partitioned_chain) need >= 8 devices;
    include_partitioned=None auto-detects."""
    import jax
    import numpy as np

    from tigerbeetle_tpu.ops import fast_kernels as fk

    state, ev, ev_s, seg = _flat_fixtures()
    n_leaves = len(jax.tree_util.tree_leaves(state))
    ts = np.uint64(1000)
    n = np.int32(1)
    out: dict[str, Entry] = {}

    def add_flat(name, jitfn, args):
        out[name] = Entry(
            name=name, route="flat", jit_fn=jitfn,
            raw_fn=jitfn, make_args=lambda _d, a=args: a,
            n_state_leaves=n_leaves)

    add_flat("create_transfers_fast_jit",
             fk.create_transfers_fast_jit, (state, ev, ts, n))
    add_flat("create_transfers_fixpoint_jit",
             fk.create_transfers_fixpoint_jit, (state, ev, ts, n))
    add_flat("create_transfers_fixpoint_deep_jit",
             fk.create_transfers_fixpoint_deep_jit, (state, ev, ts, n))
    add_flat("create_transfers_balancing_jit",
             fk.create_transfers_balancing_jit, (state, ev, ts, n))
    add_flat("create_transfers_imported_jit",
             fk.create_transfers_imported_jit, (state, ev, ts, n))
    add_flat("create_transfers_imported_fixpoint_jit",
             fk.create_transfers_imported_fixpoint_jit,
             (state, ev, ts, n))
    add_flat("create_transfers_super_jit",
             fk.create_transfers_super_jit, (state, ev_s, seg))
    add_flat("create_transfers_super_deep_jit",
             fk.create_transfers_super_deep_jit, (state, ev_s, seg))
    add_flat("create_transfers_super_ring_jit",
             fk.create_transfers_super_ring_jit, (state, ev_s, seg))
    add_flat("create_transfers_super_deep_ring_jit",
             fk.create_transfers_super_deep_ring_jit, (state, ev_s, seg))
    add_flat("create_transfers_super_balancing_jit",
             fk.create_transfers_super_balancing_jit, (state, ev_s, seg))

    def chain_args(depth, st=state):
        ev_c, seg_c = _chain_args_at(depth)
        return (st, ev_c, seg_c)

    for name, jitfn in (
            ("create_transfers_chain_jit", fk.create_transfers_chain_jit),
            ("create_transfers_chain_ring_jit",
             fk.create_transfers_chain_ring_jit),
            ("create_transfers_chain_unrolled_jit",
             fk.create_transfers_chain_unrolled_jit)):
        out[name] = Entry(
            name=name, route="chain", jit_fn=jitfn, raw_fn=jitfn,
            make_args=chain_args, depths=DEPTH_MATRIX,
            n_state_leaves=n_leaves)

    if include_partitioned is None:
        include_partitioned = len(jax.devices()) >= 8
    if not include_partitioned:
        return out

    from jax.sharding import Mesh

    from tigerbeetle_tpu.parallel.full_sharded import (
        make_sharded_create_transfers)
    from tigerbeetle_tpu.parallel.partitioned import (
        make_partitioned_chain_create_transfers,
        make_partitioned_create_transfers,
        stack_partitioned_window,
    )

    mesh = Mesh(np.array(jax.devices()[:8]), ("batch",))
    for mode in ("plain", "fixpoint"):
        step = make_sharded_create_transfers(mesh, mode=mode)
        out[f"sharded_{mode}_step"] = Entry(
            name=f"sharded_{mode}_step", route="sharded",
            jit_fn=step, raw_fn=step.__wrapped__,
            make_args=lambda _d, a=(state, ev, np.uint64(1000),
                                    np.int32(1)): a,
            mesh=mesh, n_state_leaves=n_leaves)

    pstate = _partitioned_state(mesh)
    for mode in ("plain", "fixpoint"):
        pstep = make_partitioned_create_transfers(mesh, mode=mode)
        out[f"partitioned_{mode}_step"] = Entry(
            name=f"partitioned_{mode}_step", route="partitioned",
            jit_fn=pstep, raw_fn=pstep.__wrapped__,
            make_args=lambda _d, a=(pstate, ev, np.uint64(1000),
                                    np.int32(1)): a,
            mesh=mesh, n_state_leaves=n_leaves)

    cstep = make_partitioned_chain_create_transfers(mesh, mode="plain")

    def pchain_args(depth, st=pstate):
        evs, tss = _mk_prepares(depth)
        ev_p, ts_p, n_p = stack_partitioned_window(evs, tss, _N_SUPER)
        return (st, ev_p, ts_p, n_p, None)

    out["partitioned_chain_step"] = Entry(
        name="partitioned_chain_step", route="partitioned_chain",
        jit_fn=cstep, raw_fn=cstep.__wrapped__,
        make_args=pchain_args, depths=DEPTH_MATRIX,
        mesh=mesh, n_state_leaves=n_leaves)
    return out
