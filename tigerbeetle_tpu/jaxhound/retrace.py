"""Retrace/recompile auditor: the closed compiled-signature set, pinned.

Every distinct abstract signature a dispatch entry is called with is
one more XLA compile (seconds of latency at serving time) and one more
executable resident in the backend. The dispatch layer's whole design
pads batches into PAD_BUCKETS and stacks windows so the signature set
is CLOSED — small, enumerable, flat in window depth W up to the
unavoidable leading window axis. This auditor makes that property a
committed artifact:

  - For every registry entry it drives `make_args` across the window
    matrix W∈{1,2,8,32} (the REAL stacking/padding drivers) and
    computes the abstract signature (shape/dtype/weak_type per arg
    leaf) at each depth. The per-entry signatures are UNIFIED across
    depths into one canonical signature: an axis that varies with W
    must equal W (the window axis, normalized to "W"); any other
    variation — a dtype drifting with depth, a weak-typed Python
    scalar leaking in at one depth only, an axis scaling with
    something else — is a polymorphic call: a per-depth recompile the
    route's design forbids. RED.
  - The canonical set is pinned in perf/tracebudget_r*.json (the
    opbudget budget-trail pattern: append a new round to move a pin;
    `newest_tracebudget_path` resolves the head). `check_budget`
    re-derives and compares count + digest per entry.
  - A live jit-cache-miss probe (`cache_probe`): call an entry twice
    at the same depth, then at a second depth — `_cache_size()` must
    grow exactly [1, 0, 1]: one compile per depth, zero on re-drive
    (an unstable cache key — e.g. an unhashable static or weak-type
    flapping — shows up as a miss on the re-drive).
  - A static weak-type check on scan carry avals (`weak_carries`): a
    Python scalar smuggled into a chain carry traces as
    `int32[] weak_type=True`, which re-canonicalizes — and retraces —
    the first time a strong-typed value meets it (the PR 9 int32
    chain-carry bug class under x64).
"""

from __future__ import annotations

import hashlib
import json
import os

from .core import _walk_jaxpr, newest_tracebudget_path  # noqa: F401


def leaf_signature(args) -> list[tuple]:
    """(shape, dtype, weak_type) per flattened arg leaf — the jit
    cache key's abstract part."""
    import jax
    from jax.api_util import shaped_abstractify

    leaves = jax.tree_util.tree_leaves(args)
    out = []
    for x in leaves:
        a = shaped_abstractify(x)
        out.append((tuple(int(d) for d in a.shape), str(a.dtype),
                    bool(a.weak_type)))
    return out


def canonical_signature(entry) -> tuple[list, list[str]]:
    """Unify an entry's per-depth signatures into one canonical
    signature (window axes -> "W"); the second element lists
    polymorphic-call findings (non-empty = RED)."""
    sigs = {d: leaf_signature(entry.make_args(d)) for d in entry.depths}
    depths = list(entry.depths)
    findings: list[str] = []
    n_leaves = {len(s) for s in sigs.values()}
    if len(n_leaves) != 1:
        return [], [
            f"polymorphic_tree: leaf count varies across depths "
            f"({ {d: len(s) for d, s in sigs.items()} }) — the arg "
            "pytree itself depends on W"]
    canon = []
    for i in range(n_leaves.pop()):
        shapes = [sigs[d][i][0] for d in depths]
        dtypes = {sigs[d][i][1] for d in depths}
        weaks = {sigs[d][i][2] for d in depths}
        if len(dtypes) > 1:
            findings.append(
                f"polymorphic_dtype: leaf {i} dtype varies with W "
                f"({sorted(dtypes)}) — one recompile per depth")
        if len(weaks) > 1:
            findings.append(
                f"weak_type_leak: leaf {i} weak_type flaps across W "
                "(a Python scalar leaks into the call at some depths)")
        ranks = {len(s) for s in shapes}
        if len(ranks) > 1:
            findings.append(
                f"polymorphic_shape: leaf {i} rank varies with W")
            canon.append(("<polymorphic>", sorted(dtypes)[0], False))
            continue
        cshape = []
        for ax in range(ranks.pop()):
            vals = [s[ax] for s in shapes]
            if len(set(vals)) == 1:
                cshape.append(vals[0])
            elif vals == depths:
                cshape.append("W")
            else:
                findings.append(
                    f"polymorphic_shape: leaf {i} axis {ax} varies "
                    f"with W but not AS W ({dict(zip(depths, vals))}) "
                    "— an un-normalized data-dependent dimension")
                cshape.append("?")
        canon.append((tuple(cshape), sorted(dtypes)[0],
                      sorted(weaks)[0]))
    return canon, findings


def signature_digest(canon: list) -> str:
    """Stable short digest of a canonical signature."""
    return hashlib.sha256(repr(canon).encode()).hexdigest()[:16]


def weak_carries(closed_jaxpr, name: str = "entry") -> list[str]:
    """Weak-typed scan carry avals anywhere in the program — the
    Python-scalar-leak recompile class. Empty = clean."""
    fails: list[str] = []

    def visit(eqn):
        if eqn.primitive.name != "scan":
            return
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        for i, v in enumerate(eqn.invars[nc:nc + ncar]):
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "weak_type", False):
                fails.append(
                    f"{name}: weak_carry: scan carry {i} is weak-typed "
                    f"{aval.dtype}[] (a Python scalar in the carry "
                    "retraces the first time a strong type meets it — "
                    "pin it with np/jnp dtype construction)")

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    return fails


def audit(entries: dict) -> tuple[dict, list[str]]:
    """Canonical signatures + polymorphism findings for a registry.
    Returns ({entry: {"signature": ..., "digest": ..., "n_leaves": N,
    "depths": [...]}} , RED strings)."""
    table = {}
    fails: list[str] = []
    for name, entry in entries.items():
        canon, findings = canonical_signature(entry)
        fails.extend(f"{name}: {f}" for f in findings)
        table[name] = {
            "route": entry.route,
            "depths": list(entry.depths),
            "n_signatures": 1,
            "n_leaves": len(canon),
            "digest": signature_digest(canon),
        }
    return table, fails


def check_budget(entries: dict, budget_path: str | None = None,
                 table: dict | None = None) -> list[str]:
    """Current canonical-signature table vs the committed
    tracebudget_r*.json head. Any drift — a new entry, a vanished
    entry, a digest change, a signature-count change — is a RED whose
    fix is an explicit reviewed commit of a new round."""
    if budget_path is None:
        budget_path = newest_tracebudget_path()
    with open(budget_path) as f:
        committed = json.load(f)
    pinned = committed.get("entries", {})
    if table is None:
        table, fails = audit(entries)
    else:
        fails = []
    base = os.path.basename(budget_path)
    for name, cur in table.items():
        pin = pinned.get(name)
        if pin is None:
            fails.append(
                f"{name}: not pinned in {base} — new dispatch entry "
                "needs a committed tracebudget round")
            continue
        if cur["n_signatures"] > pin["n_signatures"]:
            fails.append(
                f"{name}: {cur['n_signatures']} compiled signatures > "
                f"pinned {pin['n_signatures']} in {base}")
        if cur["digest"] != pin["digest"]:
            fails.append(
                f"{name}: canonical signature digest {cur['digest']} "
                f"!= pinned {pin['digest']} in {base} (the entry's "
                "abstract call signature changed — if intended, commit "
                "a new tracebudget round)")
    for name in pinned:
        if name not in table:
            fails.append(
                f"{name}: pinned in {base} but missing from the "
                "registry (entry removed? commit a new round)")
    return fails


def cache_probe(jit_fn, args_by_depth: list) -> list[str]:
    """Live jit-cache-miss probe: execute `jit_fn` over the args
    sequence (repeat a depth to prove a hit) and compare `_cache_size`
    deltas against the expectation — +1 the first time a signature is
    seen, +0 after. Entries without a cache-size probe skip clean."""
    import jax
    import numpy as np

    size = getattr(jit_fn, "_cache_size", None)
    if size is None:
        return []
    fails = []
    seen: set = set()
    for i, args in enumerate(args_by_depth):
        sig = repr(leaf_signature(args))
        before = size()
        # Serving entries donate their state buffers: re-drive on
        # fresh host copies so the probe never consumes a fixture
        # (same avals, same cache key).
        jit_fn(*jax.tree.map(np.copy, args))
        delta = size() - before
        want = 0 if sig in seen else 1
        # A signature compiled earlier in the process also hits: allow
        # fewer misses than expected, never more.
        if delta > want:
            fails.append(
                f"cache_probe: call {i} cost {delta} cache misses "
                f"(expected <= {want}) — unstable jit cache key")
        seen.add(sig)
    return fails


def write_budget(entries: dict, path: str) -> dict:
    """Derive the canonical table and write it as a tracebudget round
    (the explicit, reviewed act of moving a pin)."""
    table, fails = audit(entries)
    if fails:
        raise RuntimeError(
            "refusing to pin a polymorphic matrix:\n  " +
            "\n  ".join(fails))
    doc = {
        "round": int(os.path.basename(path).split("_r")[1][:2])
        if "_r" in path else 1,
        "matrix": {"depths": list(
            max((e.depths for e in entries.values()), key=len))},
        "entries": table,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc
