"""jaxhound CLI: run the report or any static pass standalone.

    python -m tigerbeetle_tpu.jaxhound                    # HLO report
    python -m tigerbeetle_tpu.jaxhound --kernel K         # one kernel
    python -m tigerbeetle_tpu.jaxhound --pass determinism # one pass
    python -m tigerbeetle_tpu.jaxhound --pass all --json

Passes run over the full serving-entry registry (registry.entries);
the mesh tiers join automatically on >= 8 devices. Exit status is
nonzero when any pass REDs — the same verdict the gate's `static` leg
enforces, runnable in isolation by an operator chasing one finding.
`--write-tracebudget PATH` derives and commits a new retrace-budget
round (the explicit act of moving a pin).
"""

from __future__ import annotations

import argparse
import json as _json
import os
import sys

PASSES = ("determinism", "host", "retrace", "sharding")


def run_passes(which: str, write_tracebudget: str | None = None) -> dict:
    """pass name -> list of RED strings (only the selected passes)."""
    from . import determinism, hostdet, registry, retrace, shardspec

    selected = PASSES if which == "all" else (which,)
    out: dict[str, list[str]] = {}
    entries = None
    traces = None

    def _entries():
        nonlocal entries
        if entries is None:
            entries = registry.entries()
        return entries

    def _traces():
        nonlocal traces
        if traces is None:
            traces = {n: e.trace() for n, e in _entries().items()}
        return traces

    if "determinism" in selected:
        out["determinism"] = determinism.run(_traces())
    if "host" in selected:
        out["host"] = hostdet.run()
    if "retrace" in selected:
        fails: list[str] = []
        if write_tracebudget:
            retrace.write_budget(_entries(), write_tracebudget)
            print(f"[jaxhound] wrote {write_tracebudget}")
        else:
            table, audit_fails = retrace.audit(_entries())
            fails.extend(audit_fails)
            try:
                fails.extend(retrace.check_budget(
                    _entries(), table=table))
            except FileNotFoundError as e:
                fails.append(f"tracebudget: {e}")
        for name, cj in _traces().items():
            fails.extend(retrace.weak_carries(cj, name))
        out["retrace"] = fails
    if "sharding" in selected:
        out["sharding"] = shardspec.run(_entries())
    return out


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m tigerbeetle_tpu.jaxhound",
        description=__doc__.splitlines()[0])
    ap.add_argument("--kernel", default=None,
                    help="restrict the HLO report to one kernel")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--pass", dest="which", default=None,
                    choices=PASSES + ("all",),
                    help="run a static pass over the serving-entry "
                         "registry instead of the HLO report")
    ap.add_argument("--write-tracebudget", default=None, metavar="PATH",
                    help="derive and write a new tracebudget round "
                         "(with --pass retrace)")
    args = ap.parse_args(argv)

    if args.which is None:
        from .core import report

        lines = report(args.kernel)
        if args.json:
            print(_json.dumps({"report": lines}, indent=1))
        else:
            print("\n".join(lines))
        return 0

    results = run_passes(args.which, args.write_tracebudget)
    if args.json:
        print(_json.dumps(
            {"passes": {k: {"ok": not v, "findings": v}
                        for k, v in results.items()}}, indent=1))
    else:
        for name, fails in results.items():
            print(f"[jaxhound] pass {name}: "
                  + ("clean" if not fails else f"{len(fails)} RED"))
            for f in fails:
                print(f"  RED {f}")
    return 1 if any(results.values()) else 0


if __name__ == "__main__":
    sys.exit(main())
