"""jaxhound core: census + lint primitives over jax compile artifacts.

reference: src/copyhound.zig:1-9 — the reference hunts large memcpys and
monomorphization bloat in LLVM IR; the TPU-native analog inspects XLA
artifacts: per-kernel HLO instruction counts, fusion counts, and the
largest temp buffers. Compile bloat here is the same disease copyhound
hunts there — generated code growing without anyone noticing.

This module holds the trace-level machinery (heavy-op census, scan-body
census, telemetry census, budget-trail resolvers, closure/while/gather
lints, lowered-artifact analysis). The whole-stack static passes —
device determinism, host determinism, retrace budget, sharding spec —
live in sibling modules of this package; `python -m
tigerbeetle_tpu.jaxhound --help` is the operator entry point.
"""

from __future__ import annotations

import collections
import glob
import os
import re
from typing import Callable

# ------------------------------------------------------------- op budgets
# Heavy-op classes (the ops the tunnel bills ~0.5-1 ms each inside large
# programs — PERF.md dispatch model). jaxpr-primitive -> budget class.
# segment_* reductions lower through scatter-add/min/max; associative
# scans and lax.scan/while are the 'scan' class.
HEAVY_CLASSES = {
    "sort": "sort",
    "gather": "gather",
    "scatter": "scatter",
    "scatter-add": "segment_sum",
    "scatter-max": "segment_sum",
    "scatter-min": "segment_sum",
    "scatter-mul": "segment_sum",
    "scan": "scan",
    "while": "scan",
    "cumsum": "scan",
    "cummax": "scan",
    "cummin": "scan",
    "cumprod": "scan",
    "reduce_window": "scan",
    "reduce_window_sum": "scan",
    "reduce_window_max": "scan",
    "reduce_window_min": "scan",
    # Cross-device collectives (the partitioned exchange's op class):
    # each is an ICI round trip billed like a heavy op, and the
    # partitioned tiers pin their count so the exchange cannot silently
    # grow (opbudget lint: none of these may move whole-state operands).
    "psum": "collective",
    "pmin": "collective",
    "pmax": "collective",
    "all_gather": "collective",
    "all_to_all": "collective",
    "ppermute": "collective",
    "reduce_scatter": "collective",
}
HEAVY_CLASS_ORDER = ("sort", "gather", "scatter", "segment_sum", "scan",
                     "collective")


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def _walk_jaxpr(jaxpr, visit) -> None:
    """Depth-first over a jaxpr and every sub-jaxpr (pjit/cond/scan/
    shard_map/...). Params carry bodies either as ClosedJaxpr (pjit,
    scan — has .jaxpr) or as a raw Jaxpr (shard_map — has .eqns
    directly); both forms recurse."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for sub in eqn.params.values():
            subs = sub if isinstance(sub, (list, tuple)) else (sub,)
            for s in subs:
                if hasattr(s, "eqns"):  # raw Jaxpr param (shard_map)
                    _walk_jaxpr(s, visit)
                    continue
                inner = getattr(s, "jaxpr", None)
                if inner is not None:
                    _walk_jaxpr(inner if hasattr(inner, "eqns") else s,
                                visit)


def heavy_census(closed_jaxpr) -> dict:
    """Per-class heavy-op counts + heavy operand bytes of a traced fn.

    Input: a ClosedJaxpr (jax.make_jaxpr(fn)(*args)). Counts the
    primitives in HEAVY_CLASSES recursively (one count per *executed*
    op instance in the unrolled program — a scan body counts once, like
    the dispatch layer sees it) and sums the operand bytes those ops
    read (the bytes-dependent term of the tunnel's per-op cost).
    Deterministic: no XLA compile, trace-level only.

    The collective class is ALSO broken out by operand bytes
    (`collective_operand_bytes`): collectives bill ICI traffic, not
    HBM reads, so the partitioned budgets pin their byte mass
    separately — including inside lax.scan bodies, where the fused
    partitioned-chain route runs the whole exchange (scan_body_census
    inherits the key; one iteration's exchange bytes, amortized x1 in
    the program like every other body op)."""
    counts = collections.Counter({c: 0 for c in HEAVY_CLASS_ORDER})
    nbytes = [0]
    coll_bytes = [0]

    def visit(eqn):
        cls = HEAVY_CLASSES.get(eqn.primitive.name)
        if cls is None:
            return
        counts[cls] += 1
        b = 0
        for v in eqn.invars:
            b += _aval_bytes(getattr(v, "aval", None))
        nbytes[0] += b
        if cls == "collective":
            coll_bytes[0] += b

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    out = {"heavy": {c: counts[c] for c in HEAVY_CLASS_ORDER}}
    out["heavy_total"] = sum(out["heavy"].values())
    out["heavy_operand_bytes"] = nbytes[0]
    out["collective_operand_bytes"] = coll_bytes[0]
    return out


def scan_bodies(closed_jaxpr) -> list:
    """Every lax.scan body (ClosedJaxpr) anywhere in the program, in
    visit order. The scan-form chain dispatch's whole point is that the
    body lowers ONCE regardless of the scan length W — these are the
    jaxprs the dispatch layer re-executes per iteration."""
    bodies: list = []

    def visit(eqn):
        if eqn.primitive.name == "scan":
            inner = eqn.params.get("jaxpr")
            if inner is not None:
                bodies.append(inner)

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    return bodies


def scan_body_census(closed_jaxpr) -> dict:
    """heavy_census of the LARGEST lax.scan body in the program (by
    heavy total) — the chain route's per-ITERATION op mass. The
    whole-window scan dispatch executes this body once per window
    iteration (body ops x 1 in the program, x W at runtime), so the
    op-budget gate pins the BODY census alongside the whole-program one
    (which counts the body once plus the outer scan op). The census
    covers every heavy class INCLUDING collectives (the fused
    partitioned chain runs the psum exchange inside its scan body) and
    carries their operand-byte mass as collective_operand_bytes —
    state_gathers() recurses into scan bodies with the same classing,
    so a whole-state collective inside a scan cannot hide from the
    lint either. Returns a zero census when the program holds no
    scan."""
    best = None
    for b in scan_bodies(closed_jaxpr):
        c = heavy_census(b)
        if best is None or c["heavy_total"] > best["heavy_total"]:
            best = c
    if best is None:
        best = {"heavy": {c: 0 for c in HEAVY_CLASS_ORDER},
                "heavy_total": 0, "heavy_operand_bytes": 0,
                "collective_operand_bytes": 0}
    return best


# The telemetry plane's pack marker: parallel/partitioned.py stacks its
# u32 telemetry words through a named, non-inlined jit wrapper so the
# pack survives tracing as a `pjit` equation carrying this name — the
# lanes are then a CENSUSABLE CLASS of their own instead of dissolving
# into the surrounding elementwise soup.
TELEMETRY_PACK_NAME = "_telemetry_pack"


def telemetry_census(closed_jaxpr) -> dict:
    """Census of the device-telemetry lanes in a traced program.

    Finds every `pjit` equation named TELEMETRY_PACK_NAME (anywhere —
    including inside the fused chain route's scan body) and reports:
    `sites` (pack call sites in the program), `lanes` (telemetry words
    per pack — the widest site), and `ops` (equation count inside the
    largest pack body). The op-budget gate pins `lanes` so the
    telemetry block cannot grow a word without a committed budget bump,
    and bounds `ops` so 'just one more derived metric' cannot smuggle
    real compute into the observability plane."""
    sites = []

    def visit(eqn):
        if eqn.primitive.name != "pjit":
            return
        if eqn.params.get("name") != TELEMETRY_PACK_NAME:
            return
        inner = eqn.params.get("jaxpr")
        n_ops = len(inner.jaxpr.eqns) if inner is not None else 0
        sites.append((len(eqn.invars), n_ops))

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    return {
        "sites": len(sites),
        "lanes": max((s[0] for s in sites), default=0),
        "ops": max((s[1] for s in sites), default=0),
    }


# Repo-relative perf/ dir: this file lives two levels below the repo
# root (tigerbeetle_tpu/jaxhound/core.py).
_DEFAULT_PERF_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "perf")


def _newest_round_path(perf_dir: str | None, prefix: str) -> str:
    """Resolve the newest committed `<prefix>_r<N>.json` budget file
    (highest round number) under perf_dir."""
    if perf_dir is None:
        perf_dir = _DEFAULT_PERF_DIR
    paths = glob.glob(os.path.join(perf_dir, f"{prefix}_r*.json"))
    best = None
    best_round = -1
    for p in paths:
        m = re.search(rf"{prefix}_r(\d+)\.json$", os.path.basename(p))
        if m and int(m.group(1)) > best_round:
            best_round = int(m.group(1))
            best = p
    if best is None:
        raise FileNotFoundError(
            f"no {prefix}_r*.json under {perf_dir!r}")
    return best


def newest_budget_path(perf_dir: str | None = None) -> str:
    """Path of the NEWEST committed perf/opbudget_r*.json (highest
    round number). The budget trail is append-oriented — every round
    that moves a pinned census commits a new file — so consumers
    (devhub, smokes, the gate) resolve the head dynamically instead of
    hardcoding a round that silently goes stale."""
    return _newest_round_path(perf_dir, "opbudget")


def newest_tracebudget_path(perf_dir: str | None = None) -> str:
    """Path of the NEWEST committed perf/tracebudget_r*.json — the
    retrace-budget trail (compiled-signature pins for the dispatch
    route matrix), same append-oriented regime as the op-budget
    trail."""
    return _newest_round_path(perf_dir, "tracebudget")


def newest_membudget_path(perf_dir: str | None = None) -> str:
    """Path of the NEWEST committed perf/membudget_r*.json — the
    static-allocation memory-budget trail (per-component resident
    bytes for the serving ledger, trace/memwatch.py), same
    append-oriented regime as the op-budget trail."""
    return _newest_round_path(perf_dir, "membudget")


# ----------------------------------------------------------- static lints

CLOSURE_CONST_LIMIT = 4096  # bytes; PERF.md: ~64 ms/call at 0.5 MB


def _collect_consts(closed_jaxpr) -> list:
    """Every closed-over constant anywhere in the program: the
    top-level ClosedJaxpr's consts PLUS the consts of every sub-
    ClosedJaxpr (pjit/cond bodies keep their own const list — a
    constant baked inside a nested jit never surfaces in the outer
    `.consts`, so a top-level-only scan misses exactly the chain /
    partitioned-chain bodies). Raw Jaxpr params (shard_map) carry no
    const list of their own; their constvars are threaded from an
    enclosing ClosedJaxpr, which this walk does see."""
    consts = list(closed_jaxpr.consts)

    def visit(eqn):
        for sub in eqn.params.values():
            subs = sub if isinstance(sub, (list, tuple)) else (sub,)
            for s in subs:
                if hasattr(s, "consts"):  # nested ClosedJaxpr
                    consts.extend(s.consts)

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    return consts


def closure_constants(closed_jaxpr) -> list[tuple[str, int]]:
    """(dtype/shape label, bytes) of every closed-over constant above
    CLOSURE_CONST_LIMIT — including constants closed inside scan/cond/
    pjit sub-jaxprs (a lookup table baked into the chain body is just
    as poisonous as one at top level). The tunnel re-ships baked-in
    constants every call (~64 ms at 0.5 MB — PERF.md 'closure constants
    are poison'), so serving-path entries must take every table as an
    argument."""
    out = []
    for c in _collect_consts(closed_jaxpr):
        shape = getattr(c, "shape", ())
        dtype = getattr(c, "dtype", None)
        if dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        size = n * dtype.itemsize
        if size > CLOSURE_CONST_LIMIT:
            out.append((f"{dtype}{list(shape)}", size))
    return out


def while_ops(closed_jaxpr) -> int:
    """Count of while/fori loops anywhere in the program. One executed
    lax.while_loop degrades every later dispatch in the process to
    5-8 ms (PERF.md round-2 finding) — serving-path lowerings must stay
    straight-line."""
    n = [0]

    def visit(eqn):
        if eqn.primitive.name == "while":
            n[0] += 1

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    return n[0]


# Whole-state gather threshold: the partitioned exchange moves compact
# per-event bundles (a few MB at N_PAD=8192); any collective whose
# operand is larger than this is moving ledger STORE rows, which is
# exactly the regression the partitioned layout exists to prevent.
STATE_GATHER_LIMIT = 16 << 20  # bytes


def state_gathers(closed_jaxpr, limit: int = STATE_GATHER_LIMIT) -> list:
    """(primitive, operand_bytes) for every cross-device collective whose
    per-device operand exceeds `limit` — the 'exchange regressed into a
    whole-state all_gather' lint for partitioned serving entries."""
    hits: list = []

    def visit(eqn):
        if HEAVY_CLASSES.get(eqn.primitive.name) != "collective":
            return
        nbytes = sum(_aval_bytes(getattr(v, "aval", None))
                     for v in eqn.invars)
        if nbytes > limit:
            hits.append((eqn.primitive.name, nbytes))

    _walk_jaxpr(closed_jaxpr.jaxpr, visit)
    return hits


def donated_inputs(lowered) -> int:
    """Number of donated parameters reported by a lowered artifact.
    State-carrying entries must donate their ledger buffers
    (donate_argnums) or every dispatch pays a full state copy. Donation
    appears as input->output aliasing (`tf.aliasing_output`) when
    resolvable at lowering time, or as a `jax.buffer_donor` mark (e.g.
    sharded programs) when the pairing is deferred to the runtime."""
    text = lowered.as_text()
    return (len(re.findall(r"tf\.aliasing_output", text))
            + len(re.findall(r"jax\.buffer_donor", text)))


def analyze_lowered(lowered) -> dict:
    """Instruction histogram + size stats from a lowered jax computation."""
    text = lowered.as_text()
    ops = collections.Counter()
    # StableHLO prints ops in two forms: pretty ('%3 = stablehlo.add %0,
    # %2 : ...') and generic ('%9 = "stablehlo.scatter"(%0, ...) ...');
    # match the op name in either (also '%cst = stablehlo.constant ...').
    op_re = re.compile(r"%[\w#]+(?::\d+)? = \"?([\w]+\.[\w.]+)\"?[ (<]")
    for line in text.splitlines():
        match = op_re.match(line.strip())
        if match:
            ops[match.group(1)] += 1
    compiled = lowered.compile()
    stats = {}
    unavailable = []
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        if analysis:
            stats = {k: analysis[k] for k in
                     ("flops", "bytes accessed", "optimal_seconds")
                     if k in analysis}
    except Exception as e:  # backend-dependent; record WHY it failed
        unavailable.append(f"cost_analysis: {type(e).__name__}: {e}")
    try:
        mem = compiled.memory_analysis()
        stats["temp_bytes"] = getattr(mem, "temp_size_in_bytes", None)
        stats["argument_bytes"] = getattr(mem, "argument_size_in_bytes", None)
        stats["output_bytes"] = getattr(mem, "output_size_in_bytes", None)
    except Exception as e:
        unavailable.append(f"memory_analysis: {type(e).__name__}: {e}")
    out = {
        "instructions": sum(ops.values()),
        "top_ops": ops.most_common(12),
        "stats": stats,
    }
    if unavailable:
        # Consumers (report(), devhub) render "n/a: <reason>" instead
        # of mistaking a swallowed backend failure for zero cost.
        out["stats_unavailable"] = "; ".join(unavailable)
    return out


def kernels() -> dict[str, Callable[[], "object"]]:
    """Lowerable entry points (thunks so nothing compiles until asked)."""

    def transfers_fast():
        import jax
        import numpy as np

        from ..ops.batch import transfers_to_arrays
        from ..ops.fast_kernels import create_transfers_fast
        from ..ops.ledger import init_state, pad_transfer_events
        from ..types import Transfer

        state = init_state(1 << 10, 1 << 12)
        ev = pad_transfer_events(transfers_to_arrays(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1)]))
        return jax.jit(create_transfers_fast).lower(
            state, ev, np.uint64(1000), np.int32(1))

    def accounts_fast():
        import jax
        import numpy as np

        from ..ops.fast_kernels import create_accounts_fast
        from ..ops.ledger import init_state, pad_account_events
        from ..ops.batch import accounts_to_arrays
        from ..types import Account

        state = init_state(1 << 10, 1 << 12)
        ev = pad_account_events(accounts_to_arrays(
            [Account(id=1, ledger=1, code=1)]))
        return jax.jit(create_accounts_fast).lower(
            state, ev, np.uint64(1000), np.int32(1))

    return {
        "create_transfers_fast": transfers_fast,
        "create_accounts_fast": accounts_fast,
    }


def report(kernel: str | None = None) -> list[str]:
    registry = kernels()
    if kernel is not None and kernel not in registry:
        raise KeyError(
            f"unknown kernel {kernel!r}; available: {sorted(registry)}")
    lines = []
    for name, thunk in registry.items():
        if kernel and name != kernel:
            continue
        info = analyze_lowered(thunk())
        lines.append(f"{name}: {info['instructions']} HLO instructions")
        for op, count in info["top_ops"]:
            lines.append(f"  {op:<24} {count}")
        for key, value in info["stats"].items():
            if value is not None:
                lines.append(f"  {key}: {value}")
        if info.get("stats_unavailable"):
            lines.append(f"  stats: n/a ({info['stats_unavailable']})")
    return lines
