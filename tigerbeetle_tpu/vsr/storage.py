"""Storage: zoned access to a replica's single data file.

reference: src/storage.zig (zone-aware sector IO) + data-file layout
docs/internals/data_file.md:11-97. Zones here:

  superblock   SUPERBLOCK_COPIES x SUPERBLOCK_COPY_SIZE
  wal_headers  slot_count x 256
  wal_prepares slot_count x message_size_max
  client_replies clients_max x message_size_max
  snapshot     2 x snapshot_size_max  (A/B checkpoint-root slots)
  grid         grid_block_count x grid_block_size (LSM copy-on-write blocks)

Round-1 simplification (vs the reference's io_uring async path): the IO
interface is synchronous; the deterministic simulator injects faults by
wrapping MemoryStorage (corrupting reads/writes per its fault plan) and by
cutting writes short at crash points. The async completion model returns
with the native C++ storage engine.
"""

from __future__ import annotations

import dataclasses
import os

from .header import HEADER_SIZE

SUPERBLOCK_COPIES = 4
SUPERBLOCK_COPY_SIZE = 4096


@dataclasses.dataclass(frozen=True)
class StorageLayout:
    """Sizes that shape the data file (consensus-critical; reference:
    src/config.zig:153-163)."""

    slot_count: int = 1024
    message_size_max: int = 1024 * 1024
    clients_max: int = 64
    # The snapshot zone holds the two A/B checkpoint-root blobs (forest
    # manifests address + free set) — small; bulk state lives in the grid.
    snapshot_size_max: int = 4 * 1024 * 1024
    grid_block_size: int = 64 * 1024
    grid_block_count: int = 8192  # 512 MiB grid zone

    @property
    def zone_offsets(self) -> dict:
        off = {}
        pos = 0
        off["superblock"] = pos
        pos += SUPERBLOCK_COPIES * SUPERBLOCK_COPY_SIZE
        off["wal_headers"] = pos
        pos += self.slot_count * HEADER_SIZE
        off["wal_prepares"] = pos
        pos += self.slot_count * self.message_size_max
        off["client_replies"] = pos
        pos += self.clients_max * self.message_size_max
        off["snapshot"] = pos
        pos += 2 * self.snapshot_size_max
        off["grid"] = pos
        pos += self.grid_block_count * self.grid_block_size
        off["_end"] = pos
        return off

    @property
    def size(self) -> int:
        return self.zone_offsets["_end"]


TEST_LAYOUT = StorageLayout(
    slot_count=32, message_size_max=64 * 1024, clients_max=8,
    snapshot_size_max=256 * 1024, grid_block_size=8 * 1024,
    grid_block_count=2048)


class Storage:
    """Abstract zoned storage."""

    layout: StorageLayout

    def read(self, zone: str, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def write(self, zone: str, offset: int, data: bytes) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        pass

    def erase(self) -> None:
        """Zero the entire data file (the vortex data-file-destruction
        fault: total single-replica data loss, recoverable only via
        `recover --from-cluster`). Chunked so a production-size file
        never materializes in memory at once."""
        chunk = 1 << 20
        zones = self.layout.zone_offsets
        names = [z for z in zones if z != "_end"]
        for i, zone in enumerate(names):
            size = (zones[names[i + 1]] if i + 1 < len(names)
                    else zones["_end"]) - zones[zone]
            for off in range(0, size, chunk):
                self.write(zone, off, b"\x00" * min(chunk, size - off))
        self.sync()

    # ------------------------------------------------ async (optional)
    # Overlapped IO for the WAL path (reference: src/io/linux.zig). The
    # default implementation is synchronous-only: write_pair_async
    # returns None and the caller falls back to blocking writes — the
    # deterministic simulator keeps this behavior.

    def write_pair_async(self, zone1: str, off1: int, data1: bytes,
                         zone2: str, off2: int, data2: bytes):
        """Submit an ordered write pair (data2 strictly after data1);
        returns a completion token, or None when unsupported."""
        return None

    def io_poll(self) -> list:
        """Nonblocking: completion tokens ready to reap."""
        return []

    def io_reap(self, token) -> None:
        """Block until `token` completes; raises on write failure."""
        raise KeyError(f"unknown io token {token!r}")

    def read_batch(self, zone: str, reqs: list) -> list:
        """Read many (offset, size) extents; concurrent when the engine
        supports it (reference: the prefetch fan-out issues all of a
        batch's reads at once, src/lsm/groove.zig:996,1339)."""
        return [self.read(zone, off, size) for off, size in reqs]

    def read_submit(self, zone: str, reqs: list):
        """Submit (offset, size) reads WITHOUT waiting; returns tokens
        for read_fetch, or None when unsupported (the caller reads
        synchronously instead). This is the fire-and-continue half of
        the reference's overlapped read path (src/storage.zig:177 —
        every read is an io_uring submission the event loop outlives);
        the grid's block read-ahead rides it."""
        return None

    def read_fetch(self, token, size: int) -> bytes:
        """Block until a read_submit token completes; returns the data."""
        raise KeyError(f"unknown read token {token!r}")

    def _check(self, zone: str, offset: int, size: int) -> int:
        zones = self.layout.zone_offsets
        base = zones[zone]
        keys = list(zones)
        limit = zones[keys[keys.index(zone) + 1]]
        assert base + offset + size <= limit, (zone, offset, size)
        return base + offset


class MemoryStorage(Storage):
    """In-memory data file (simulator base; reference testing/storage.zig)."""

    def __init__(self, layout: StorageLayout = TEST_LAYOUT):
        self.layout = layout
        self.data = bytearray(layout.size)
        self.reads = 0
        self.writes = 0

    def read(self, zone: str, offset: int, size: int) -> bytes:
        pos = self._check(zone, offset, size)
        self.reads += 1
        return bytes(self.data[pos:pos + size])

    def write(self, zone: str, offset: int, data: bytes) -> None:
        pos = self._check(zone, offset, len(data))
        self.writes += 1
        self.data[pos:pos + len(data)] = data


class FileStorage(Storage):
    """File-backed storage, served by the native C++ engine when available
    (native/storage_engine.cpp via ctypes; reference: src/storage.zig
    read_sectors/write_sectors). Falls back to os.pread/pwrite."""

    def __init__(self, path: str, layout: StorageLayout = StorageLayout(),
                 create: bool = False, async_grid: bool = True):
        from .. import native as native_mod

        self.layout = layout
        self.path = path
        self.native = None
        # Async grid-zone writes through the native submission engine
        # (reference: the io_uring layer, src/io/linux.zig): LSM block
        # writes (compaction, flush) no longer block the replica loop.
        # Correctness: grid blocks are immutable copy-on-write and cached
        # at write, so the only read that could race a pending write is a
        # cold/bypass read — those drain first (`_drain_grid`); sync()
        # drains + fsyncs (the checkpoint barrier).
        self.aio = None
        self._grid_pending: dict[int, tuple[int, int]] = {}  # token -> (pos, end)
        self._read_pending: set[int] = set()  # read-ahead tokens in flight
        if native_mod.available():
            self.native = native_mod.NativeFile(path, layout.size, create)
            self.fd = -1
            if async_grid:
                self.aio = native_mod.AsyncEngine(self.native)
            return
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self.fd = os.open(path, flags, 0o644)
        if create:
            os.ftruncate(self.fd, layout.size)

    def _drain_grid(self, pos: int = None, size: int = None) -> None:
        """Settle pending grid writes overlapping [pos, pos+size) — or all
        of them. Waits only on the overlapping grid tokens, never on
        unrelated in-flight ops (the journal's async WAL pairs share the
        engine; a cold grid read must not stall behind them)."""
        if self.aio is None or not self._grid_pending:
            return
        if pos is None:
            tokens = list(self._grid_pending)
        else:
            end = pos + size
            tokens = [tok for tok, (p, e) in self._grid_pending.items()
                      if p < end and pos < e]
            if not tokens:
                return
        for token in tokens:
            del self._grid_pending[token]
            self._reap_grid(token)

    def _reap_grid(self, token: int) -> None:
        try:
            self.aio.fetch(token)
        except OSError:
            # Same contract as the drain barrier: a lost grid write
            # means durability is compromised (sticky in the engine).
            raise RuntimeError(
                "async write failed (sticky): storage compromised")

    def read(self, zone: str, offset: int, size: int) -> bytes:
        pos = self._check(zone, offset, size)
        if zone == "grid":
            self._drain_grid(pos, size)
        if self.native is not None:
            return self.native.read(pos, size)
        data = os.pread(self.fd, size, pos)
        if len(data) < size:
            data += b"\x00" * (size - len(data))
        return data

    def write(self, zone: str, offset: int, data: bytes) -> None:
        pos = self._check(zone, offset, len(data))
        if zone == "grid" and self.aio is not None:
            token = self.aio.submit_write_tracked(pos, data)
            self._grid_pending[token] = (pos, pos + len(data))
            return
        if self.native is not None:
            self.native.write(pos, data)
            return
        os.pwrite(self.fd, data, pos)

    def write_pair_async(self, zone1: str, off1: int, data1: bytes,
                         zone2: str, off2: int, data2: bytes):
        if self.aio is None:
            return None
        pos1 = self._check(zone1, off1, len(data1))
        pos2 = self._check(zone2, off2, len(data2))
        return self.aio.submit_write_pair(pos1, data1, pos2, data2)

    def io_poll(self) -> list:
        """Completion tokens for OTHER subsystems (the journal's WAL
        pairs). Completed grid-write records are reaped here as a side
        effect — left unfetched they would pile up in the engine and
        crowd real tokens out of the poll window (a stalled WAL callback
        is a stalled commit)."""
        if self.aio is None:
            return []
        out = []
        for token in self.aio.poll():
            if token in self._grid_pending:
                del self._grid_pending[token]
                self._reap_grid(token)
            elif token not in self._read_pending:
                # Read-ahead tokens stay in the engine until their
                # owner fetches them (tbio_poll is non-consuming).
                out.append(token)
        return out

    def io_reap(self, token) -> None:
        assert self.aio is not None
        self.aio.fetch(token)

    def read_batch(self, zone: str, reqs: list) -> list:
        if self.aio is None or len(reqs) <= 1:
            return [self.read(zone, off, size) for off, size in reqs]
        positions = []
        for off, size in reqs:
            pos = self._check(zone, off, size)
            if zone == "grid":
                self._drain_grid(pos, size)
            positions.append(pos)
        tokens = [self.aio.submit_read(pos, size)
                  for pos, (_, size) in zip(positions, reqs)]
        out = []
        for tok, (_, size) in zip(tokens, reqs):
            data = self.aio.fetch(tok, size)
            if len(data) < size:
                data += b"\x00" * (size - len(data))
            out.append(data)
        return out

    def read_submit(self, zone: str, reqs: list):
        if self.aio is None:
            return None
        tokens = []
        for off, size in reqs:
            pos = self._check(zone, off, size)
            if zone == "grid":
                self._drain_grid(pos, size)
            token = self.aio.submit_read(pos, size)
            self._read_pending.add(token)
            tokens.append(token)
        return tokens

    def read_fetch(self, token, size: int) -> bytes:
        self._read_pending.discard(token)
        data = self.aio.fetch(token, size)
        if len(data) < size:
            data += b"\x00" * (size - len(data))
        return data

    def sync(self) -> None:
        if self.aio is not None:
            # Reap tracked grid tokens first (drain alone would leave
            # their completion records unfetched in the engine), then the
            # engine-wide durability barrier.
            self._drain_grid()
            self.aio.drain(sync=True)
            return
        if self.native is not None:
            self.native.sync()
            return
        os.fsync(self.fd)

    def close(self) -> None:
        if self.aio is not None:
            try:
                self.aio.drain(sync=True)
            finally:
                # Even a failed final drain must release the worker
                # threads and the fd.
                self.aio.close()
                self.aio = None
        if self.native is not None:
            self.native.close()
            return
        os.close(self.fd)
