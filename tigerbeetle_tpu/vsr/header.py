"""256-byte checksummed message header.

Every message and WAL entry starts with one (reference:
src/vsr/message_header.zig:17-76). This is a fresh layout — same size, same
invariant style (checksum covers the rest of the header; checksum_body
covers the body; `parent` hash-chains prepares) — designed for this
framework rather than wire compatibility with the reference.

Layout (little-endian, 256 bytes):
  offset size field
  0      16   checksum        (over bytes 16..256)
  16     16   checksum_body
  32     16   parent          (hash chain: previous prepare's checksum)
  48     16   client          (client id, u128)
  64     16   context         (command-specific, e.g. reply's request chain)
  80     8    cluster
  96+    ...  see _FMT below
"""

from __future__ import annotations

import dataclasses
import enum
import struct

from ..trace.context import CTX_WIRE_SIZE, TraceContext
from .checksum import checksum

HEADER_SIZE = 256

# The trace-context block (ISSUE 15) rides in the reserved region, at
# this offset into the packed 256 bytes.  The header checksum is
# computed over a ZEROED reserved region (`_packed_tail`), so the block
# is out-of-checksum by construction: corrupting it degrades the frame
# to "unsampled" (TraceContext.unpack -> None) without invalidating the
# header or body.
TRACE_CTX_OFFSET = HEADER_SIZE - 116
assert TRACE_CTX_OFFSET + CTX_WIRE_SIZE <= HEADER_SIZE


class Command(enum.IntEnum):
    """reference: src/vsr.zig:230 (21 live commands, table in
    docs/internals/vsr.md:30-51)."""

    reserved = 0
    ping = 1
    pong = 2
    ping_client = 3
    pong_client = 4
    request = 5
    prepare = 6
    prepare_ok = 7
    reply = 8
    commit = 9
    start_view_change = 10
    do_view_change = 11
    start_view = 12
    request_start_view = 13
    request_headers = 14
    headers = 15
    request_prepare = 16
    request_reply = 17
    eviction = 18
    request_blocks = 19
    block = 20
    # Protocol-aware recovery (reference: quorum_nack_prepare,
    # src/vsr/replica.zig:254, docs/ARCHITECTURE.md:540-563): "I can
    # prove I never prepared this op/checksum" — sent in response to an
    # unserviceable request_prepare by a replica whose WAL slot for the
    # op is demonstrably not a torn write of it.
    nack_prepare = 21


_FMT = struct.Struct(
    "<16s16s16s16s16s"  # checksum, checksum_body, parent, client, context
    "QII"               # cluster, size, epoch
    "QQQQ"              # view, op, commit, timestamp
    "IIHBB"             # request, release, operation, command, replica
    "116s"              # reserved
)
assert _FMT.size == HEADER_SIZE


def _u128b(x: int) -> bytes:
    return x.to_bytes(16, "little")


def _u128i(b: bytes) -> int:
    return int.from_bytes(b, "little")


@dataclasses.dataclass
class Header:
    checksum: int = 0
    checksum_body: int = 0
    parent: int = 0
    client: int = 0
    context: int = 0
    cluster: int = 0
    size: int = HEADER_SIZE  # header + body bytes
    epoch: int = 0
    view: int = 0
    op: int = 0
    commit: int = 0
    timestamp: int = 0
    request: int = 0
    release: int = 0
    operation: int = 0
    command: Command = Command.reserved
    replica: int = 0
    # Causal identity (not part of either checksum; see TRACE_CTX_OFFSET).
    trace_ctx: TraceContext | None = None

    def _packed_tail(self) -> bytes:
        return _FMT.pack(
            b"\x00" * 16,
            _u128b(self.checksum_body),
            _u128b(self.parent),
            _u128b(self.client),
            _u128b(self.context),
            self.cluster, self.size, self.epoch,
            self.view, self.op, self.commit, self.timestamp,
            self.request, self.release, self.operation,
            int(self.command), self.replica,
            b"\x00" * 116,
        )[16:]

    def calculate_checksum(self) -> int:
        return checksum(self._packed_tail(), domain=b"hdr")

    def set_checksum_body(self, body: bytes) -> None:
        assert len(body) == self.size - HEADER_SIZE
        self.checksum_body = checksum(body, domain=b"body")

    def finalize(self, body: bytes = b"") -> "Header":
        """Set size/checksum_body/checksum for this header+body."""
        self.size = HEADER_SIZE + len(body)
        self.set_checksum_body(body)
        self.checksum = self.calculate_checksum()
        return self

    def pack(self) -> bytes:
        raw = _u128b(self.checksum) + self._packed_tail()
        if self.trace_ctx is None:
            return raw
        return (raw[:TRACE_CTX_OFFSET] + self.trace_ctx.pack()
                + raw[TRACE_CTX_OFFSET + CTX_WIRE_SIZE:])

    @classmethod
    def unpack(cls, data: bytes) -> "Header":
        f = _FMT.unpack(data[:HEADER_SIZE])
        return cls(
            checksum=_u128i(data[:16]),
            checksum_body=_u128i(f[1]),
            parent=_u128i(f[2]),
            client=_u128i(f[3]),
            context=_u128i(f[4]),
            cluster=f[5], size=f[6], epoch=f[7],
            view=f[8], op=f[9], commit=f[10], timestamp=f[11],
            request=f[12], release=f[13], operation=f[14],
            command=Command(f[15]), replica=f[16],
            trace_ctx=TraceContext.unpack(
                data[TRACE_CTX_OFFSET:TRACE_CTX_OFFSET + CTX_WIRE_SIZE]),
        )

    def valid_checksum(self) -> bool:
        return self.checksum == self.calculate_checksum()

    def valid_checksum_body(self, body: bytes) -> bool:
        if len(body) != self.size - HEADER_SIZE:
            return False
        return self.checksum_body == checksum(body, domain=b"body")


@dataclasses.dataclass
class Message:
    """A header + body pair (reference: src/message_pool.zig Message)."""

    header: Header
    body: bytes = b""

    def pack(self) -> bytes:
        return self.header.pack() + self.body

    @classmethod
    def unpack(cls, data: bytes) -> "Message":
        header = Header.unpack(data[:HEADER_SIZE])
        body = data[HEADER_SIZE:header.size]
        return cls(header=header, body=body)

    def valid(self) -> bool:
        return (self.header.valid_checksum()
                and self.header.valid_checksum_body(self.body))
