"""Cluster client: sessions, request/reply, hedged retries.

reference: src/vsr/client.zig (ClientType: register :273, request :326,
send_request_with_hedging :734). Sessions are implicit (created on first
request); one in-flight request at a time (the reference enforces the same
per-client serialization). Hedging: the request goes to the believed
primary first; only if no reply arrives within the hedge delay does it fan
out to every replica — steady-state traffic is 1 message per request, not
N, while view changes still resolve via the fan-out.
"""

from __future__ import annotations

import time as _time
from typing import Optional

from ..clients.common import ClientHelpers
from ..types import Operation
from .header import Command, Header, Message
from .message_bus import MessageBus


class SessionEvicted(Exception):
    """The cluster evicted this client's session (table full); create a
    new Client (new session) to continue (reference: eviction message)."""


class Client(ClientHelpers):
    def __init__(self, *, cluster: int, client_id: int,
                 replica_addresses: list[tuple[str, int]],
                 hedge_delay_s: float = 0.1):
        self.cluster = cluster
        self.client_id = client_id
        self.request_number = 0
        self.hedge_delay_s = hedge_delay_s
        self._reply: Optional[Message] = None
        self._evicted = False
        self._primary_guess = 0
        self.bus = MessageBus(
            cluster=cluster, on_message=self._on_message,
            replica_addresses=replica_addresses)

    def _on_message(self, msg: Message) -> None:
        h = msg.header
        if h.command == Command.eviction and h.client == self.client_id:
            self._evicted = True
            return
        if h.command == Command.reply and h.request == self.request_number:
            self._reply = msg
            # The reply carries the committing view: remember its primary
            # so the next request goes straight there (hedging).
            self._primary_guess = h.view % len(self.bus.replica_addresses)

    def request(self, operation: Operation, body: bytes,
                timeout_s: float = 10.0) -> bytes:
        """Send one request and block until its reply. Hedged: believed
        primary first, full fan-out only after hedge_delay_s, then resends
        every 500ms until the deadline."""
        if self._evicted:
            raise SessionEvicted(f"client {self.client_id} was evicted")
        self.request_number += 1
        header = Header(
            command=Command.request, cluster=self.cluster,
            client=self.client_id, request=self.request_number,
            operation=int(operation))
        msg = Message(header.finalize(body), body=body)
        self._reply = None
        start = _time.monotonic()
        deadline = start + timeout_s
        hedge_at = start + self.hedge_delay_s
        resend_at = 0.0
        self.bus.send_to_replica(self._primary_guess, msg)
        while self._reply is None:
            if self._evicted:
                raise SessionEvicted(
                    f"client {self.client_id} was evicted")
            now = _time.monotonic()
            if now >= deadline:
                raise TimeoutError(f"request {self.request_number} timed out")
            if now >= hedge_at and now >= resend_at:
                resend_at = now + 0.5
                for r in range(len(self.bus.replica_addresses)):
                    self.bus.send_to_replica(r, msg)
            self.bus.poll(0.02)
        return self._reply.body

    # Typed helpers (create_accounts, lookups, queries) come from
    # ClientHelpers — shared with the native C binding.

    def close(self) -> None:
        self.bus.close()
