"""Cluster client: sessions, request/reply, adaptive hedged retries.

reference: src/vsr/client.zig (ClientType: register :273, request :326,
send_request_with_hedging :734). Sessions are implicit (created on first
request); one in-flight request at a time (the reference enforces the same
per-client serialization). Hedging: the request goes to the believed
primary first; only if no reply arrives within the hedge delay does it fan
out to every replica — steady-state traffic is 1 message per request, not
N, while view changes still resolve via the fan-out.

Adaptivity (the reference's resend battery is RTT-driven, not fixed):
the hedge delay tracks an EWMA of observed reply round-trips (hedge =
multiple of smoothed RTT, clamped), and fan-out resends back off
exponentially with deterministic jitter — a slow-but-alive cluster isn't
drowned in duplicate requests, a fast one hedges in milliseconds.
"""

from __future__ import annotations

import time as _time
from typing import Optional

from ..clients.common import ClientHelpers
from ..trace import Event, NullTracer, mint_context
from ..types import Operation
from .header import Command, Header, Message
from .message_bus import MessageBus

# Hedge-delay bounds (seconds): even a sub-ms RTT keeps a floor (one
# scheduling quantum), and a degraded link never pushes the first
# fan-out past the ceiling.
HEDGE_MIN_S = 0.01
HEDGE_MAX_S = 1.0
HEDGE_RTT_MULTIPLIER = 4.0
RESEND_BASE_S = 0.25
RESEND_MAX_S = 4.0
RTT_EWMA_ALPHA = 0.2


class SessionEvicted(Exception):
    """The cluster evicted this client's session (table full); create a
    new Client (new session) to continue (reference: eviction message)."""


class Client(ClientHelpers):
    def __init__(self, *, cluster: int, client_id: int,
                 replica_addresses: list[tuple[str, int]],
                 hedge_delay_s: Optional[float] = None,
                 tracer=None, trace_head_rate: float = 1.0,
                 trace_seed: int = 0):
        self.cluster = cluster
        self.client_id = client_id
        self.request_number = 0
        # Fixed override for tests/operators; None = adapt to RTT.
        self._hedge_override = hedge_delay_s
        self.rtt_ewma_s: Optional[float] = None
        self._reply: Optional[Message] = None
        self._evicted = False
        self._primary_guess = 0
        # Causal tracing: every request mints a deterministic trace
        # context (ISSUE 15); the recording span is the request's ROOT,
        # and the context rides the wire header to the replicas.
        self.tracer = tracer if tracer is not None else NullTracer()
        self.trace_head_rate = trace_head_rate
        self.trace_seed = trace_seed
        self.bus = MessageBus(
            cluster=cluster, on_message=self._on_message,
            replica_addresses=replica_addresses, tracer=self.tracer)

    # ------------------------------------------------------- adaptivity

    def _observe_rtt(self, rtt_s: float) -> None:
        """Fold one observed request->reply round-trip into the EWMA
        (reference: the client's timeouts are RTT-informed rather than
        fixed constants, src/vsr/client.zig:734)."""
        if self.rtt_ewma_s is None:
            self.rtt_ewma_s = rtt_s
        else:
            self.rtt_ewma_s += RTT_EWMA_ALPHA * (rtt_s - self.rtt_ewma_s)

    def hedge_delay_s(self) -> float:
        """Current hedge delay: a multiple of the smoothed RTT, clamped.
        Before any reply has been observed, the ceiling applies (an
        unknown cluster gets maximum patience before the fan-out)."""
        if self._hedge_override is not None:
            return self._hedge_override
        if self.rtt_ewma_s is None:
            return HEDGE_MAX_S
        return min(HEDGE_MAX_S,
                   max(HEDGE_MIN_S, HEDGE_RTT_MULTIPLIER * self.rtt_ewma_s))

    def _resend_delay_s(self, attempt: int) -> float:
        """Exponential backoff with deterministic per-client jitter
        (clients hash to different phases so synchronized retry storms
        can't form)."""
        base = min(RESEND_MAX_S, RESEND_BASE_S * (2 ** attempt))
        jitter = 1.0 + 0.25 * (((self.client_id * 2654435761) >> 7 & 0xFF)
                               / 255.0)
        return base * jitter

    # --------------------------------------------------------- messages

    def _on_message(self, msg: Message) -> None:
        h = msg.header
        if h.command == Command.eviction and h.client == self.client_id:
            self._evicted = True
            return
        if h.command == Command.reply and h.request == self.request_number:
            self._reply = msg
            # The reply carries the committing view: remember its primary
            # so the next request goes straight there (hedging).
            self._primary_guess = h.view % len(self.bus.replica_addresses)

    def request(self, operation: Operation, body: bytes,
                timeout_s: float = 10.0) -> bytes:
        """Send one request and block until its reply. Hedged: believed
        primary first; full fan-out only after the adaptive hedge delay,
        then resends with exponential backoff until the deadline."""
        if self._evicted:
            raise SessionEvicted(f"client {self.client_id} was evicted")
        self.request_number += 1
        ctx = mint_context(self.client_id, self.request_number,
                           head_rate=self.trace_head_rate,
                           seed=self.trace_seed)
        with self.tracer.span(Event.client_request, ctx=ctx,
                              operation=int(operation)) as root:
            header = Header(
                command=Command.request, cluster=self.cluster,
                client=self.client_id, request=self.request_number,
                operation=int(operation), trace_ctx=root.ctx or ctx)
            msg = Message(header.finalize(body), body=body)
            self._reply = None
            # Liveness plane (timeout/hedge pacing), never committed
            # state: replies are ordered by the replicas, not by when
            # this client observed them.
            start = _time.monotonic()  # jaxhound: allow(wall_clock)
            deadline = start + timeout_s
            hedge_at = start + self.hedge_delay_s()
            resend_at = 0.0
            attempt = 0
            self.bus.send_to_replica(self._primary_guess, msg)
            while self._reply is None:
                if self._evicted:
                    raise SessionEvicted(
                        f"client {self.client_id} was evicted")
                now = _time.monotonic()  # jaxhound: allow(wall_clock)
                if now >= deadline:
                    raise TimeoutError(
                        f"request {self.request_number} timed out")
                if now >= hedge_at and now >= resend_at:
                    resend_at = now + self._resend_delay_s(attempt)
                    attempt += 1
                    for r in range(len(self.bus.replica_addresses)):
                        self.bus.send_to_replica(r, msg)
                self.bus.poll(0.02)
            if attempt == 0:
                # Only un-hedged round-trips feed the EWMA: a reply that
                # needed the fan-out measures hedge-wait + loss recovery,
                # not RTT — folding those in would ratchet the hedge
                # delay toward the cap exactly when fast fan-out matters
                # most.
                self._observe_rtt(
                    _time.monotonic() - start)  # jaxhound: allow(wall_clock)
            return self._reply.body

    # Typed helpers (create_accounts, lookups, queries) come from
    # ClientHelpers — shared with the native C binding.

    def close(self) -> None:
        self.bus.close()
