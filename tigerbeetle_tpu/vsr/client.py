"""Cluster client: sessions, request/reply, retries.

reference: src/vsr/client.zig (ClientType: register :273, request :326).
Simplified for round 1: no request hedging, sessions are implicit (created
on first request), one in-flight request at a time (the reference enforces
the same per-client serialization).
"""

from __future__ import annotations

import time as _time
from typing import Optional

from ..clients.common import ClientHelpers
from ..types import Operation
from .header import Command, Header, Message
from .message_bus import MessageBus


class Client(ClientHelpers):
    def __init__(self, *, cluster: int, client_id: int,
                 replica_addresses: list[tuple[str, int]]):
        self.cluster = cluster
        self.client_id = client_id
        self.request_number = 0
        self._reply: Optional[Message] = None
        self.bus = MessageBus(
            cluster=cluster, on_message=self._on_message,
            replica_addresses=replica_addresses)

    def _on_message(self, msg: Message) -> None:
        if (msg.header.command == Command.reply
                and msg.header.request == self.request_number):
            self._reply = msg

    def request(self, operation: Operation, body: bytes,
                timeout_s: float = 10.0) -> bytes:
        """Send one request and block until its reply (resending on
        timeout; all replicas are addressed, only the primary acts)."""
        self.request_number += 1
        header = Header(
            command=Command.request, cluster=self.cluster,
            client=self.client_id, request=self.request_number,
            operation=int(operation))
        msg = Message(header.finalize(body), body=body)
        self._reply = None
        deadline = _time.monotonic() + timeout_s
        resend_at = 0.0
        while self._reply is None:
            now = _time.monotonic()
            if now >= deadline:
                raise TimeoutError(f"request {self.request_number} timed out")
            if now >= resend_at:
                resend_at = now + 0.5
                for r in range(len(self.bus.replica_addresses)):
                    self.bus.send_to_replica(r, msg)
            self.bus.poll(0.02)
        return self._reply.body

    # Typed helpers (create_accounts, lookups, queries) come from
    # ClientHelpers — shared with the native C binding.

    def close(self) -> None:
        self.bus.close()
