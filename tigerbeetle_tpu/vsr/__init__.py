"""VSR consensus and durability (reference: src/vsr/, SURVEY §2.1).

The control plane of the framework: replicated state machines over a custom
message bus, a write-ahead journal, quorum-replicated superblocks, and
deterministic checkpoints. All components are sans-IO: Storage, MessageBus,
and Time are constructor-injected so the deterministic simulator
(tigerbeetle_tpu.testing) can drive whole clusters in one process — the
Python restatement of the reference's comptime dependency injection
(src/testing/cluster.zig:70).
"""

from .checksum import checksum
from .header import Command, Header

__all__ = ["checksum", "Command", "Header"]
