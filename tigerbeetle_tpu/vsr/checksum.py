"""128-bit content checksums.

The reference uses AEGIS-128L with a zero key for speed on AES-NI hardware
(src/vsr/checksum.zig:1-63). This rebuild uses keyed BLAKE2b truncated to
128 bits — the fastest cryptographic-quality hash in the Python stdlib and
available everywhere the host runtime runs. The role is identical: detect
disk/network corruption and misdirected reads, not authenticate adversaries.

Checksums are domain-separated by a context byte so a header checksum can
never validate as a body checksum.
"""

from __future__ import annotations

import hashlib

_SEED = b"tigerbeetle-tpu-checksum"


def checksum(data: bytes, domain: bytes = b"") -> int:
    """128-bit checksum of `data` as an int."""
    h = hashlib.blake2b(data, digest_size=16, key=_SEED + domain)
    return int.from_bytes(h.digest(), "little")


def checksum_bytes(data: bytes, domain: bytes = b"") -> bytes:
    return hashlib.blake2b(data, digest_size=16, key=_SEED + domain).digest()
