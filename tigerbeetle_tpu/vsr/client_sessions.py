"""Client sessions + durable replies.

reference: src/vsr/client_sessions.zig (session table, at-most-once
semantics, eviction) + src/vsr/client_replies.zig (latest reply per client
persisted in the client_replies zone, one slot per session). The session
table itself rides in the checkpoint root blob (reference: checkpoint
trailer); reply bodies live in the zone so a restarted replica can answer
duplicate requests without re-executing them.

Each entry records its reply's size + checksum independently of whether the
reply bytes are currently present: a torn/corrupt reply slot (or a
state-synced table whose zone hasn't been filled yet) keeps the entry with
`reply=None` and is repaired from peers (request_reply), while `pack()`
stays a pure function of the committed op sequence — so checkpoint roots
remain byte-identical across replicas even while a reply is missing
locally (reference: reply slots are repairable faults the same way).

Determinism: slot assignment and eviction are pure functions of the
committed op sequence (first-free slot; evict the session with the oldest
request number, ties on client id), so all replicas agree byte-for-byte.
"""

from __future__ import annotations

import struct
from typing import Optional

from .checksum import checksum
from .header import Message
from .storage import Storage

_ENTRY = struct.Struct("<16sIIQ16s")  # client, request, slot, size, checksum


class ClientSessions:
    def __init__(self, storage: Storage):
        self.storage = storage
        self.capacity = storage.layout.clients_max
        # client id -> {"request", "slot", "reply": Optional[Message],
        #               "reply_size", "reply_checksum"}
        self.entries: dict[int, dict] = {}

    # ------------------------------------------------------------- lookups

    def get(self, client: int) -> Optional[dict]:
        return self.entries.get(client)

    def missing_replies(self) -> list[int]:
        """Clients whose recorded reply bytes are absent locally (torn slot
        or post-state-sync) — the repair work list."""
        return [c for c, e in self.entries.items()
                if e["reply"] is None and e["reply_size"] > 0]

    # ------------------------------------------------------------- updates

    def put_reply(self, client: int, request: int,
                  reply: Message) -> Optional[int]:
        """Record the latest reply for `client`; persist it to the zone.
        Returns an evicted client id when the table was full (the caller
        sends it an eviction message), else None."""
        evicted = None
        entry = self.entries.get(client)
        if entry is None:
            if len(self.entries) >= self.capacity:
                evicted = min(
                    self.entries,
                    key=lambda c: (self.entries[c]["request"], c))
                entry = self.entries.pop(evicted)
                slot = entry["slot"]
            else:
                used = {e["slot"] for e in self.entries.values()}
                slot = next(s for s in range(self.capacity) if s not in used)
            entry = {"slot": slot}
            self.entries[client] = entry
        raw = reply.pack()
        assert len(raw) <= self.storage.layout.message_size_max
        entry["request"] = request
        entry["reply"] = reply
        entry["reply_size"] = len(raw)
        entry["reply_checksum"] = checksum(raw, domain=b"reply")
        self.storage.write(
            "client_replies",
            entry["slot"] * self.storage.layout.message_size_max, raw)
        return evicted

    def repair_reply(self, client: int, reply: Message) -> bool:
        """Install a peer-provided reply iff it matches the entry's recorded
        checksum (reference: client_replies repair via request_reply)."""
        entry = self.entries.get(client)
        if entry is None or entry["reply"] is not None:
            return False
        raw = reply.pack()
        if (len(raw) != entry["reply_size"]
                or checksum(raw, domain=b"reply") != entry["reply_checksum"]):
            return False
        entry["reply"] = reply
        self.storage.write(
            "client_replies",
            entry["slot"] * self.storage.layout.message_size_max, raw)
        return True

    # ---------------------------------------------------------- checkpoint

    def pack(self) -> bytes:
        """Session table blob for the checkpoint root. A pure function of
        the committed op sequence (recorded sizes/checksums), regardless of
        which reply bytes happen to be present locally."""
        parts = [struct.pack("<I", len(self.entries))]
        for client in sorted(self.entries):
            e = self.entries[client]
            parts.append(_ENTRY.pack(
                client.to_bytes(16, "little"), e["request"], e["slot"],
                e["reply_size"],
                e["reply_checksum"].to_bytes(16, "little")))
        return b"".join(parts)

    def restore(self, blob: bytes) -> None:
        """Rebuild the table; re-read each reply from its zone slot,
        validating against the checkpointed checksum. Mismatches (torn
        write, or a freshly state-synced table) leave `reply=None` for the
        repair path."""
        self.entries.clear()
        (count,) = struct.unpack_from("<I", blob)
        pos = 4
        for _ in range(count):
            client_b, request, slot, size, csum_b = _ENTRY.unpack_from(blob, pos)
            pos += _ENTRY.size
            client = int.from_bytes(client_b, "little")
            csum = int.from_bytes(csum_b, "little")
            reply: Optional[Message] = None
            if size:
                raw = self.storage.read(
                    "client_replies",
                    slot * self.storage.layout.message_size_max, size)
                if checksum(raw, domain=b"reply") == csum:
                    reply = Message.unpack(raw)
            self.entries[client] = {
                "request": request, "slot": slot, "reply": reply,
                "reply_size": size, "reply_checksum": csum}
