"""Write-ahead journal: two on-disk rings (redundant headers + prepares).

reference: src/vsr/journal.zig:16-27 — the WAL is two rings indexed by
op % slot_count: a ring of full prepare messages and a ring of just their
256-byte headers. The redundant header ring disambiguates torn prepare
writes during recovery: a valid header whose prepare is corrupt marks the
slot faulty-but-known, repairable from peers; both-invalid marks it
unknown.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from .header import HEADER_SIZE, Command, Header, Message
from .storage import Storage


class SlotState(enum.Enum):
    clean = "clean"  # header and prepare agree and validate
    faulty = "faulty"  # header valid, prepare torn/corrupt -> repair
    unknown = "unknown"  # nothing valid in the slot


@dataclasses.dataclass
class Slot:
    state: SlotState
    header: Optional[Header] = None  # valid for clean/faulty


class Journal:
    def __init__(self, storage: Storage):
        self.storage = storage
        self.slot_count = storage.layout.slot_count
        self.prepare_size_max = storage.layout.message_size_max
        # In-memory copy of the header ring (reference keeps headers
        # resident: src/vsr/journal.zig headers array).
        self.headers: list[Optional[Header]] = [None] * self.slot_count
        self.dirty: set[int] = set()
        self.faulty: set[int] = set()

    def slot_for_op(self, op: int) -> int:
        return op % self.slot_count

    # ---------------------------------------------------------------- write

    def append(self, message: Message) -> None:
        """Write prepare body then its redundant header (ordering matters:
        a crash between the two leaves the old header pointing at the old,
        still-valid prepare, or the new prepare not yet referenced). Uses
        the native engine's ordered append when available."""
        header = message.header
        assert header.command == Command.prepare
        assert header.size <= self.prepare_size_max
        slot = self.slot_for_op(header.op)
        raw = message.pack()
        native_file = getattr(self.storage, "native", None)
        if native_file is not None:
            zones = self.storage.layout.zone_offsets
            native_file.wal_append(
                zones["wal_headers"], zones["wal_prepares"], slot,
                self.prepare_size_max, raw)
        else:
            self.storage.write("wal_prepares", slot * self.prepare_size_max, raw)
            self.storage.write("wal_headers", slot * HEADER_SIZE, header.pack())
        self.headers[slot] = header
        self.dirty.discard(slot)
        self.faulty.discard(slot)

    # ---------------------------------------------------------------- read

    def read_prepare(self, op: int) -> Optional[Message]:
        slot = self.slot_for_op(op)
        header = self.headers[slot]
        if header is None or header.op != op:
            return None
        raw = self.storage.read(
            "wal_prepares", slot * self.prepare_size_max,
            min(self.prepare_size_max, max(header.size, HEADER_SIZE)))
        try:
            msg = Message.unpack(raw)
        except Exception:
            return None
        if not msg.valid() or msg.header.op != op:
            return None
        return msg

    # ------------------------------------------------------------- recovery

    def recover(self) -> list[Slot]:
        """Scan both rings, classify each slot, and load the in-memory header
        ring (reference: journal recovery in src/vsr/journal.zig; decision
        table in docs/internals/vsr.md:188-217). Runs on the native engine
        when the storage is native-backed."""
        native_file = getattr(self.storage, "native", None)
        if native_file is not None:
            return self._recover_native(native_file)
        slots: list[Slot] = []
        for slot in range(self.slot_count):
            hdr_raw = self.storage.read(
                "wal_headers", slot * HEADER_SIZE, HEADER_SIZE)
            header = _try_header(hdr_raw)
            prep_raw = self.storage.read(
                "wal_prepares", slot * self.prepare_size_max, HEADER_SIZE)
            prep_header = _try_header(prep_raw)

            prepare_valid = False
            if prep_header is not None and prep_header.command == Command.prepare:
                msg = None
                if prep_header.size <= self.prepare_size_max:
                    body_raw = self.storage.read(
                        "wal_prepares", slot * self.prepare_size_max,
                        prep_header.size)
                    try:
                        msg = Message.unpack(body_raw)
                    except Exception:
                        msg = None
                prepare_valid = msg is not None and msg.valid()

            if header is not None and header.command == Command.prepare:
                if (prepare_valid and prep_header.checksum == header.checksum):
                    slots.append(Slot(SlotState.clean, header))
                    self.headers[slot] = header
                elif prepare_valid and prep_header.op > header.op:
                    # Torn header write after a newer prepare landed: trust
                    # the newer prepare.
                    slots.append(Slot(SlotState.clean, prep_header))
                    self.headers[slot] = prep_header
                else:
                    slots.append(Slot(SlotState.faulty, header))
                    self.headers[slot] = header
                    self.faulty.add(slot)
            elif prepare_valid:
                # Header torn, prepare intact.
                slots.append(Slot(SlotState.clean, prep_header))
                self.headers[slot] = prep_header
            elif header is not None and header.command == Command.reserved:
                # Formatted-empty (replica_format wrote a valid reserved
                # header and no prepare ever landed): provably never
                # prepared anything — NOT faulty, so the replica may NACK
                # ops mapping here (reference: the empty/torn distinction
                # behind quorum_nack_prepare eligibility).
                slots.append(Slot(SlotState.clean))
            else:
                slots.append(Slot(SlotState.unknown))
                self.faulty.add(slot)
        return slots

    def _recover_native(self, native_file) -> list[Slot]:
        """Native scan: classification logic is mirrored in C++
        (native/storage_engine.cpp tbs_wal_scan); differential-tested
        against the Python path in tests/test_native.py."""
        from ..vsr.checksum import _SEED

        zones = self.storage.layout.zone_offsets
        states, headers_raw = native_file.wal_scan(
            zones["wal_headers"], zones["wal_prepares"],
            self.slot_count, self.prepare_size_max,
            _SEED + b"hdr", _SEED + b"body")
        slots: list[Slot] = []
        for slot in range(self.slot_count):
            state = states[slot]
            raw = headers_raw[slot * HEADER_SIZE:(slot + 1) * HEADER_SIZE]
            if state == 0:
                header = Header.unpack(raw)
                slots.append(Slot(SlotState.clean, header))
                self.headers[slot] = header
            elif state == 1:
                header = Header.unpack(raw)
                slots.append(Slot(SlotState.faulty, header))
                self.headers[slot] = header
                self.faulty.add(slot)
            elif state == 3:
                # Formatted-empty slot (valid reserved ring header, no
                # prepare): clean, nack-eligible — see the Python
                # classifier above.
                slots.append(Slot(SlotState.clean))
            else:
                slots.append(Slot(SlotState.unknown))
                self.faulty.add(slot)
        return slots

    def op_max(self) -> int:
        """Highest op in the journal (after recover())."""
        return max((h.op for h in self.headers if h is not None), default=0)


def _try_header(raw: bytes) -> Optional[Header]:
    try:
        header = Header.unpack(raw)
    except Exception:
        return None
    if not header.valid_checksum():
        return None
    return header
