"""Write-ahead journal: two on-disk rings (redundant headers + prepares).

reference: src/vsr/journal.zig:16-27 — the WAL is two rings indexed by
op % slot_count: a ring of full prepare messages and a ring of just their
256-byte headers. The redundant header ring disambiguates torn prepare
writes during recovery: a valid header whose prepare is corrupt marks the
slot faulty-but-known, repairable from peers; both-invalid marks it
unknown.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from ..trace import Event, NullTracer
from .header import HEADER_SIZE, Command, Header, Message
from .storage import Storage


class SlotState(enum.Enum):
    clean = "clean"  # header and prepare agree and validate
    faulty = "faulty"  # header valid, prepare torn/corrupt -> repair
    unknown = "unknown"  # nothing valid in the slot


@dataclasses.dataclass
class Slot:
    state: SlotState
    header: Optional[Header] = None  # valid for clean/faulty


class Journal:
    def __init__(self, storage: Storage, tracer=None):
        self.storage = storage
        self.tracer = tracer if tracer is not None else NullTracer()
        self.slot_count = storage.layout.slot_count
        self.prepare_size_max = storage.layout.message_size_max
        # In-memory copy of the header ring (reference keeps headers
        # resident: src/vsr/journal.zig headers array).
        self.headers: list[Optional[Header]] = [None] * self.slot_count
        self.dirty: set[int] = set()
        self.faulty: set[int] = set()
        # In-flight async appends: token -> (slot, message, callbacks);
        # reads of a pending slot are served from the retained message, so
        # the disk write never blocks the replica loop (reference: the
        # journal overlaps write_prepare with replication,
        # src/io/linux.zig + src/vsr/journal.zig:137).
        self._pending: dict[int, tuple[int, Message, list]] = {}
        self._pending_by_slot: dict[int, int] = {}
        # Durability callbacks reaped at a no-fire barrier (checkpoint) or
        # mid-append; fired in order at the next poll_io.
        self._deferred: list = []

    def slot_for_op(self, op: int) -> int:
        return op % self.slot_count

    # ---------------------------------------------------------------- write

    def append(self, message: Message, on_durable=None) -> bool:
        """Write prepare body then its redundant header (ordering matters:
        a crash between the two leaves the old header pointing at the old,
        still-valid prepare, or the new prepare not yet referenced).

        When the storage has an async engine the ordered pair is submitted
        without blocking and `on_durable` fires at a later poll_io() /
        wait barrier; otherwise the write is synchronous (the
        deterministic simulator path) and `on_durable` fires before
        return. Returns True if the append is already durable."""
        with self.tracer.span(Event.journal_write, op=message.header.op):
            return self._append(message, on_durable)

    def _append(self, message: Message, on_durable) -> bool:
        header = message.header
        assert header.command == Command.prepare
        assert header.size <= self.prepare_size_max
        slot = self.slot_for_op(header.op)
        raw = message.pack()
        # Same-slot appends must not reorder across the worker pool:
        # settle the in-flight one first (rare — a wrapped ring reusing a
        # slot, or a repair overwrite racing the original write).
        prev = self._pending_by_slot.get(slot)
        if prev is not None:
            # Callbacks are deferred, not fired here: firing mid-append
            # could reenter the replica (quorum -> commit) from inside
            # another replica action.
            self._finish(prev, fire=False)
        token = self.storage.write_pair_async(
            "wal_prepares", slot * self.prepare_size_max, raw,
            "wal_headers", slot * HEADER_SIZE, header.pack())
        self.headers[slot] = header
        self.dirty.discard(slot)
        self.faulty.discard(slot)
        if token is None:
            native_file = getattr(self.storage, "native", None)
            if native_file is not None:
                zones = self.storage.layout.zone_offsets
                native_file.wal_append(
                    zones["wal_headers"], zones["wal_prepares"], slot,
                    self.prepare_size_max, raw)
            else:
                self.storage.write(
                    "wal_prepares", slot * self.prepare_size_max, raw)
                self.storage.write(
                    "wal_headers", slot * HEADER_SIZE, header.pack())
            if on_durable is not None:
                on_durable()
            return True
        self._pending[token] = (
            slot, message, [on_durable] if on_durable is not None else [])
        self._pending_by_slot[slot] = token
        return False

    def on_slot_durable(self, op: int, callback) -> None:
        """Run `callback` once the slot holding `op` is durable — now, if
        no append is in flight for it."""
        token = self._pending_by_slot.get(self.slot_for_op(op))
        if token is None:
            callback()
        else:
            self._pending[token][2].append(callback)

    def _fire_deferred(self) -> None:
        while self._deferred:
            deferred, self._deferred = self._deferred, []
            for cb in deferred:
                cb()

    def poll_io(self) -> None:
        """Reap completed async appends and fire their callbacks in append
        order (called from the replica tick; cheap no-op when nothing is
        in flight)."""
        self._fire_deferred()
        if not self._pending:
            return
        for token in self.storage.io_poll():
            if token in self._pending:
                self._finish(token)

    def wait_all(self, fire: bool = True) -> None:
        """Durability barrier: every in-flight append lands. With
        fire=False the callbacks are DEFERRED to the next poll_io — the
        checkpoint barrier must not let a quorum callback advance
        commit_min (and reenter the checkpoint) mid-flip."""
        while self._pending:
            self._finish(next(iter(self._pending)), fire=fire)
        if fire:
            self._fire_deferred()

    def _finish(self, token: int, fire: bool = True) -> None:
        slot, _message, callbacks = self._pending.pop(token)
        if self._pending_by_slot.get(slot) == token:
            del self._pending_by_slot[slot]
        # Blocks if still in flight; raises if the write failed (sticky in
        # the engine — durability is compromised, never paper over it).
        self.storage.io_reap(token)
        if fire and not self._deferred:
            for cb in callbacks:
                cb()
        else:
            # Keep append order: once anything is deferred, everything
            # later defers behind it.
            self._deferred.extend(callbacks)

    # ---------------------------------------------------------------- read

    def read_prepare(self, op: int) -> Optional[Message]:
        slot = self.slot_for_op(op)
        header = self.headers[slot]
        if header is None or header.op != op:
            return None
        # An in-flight async append is served from the retained message —
        # the write-buffer read path (the disk bytes are not there yet).
        token = self._pending_by_slot.get(slot)
        if token is not None:
            msg = self._pending[token][1]
            return msg if msg.header.op == op else None
        raw = self.storage.read(
            "wal_prepares", slot * self.prepare_size_max,
            min(self.prepare_size_max, max(header.size, HEADER_SIZE)))
        try:
            msg = Message.unpack(raw)
        except Exception:
            return None
        if not msg.valid() or msg.header.op != op:
            return None
        return msg

    # ------------------------------------------------------------- recovery

    def recover(self) -> list[Slot]:
        """Scan both rings, classify each slot, and load the in-memory header
        ring (reference: journal recovery in src/vsr/journal.zig; decision
        table in docs/internals/vsr.md:188-217). Runs on the native engine
        when the storage is native-backed."""
        with self.tracer.span(Event.journal_recover):
            return self._recover_scan()

    def _recover_scan(self) -> list[Slot]:
        native_file = getattr(self.storage, "native", None)
        if native_file is not None:
            return self._recover_native(native_file)
        slots: list[Slot] = []
        for slot in range(self.slot_count):
            hdr_raw = self.storage.read(
                "wal_headers", slot * HEADER_SIZE, HEADER_SIZE)
            header = _try_header(hdr_raw)
            prep_raw = self.storage.read(
                "wal_prepares", slot * self.prepare_size_max, HEADER_SIZE)
            prep_header = _try_header(prep_raw)

            prepare_valid = False
            if prep_header is not None and prep_header.command == Command.prepare:
                msg = None
                if prep_header.size <= self.prepare_size_max:
                    body_raw = self.storage.read(
                        "wal_prepares", slot * self.prepare_size_max,
                        prep_header.size)
                    try:
                        msg = Message.unpack(body_raw)
                    except Exception:
                        msg = None
                prepare_valid = msg is not None and msg.valid()

            if header is not None and header.command == Command.prepare:
                if (prepare_valid and prep_header.checksum == header.checksum):
                    slots.append(Slot(SlotState.clean, header))
                    self.headers[slot] = header
                elif prepare_valid and prep_header.op > header.op:
                    # Torn header write after a newer prepare landed: trust
                    # the newer prepare.
                    slots.append(Slot(SlotState.clean, prep_header))
                    self.headers[slot] = prep_header
                else:
                    slots.append(Slot(SlotState.faulty, header))
                    self.headers[slot] = header
                    self.faulty.add(slot)
            elif prepare_valid:
                # Header torn, prepare intact.
                slots.append(Slot(SlotState.clean, prep_header))
                self.headers[slot] = prep_header
            elif header is not None and header.command == Command.reserved:
                # Formatted-empty (replica_format wrote a valid reserved
                # header and no prepare ever landed): provably never
                # prepared anything — NOT faulty, so the replica may NACK
                # ops mapping here (reference: the empty/torn distinction
                # behind quorum_nack_prepare eligibility).
                slots.append(Slot(SlotState.clean))
            else:
                slots.append(Slot(SlotState.unknown))
                self.faulty.add(slot)
        return slots

    def _recover_native(self, native_file) -> list[Slot]:
        """Native scan: classification logic is mirrored in C++
        (native/storage_engine.cpp tbs_wal_scan); differential-tested
        against the Python path in tests/test_native.py."""
        from ..vsr.checksum import _SEED

        zones = self.storage.layout.zone_offsets
        states, headers_raw = native_file.wal_scan(
            zones["wal_headers"], zones["wal_prepares"],
            self.slot_count, self.prepare_size_max,
            _SEED + b"hdr", _SEED + b"body")
        slots: list[Slot] = []
        for slot in range(self.slot_count):
            state = states[slot]
            raw = headers_raw[slot * HEADER_SIZE:(slot + 1) * HEADER_SIZE]
            if state == 0:
                header = Header.unpack(raw)
                slots.append(Slot(SlotState.clean, header))
                self.headers[slot] = header
            elif state == 1:
                header = Header.unpack(raw)
                slots.append(Slot(SlotState.faulty, header))
                self.headers[slot] = header
                self.faulty.add(slot)
            elif state == 3:
                # Formatted-empty slot (valid reserved ring header, no
                # prepare): clean, nack-eligible — see the Python
                # classifier above.
                slots.append(Slot(SlotState.clean))
            else:
                slots.append(Slot(SlotState.unknown))
                self.faulty.add(slot)
        return slots

    def op_max(self) -> int:
        """Highest op in the journal (after recover())."""
        return max((h.op for h in self.headers if h is not None), default=0)


def _try_header(raw: bytes) -> Optional[Header]:
    try:
        header = Header.unpack(raw)
    except Exception:
        return None
    if not header.valid_checksum():
        return None
    return header
