"""Grid scrubber: proactive background validation of LSM grid blocks.

reference: src/vsr/grid_scrubber.zig:1-20 — latent sector errors are only
caught when a block is read; rarely-read blocks (deep LSM levels) could
decay silently past the point of repair. The scrubber tours every
reachable block (all tables of all trees plus the manifest chain) in a
deterministic cycle, surfacing corruption early while peers still hold
good copies.

Design, matching the reference's shape (grid_scrubber.zig:101-138,
165-190) re-derived for the sans-io runtime:

- **Cycle pacing**: a full tour is budgeted over `cycle_ticks` ticks; each
  tick reads ceil(remaining blocks / remaining ticks) blocks, so the tour
  finishes on schedule whether the grid holds ten blocks or a million —
  the reference derives its read rate from the target cycle duration the
  same way ("latent sector errors ... discovered by a scrubber that
  cycles every 2 weeks", grid_scrubber.zig:12-14). A hard
  `reads_per_tick_max` bounds the IO burst of any single tick.
- **Per-replica tour origin** (grid_scrubber.zig:170-182): each replica
  starts its tour at a different rotation of the block sequence so
  replicas scrub the same block at different times — minimizing the
  window where an unscrubbed latent fault on one replica intersects the
  same fault on another (the double-fault scenario the scrubber exists
  to prevent).
- **Fault→repair handoff**: `tick()` returns the faulty addresses found;
  the replica queues them in `block_repair` and requests validated
  copies from peers (grids are byte-identical across replicas —
  docs/ARCHITECTURE.md:281-307). Blocks freed by compaction mid-tour
  are never queued (the reference's `released` status,
  grid_scrubber.zig:65-72).

The free set and client sessions live in the superblock-referenced A/B
snapshot zone here, not in grid blocks (documented substitution —
ROUND3.md), so the checkpoint-trailer legs of the reference's tour have
no grid analog; the snapshot zone is checksummed and quorum-protected on
its own read path.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..lsm.forest import Forest
from ..lsm.grid import BlockAddress
from ..trace import Event, NullTracer


class GridScrubber:
    def __init__(self, forest: Forest, *, cycle_ticks: int = 1024,
                 reads_per_tick_max: int = 64, origin_seed: int = 0,
                 tracer=None):
        self.forest = forest
        self.tracer = tracer if tracer is not None else NullTracer()
        # Tour pacing: finish one full cycle per `cycle_ticks` ticks.
        self.cycle_ticks = max(1, cycle_ticks)
        self.reads_per_tick_max = reads_per_tick_max
        # Per-replica origin rotation (decorrelates replica tours).
        self.origin_seed = origin_seed
        self._iter: Optional[Iterator[tuple[str, BlockAddress, int]]] = None
        self._tour_remaining = 0  # blocks left in the current tour
        self._ticks_remaining = 0  # ticks left in the current cycle
        self.cycles = 0  # completed full tours
        self.checked = 0  # blocks validated, lifetime
        self.tour_blocks_scrubbed = 0  # blocks validated, current tour
        self.tour_size = 0  # blocks in the current tour at its start
        # block index -> (tree, address, size); deduped across tours.
        self.faults: dict[int, tuple[str, BlockAddress, int]] = {}

    def _blocks(self) -> Iterator[tuple[str, BlockAddress, int]]:
        """Every reachable (tree, address, size) at tour start. Tables hold
        their index block address in the manifest; value-block addresses
        live inside the index block (already parsed by Table)."""
        for name, tree in sorted(self.forest.trees.items()):
            for level in tree.levels:
                for table in level:
                    yield name, table.info.index_address, table.info.index_size
                    for i, addr in enumerate(table.block_addresses):
                        yield name, addr, table.block_sizes[i]
        # The checkpoint's manifest chain is reachable grid state too —
        # a decayed chain block would make the NEXT restart unrecoverable
        # locally even though every table block is fine.
        for addr, size in self.forest.manifest_chain_blocks:
            yield "__manifest__", addr, size

    def _tour(self) -> Iterator[tuple[str, BlockAddress, int]]:
        """One full tour, rotated to this replica's origin. The rotation
        point is `origin_seed mod tour_size`, recomputed per tour so the
        origin tracks grid growth (reference grid_scrubber.zig:179-182
        selects an origin uniformly across blocks the same way)."""
        blocks = list(self._blocks())
        self.tour_size = len(blocks)
        if not blocks:
            return iter(())
        start = self.origin_seed % len(blocks)
        return iter(blocks[start:] + blocks[:start])

    def certify(self) -> list[tuple[str, BlockAddress, int]]:
        """One immediate, unpaced full tour: validate EVERY reachable
        block now and return the faults (also recorded in self.faults).
        This is the post-rebuild certification pass — a freshly installed
        checkpoint (recover --from-cluster) is only trusted once every
        block it reaches has been read back from the media and matched
        its parent-held checksum. Orthogonal to the paced background
        tour: the incremental iterator/pacing state is untouched."""
        found: list[tuple[str, BlockAddress, int]] = []
        with self.tracer.span(Event.grid_scrub_certify):
            for name, address, size in self._blocks():
                self.checked += 1
                try:
                    self.forest.grid.read_block(address, size,
                                                bypass_cache=True)
                except IOError:
                    found.append((name, address, size))
                    self.faults[address.index] = (name, address, size)
        return found

    def still_referenced(self, address: BlockAddress) -> bool:
        """True iff the CURRENT manifests still reach this exact address.
        The tour snapshot is taken at tour start, so a block freed and
        reused mid-tour can surface as a stale read failure — such an
        address must never be queued for repair (peers hold the NEW content
        too, so the repair could never converge)."""
        return any(a == address for _, a, _ in self._blocks())

    def reads_this_tick(self) -> int:
        """Cycle pacing: spread the remaining tour evenly over the
        remaining ticks of the cycle (ceil division keeps the tour ahead
        of schedule; the max bounds any single tick's IO burst)."""
        if self._iter is None:
            return 1  # first tick of a tour: open it, then pace
        if self._ticks_remaining <= 0:
            return min(self._tour_remaining, self.reads_per_tick_max)
        need = -(-self._tour_remaining // self._ticks_remaining)
        return min(max(need, 0), self.reads_per_tick_max)

    def tick(self) -> list[tuple[str, BlockAddress, int]]:
        """Validate the tick's block budget; returns faults found now
        (the replica queues them for peer repair via request_blocks)."""
        with self.tracer.span(Event.grid_scrub_tick):
            return self._tick()

    def _tick(self) -> list[tuple[str, BlockAddress, int]]:
        found: list[tuple[str, BlockAddress, int]] = []
        if self._iter is None:
            self._iter = self._tour()
            self._tour_remaining = self.tour_size
            self._ticks_remaining = self.cycle_ticks
            self.tour_blocks_scrubbed = 0
        budget = self.reads_this_tick()
        self._ticks_remaining -= 1
        for _ in range(budget):
            try:
                name, address, size = next(self._iter)
            except StopIteration:
                self._iter = None
                self.cycles += 1
                break
            self.checked += 1
            self.tour_blocks_scrubbed += 1
            self._tour_remaining -= 1
            try:
                self.forest.grid.read_block(address, size,
                                            bypass_cache=True)
            except IOError:
                if self.still_referenced(address):
                    found.append((name, address, size))
                    self.faults[address.index] = (name, address, size)
        else:
            # Tour exhausted exactly at the budget boundary (the tour is
            # a fixed snapshot, so remaining==0 means the iterator is
            # spent): close it now so the next tick opens a fresh tour
            # instead of burning a tick on StopIteration.
            if self._tour_remaining <= 0 and self._iter is not None:
                self._iter = None
                self.cycles += 1
        # Faults whose tables were since compacted away resolve themselves.
        if self.faults:
            live = {a for _, a, _ in self._blocks()}
            for index in [i for i, (_, a, _) in self.faults.items()
                          if a not in live]:
                del self.faults[index]
        return found
