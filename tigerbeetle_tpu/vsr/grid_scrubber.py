"""Grid scrubber: proactive background validation of LSM grid blocks.

reference: src/vsr/grid_scrubber.zig:1-20 — latent sector errors are only
caught when a block is read; rarely-read blocks (deep LSM levels) could
decay silently past the point of repair. The scrubber tours every reachable
block (all tables of all trees, via the manifests) a few reads per tick,
surfacing corruption early while peers still hold good copies.

Sans-io over the forest: `tour()` yields (tree, address) pairs in a
deterministic cycle; `tick()` validates up to `reads_per_tick` blocks and
returns the faulty addresses found (the replica queues them for repair).
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..lsm.forest import Forest
from ..lsm.grid import BlockAddress


class GridScrubber:
    def __init__(self, forest: Forest, *, reads_per_tick: int = 2):
        self.forest = forest
        self.reads_per_tick = reads_per_tick
        self._iter: Optional[Iterator[tuple[str, BlockAddress, int]]] = None
        self.cycles = 0  # completed full tours
        self.checked = 0
        # block index -> (tree, address, size); deduped across tours.
        self.faults: dict[int, tuple[str, BlockAddress, int]] = {}

    def _blocks(self) -> Iterator[tuple[str, BlockAddress, int]]:
        """Every reachable (tree, address, size) at tour start. Tables hold
        their index block address in the manifest; value-block addresses
        live inside the index block (already parsed by Table)."""
        for name, tree in sorted(self.forest.trees.items()):
            for level in tree.levels:
                for table in level:
                    yield name, table.info.index_address, table.info.index_size
                    for i, addr in enumerate(table.block_addresses):
                        yield name, addr, table.block_sizes[i]
        # The checkpoint's manifest chain is reachable grid state too —
        # a decayed chain block would make the NEXT restart unrecoverable
        # locally even though every table block is fine.
        for addr, size in self.forest.manifest_chain_blocks:
            yield "__manifest__", addr, size

    def still_referenced(self, address: BlockAddress) -> bool:
        """True iff the CURRENT manifests still reach this exact address.
        The tour iterator is lazy over live levels, so a block freed and
        reused mid-tour can surface as a stale read failure — such an
        address must never be queued for repair (peers hold the NEW content
        too, so the repair could never converge)."""
        return any(a == address for _, a, _ in self._blocks())

    def tick(self) -> list[tuple[str, BlockAddress, int]]:
        """Validate up to reads_per_tick blocks; returns faults found now
        (the replica queues them for peer repair via request_blocks)."""
        found: list[tuple[str, BlockAddress, int]] = []
        for _ in range(self.reads_per_tick):
            if self._iter is None:
                self._iter = self._blocks()
            try:
                name, address, size = next(self._iter)
            except StopIteration:
                self._iter = None
                self.cycles += 1
                break
            self.checked += 1
            try:
                self.forest.grid.read_block(address, size,
                                            bypass_cache=True)
            except IOError:
                if self.still_referenced(address):
                    found.append((name, address, size))
                    self.faults[address.index] = (name, address, size)
        # Faults whose tables were since compacted away resolve themselves.
        if self.faults:
            live = {a for _, a, _ in self._blocks()}
            for index in [i for i, (_, a, _) in self.faults.items()
                          if a not in live]:
                del self.faults[index]
        return found
