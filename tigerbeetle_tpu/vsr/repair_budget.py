"""Repair traffic rate limiter.

reference: src/vsr/repair_budget.zig — repair (request_prepare /
request_blocks) must not starve the normal protocol path, so each replica
spends from a refilling budget when requesting repair and stops when
exhausted. Token bucket over nanosecond time, sans-io.
"""

from __future__ import annotations

MS = 1_000_000  # ns


class RepairBudget:
    def __init__(self, *, capacity: int = 8,
                 refill_interval_ns: int = 50 * MS):
        self.capacity = capacity
        self.refill_interval_ns = refill_interval_ns
        self.tokens = capacity
        self.last_refill_ns = 0

    def refill(self, now_ns: int) -> None:
        if not self.last_refill_ns:
            self.last_refill_ns = now_ns
            return
        elapsed = now_ns - self.last_refill_ns
        earned = int(elapsed // self.refill_interval_ns)
        if earned > 0:
            self.tokens = min(self.capacity, self.tokens + earned)
            self.last_refill_ns += earned * self.refill_interval_ns

    def available(self, now_ns: int) -> int:
        """Tokens spendable right now (after refill) — lets a caller
        size a burst (e.g. the post-rebuild certification's block-repair
        batches) to the budget instead of probing one token at a time."""
        self.refill(now_ns)
        return self.tokens

    def spend(self, now_ns: int, amount: int = 1) -> bool:
        """True (and deducts) if the budget allows `amount` repair sends."""
        self.refill(now_ns)
        if self.tokens < amount:
            return False
        self.tokens -= amount
        return True
