"""TCP message bus: the production network transport.

reference: src/message_bus.zig (MessageBusType over io_uring sockets) +
src/message_buffer.zig (checksum-validated framing). This implementation is
a single-threaded selectors-based event loop — the same run-to-completion
model as the reference's io_uring loop, portable Python instead of Zig.

Delivery contract is deliberately weak, exactly like the reference
(docs/ARCHITECTURE.md:610-615): messages may be dropped (send buffers full,
connection resets), duplicated (reconnects), or reordered across
connections; VSR tolerates all of it. Frames are validated by header +
body checksums before delivery; garbage closes the connection.

Peers: each replica listens on its address and dials every other replica;
inbound connections are identified by the `replica` field of their first
valid message. Clients connect inbound only and are identified by the
`client` field of their requests.
"""

from __future__ import annotations

import errno
import selectors
import socket
from collections import deque
from typing import Callable, Optional

from ..trace import Event, NullTracer
from .header import HEADER_SIZE, Command, Header, Message

RECV_CHUNK = 256 * 1024
SEND_BUFFER_MAX = 64 * 1024 * 1024

# Static message pool (reference: src/message_pool.zig:107 — a fixed
# buffer budget shared by every connection; exhaustion SUSPENDS reads
# instead of growing memory). Here the pooled resource is queued outbound
# messages: client reads stop at the high watermark and resume at the low
# one, so overload turns into TCP backpressure on clients instead of
# reply drops + retry storms (reference: message_bus suspend/resume,
# src/message_bus.zig:1217-1223). Replica-to-replica traffic is never
# suspended — VSR liveness rides on it (its contract tolerates drops).
MESSAGE_POOL_SIZE = 4096
POOL_SUSPEND_AT = MESSAGE_POOL_SIZE * 3 // 4
POOL_RESUME_AT = MESSAGE_POOL_SIZE // 2


class _Connection:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rx = bytearray()
        self.tx = bytearray()
        self.tx_sizes: deque = deque()  # per-message byte sizes (pool acct)
        self.tx_sent = 0  # bytes sent of tx_sizes[0]
        self.peer: Optional[tuple] = None  # ("replica", i) | ("client", id)
        self.read_suspended = False

    def want_write(self) -> bool:
        return bool(self.tx)


class MessageBus:
    """One event loop endpoint (a replica process or a client process)."""

    def __init__(self, *, cluster: int,
                 on_message: Callable[[Message], None],
                 replica_addresses: list[tuple[str, int]],
                 replica_id: Optional[int] = None,
                 listen: bool = False,
                 listen_port: Optional[int] = None,
                 tracer=None):
        self.cluster = cluster
        self.on_message = on_message
        self.tracer = tracer if tracer is not None else NullTracer()
        self.replica_addresses = replica_addresses
        self.replica_id = replica_id
        self.selector = selectors.DefaultSelector()
        self.connections: dict[socket.socket, _Connection] = {}
        self.by_peer: dict[tuple, _Connection] = {}
        # Pool accounting + drop counters (observable backpressure).
        self.pool_used = 0
        self.dropped_replica = 0
        self.dropped_client = 0
        # Regime flags: O(1) hot-path checks instead of per-message scans.
        self._global_suspended = False
        self._suspended_count = 0
        self.listener: Optional[socket.socket] = None
        if listen:
            assert replica_id is not None
            host, port = replica_addresses[replica_id]
            if listen_port is not None:
                # Bind here while peers dial us at the advertised address —
                # lets a fault-injecting proxy sit in between (vortex).
                port = listen_port
            self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self.listener.bind((host, port))
            self.listener.listen(64)
            self.listener.setblocking(False)
            self.selector.register(self.listener, selectors.EVENT_READ, None)

    @property
    def listen_address(self) -> tuple[str, int]:
        return self.listener.getsockname()

    # ------------------------------------------------------------- sending

    def send_to_replica(self, dst: int, msg: Message) -> None:
        if dst == self.replica_id:
            self.on_message(msg)
            return
        conn = self.by_peer.get(("replica", dst))
        if conn is None:
            conn = self._dial(dst)
            if conn is None:
                return  # dropped: weak delivery contract
        self._enqueue(conn, msg)

    def send_to_client(self, client_id: int, msg: Message) -> None:
        conn = self.by_peer.get(("client", client_id))
        if conn is not None:
            self._enqueue(conn, msg)

    def _enqueue(self, conn: _Connection, msg: Message) -> None:
        is_client = conn.peer is not None and conn.peer[0] == "client"
        # Replica traffic may use the FULL pool; client replies stop at
        # the suspend watermark — wedged clients (connected, never
        # draining) must not starve consensus messages of slots.
        budget = POOL_SUSPEND_AT if is_client else MESSAGE_POOL_SIZE
        if self.pool_used >= budget or len(conn.tx) > SEND_BUFFER_MAX:
            # Pool exhausted / peer not draining: drop is the last resort
            # (the suspend watermarks below make this rare for clients).
            if is_client:
                self.dropped_client += 1
            else:
                self.dropped_replica += 1
            return
        # `csum` ties this span to the receiver's bus_recv of the SAME
        # frame: trace/merge.py matches the pairs to estimate per-pid
        # clock offsets before causal assembly (low 32 bits are plenty
        # to match within one trace window).
        with self.tracer.span(Event.bus_send,
                              command=Command(msg.header.command).name,
                              csum=msg.header.checksum & 0xFFFFFFFF):
            raw = msg.pack()
            conn.tx += raw
        conn.tx_sizes.append(len(raw))
        self.pool_used += 1
        self.tracer.gauge(Event.bus_pool_used, self.pool_used)
        if self.pool_used >= POOL_SUSPEND_AT and not self._global_suspended:
            self._global_suspended = True
            self._suspend_client_reads()
        elif (is_client and not conn.read_suspended
                and len(conn.tx) > SEND_BUFFER_MAX // 2):
            # A single slow client: stop reading ITS requests before its
            # reply queue forces drops (per-connection backpressure).
            conn.read_suspended = True
            self._suspended_count += 1
        self._update_events(conn)

    def _suspend_client_reads(self) -> None:
        for conn in self.connections.values():
            if (not conn.read_suspended and conn.peer is not None
                    and conn.peer[0] == "client"):
                conn.read_suspended = True
                self._suspended_count += 1
                self._update_events(conn)

    def _maybe_resume_reads(self) -> None:
        if not self._suspended_count:
            return
        if self._global_suspended:
            if self.pool_used > POOL_RESUME_AT:
                return  # the GLOBAL regime holds everyone parked
            self._global_suspended = False
        # Per-connection hysteresis: resume once the connection's own
        # queue falls back below its suspend watermark (the global axis,
        # once cleared above, must not keep an individually-drained
        # client parked forever).
        for conn in self.connections.values():
            if conn.read_suspended and len(conn.tx) <= SEND_BUFFER_MAX // 2:
                conn.read_suspended = False
                self._suspended_count -= 1
                self._update_events(conn)

    def _dial(self, dst: int) -> Optional[_Connection]:
        host, port = self.replica_addresses[dst]
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        try:
            sock.connect((host, port))
        except BlockingIOError:
            pass
        except OSError:
            sock.close()
            return None
        conn = _Connection(sock)
        conn.peer = ("replica", dst)
        self.connections[sock] = conn
        self.by_peer[conn.peer] = conn
        self.selector.register(sock, selectors.EVENT_READ | selectors.EVENT_WRITE,
                               conn)
        if self.replica_id is not None:
            # Identify ourselves so the peer can route prepare_oks back
            # (reference: peer handshake via header fields, src/vsr.zig:88-94).
            # Through _enqueue like any message: the pool accounting reaps
            # per tx_sizes entry, and an unaccounted prefix would skew it
            # one message early forever.
            hello = Header(command=Command.ping, cluster=self.cluster,
                           replica=self.replica_id)
            self._enqueue(conn, Message(hello.finalize()))
        return conn

    # ------------------------------------------------------------ the loop

    def poll(self, timeout: float = 0.0) -> None:
        for key, events in self.selector.select(timeout):
            if key.fileobj is self.listener:
                self._accept()
                continue
            conn: _Connection = key.data
            if events & selectors.EVENT_WRITE:
                self._flush(conn)
            if events & selectors.EVENT_READ and conn.sock in self.connections:
                self._drain(conn)

    def _accept(self) -> None:
        try:
            sock, _addr = self.listener.accept()
        except OSError:
            return
        sock.setblocking(False)
        conn = _Connection(sock)
        self.connections[sock] = conn
        self.selector.register(sock, selectors.EVENT_READ, conn)

    def _flush(self, conn: _Connection) -> None:
        try:
            while conn.tx:
                sent = conn.sock.send(conn.tx[:RECV_CHUNK])
                if sent == 0:
                    break
                del conn.tx[:sent]
                self._reap_sent(conn, sent)
        except OSError as e:
            if e.errno not in (errno.EAGAIN, errno.EWOULDBLOCK):
                self._close(conn)
                return
        self._maybe_resume_reads()
        self._update_events(conn)

    def _reap_sent(self, conn: _Connection, sent: int) -> None:
        """Release pool slots for fully-transmitted messages."""
        conn.tx_sent += sent
        while conn.tx_sizes and conn.tx_sent >= conn.tx_sizes[0]:
            conn.tx_sent -= conn.tx_sizes.popleft()
            self.pool_used -= 1

    def _drain(self, conn: _Connection) -> None:
        try:
            chunk = conn.sock.recv(RECV_CHUNK)
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return
            self._close(conn)
            return
        if not chunk:
            self._close(conn)
            return
        conn.rx += chunk
        while len(conn.rx) >= HEADER_SIZE:
            try:
                header = Header.unpack(bytes(conn.rx[:HEADER_SIZE]))
            except Exception:
                self._close(conn)
                return
            if (not header.valid_checksum()
                    or header.size < HEADER_SIZE
                    or header.size > 64 * 1024 * 1024):
                self._close(conn)  # corrupt stream: force reconnect
                return
            if len(conn.rx) < header.size:
                break
            raw = bytes(conn.rx[:header.size])
            del conn.rx[:header.size]
            msg = Message.unpack(raw)
            if not msg.valid() or msg.header.cluster != self.cluster:
                continue
            with self.tracer.span(
                    Event.bus_recv,
                    command=Command(msg.header.command).name,
                    csum=msg.header.checksum & 0xFFFFFFFF):
                self._identify(conn, msg.header)
                self.on_message(msg)

    def _identify(self, conn: _Connection, header: Header) -> None:
        if conn.peer is not None:
            return
        if header.command == Command.request or header.command in (
                Command.ping_client, Command.pong_client):
            peer = ("client", header.client)
        else:
            peer = ("replica", header.replica)
        conn.peer = peer
        old = self.by_peer.get(peer)
        self.by_peer[peer] = conn
        if old is not None and old is not conn:
            self._close(old, forget_peer=False)

    def _update_events(self, conn: _Connection) -> None:
        if conn.sock not in self.connections:
            return
        events = 0 if conn.read_suspended else selectors.EVENT_READ
        if conn.want_write():
            events |= selectors.EVENT_WRITE
        try:
            if events:
                try:
                    self.selector.modify(conn.sock, events, conn)
                except KeyError:
                    self.selector.register(conn.sock, events, conn)
            else:
                # selectors cannot watch for "nothing": park the socket
                # (resume re-registers it).
                try:
                    self.selector.unregister(conn.sock)
                except KeyError:
                    pass
        except ValueError:
            pass

    def _close(self, conn: _Connection, forget_peer: bool = True) -> None:
        self.pool_used -= len(conn.tx_sizes)  # unsent slots return
        conn.tx_sizes = deque()
        if conn.read_suspended:
            conn.read_suspended = False
            self._suspended_count -= 1
        self.connections.pop(conn.sock, None)
        # Slots released by the close may be what suspended clients were
        # waiting for — a quiet bus would otherwise never resume them.
        self._maybe_resume_reads()
        if forget_peer and conn.peer is not None:
            if self.by_peer.get(conn.peer) is conn:
                del self.by_peer[conn.peer]
        try:
            self.selector.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.sock.close()

    def close(self) -> None:
        for conn in list(self.connections.values()):
            self._close(conn)
        if self.listener is not None:
            self.selector.unregister(self.listener)
            self.listener.close()
