"""Cluster-synchronized clock: Marzullo interval agreement over peer
clock samples.

reference: src/vsr/clock.zig (+ src/vsr/marzullo.zig). The primary samples
backup clocks via ping/pong round trips; each sample yields an interval
[offset - rtt/2, offset + rtt/2] within which the peer's clock offset must
lie. Marzullo's algorithm finds the point covered by the most intervals —
the cluster-agreed offset bound — so the primary can assert its timestamps
are within tolerance of the cluster majority. Consensus drives time; time
never drives consensus (the reference's doctrine)."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Interval:
    lo: int
    hi: int


def marzullo(intervals: list[Interval]) -> Optional[Interval]:
    """The smallest interval consistent with the largest number of sources
    (reference: src/vsr/marzullo.zig:8 smallest_interval)."""
    iv, _ = marzullo_with_count(intervals)
    return iv


def marzullo_with_count(intervals: list[Interval]):
    """(best interval, number of sources covering it). The count is what
    agreement means: sources merely SAMPLED is not sources AGREEING
    (reference clock.zig synchronizes only when the smallest interval is
    consistent with a replica quorum of sources)."""
    if not intervals:
        return None, 0
    edges: list[tuple[int, int]] = []
    for iv in intervals:
        edges.append((iv.lo, -1))  # -1 sorts starts before ends at a tie
        edges.append((iv.hi, +1))
    edges.sort()
    best = 0
    count = 0
    best_lo = best_hi = None
    for i, (value, kind) in enumerate(edges):
        if kind == -1:
            count += 1
            if count > best:
                best = count
                best_lo = value
                best_hi = edges[i + 1][0] if i + 1 < len(edges) else value
        else:
            count -= 1
    if best_lo is None:
        return None, 0
    return Interval(best_lo, best_hi), best


class Clock:
    """Offset estimation against cluster peers.

    Samples are (monotonic_tx, peer_realtime, monotonic_rx) triples from
    ping/pong exchanges; each gives offset = peer_realtime - local_mid with
    uncertainty rtt/2. Samples expire after `window_ns` — a partitioned
    peer's hours-old offset must not keep "synchronizing" the clock
    (reference: epoch expiry in src/vsr/clock.zig)."""

    WINDOW_NS_DEFAULT = 10_000_000_000  # 10s

    def __init__(self, replica_id: int, replica_count: int, time,
                 window_ns: int = WINDOW_NS_DEFAULT):
        self.replica_id = replica_id
        self.replica_count = replica_count
        self.time = time
        self.window_ns = window_ns
        self.samples: dict[int, tuple[int, Interval]] = {}  # peer -> (at, iv)

    def learn(self, peer: int, monotonic_tx: int, peer_realtime: int,
              monotonic_rx: int) -> None:
        assert peer != self.replica_id
        rtt = monotonic_rx - monotonic_tx
        if rtt < 0:
            return
        local_mid = self.time.realtime() - (monotonic_rx - monotonic_tx) // 2
        offset = peer_realtime - local_mid
        self.samples[peer] = (
            monotonic_rx, Interval(offset - rtt // 2, offset + rtt // 2))

    def _fresh(self) -> list[Interval]:
        horizon = self.time.monotonic() - self.window_ns
        return [iv for at, iv in self.samples.values() if at >= horizon]

    def offset(self) -> Optional[Interval]:
        """Agreed offset interval — None unless a QUORUM of sources (our
        own zero-offset interval plus fresh peer samples) actually
        overlap. Peers sampled but wildly disagreeing are not agreement
        (reference clock.zig: the smallest interval must be consistent
        with a replica quorum)."""
        own = [Interval(0, 0)]  # our own clock, zero offset
        intervals = own + self._fresh()
        quorum = self.replica_count // 2 + 1
        if len(intervals) < quorum:
            return None
        iv, covered = marzullo_with_count(intervals)
        if covered < quorum:
            return None
        return iv

    def realtime_synchronized(self) -> Optional[int]:
        iv = self.offset()
        if iv is None:
            return None
        return self.time.realtime() + (iv.lo + iv.hi) // 2
