"""The VSR replica: consensus participant + commit pipeline.

reference: src/vsr/replica.zig (normal protocol :1944-2330, commit pipeline
:4374-5440, view change per docs/internals/vsr.md:106-186). This is a fresh
sans-IO implementation: all effects go through injected Storage / MessageBus
/ Time, so the deterministic simulator can run whole clusters in-process
(the reference achieves the same via comptime injection,
src/testing/cluster.zig:70).

Protocol summary (faithful to VSR; simplified where noted):
- normal: primary assigns (op, timestamp) to client requests, appends to its
  journal, replicates `prepare` to backups; backups append + `prepare_ok`;
  primary commits on replication quorum, executes the state machine, replies
  to the client; backups learn commits from piggybacked `commit` numbers and
  heartbeat `commit` messages.
- view change: on primary timeout, replicas send `start_view_change` for
  view v+1; on quorum each sends `do_view_change` (carrying log_view, op,
  and the header suffix above the checkpoint) to v+1's primary; the new
  primary adopts the best log (max log_view, then max op), sends
  `start_view`; backups install the suffix and repair missing prepares.
- repair: gaps are filled via `request_prepare`/`prepare` from any peer.
- checkpoint: state-machine objects are written through to the LSM forest
  after every commit (vsr/durable.py), compaction is paced by op number, and
  every `checkpoint_interval` commits the forest checkpoints: manifests +
  free set serialize into a small root blob written to the alternating
  snapshot slot, then the superblock flips — an incremental checkpoint, like
  the reference's grid + checkpoint trailer (docs/internals/data_file.md).

State sync (docs/internals/sync.md): a replica that fell behind the WAL
wrap jumps to a peer's checkpoint — the peer offers its checkpoint root in
response to an unserviceable request_prepare, the lagging replica fetches
the reachable grid blocks (request_blocks/block) and installs checkpoint +
sessions + superblock atomically.

Standbys (ids >= replica_count) follow the replication stream and hold
checkpoints without voting — warm spares outside the quorums.

NACK / protocol-aware recovery (reference: quorum_nack_prepare,
src/vsr/replica.zig:254,825; docs/ARCHITECTURE.md:540-563): a new
primary whose chosen log has an unobtainable prepare (every copy lost or
corrupted) must decide whether the op could have committed. Peers that
can PROVE they never prepared it — their WAL slot holds nothing for the
op (and is not a torn write: a faulty slot abstains, it may be the very
prepare in question), or holds a different-checksum prepare (a replica
prepares at most one body per op) — answer request_prepare with
`nack_prepare`. Collecting `replica_count - quorum_replication + 1`
distinct nacks proves no replication quorum ever existed, so the op (and
the suffix above it, which chains through it) is truncated and the view
starts. Without this, "repairs when a good copy exists" is the best the
protocol can do; with it, an uncommitted-but-lost prepare can never
wedge a view change, while a committed prepare is never truncated (the
nack quorum intersects every replication quorum).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional

from .. import constants
from ..constants import PIPELINE_PREPARE_QUEUE_MAX
from ..state_machine import StateMachine, _base_operation
from ..trace import Event
from ..types import Operation
import struct

from .checksum import checksum
from .client_sessions import ClientSessions
from .durable import DurableState
from .fault_detector import FaultDetector
from .grid_scrubber import GridScrubber
from .header import HEADER_SIZE, Command, Header, Message
from .journal import Journal
from .repair_budget import RepairBudget
from .storage import Storage
from .superblock import SuperBlock

MS = 1_000_000  # ns


@dataclasses.dataclass
class ReplicaOptions:
    heartbeat_interval_ns: int = 100 * MS
    view_change_timeout_ns: int = 500 * MS
    repair_interval_ns: int = 50 * MS
    checkpoint_interval: int = 16  # ops between checkpoints


class Replica:
    def __init__(self, *, cluster: int, replica_id: int, replica_count: int,
                 storage: Storage, bus, time,
                 state_machine_factory: Callable[[], StateMachine] = StateMachine,
                 options: ReplicaOptions = ReplicaOptions(),
                 tracer=None, aof=None, standby_count: int = 0):
        from ..multiversion import RELEASE, ReleaseTracker
        from ..trace import NullTracer
        from .clock import Clock

        assert 1 <= replica_count <= 6
        assert 0 <= standby_count <= 6
        # Standbys (ids >= replica_count) receive the replication stream
        # and commit like backups, but hold no vote: they never ack
        # prepares, never join view changes, never become primary
        # (reference: docs/ARCHITECTURE.md standbys — extra durability and
        # warm spares without quorum cost).
        assert 0 <= replica_id < replica_count + standby_count
        self.standby_count = standby_count
        self.is_standby = replica_id >= replica_count
        self.tracer = tracer if tracer is not None else NullTracer()
        self.aof = aof
        self.release = RELEASE
        # own= explicitly: the dataclass default binds the module RELEASE
        # at class-definition time, which would go stale across an
        # in-process upgrade (rolling-upgrade test).
        self.releases = ReleaseTracker(own=self.release)
        self.clock = Clock(replica_id, replica_count, time)
        self.last_ping_tx = 0
        self.cluster = cluster
        self.replica_id = replica_id
        self.replica_count = replica_count
        self.storage = storage
        self.bus = bus
        self.time = time
        self.options = options
        self.state_machine_factory = state_machine_factory

        from ..constants import config_fingerprint

        # Cluster-config fingerprint (constants + THIS replica's storage
        # geometry), cached: exchanged on pings, enforced in on_message.
        self._config_fp = config_fingerprint(
            (storage.layout.slot_count, storage.layout.message_size_max,
             storage.layout.grid_block_size))
        # Peers whose fingerprint mismatched: ALL their replica-to-replica
        # traffic is dropped until a matching ping clears them.
        self._config_mismatch: set[int] = set()
        self.journal = Journal(storage, tracer=self.tracer)
        self.state_machine: StateMachine = state_machine_factory()
        self.durable = DurableState(storage)
        # Serve reads from the LSM with a bounded object cache
        # (state_machine.attach_durable; reference: groove object cache).
        self.state_machine.attach_durable(self.durable)
        # Standing missing-block tracker (reference: grid_blocks_missing):
        # a corrupt read ANYWHERE (serving path, not just the scrubber)
        # queues the block for peer repair.
        self.durable.grid.on_corrupt = self._note_missing_block
        self.superblock: Optional[SuperBlock] = None
        self.fault_detector = FaultDetector(suspect_multiplier=4.0)
        self.repair_budget = RepairBudget()
        # Origin spread: each replica tours the grid from a different
        # rotation so the same latent fault is scrubbed at different
        # times on different replicas (grid_scrubber.zig:170-182).
        self.scrubber = GridScrubber(
            self.durable.forest,
            origin_seed=replica_id * 2654435761, tracer=self.tracer)
        self._scrub_phase = 0

        self.status = "recovering"
        # Rebuild-from-cluster mode (reference: src/vsr/replica_reformat
        # .zig): a replica whose data file was lost/zeroed re-enters the
        # cluster WITHOUT a vote — it solicits a peer checkpoint, installs
        # it via state sync (staged: superblock sync_op brackets the grid
        # writes), repairs the WAL suffix through normal VSR repair, and
        # certifies the installed grid with a full scrub tour before it is
        # allowed to ack, nack, or elect again. Its lost promises are only
        # safe to forget because it rejoins at/above the cluster's durable
        # checkpoint while a healthy quorum carries the log.
        self.rebuilding = False
        self._rebuild_goal = 0  # cluster commit to catch up to (frozen)
        self._rebuild_heard = False  # a peer answered the solicitation
        self._rebuild_synced = False  # a checkpoint install happened
        self._rebuild_certified = False  # full scrub tour came back clean
        self._rebuild_solicit_last = 0
        self._rebuild_certify_last = 0
        self.view = 0
        self.log_view = 0
        self.op = 0  # highest op appended to our journal
        self.commit_min = 0  # highest op executed
        self.commit_max = 0  # highest op known committed cluster-wide
        self.prepare_timestamp = 0

        # Primary pipeline: op -> {"message": Message, "oks": set[replica]}
        self.pipeline: dict[int, dict] = {}
        # Durable session table + latest replies (client_replies zone).
        self.sessions = ClientSessions(storage)
        # View change collection state.
        self.svc_votes: dict[int, set[int]] = {}
        self.dvc_messages: dict[int, dict[int, Message]] = {}
        # Canonical HEADERS installed from start_view/do_view_change:
        # prepares matching their checksums are authoritative regardless of
        # their view (the view-change quorum chose this log). Full headers
        # are kept so a new primary can broadcast the canonical suffix even
        # before it repairs the bodies.
        self.canonical: dict[int, Header] = {}
        # Repair bookkeeping.
        self.repair_requested: dict[int, int] = {}  # op -> last request ns
        # State-sync progress (None when not syncing).
        self.syncing: Optional[dict] = None
        # A view this (new primary) replica is completing repair for,
        # before broadcasting start_view.
        self._pending_view: Optional[int] = None
        # Ops below this are unverifiable from our journal (a start_view's
        # suffix began beyond them): execute only canonical entries there.
        self.sync_floor = 0
        # Checkpoint-rollback recovery: at most one attempt per
        # (checkpoint, log_view) — re-divergence against the SAME
        # canonical knowledge proves the checkpoint itself diverged (only
        # state sync can help), while a later view's new canonical suffix
        # legitimately warrants a fresh attempt.
        self._rollback_checkpoint: tuple[int, int] | None = None
        # op -> monotonic time it entered rollback quarantine; lingering
        # entries escalate to the state-sync path.
        self._suspect_since: dict[int, int] = {}
        # op -> re-request count; stalled repairs re-solicit the current
        # view's start_view (canonical anchor) every 8th attempt
        # (throttled to one solicitation per interval, replica-wide).
        self._repair_attempts: dict[int, int] = {}
        self._rsv_last = 0
        # Ops the DVC merge could not resolve (same-log_view conflict
        # with no chain pin): the view must NOT finalize over them — the
        # view-change timer escalates to the next view instead, where a
        # different electorate can resolve the fork.
        self._dvc_ambiguous: set[int] = set()
        # Ops whose journaled prepare failed the forward-chain check (a
        # stale leftover under a committed op number): repair must fetch a
        # replacement even though a prepare is held.
        self.chain_suspect: set[int] = set()
        self._windows_committed = 0  # commit-window aggregations served
        # NACK collection (pending-view primary only): op -> set[replica]
        # of peers proving they never prepared the canonical entry.
        self.nacks: dict[int, set[int]] = {}
        # Scrub-detected corrupt blocks awaiting peer repair:
        # block index -> (tree, address, size).
        self.block_repair: dict[int, tuple] = {}
        self._reply_repair_last = 0

        self.last_heartbeat_rx = 0
        self.last_heartbeat_tx = 0
        self.last_repair_tick = 0
        # Commit-progress watchdog (send-only-primary liveness).
        self._progress_commit = 0
        self._progress_view = 0
        self._progress_ts = 0

    # ------------------------------------------------------------ lifecycle

    @staticmethod
    def format(storage: Storage, *, cluster: int, replica_id: int,
               replica_count: int) -> None:
        """Create a fresh data file (reference: src/vsr/replica_format.zig):
        an empty forest checkpoint root + the genesis superblock."""
        from ..multiversion import RELEASE

        durable = DurableState(storage)
        sessions_blob = ClientSessions(storage).pack()
        root = (durable.checkpoint(StateMachine(engine="oracle").state)
                + sessions_blob + struct.pack("<I", len(sessions_blob)))
        storage.write("snapshot", 0, root)
        # Format the WAL header ring with valid RESERVED headers
        # (reference: src/vsr/replica_format.zig formats every slot): a
        # recovering journal can then distinguish formatted-empty slots
        # (provably never prepared — eligible to NACK) from torn writes
        # (faulty — must abstain).
        for slot in range(storage.layout.slot_count):
            reserved = Header(command=Command.reserved, cluster=cluster,
                              replica=replica_id, op=slot).finalize()
            storage.write("wal_headers", slot * HEADER_SIZE, reserved.pack())
        sb = SuperBlock(
            cluster=cluster, replica_id=replica_id,
            replica_count=replica_count, release=RELEASE,
            snapshot_slot=0, snapshot_size=len(root),
            snapshot_checksum=checksum(root, domain=b"ckptroot"))
        sb.store(storage)

    def open(self) -> None:
        """Recover durable state: superblock quorum -> snapshot -> WAL replay
        (reference: src/vsr/replica.zig:654 open + commit_journal)."""
        sb = SuperBlock.load(self.storage)
        assert sb is not None, "data file not formatted"
        assert sb.cluster == self.cluster
        assert sb.replica_id == self.replica_id
        if sb.sync_op:
            # A state-sync install was torn by a crash: the grid may hold
            # a mix of old- and new-checkpoint blocks. Half-installed
            # state must never serve reads or vote — only the rebuild
            # path (which re-validates every block it keeps) may open it.
            raise RuntimeError(
                f"data file is mid-rebuild (state-sync install to op "
                f"{sb.sync_op} was interrupted) — run "
                "`recover --from-cluster` to finish the rebuild")
        if not self.releases.openable(sb.release):
            if self.releases.compatible(sb.release):
                raise RuntimeError(
                    f"data file checkpointed by release {sb.release} is "
                    f"below this binary's format floor — rebuild it via "
                    "`recover` (r2 changed the index-tree schema)")
            raise RuntimeError(
                f"data file checkpointed by release {sb.release}; this "
                f"binary is release {self.release} — upgrade before starting "
                "(reference: multiversion re-exec decision)")
        self.superblock = sb
        self.view = sb.view
        self.log_view = sb.log_view

        root = self.storage.read(
            "snapshot", sb.snapshot_slot * self.storage.layout.snapshot_size_max,
            sb.snapshot_size)
        assert checksum(root, domain=b"ckptroot") == sb.snapshot_checksum, \
            "checkpoint root corrupt"
        # Root layout: forest-root || sessions-blob || u32 sessions length
        # (reference: checkpoint trailer carries the client sessions too).
        forest_root, sessions_blob = _split_root(root)
        self.sessions.restore(sessions_blob)
        self.state_machine = self.state_machine_factory()
        self.state_machine.state = self.durable.open(forest_root,
                                                     load_events=False)
        self.state_machine.attach_durable(self.durable)

        self.journal.recover()
        self.op = max(sb.op_checkpoint, self._journal_contiguous_max(sb.op_checkpoint))
        self.commit_min = sb.op_checkpoint
        self.commit_max = max(sb.commit_max, sb.op_checkpoint)
        self.prepare_timestamp = self.state_machine.state.commit_timestamp
        # Replay the WAL suffix above the checkpoint — but only up to the
        # durably-KNOWN commit point. A primary that COMPLETED its view's
        # change (log_view == view) provably holds the canonical log up to
        # that commit point (it verified its journal against the chosen
        # log before start_view, and every later entry is its own), so it
        # replays fully — prepares legitimately keep their original older
        # views, which is why a view filter alone would wedge it. Everyone
        # else stops at the first entry not written under sb.log_view: it
        # may be a stale leftover a view change replaced while we were
        # down (the canonical/sync-floor guards are volatile); deferred
        # entries re-commit through the live protocol once we rejoin.
        own_primary = (self.primary_index(sb.view) == self.replica_id
                       and sb.log_view == sb.view and not self.is_standby)
        replay_to = min(self.op, self.commit_max)
        if not own_primary:
            for op in range(sb.op_checkpoint + 1, replay_to + 1):
                m = self.journal.read_prepare(op)
                if m is None or m.header.view != sb.log_view:
                    replay_to = op - 1
                    break
        self._commit_journal(replay_to)
        if sb.log_view < sb.view:
            # We persisted a view we never completed (crashed mid
            # view-change): we hold no proof of that view's log — rejoining
            # as view_change defers everything to the live protocol, and
            # crucially prevents acting as that view's primary without a
            # do_view_change quorum.
            self.status = "view_change"
        else:
            self.status = "normal"
        self.last_heartbeat_rx = self.time.monotonic()
        if self.is_primary:
            # Re-install canonical headers on the backups (their canonical
            # sets died with their processes; without this they drop our
            # old-view prepares), then re-replicate our uncommitted suffix
            # so it regains a quorum (single-replica clusters commit it
            # immediately: quorum 1). If the cluster moved to a newer view
            # while we were down, backups ignore both (view guards) and we
            # learn the new view from their traffic instead.
            self._broadcast_start_view()
            for op in range(self.commit_min + 1, self.op + 1):
                m = self.journal.read_prepare(op)
                if m is not None:
                    self._primary_adopt_canonical(m)

    def open_rebuild(self) -> None:
        """Open a blank / suspect data file for rebuild-from-cluster
        (reference: src/vsr/replica_reformat.zig): (re)format if the file
        is unformatted, mid-install (sync_op), or its checkpoint root is
        corrupt, then open passively. The grid zone survives a reformat —
        every block a later sync install reuses is validated against the
        offered root's checksums, so blocks fetched before a crash resume
        the transfer for free (delta sync) while clobbered ones are simply
        re-fetched."""
        sb = SuperBlock.load(self.storage)
        needs_format = (sb is None or sb.sync_op != 0
                        or sb.cluster != self.cluster
                        or sb.replica_id != self.replica_id)
        if not needs_format:
            root = self.storage.read(
                "snapshot",
                sb.snapshot_slot * self.storage.layout.snapshot_size_max,
                sb.snapshot_size)
            if checksum(root, domain=b"ckptroot") != sb.snapshot_checksum:
                needs_format = True
        if needs_format:
            Replica.format(self.storage, cluster=self.cluster,
                           replica_id=self.replica_id,
                           replica_count=self.replica_count)
        self.rebuilding = True
        self.tracer.begin(Event.rebuild)
        self.open()
        # A persisted log_view < view would open as "view_change", whose
        # liveness branch elects — a rebuilding replica never does. It
        # follows the live electorate passively and adopts whatever view
        # the cluster's start_view teaches it.
        self.status = "normal"

    @property
    def rebuild_complete(self) -> bool:
        """The rebuild reached its frozen goal: checkpoint installed (or
        reachable via WAL repair), committed up to the cluster commit
        observed at first contact, and the grid certified by a clean full
        scrub tour."""
        return (self.rebuilding and self._rebuild_heard
                and self.syncing is None
                and self.commit_min >= self._rebuild_goal
                and self._rebuild_certified)

    def finish_rebuild(self) -> None:
        """Re-enter the voting set (only once the rebuild is complete)."""
        assert self.rebuild_complete
        self.rebuilding = False
        self.tracer.end(Event.rebuild)

    def rebuild_progress(self) -> str:
        """One-line operator-facing progress (recover --from-cluster)."""
        if self.syncing is not None:
            have = len(self.syncing["have"])
            return (f"syncing checkpoint op {self.syncing['target_op']} "
                    f"from r{self.syncing['source']}: {have} blocks "
                    f"staged, {len(self.syncing['needed'])} to fetch")
        if not self._rebuild_heard:
            return "soliciting a checkpoint from the cluster"
        if self.commit_min < self._rebuild_goal:
            return (f"repairing WAL suffix: commit {self.commit_min}/"
                    f"{self._rebuild_goal}")
        if not self._rebuild_certified:
            return (f"certifying grid ({len(self.block_repair)} "
                    "blocks awaiting peer repair)")
        return (f"complete: checkpoint op "
                f"{self.superblock.op_checkpoint}, commit "
                f"{self.commit_min}")

    def _rebuild_tick(self, now: int) -> None:
        """Drive the rebuild: solicit a checkpoint until a peer answers,
        then certify the installed grid once caught up. The actual data
        movement rides the existing machinery (sync offers, block fetch,
        WAL repair)."""
        if (self.syncing is None
                and not (self._rebuild_heard
                         and self.commit_min >= self._rebuild_goal)
                and now - self._rebuild_solicit_last
                >= 4 * self.options.repair_interval_ns):
            # context=1: "I cannot trust any served prepare" — a peer
            # whose checkpoint covers the op answers with a sync offer,
            # the primary answers with start_view otherwise.
            self._rebuild_solicit_last = now
            header = Header(
                command=Command.request_prepare, cluster=self.cluster,
                replica=self.replica_id, view=self.view,
                op=self.commit_min + 1, context=1)
            msg = Message(header.finalize())
            for r in range(self.peer_count):
                if r != self.replica_id:
                    self.bus.send_to_replica(r, msg)
        if (self.syncing is None and self._rebuild_heard
                and self.commit_min >= self._rebuild_goal
                and not self._rebuild_certified
                and not self.block_repair
                and now - self._rebuild_certify_last
                >= 8 * self.options.repair_interval_ns):
            # Post-rebuild certification: one immediate full scrub tour.
            # Faults queue for peer repair (within the repair budget);
            # only a tour with zero faults AND an empty repair queue
            # certifies.
            self._rebuild_certify_last = now
            faults = self.scrubber.certify()
            for name, address, size in faults:
                self.block_repair[address.index] = (name, address, size)
            if not faults:
                self._rebuild_certified = True

    def _journal_contiguous_max(self, from_op: int) -> int:
        """Highest op such that every (from_op, op] slot holds a valid,
        hash-chained prepare."""
        op = from_op
        while True:
            nxt = self.journal.read_prepare(op + 1)
            if nxt is None:
                return op
            if op > from_op:
                cur = self.journal.read_prepare(op)
                if cur is None or nxt.header.parent != cur.header.checksum:
                    return op
            op += 1

    # ------------------------------------------------------------ identity

    def primary_index(self, view: Optional[int] = None) -> int:
        return (self.view if view is None else view) % self.replica_count

    @property
    def is_primary(self) -> bool:
        # A rebuilding replica is never primary, whatever the view math
        # says: half-installed state must not serve reads or assign ops.
        return (self.status == "normal"
                and self.primary_index() == self.replica_id
                and not self.rebuilding)

    @property
    def peer_count(self) -> int:
        """All message-reachable replicas: active + standbys."""
        return self.replica_count + self.standby_count

    @property
    def quorum_replication(self) -> int:
        """Flexible quorums (reference: docs/internals/vsr.md:283-289)."""
        return {1: 1, 2: 2, 3: 2, 4: 2, 5: 3, 6: 3}[self.replica_count]

    @property
    def quorum_nack(self) -> int:
        """Nacks that prove an op never reached a replication quorum: if it
        had, at most replica_count - quorum_replication replicas could
        truthfully lack it (reference: docs/ARCHITECTURE.md:540-563)."""
        return self.replica_count - self.quorum_replication + 1

    @property
    def quorum_view_change(self) -> int:
        return {1: 1, 2: 2, 3: 2, 4: 3, 5: 3, 6: 4}[self.replica_count]

    # ------------------------------------------------------------- messages

    def on_message(self, msg: Message) -> None:
        if not msg.valid():
            return
        h = msg.header
        if h.cluster != self.cluster:
            return
        if (h.replica in self._config_mismatch
                and h.command not in (Command.request, Command.ping)):
            # A config-mismatched peer must not participate in consensus
            # (its geometry could corrupt journals/quorum math); pings
            # stay visible so a fixed peer can clear the flag, and
            # `request` is exempt because clients default to
            # header.replica=0, which can collide with a replica id.
            return
        handler = {
            Command.request: self.on_request,
            Command.prepare: self.on_prepare,
            Command.prepare_ok: self.on_prepare_ok,
            Command.commit: self.on_commit,
            Command.start_view_change: self.on_start_view_change,
            Command.do_view_change: self.on_do_view_change,
            Command.start_view: self.on_start_view,
            Command.request_start_view: self.on_request_start_view,
            Command.request_prepare: self.on_request_prepare,
            Command.request_reply: self.on_request_reply,
            Command.reply: self.on_reply,
            Command.headers: self.on_sync_offer,
            Command.request_blocks: self.on_request_blocks,
            Command.block: self.on_block,
            Command.nack_prepare: self.on_nack_prepare,
            Command.ping: self.on_ping,
            Command.pong: self.on_pong,
        }.get(h.command)
        if handler is not None:
            handler(msg)

    # --------------------------------------------------------- normal path

    def on_request(self, msg: Message) -> None:
        if not self.is_primary:
            return  # client retries against the right primary
        h = msg.header
        try:
            operation = Operation(h.operation)
        except ValueError:
            return  # unknown operation: drop, never crash the replica
        session = self.sessions.get(h.client)
        if session is not None:
            if h.request < session["request"]:
                return  # stale duplicate
            if h.request == session["request"]:
                if session["reply"] is not None:
                    self.bus.send_to_client(h.client, session["reply"])
                else:
                    # Reply bytes missing locally (torn slot / state sync):
                    # repair from peers; the client's retry answers then.
                    self._request_reply_repair(h.client)
                return
        for entry in self.pipeline.values():
            eh = entry["message"].header
            if eh.client == h.client and eh.request == h.request:
                return  # already preparing this request
        if len(self.pipeline) >= PIPELINE_PREPARE_QUEUE_MAX:
            return  # backpressure: client will retry
        if HEADER_SIZE + len(msg.body) > self.storage.layout.message_size_max:
            return  # would not fit THIS replica's journal slot (small layout)
        if not _reply_fits(operation, len(msg.body),
                           self.storage.layout.message_size_max):
            return  # worst-case reply would not fit a message/reply slot
        if not self.state_machine.input_valid(operation, msg.body):
            return  # malformed body: never prepare it (client bug)
        self._primary_prepare(operation, msg.body, client=h.client,
                              request=h.request, ctx=h.trace_ctx)

    def _primary_prepare(self, operation: Operation, body: bytes, *,
                         client: int = 0, request: int = 0,
                         ctx=None) -> None:
        assert self.is_primary
        op = self.op + 1
        # Consensus drives time, not vice versa (reference clock.zig:1-45;
        # replica.zig prepare_timestamp via realtime_synchronized): a
        # primary without Marzullo agreement from a quorum of fresh clock
        # samples must NOT stamp prepares — it drops the request and the
        # client retries (a multi-replica cluster with an unsynchronizable
        # primary makes no progress, replica_test.zig "primary no clock
        # sync"). A solo replica is trivially synchronized with itself.
        if self.replica_count > 1:
            now = self.clock.realtime_synchronized()
            if now is None:
                return
        else:
            now = self.time.realtime()
        self.prepare_timestamp = max(
            self.prepare_timestamp + _event_count(operation, body), now)
        parent = self._prepare_checksum(self.op)
        header = Header(
            command=Command.prepare, cluster=self.cluster,
            replica=self.replica_id, view=self.view, op=op,
            commit=self.commit_max, timestamp=self.prepare_timestamp,
            operation=int(operation), client=client, request=request,
            parent=parent, release=self.release,
            # The request's trace context rides the prepare to the
            # backups (their replication spans parent to the client's
            # root span) and — derived ONLY from prepare fields — into
            # the reply, keeping replies byte-identical across replicas.
            trace_ctx=ctx,
        )
        prepare = Message(header=header.finalize(body), body=body)
        self.op = op
        self.pipeline[op] = {"message": prepare, "oks": set(),
                             "ctx": ctx, "t0": self.tracer.now_ns()}
        # The local journal write and the network replication proceed
        # CONCURRENTLY (reference: src/io/linux.zig overlap); the primary
        # counts its own ack only once its WAL slot is durable.
        self.journal.append(prepare, on_durable=self._self_ack_fn(prepare))
        for r in range(self.peer_count):
            if r != self.replica_id:
                self.bus.send_to_replica(r, prepare)
        self._check_quorum(op)

    def _self_ack_fn(self, prepare: Message):
        op, csum = prepare.header.op, prepare.header.checksum
        def _ack():
            entry = self.pipeline.get(op)
            if entry is not None and entry["message"].header.checksum == csum:
                entry["oks"].add(self.replica_id)
                self._check_quorum(op)
        return _ack

    def _prepare_checksum(self, op: int) -> int:
        if op == 0:
            return checksum(
                self.cluster.to_bytes(16, "little"), domain=b"genesis")
        msg = self.journal.read_prepare(op)
        return msg.header.checksum if msg else 0

    def on_prepare(self, msg: Message) -> None:
        h = msg.header
        # Causal tracing: a backup's replication span runs from receipt
        # to the durable-slot ack (recorded in _send_prepare_ok).
        t0 = self.tracer.now_ns()
        # A prepare matching a canonical header (installed by the view-change
        # quorum) is authoritative regardless of its original view.
        want_hdr = self.canonical.get(h.op)
        if (want_hdr is not None and want_hdr.checksum == h.checksum
                and self.status in ("normal", "view_change")):
            held = self.journal.read_prepare(h.op)
            if held is None or held.header.checksum != h.checksum:
                self.journal.append(msg)  # overwrite a stale same-op prepare
            self.op = max(self.op, h.op)
            if self.is_standby or self.rebuilding \
                    or self._pending_view is not None:
                pass  # no vote; a pending primary finalizes below instead
            elif not self.is_primary:
                self.journal.on_slot_durable(
                    h.op, lambda h=h, t0=t0: self._send_prepare_ok(h, t0))
            else:
                self._primary_adopt_canonical(msg)
            self._commit_journal(self.commit_max)
            return
        if self.status != "normal" or h.view != self.view:
            if h.view > self.view:
                self._request_start_view(h.view)
            return
        if self.is_primary:
            return
        self.last_heartbeat_rx = self.time.monotonic()
        self.fault_detector.observe_progress(self.last_heartbeat_rx)
        if h.op <= self.op:
            held = self.journal.read_prepare(h.op)
            replace_suspect = (
                held is not None and h.op in self.chain_suspect
                and held.header.checksum != h.checksum)
            if (held is None or replace_suspect) and self._chains_into_log(h):
                # Repair fill: the prepare for a gap slot — or the
                # replacement for a stale chain-suspect leftover — validated
                # by its hash-chain linkage to neighbors we already hold.
                self.journal.append(msg)
                self.op = max(self.op, h.op)
                self.chain_suspect.discard(h.op)
                held = msg
                self._commit_journal(self.commit_max)
            if held is not None and held.header.checksum == h.checksum \
                    and not self.is_standby and not self.rebuilding:
                # Ack only what we actually hold — and only once the slot
                # is durable (an in-flight async append is not yet ours
                # to vouch for).
                self.journal.on_slot_durable(
                    h.op, lambda h=h, t0=t0: self._send_prepare_ok(h, t0))
        elif h.op == self.op + 1 and h.parent == self._prepare_checksum(self.op):
            self.journal.append(
                msg, on_durable=(
                    None if self.is_standby or self.rebuilding
                    else lambda h=h, t0=t0: self._send_prepare_ok(h, t0)))
            self.op = h.op
        else:
            # Gap or chain break: repair.
            for missing in range(self.op + 1, h.op):
                self.repair_requested.setdefault(missing, 0)
            self.journal.append(msg)  # keep the prepare; chain checked later
            self.op = max(self.op, h.op)
        self.commit_max = max(self.commit_max, h.commit)
        self._commit_journal(self.commit_max)

    def _chains_into_log(self, h: Header) -> bool:
        """Validate a repair prepare by hash-chain linkage. Forward linkage
        (op+1's parent pins this checksum) is authoritative at any view;
        backward linkage is only safe within the current view — an op
        replaced during a view change chains backward identically to its
        canonical replacement, so a stale prepare from a deposed primary
        must not be admitted that way."""
        nxt = self.journal.read_prepare(h.op + 1)
        if nxt is not None:
            return nxt.header.parent == h.checksum
        if h.op == 0 or h.view != self.view:
            return False
        prev_checksum = self._prepare_checksum(h.op - 1)
        return prev_checksum != 0 and h.parent == prev_checksum

    def _primary_adopt_canonical(self, msg: Message) -> None:
        """New primary obtained a canonical suffix prepare body: re-replicate
        it in the new view so it can gather a fresh quorum."""
        op = msg.header.op
        if op <= self.commit_min or op in self.pipeline:
            return
        # Replay path: the re-replicated prepare keeps its ORIGINAL
        # trace context, so the new quorum wait re-links to the same
        # request trace instead of orphaning it.
        self.pipeline[op] = {"message": msg, "oks": set(),
                             "ctx": msg.header.trace_ctx,
                             "t0": self.tracer.now_ns()}
        self.journal.on_slot_durable(op, self._self_ack_fn(msg))
        for r in range(self.peer_count):
            if r != self.replica_id:
                self.bus.send_to_replica(r, msg)
        self._check_quorum(op)

    def _send_prepare_ok(self, prepare_header: Header,
                         t0: int = 0) -> None:
        ctx = prepare_header.trace_ctx
        if ctx is not None and t0:
            self.tracer.record_span(
                Event.replica_ack, t0, self.tracer.now_ns() - t0,
                ctx=ctx, op=prepare_header.op)
        ok = Header(
            command=Command.prepare_ok, cluster=self.cluster,
            replica=self.replica_id, view=self.view, op=prepare_header.op,
            context=prepare_header.checksum,
            commit=self.commit_min,
        )
        self.bus.send_to_replica(self.primary_index(), Message(ok.finalize()))

    def on_prepare_ok(self, msg: Message) -> None:
        if not self.is_primary or msg.header.view != self.view:
            return
        entry = self.pipeline.get(msg.header.op)
        if entry is None:
            return
        if msg.header.context != entry["message"].header.checksum:
            return
        entry["oks"].add(msg.header.replica)
        self._check_quorum(msg.header.op)

    def _check_quorum(self, op: int) -> None:
        """Commit in order as quorums complete (reference commit_dispatch)."""
        while True:
            # The primary's prefetch stage: its prepare comes from the
            # in-memory pipeline, not a journal read — the span still
            # measures the fetch + quorum check so all four commit
            # stages appear on every replica's trace.
            with self.tracer.span(Event.commit_prefetch,
                                  op=self.commit_min + 1):
                entry = self.pipeline.get(self.commit_min + 1)
                ready = (entry is not None and
                         len(entry["oks"]) >= self.quorum_replication)
            if not ready:
                return
            # The explicit quorum-wait span (ISSUE 15): prepare fan-out
            # to quorum reached, parented to the request's root — read
            # from the entry BEFORE it leaves the pipeline.
            ctx = entry.get("ctx")
            if ctx is not None:
                t0 = entry.get("t0", 0)
                self.tracer.record_span(
                    Event.commit_quorum, t0, self.tracer.now_ns() - t0,
                    ctx=ctx, op=self.commit_min + 1)
            self.commit_max = max(self.commit_max, self.commit_min + 1)
            self._commit_op(entry["message"])
            del self.pipeline[self.commit_min]

    def on_commit(self, msg: Message) -> None:
        if self.status != "normal" or msg.header.view != self.view:
            if msg.header.view > self.view:
                self._request_start_view(msg.header.view)
            return
        if self.is_primary:
            return
        self.last_heartbeat_rx = self.time.monotonic()
        self.fault_detector.observe_progress(self.last_heartbeat_rx)
        self.commit_max = max(self.commit_max, msg.header.commit)
        self._commit_journal(self.commit_max)

    def _commit_journal(self, commit_target: int) -> None:
        """Execute committed prepares from the journal, in order, as far as
        we have them (reference: commit_journal :4310). A journaled prepare
        that contradicts a canonical header (stale op from a deposed
        primary) must be repaired, never executed. Two further guards
        against stale leftovers (a prepare the old view wrote but the
        cluster later committed DIFFERENTLY under the same op number):
        - sync floor: a start_view whose suffix begins beyond our position
          means our journal entries below it are unverifiable (the
          electorate checkpointed past them) — never execute them; repair
          leads to a state-sync offer instead;
        - forward chain: if the successor prepare is already journaled (and
          not itself contradicted by a canonical header), this op's
          checksum must be its parent — a mismatch means one of the two is
          stale, so repair rather than execute."""
        prev_checksum = None
        window_backoff = False
        while self.commit_min < commit_target:
            op = self.commit_min + 1
            with self.tracer.span(Event.commit_prefetch, op=op):
                msg = self.journal.read_prepare(op)
            want_hdr = self.canonical.get(op)
            want = None if want_hdr is None else want_hdr.checksum
            if msg is None or (want is not None
                               and msg.header.checksum != want):
                self.repair_requested.setdefault(op, 0)
                return
            if want is None and op < self.sync_floor:
                # Unverifiable leftover below the electorate's checkpoint.
                self.repair_requested.setdefault(op, 0)
                return
            if op in self.chain_suspect:
                # Quarantined (e.g. the rollback range): a stale chain can
                # share ancestry with the truth up to its fork, so parent
                # linkage alone cannot clear it. A canonical match IS the
                # confirmation (the mismatch case returned above);
                # otherwise execution waits for a replacement or a
                # forward-chain confirmation from a trusted op above
                # (repair tick).
                if want is None:
                    self.repair_requested.setdefault(op, 0)
                    return
                self.chain_suspect.discard(op)
            if prev_checksum is None:
                # 0 = base unknown (e.g. the op behind a synced checkpoint
                # is not in our journal): the tripwire can't fire there.
                prev_checksum = self._prepare_checksum(self.commit_min)
            if prev_checksum and msg.header.parent != prev_checksum:
                if want is not None:
                    # The CANONICAL prepare doesn't chain from what we
                    # executed: our own prefix diverged (we executed a
                    # deposed primary's prepare under a reused op number).
                    # Recovery, in preference order:
                    #   1. checkpoint rollback + re-execution: reload the
                    #      last persisted checkpoint (a pure function of
                    #      the committed prefix IF that prefix was
                    #      canonical), quarantine the stale journal range,
                    #      and let peer repairs — validated by forward
                    #      hash-chaining down from the canonical suffix —
                    #      replace and re-execute it;
                    #   2. if the rollback was already tried at this
                    #      checkpoint (the checkpoint itself diverged) or
                    #      the checkpoint doesn't precede the divergence:
                    #      refuse to execute (sync floor) and solicit a
                    #      state-sync offer once a peer checkpoint covers
                    #      us. Divergence is always preferred stalled over
                    #      executed.
                    if self._rollback_to_checkpoint(op):
                        return
                    self.sync_floor = max(self.sync_floor,
                                          max(self.commit_max, op) + 1)
                    self.canonical.pop(op, None)
                else:
                    # Backward-chain tripwire: a prepare that doesn't chain
                    # from the op we just committed is a stale leftover.
                    self.chain_suspect.add(op)
                self.repair_requested.setdefault(op, 0)
                return
            self.chain_suspect.discard(op)
            window = (None if window_backoff
                      else self._collect_commit_window(msg, commit_target))
            if window is not None:
                # Fan-in across batching: the window span joins the
                # FIRST traced constituent's tree and links every
                # member's trace id, so each request's trace crosses
                # the batch boundary and back out to its reply.
                wctxs = [m.header.trace_ctx for m in window]
                with self.tracer.span(
                        Event.commit_execute, op=window[0].header.op,
                        ctx=next((c for c in wctxs if c is not None),
                                 None),
                        operation=int(window[0].header.operation),
                        window=len(window)) as wsp:
                    for c in wctxs:
                        if c is not None:
                            wsp.link(c.trace_id)
                    out = self.state_machine.commit_window(
                        Operation(window[0].header.operation),
                        [m.body for m in window],
                        [m.header.timestamp for m in window],
                        all_or_nothing=True)
                if out is None:
                    # Cross-prepare dependency in this suffix: stop
                    # attempting windows for the rest of this call (the
                    # per-op path handles it exactly; retrying per
                    # iteration would pay a doomed dispatch per op).
                    window_backoff = True
                if out is not None:
                    replies, shape = out
                    self.tracer.count(Event.commit_windows)
                    self._windows_committed += 1
                    for m, res, k in zip(window, replies, shape):
                        self._post_commit(m, res, chunk_count=k)
                    prev_checksum = window[-1].header.checksum
                    continue
            self._commit_op(msg)
            prev_checksum = msg.header.checksum

    def _rollback_to_checkpoint(self, first_divergent_op: int) -> bool:
        """In-process checkpoint rollback for divergence recovery: reload
        the last persisted checkpoint's state (forest, sessions, state
        machine) exactly as a restart would, rewind commit_min to it, and
        quarantine the stale journal range (chain_suspect) so repairs can
        replace it with prepares that forward-chain from the canonical
        suffix. Returns False when rollback cannot help: no superblock, a
        corrupt snapshot, a checkpoint at/after the divergence, or a prior
        attempt at this same checkpoint (re-divergence proves the
        checkpoint itself is off the canonical history — the sync-floor /
        state-sync path is then the only recovery).

        Soundness: the rolled-back state re-executes ONLY prepares that
        hash-chain down from view-change-quorum-installed canonical
        headers; if our checkpoint prefix itself diverged, the first
        re-executed op fails the backward-chain tripwire again and falls
        through to the sync path — a wrong prefix is never extended."""
        sb = self.superblock
        if (sb is None or sb.op_checkpoint >= first_divergent_op
                or self._rollback_checkpoint == (sb.op_checkpoint,
                                                 self.log_view)):
            return False
        root = self.storage.read(
            "snapshot",
            sb.snapshot_slot * self.storage.layout.snapshot_size_max,
            sb.snapshot_size)
        if checksum(root, domain=b"ckptroot") != sb.snapshot_checksum:
            return False
        self._rollback_checkpoint = (sb.op_checkpoint, self.log_view)
        forest_root, sessions_blob = _split_root(root)
        # Fresh durable engine over the same storage: drops every
        # in-memory LSM/grid structure the divergent suffix built (the
        # copy-on-write grid still holds the checkpoint's blocks; blocks
        # written after it are unreferenced from this root).
        self.durable = DurableState(self.storage)
        self.sessions.restore(sessions_blob)
        self.state_machine = self.state_machine_factory()
        self.state_machine.state = self.durable.open(forest_root,
                                                     load_events=False)
        self.state_machine.attach_durable(self.durable)
        old_commit_min = self.commit_min
        self.commit_min = sb.op_checkpoint
        self.prepare_timestamp = self.state_machine.state.commit_timestamp
        now = self.time.monotonic()
        for op in range(sb.op_checkpoint + 1, first_divergent_op):
            # The stale executed range: replaceable only by prepares that
            # chain down from the canonical suffix.
            self.chain_suspect.add(op)
            self.repair_requested.setdefault(op, 0)
            self._suspect_since.setdefault(op, now)
        self.tracer.count(Event.rollbacks)
        logging.getLogger("tigerbeetle_tpu.vsr").warning(
            "replica %d: divergence at op %d — rolled back to checkpoint "
            "%d (was %d); re-executing the canonical history",
            self.replica_id, first_divergent_op, sb.op_checkpoint,
            old_commit_min)
        return True

    COMMIT_WINDOW_MAX = 8

    def _mirror_quiescent(self) -> bool:
        """The regime in which window commits keep per-op flush content
        identical to single commits (shared predicate: durable.py)."""
        from .durable import mirror_quiescent

        return mirror_quiescent(self.state_machine.raw_state,
                                self.durable.events_persisted)

    def _collect_commit_window(self, head: Message,
                               commit_target: int) -> Optional[list]:
        """Extend the validated head prepare into a contiguous run of
        same-operation create_transfers prepares the state machine may
        execute as ONE device dispatch (commit-window aggregation; the
        reference pipelines 8 prepares, src/config.zig:155). Lookahead
        prepares get the same safety checks the head already passed
        (canonical match, sync floor, quarantine, hash chain); any
        obstacle just ends the run — the head path re-examines it on
        the next loop iteration. Windows never span a checkpoint
        boundary: each op's _post_commit must checkpoint state that
        contains exactly the ops up to it."""
        sm = self.state_machine
        if getattr(sm, "engine", None) != "device" or sm.led is None:
            return None
        # Mirror the ledger's own eligibility gate: in the host-mirror
        # or fixpoint-first regime the window dispatch would be a
        # guaranteed waste (collected, decoded, then refused).
        if sm.led._mirror_route() or sm.led._fixpoint_first:
            return None
        try:
            o = Operation(head.header.operation)
        except ValueError:
            return None
        if (_base_operation(o) != Operation.create_transfers
                or not o.is_multi_batch()):
            return None
        if not self._mirror_quiescent():
            return None
        run = [head]
        prev = head.header.checksum
        interval = self.options.checkpoint_interval
        while len(run) < self.COMMIT_WINDOW_MAX:
            last_op = head.header.op + len(run) - 1
            if last_op % interval == 0:
                break  # a checkpoint fires right after last_op
            nop = last_op + 1
            if nop > commit_target:
                break
            m = self.journal.read_prepare(nop)
            if m is None or m.header.operation != head.header.operation:
                break
            want_hdr = self.canonical.get(nop)
            if want_hdr is not None and m.header.checksum != \
                    want_hdr.checksum:
                break
            if want_hdr is None and nop < self.sync_floor:
                break
            if nop in self.chain_suspect:
                break
            if m.header.parent != prev:
                break
            run.append(m)
            prev = m.header.checksum
        return run if len(run) > 1 else None

    def _commit_op(self, prepare: Message) -> None:
        h = prepare.header
        assert h.op == self.commit_min + 1
        operation = Operation(h.operation)
        with self.tracer.span(Event.commit_execute, ctx=h.trace_ctx,
                              op=h.op, operation=int(operation),
                              window=1):
            result = self.state_machine.commit(operation, prepare.body,
                                               h.timestamp)
        self._post_commit(prepare, result)

    def _post_commit(self, prepare: Message, result: bytes,
                     chunk_count: int = None) -> None:
        """Everything a committed op owes besides state-machine
        execution: AOF, commit_min, durable flush + compaction beat,
        reply recording, checkpoint trigger. chunk_count attributes
        flush chunks to this op in window commits (None = pop all, the
        single-op path)."""
        h = prepare.header
        assert h.op == self.commit_min + 1
        self.tracer.count(Event.commits)
        if self.aof is not None:
            self.aof.append(prepare)
        self.commit_min = h.op
        # Write-through to the LSM forest + one deterministic compaction
        # beat (reference: commit_compact, one beat per op — §3.4).
        # raw_state: the flush consumes device delta columns directly —
        # the mirror drain stays DEFERRED (it runs at read boundaries and
        # checkpoints, amortized), which is most of the serving win.
        with self.tracer.span(Event.commit_compact, op=h.op):
            led = self.state_machine.led
            cols = (led.take_flush_columns(chunk_count)
                    if led is not None else None)
            raw = self.state_machine.raw_state
            if cols and not self._mirror_quiescent():
                # Interleaved history (hard-regime handoff, account
                # creation, expiry): the mirror and the chunks describe
                # overlapping order that only ONE authority may
                # serialize — drain, then flush everything through the
                # object path. Window commits form only in the quiescent
                # regime and execute purely on device, so this must
                # never fire mid-window (a drain here would serialize
                # LATER window ops' chunks into THIS op's flush and
                # break cross-replica physical determinism).
                assert chunk_count is None, \
                    "window commit entered a dirty-mirror regime"
                self.state_machine.state  # drains; chunks become stale
                cols = None
            flushed = self.durable.flush(raw, flush_columns=cols)
            self.state_machine.cache_upsert(*flushed)
            self.durable.compact_beat(h.op)
        if h.client:
            # Reply fields derive from the PREPARE (its view and original
            # primary), never from this replica's identity/current view —
            # replies must be byte-identical across replicas so checkpoints
            # (which carry the session table) are byte-identical and reply
            # slots are peer-repairable (reference: client_replies repair).
            reply_header = Header(
                command=Command.reply, cluster=self.cluster,
                replica=h.replica, view=h.view, op=h.op,
                client=h.client, request=h.request, commit=h.op,
                context=h.checksum, operation=h.operation,
                timestamp=h.timestamp,
                # Derived ONLY from the prepare (like every reply
                # field): the context closes the causal loop at the
                # client without breaking cross-replica byte identity.
                trace_ctx=h.trace_ctx,
            )
            reply = Message(reply_header.finalize(result), body=result)
            evicted = self.sessions.put_reply(h.client, h.request, reply)
            if evicted is not None and self.is_primary:
                ev = Header(
                    command=Command.eviction, cluster=self.cluster,
                    replica=self.replica_id, view=self.view, client=evicted)
                self.bus.send_to_client(evicted, Message(ev.finalize()))
            if self.is_primary:
                self.bus.send_to_client(h.client, reply)
        if self.commit_min % self.options.checkpoint_interval == 0:
            with self.tracer.span(Event.commit_checkpoint,
                                  op=self.commit_min):
                self._checkpoint()

    def _checkpoint(self) -> None:
        """Forest checkpoint + superblock flip (reference
        commit_checkpoint_data / commit_checkpoint_superblock :4989,5110).
        Only manifests + the free set are serialized — table data is already
        durable in the copy-on-write grid, so the flip is incremental."""
        sb = self.superblock
        # WAL durability barrier: every in-flight async append lands
        # before state derived from those prepares is checkpointed.
        # fire=False: a quorum callback firing here could advance
        # commit_min mid-flip (and reenter _checkpoint); the callbacks
        # run at the next tick's poll_io instead.
        self.journal.wait_all(fire=False)
        if constants.VERIFY:
            # Extra-check mode: walk the committed WAL suffix's hash
            # chain (parent linkage across held neighbors).
            prev = None
            for op in range(max(1, self.commit_min - 64),
                            self.commit_min + 1):
                m = self.journal.read_prepare(op)
                if m is None:
                    prev = None
                    continue
                if prev is not None:
                    assert m.header.parent == prev, \
                        f"verify: journal chain break at op {op}"
                prev = m.header.checksum
        sessions_blob = self.sessions.pack()
        ckpt_state = self.state_machine.state  # drains the mirror first
        led = self.state_machine.led
        if led is not None:
            # The drain above made any queued columns stale (the object
            # path now covers everything) — pop them so they cannot leak
            # or trip the column path's quiescent-mirror contract.
            led.take_flush_columns()
        root = (self.durable.checkpoint(ckpt_state)
                + sessions_blob + struct.pack("<I", len(sessions_blob)))
        assert len(root) <= self.storage.layout.snapshot_size_max, \
            "checkpoint root exceeds slot (raise snapshot_size_max)"
        slot = 1 - sb.snapshot_slot
        self.storage.write(
            "snapshot", slot * self.storage.layout.snapshot_size_max, root)
        sb.snapshot_slot = slot
        sb.snapshot_size = len(root)
        sb.snapshot_checksum = checksum(root, domain=b"ckptroot")
        sb.op_checkpoint = self.commit_min
        sb.commit_min = self.commit_min
        sb.commit_max = self.commit_max
        sb.view = self.view
        sb.log_view = self.log_view
        sb.release = self.release
        sb.checkpoint_id = checksum(
            sb.checkpoint_id.to_bytes(16, "little") + root[:64], domain=b"ckpt")
        sb.store(self.storage)
        # Memory-bounds doctrine: everything below the checkpoint is
        # durable in the forest's events tree — prune the host tail at
        # this DETERMINISTIC point (same op on every replica, so states
        # stay byte-identical; restart restores the same base).
        self.state_machine.state.prune_account_events(
            self.durable.events_persisted)

    # ---------------------------------------------------------- view change

    def _start_view_change(self, new_view: int) -> None:
        # Standbys follow, never elect; a rebuilding replica's empty
        # journal must never weigh in a view change either.
        assert not self.is_standby and not self.rebuilding
        assert new_view > self.view
        # One span per attempted view: an escalation (view+1 while still
        # changing) closes the stalled attempt and opens the next.
        self.tracer.end(Event.view_change)
        self.tracer.begin(Event.view_change, view=new_view)
        self._pending_view = None
        self.status = "view_change"
        self.view = new_view
        self.pipeline.clear()
        self.nacks.clear()
        self._dvc_ambiguous.clear()
        self._repair_attempts.clear()
        self._persist_view()
        votes = self.svc_votes.setdefault(new_view, set())
        votes.add(self.replica_id)
        header = Header(
            command=Command.start_view_change, cluster=self.cluster,
            replica=self.replica_id, view=new_view)
        msg = Message(header.finalize())
        for r in range(self.replica_count):
            if r != self.replica_id:
                self.bus.send_to_replica(r, msg)
        self._check_svc_quorum(new_view)

    def on_start_view_change(self, msg: Message) -> None:
        v = msg.header.view
        if self.is_standby or self.rebuilding or v < self.view:
            return
        if v > self.view:
            self._start_view_change(v)
        self.svc_votes.setdefault(v, set()).add(msg.header.replica)
        self._check_svc_quorum(v)

    def _check_svc_quorum(self, v: int) -> None:
        if self.status != "view_change" or v != self.view:
            return
        if len(self.svc_votes.get(v, ())) < self.quorum_view_change:
            return
        self._send_do_view_change(v)

    def _dvc_suffix_headers(self) -> list[Header]:
        """The log suffix as journal-ring HEADERS — including faulty slots
        whose bodies are torn. A torn-but-headered op MUST be advertised:
        omitting it could silently drop a committed op whose only
        surviving quorum-member copy is torn (the new primary resolves
        presence via repair, absence via the nack quorum — reference:
        DVC nack/present bitsets, src/vsr/replica.zig:254)."""
        base = self.superblock.op_checkpoint if self.superblock else 0
        out = []
        for op in range(base + 1, self.op + 1):
            h = self.journal.headers[self.journal.slot_for_op(op)]
            if h is not None and h.op == op and h.command == Command.prepare:
                out.append(h)
        return out

    def _send_do_view_change(self, v: int) -> None:
        """Send our log suffix to the new primary (headers above checkpoint)."""
        body = b"".join(h.pack() for h in self._dvc_suffix_headers())
        header = Header(
            command=Command.do_view_change, cluster=self.cluster,
            replica=self.replica_id, view=v, op=self.op,
            commit=self.commit_min, context=self.log_view)
        msg = Message(header.finalize(body), body=body)
        if self.primary_index(v) == self.replica_id:
            self.on_do_view_change(msg)
        else:
            self.bus.send_to_replica(self.primary_index(v), msg)

    def _suffix_headers(self) -> list[Header]:
        """The log suffix as HEADERS: canonical knowledge FIRST (the
        view-change quorum's truth — our journal may still hold a deposed
        primary's unrepaired prepare under a reused op number), else the
        journal-held header (a new primary knows the chosen log's headers
        before it has repaired the bodies — backups must still learn them,
        or they silently drop the re-replicated old-view prepares)."""
        base = self.superblock.op_checkpoint if self.superblock else 0
        out = []
        for op in range(base + 1, self.op + 1):
            if op in self.canonical:
                out.append(self.canonical[op])
                continue
            m = self.journal.read_prepare(op)
            if m is not None:
                out.append(m.header)
        return out

    def on_do_view_change(self, msg: Message) -> None:
        if self.is_standby or self.rebuilding:
            return
        v = msg.header.view
        if v < self.view or self.primary_index(v) != self.replica_id:
            return
        if v > self.view:
            self._start_view_change(v)
        if self.status != "view_change" or v != self.view:
            return
        self.dvc_messages.setdefault(v, {})[msg.header.replica] = msg
        dvcs = self.dvc_messages[v]
        if self.replica_id not in dvcs:
            body = b"".join(h.pack() for h in self._dvc_suffix_headers())
            own = Header(
                command=Command.do_view_change, cluster=self.cluster,
                replica=self.replica_id, view=v, op=self.op,
                commit=self.commit_min, context=self.log_view)
            dvcs[self.replica_id] = Message(own.finalize(body), body=body)
        if len(dvcs) < self.quorum_view_change:
            return
        # Adopt the best log: max (log_view, op) (VSR view-change rule).
        best = max(dvcs.values(),
                   key=lambda m: (m.header.context, m.header.op))
        # Our own log may extend beyond the chosen one (e.g. a higher
        # log_view with a lower op wins): the excess is uncommitted. Never
        # truncate below commit_min — committed ops are final.
        if self.op > best.header.op:
            self.op = max(best.header.op, self.commit_min)
        # UNION-merge headers across every DVC of the winning log_view:
        # the true log of one log_view is unique, so a peer's copy can
        # fill a hole in the chosen suffix — without this, a tie-broken
        # DVC with a gap would drop the canonical header and the repair
        # prepare would then be rejected as non-canonical (liveness).
        # Same-log_view DVCs CAN conflict at an op: a replica that joined
        # the log_view via start_view may still journal a deposed
        # primary's unrepaired prepare under a reused op number (soak
        # seed 517731180). Resolve by hash-chain walk-down from the tip:
        # the accepted header at op+1 pins op's checksum via its parent;
        # an op with no pinned resolution becomes a HOLE (left out of the
        # canonical set — repair/nack decide it later, and the commit
        # path's chain tripwire guards execution regardless).
        cands: dict[int, list[Header]] = {}
        for m in dvcs.values():
            if m.header.context != best.header.context:
                continue
            for hh in _unpack_headers(m.body):
                if hh.op > best.header.op:
                    continue
                bucket = cands.setdefault(hh.op, [])
                if all(c.checksum != hh.checksum for c in bucket):
                    bucket.append(hh)
        best_headers = []
        expect = None  # checksum pinned by the accepted header above
        prev_op = None
        for op in sorted(cands, reverse=True):
            if prev_op is not None and op != prev_op - 1:
                expect = None  # gap: the chain pin does not carry across
            prev_op = op
            bucket = cands[op]
            if expect is not None:
                chosen = next(
                    (c for c in bucket if c.checksum == expect), None)
            elif len(bucket) == 1:
                chosen = bucket[0]
            else:
                chosen = None  # ambiguous with no pin from above
            if chosen is None:
                if len(bucket) > 1:
                    self._dvc_ambiguous.add(op)
                expect = None
                continue
            best_headers.append(chosen)
            expect = chosen.parent
        best_headers.reverse()
        suffix_base = (min(hh.op for hh in best_headers) if best_headers
                       else best.header.op + 1)
        if suffix_base > self.commit_min + 1:
            # Same unverifiable-base rule as on_start_view, for the new
            # primary itself (the chosen log's sender checkpointed past
            # our position).
            self.sync_floor = max(self.sync_floor, suffix_base)
        self._install_log(best_headers)
        commit_max = max(m.header.commit for m in dvcs.values())
        self.commit_max = max(self.commit_max, commit_max)
        # The view does NOT start yet: the primary must hold the COMPLETE
        # canonical log first (reference: the new primary repairs before
        # start_view; a suffix with holes would strand backups on
        # unverifiable ops). _try_start_view finalizes once repair (already
        # requested by _install_log for mismatches/gaps) completes; if the
        # bodies are unobtainable the view-change timer escalates.
        self._pending_view = v
        self._try_start_view()

    def _try_start_view(self) -> None:
        """Finalize a pending view once the primary's log is complete."""
        if self._pending_view != self.view or self.status != "view_change":
            return
        if self._dvc_ambiguous:
            # Same-log_view fork with no local resolution: finalizing
            # would let this primary's own journal copy masquerade as
            # canonical truth. Stall; the view-change timer escalates to
            # the next view, whose electorate can resolve it.
            return
        for op in range(max(self.commit_min, self.sync_floor - 1) + 1,
                        self.op + 1):
            m = self.journal.read_prepare(op)
            if m is None:
                self.repair_requested.setdefault(op, 0)
                return
            want = self.canonical.get(op)
            if want is not None and m.header.checksum != want.checksum:
                self.repair_requested.setdefault(op, 0)
                return
        v = self._pending_view
        self._pending_view = None
        self.log_view = v
        self.status = "normal"
        self.tracer.end(Event.view_change)
        self._persist_view()
        self._broadcast_start_view()
        self._commit_journal(self.commit_max)
        # Re-replicate the uncommitted canonical suffix in the new view so
        # possibly-committed ops regain a quorum (VSR safety: the view-change
        # quorum intersects every replication quorum).
        for op in range(self.commit_min + 1, self.op + 1):
            m = self.journal.read_prepare(op)
            if m is not None and (
                    op not in self.canonical
                    or self.canonical[op].checksum == m.header.checksum):
                self._primary_adopt_canonical(m)

    def _install_log(self, headers: list) -> None:
        """Install a canonical header suffix; fetch bodies we lack via
        repair. REPLACES the previous canonical set: entries from older
        views are obsolete (the new electorate's log is the only truth),
        and a stale leftover would reject the true prepare forever."""
        self.canonical = {}
        for h in headers:
            self.canonical[h.op] = h
            ours = self.journal.read_prepare(h.op)
            if ours is None or ours.header.checksum != h.checksum:
                self.repair_requested.setdefault(h.op, 0)
        if headers:
            self.op = max(self.op, max(h.op for h in headers))

    def _start_view_message(self) -> Message:
        body = b"".join(h.pack() for h in self._suffix_headers())
        header = Header(
            command=Command.start_view, cluster=self.cluster,
            replica=self.replica_id, view=self.view, op=self.op,
            commit=self.commit_max)
        return Message(header.finalize(body), body=body)

    def _broadcast_start_view(self) -> None:
        msg = self._start_view_message()
        for r in range(self.peer_count):
            if r != self.replica_id:
                self.bus.send_to_replica(r, msg)

    def on_start_view(self, msg: Message) -> None:
        h = msg.header
        if h.view < self.view or h.replica != self.primary_index(h.view):
            return
        if self.rebuilding and not self._rebuild_heard:
            # First contact is the primary itself (no peer checkpoint
            # covers us yet): the goal is its commit_max — reachable
            # through ordinary WAL repair under the canonical suffix.
            self._rebuild_heard = True
            self._rebuild_goal = h.commit
        self.view = h.view
        self.log_view = h.view
        if self.status == "view_change":
            self.tracer.end(Event.view_change)
        self.status = "normal"
        self.pipeline.clear()
        self._persist_view()
        headers = _unpack_headers(msg.body)
        # The suffix covers (primary's checkpoint, primary's op]; an EMPTY
        # suffix means the primary checkpointed at its log end, so the
        # verifiable base is op+1. Anything of ours below the base is
        # UNVERIFIABLE (a deposed primary may have written different
        # prepares under the same op numbers) — never execute it; repair
        # solicits a state-sync offer instead.
        suffix_base = (min(hh.op for hh in headers) if headers
                       else h.op + 1)
        if suffix_base > self.commit_min + 1:
            self.sync_floor = max(self.sync_floor, suffix_base)
        # The electorate's log ends at h.op: anything we hold beyond it is
        # uncommitted by definition — truncate rather than risk executing a
        # deposed primary's prepares under reused op numbers. Never below
        # commit_min: committed ops are final (a raced/stale same-view
        # re-broadcast must not push op under what we executed).
        if self.op > h.op:
            self.op = max(h.op, self.commit_min)
        self._install_log(headers)
        self.commit_max = max(self.commit_max, h.commit)
        self.last_heartbeat_rx = self.time.monotonic()
        self.fault_detector.reset(self.last_heartbeat_rx)
        self._commit_journal(self.commit_max)

    def on_request_start_view(self, msg: Message) -> None:
        if self.is_primary and msg.header.view <= self.view:
            self._broadcast_start_view()

    def _request_start_view(self, view: int) -> None:
        header = Header(
            command=Command.request_start_view, cluster=self.cluster,
            replica=self.replica_id, view=view)
        self.bus.send_to_replica(self.primary_index(view),
                                 Message(header.finalize()))

    def _persist_view(self) -> None:
        if self.superblock is None:
            return
        self.superblock.view = self.view
        self.superblock.log_view = self.log_view
        self.superblock.store(self.storage)

    # -------------------------------------------------------------- repair

    def on_request_prepare(self, msg: Message) -> None:
        if msg.header.context == 1:
            # The requester cannot trust any served prepare for this op (it
            # is below its sync floor): offer our checkpoint — or, when no
            # checkpoint covers it yet, the primary answers with a FULL
            # start_view whose canonical suffix re-verifies the op.
            if (self.superblock is not None
                    and msg.header.op <= self.superblock.op_checkpoint):
                self._send_sync_offer(msg.header.replica)
                return
            if self.is_primary:
                self.bus.send_to_replica(msg.header.replica,
                                         self._start_view_message())
                return
        m = self.journal.read_prepare(msg.header.op)
        wanted = msg.header.parent  # canonical checksum sought (0: unknown)
        if m is not None:
            self.bus.send_to_replica(msg.header.replica, m)
            if wanted != 0 and m.header.checksum != wanted \
                    and not self.rebuilding:
                # We hold a DIFFERENT prepare for this op. A replica
                # prepares at most one body per op, so holding another
                # checksum proves we never prepared the canonical one —
                # the served prepare won't satisfy the repair, but the
                # nack can complete a truncation quorum. (A rebuilding
                # replica lost its promise history with its data file —
                # it can prove nothing and must not nack.)
                self._send_nack(msg.header.replica, msg.header.op, wanted)
        elif (self.superblock is not None
              and msg.header.op <= self.superblock.op_checkpoint):
            # We committed past this op and the WAL wrapped: the peer can
            # never repair forward — offer our checkpoint instead
            # (reference: state sync, docs/internals/sync.md:49-79).
            self._send_sync_offer(msg.header.replica)
        elif msg.header.op > self.commit_min and not self.is_standby \
                and not self.rebuilding:
            # Nothing servable for this op. We may nack only if we can
            # PROVE we never prepared it: the slot must not be a torn
            # write of it (faulty), and the header ring must not hold its
            # header (a held header with an unreadable body means we DID
            # prepare it — reference: the nack eligibility rule,
            # replica.zig:825).
            slot = self.journal.slot_for_op(msg.header.op)
            held_hdr = self.journal.headers[slot]
            prepared_it = (held_hdr is not None
                           and held_hdr.op == msg.header.op
                           and held_hdr.command == Command.prepare)
            if slot not in self.journal.faulty and not prepared_it:
                self._send_nack(msg.header.replica, msg.header.op, wanted)

    def _send_nack(self, dst: int, op: int, wanted: int) -> None:
        header = Header(
            command=Command.nack_prepare, cluster=self.cluster,
            replica=self.replica_id, view=self.view, op=op, parent=wanted)
        self.bus.send_to_replica(dst, Message(header.finalize()))

    def on_nack_prepare(self, msg: Message) -> None:
        """Count nack votes while completing a view change; truncate the
        uncommitted suffix at nack quorum (reference: replica.zig:254
        quorum_nack_prepare + docs/ARCHITECTURE.md:540-563)."""
        h = msg.header
        if (self._pending_view != self.view or self.status != "view_change"
                or h.replica >= self.replica_count
                or h.view != self.view):
            # The view guard is safety-critical: a delayed nack from an
            # earlier view-change round could count toward truncating an
            # op its sender has since acquired (and possibly committed).
            return
        op = h.op
        if op <= max(self.commit_max, self.commit_min) or op > self.op:
            return
        want = self.canonical.get(op)
        if (want.checksum if want is not None else 0) != h.parent:
            return  # nack for a stale/foreign checksum
        votes = self.nacks.setdefault(op, set())
        votes.add(h.replica)
        # Our own journal votes too, under the same eligibility rule.
        held = self.journal.read_prepare(op)
        slot = self.journal.slot_for_op(op)
        held_hdr = self.journal.headers[slot]
        prepared_it = (held_hdr is not None and held_hdr.op == op
                       and held_hdr.command == Command.prepare)
        if held is not None:
            if want is not None and held.header.checksum != want.checksum:
                votes.add(self.replica_id)
        elif slot not in self.journal.faulty and not prepared_it:
            votes.add(self.replica_id)
        if len(votes) < self.quorum_nack:
            return
        # Proven uncommitted: truncate op and the suffix that chains
        # through it, then finalize the view.
        for o in range(op, self.op + 1):
            self.canonical.pop(o, None)
            self.repair_requested.pop(o, None)
            self.chain_suspect.discard(o)
            self.nacks.pop(o, None)
        self.op = op - 1
        self._try_start_view()

    # ---------------------------------------------------------- state sync
    #
    # A replica that fell behind the cluster's WAL coverage jumps to a
    # peer's checkpoint: it receives the checkpoint root blob (`headers`
    # message), fetches every grid block the root reaches
    # (`request_blocks`/`block` — reachability = the root's free-set
    # complement), installs the blocks + root + superblock, and reopens its
    # forest from them. Block integrity is validated transitively on open
    # (every read checks the parent-held checksum), so a corrupted transfer
    # aborts the install and the sync retries.

    def _send_sync_offer(self, dst: int) -> None:
        sb = self.superblock
        root = self.storage.read(
            "snapshot", sb.snapshot_slot * self.storage.layout.snapshot_size_max,
            sb.snapshot_size)
        header = Header(
            command=Command.headers, cluster=self.cluster,
            replica=self.replica_id, view=self.view, op=sb.op_checkpoint,
            commit=self.commit_max, context=sb.checkpoint_id,
            # The release that CHECKPOINTED this root (not our binary's):
            # the receiver must gate on it and stamp it at install.
            release=sb.release)
        self.bus.send_to_replica(dst, Message(header.finalize(root), body=root))

    def on_sync_offer(self, msg: Message) -> None:
        from . import durable as durable_mod

        h = msg.header
        if self.rebuilding and not self._rebuild_heard:
            # Freeze the rebuild goal at first contact: the offering
            # peer's commit_max is a finite catch-up target even under
            # live traffic (the replica keeps following afterwards; the
            # goal only gates when the rebuild may DECLARE completion).
            self._rebuild_heard = True
            self._rebuild_goal = max(h.commit, h.op)
        if h.op <= self.commit_min:
            return  # not ahead of us
        if not self.releases.openable(h.release):
            # A checkpoint from a release this binary can't run (rolling
            # upgrade: we're the lagging binary). Installing it would run
            # new-format data under an old binary — wait for the operator
            # upgrade instead; consensus keeps us in view as a follower.
            return
        if self.syncing is not None and self.syncing["target_op"] >= h.op:
            return  # already syncing to an equal-or-newer target
        try:
            root_forest, _ = _split_root(msg.body)
            manifest_addr, manifest_size = \
                durable_mod.checkpoint_manifest(root_forest)
        except Exception:
            return  # malformed offer
        # A fresh (or retargeted) sync is one phase span, offer→install.
        self.tracer.end(Event.state_sync)
        self.tracer.begin(Event.state_sync, target_op=h.op)
        self.syncing = {
            "target_op": h.op, "root": msg.body, "source": h.replica,
            "commit_max": h.commit, "release": h.release,
            # block index -> full zone-stride bytes (validated)
            "have": {},
            # block index -> (kind, address, size, key_size) to fetch
            "needed": {},
            # manifest chain payloads, head-first (chain fetch is
            # sequential: each block names its successor)
            "manifest_parts": [],
            "last_request": 0,
        }
        # Delta sync: expand the checkpoint's reachability graph from the
        # manifest down, reusing every LOCAL block whose bytes already
        # match its address checksum (copy-on-write checkpoints share most
        # blocks, so a slightly-lagging replica transfers only the delta).
        self._sync_resolve("manifest", manifest_addr, manifest_size, 0)
        self._sync_request_blocks(self.time.monotonic())

    def _sync_resolve(self, kind: str, address, size: int,
                      key_size: int) -> None:
        from .checksum import checksum as _checksum

        sync = self.syncing
        index = address.index
        if index in sync["have"] or index in sync["needed"]:
            return
        block_size = self.storage.layout.grid_block_size
        if size <= block_size and index < self.storage.layout.grid_block_count:
            local = self.storage.read("grid", index * block_size, block_size)
            if _checksum(local[:size], domain=b"blk") == address.checksum:
                sync["have"][index] = local
                self._sync_expand(kind, local[:size], key_size)
                return
        sync["needed"][index] = (kind, address, size, key_size)

    def _sync_expand(self, kind: str, raw: bytes, key_size: int) -> None:
        from ..lsm.forest import chain_next, chain_payload
        from . import durable as durable_mod

        if kind == "manifest":
            sync = self.syncing
            sync["manifest_parts"].append(chain_payload(raw))
            nxt = chain_next(raw)
            if nxt is not None:
                self._sync_resolve("manifest", nxt[0], nxt[1], 0)
            else:
                full = b"".join(sync["manifest_parts"])
                for _name, child_key_size, info in \
                        durable_mod.manifest_children(full):
                    self._sync_resolve("index", info.index_address,
                                       info.index_size, child_key_size)
        elif kind == "index":
            for addr, size in durable_mod.index_children(raw, key_size):
                self._sync_resolve("value", addr, size, key_size)
        # "value": leaf — nothing beneath.

    def _sync_request_blocks(self, now: int) -> None:
        sync = self.syncing
        if sync is None:
            return
        if not sync["needed"]:
            self._sync_install()
            return
        if now - sync["last_request"] < self.options.repair_interval_ns:
            return
        sync["last_request"] = now
        missing = sorted(sync["needed"])[:64]
        body = b"".join(struct.pack("<Q", i) for i in missing)
        header = Header(
            command=Command.request_blocks, cluster=self.cluster,
            replica=self.replica_id, view=self.view, op=sync["target_op"])
        self.bus.send_to_replica(sync["source"],
                                 Message(header.finalize(body), body=body))

    def on_request_blocks(self, msg: Message) -> None:
        block_size = self.storage.layout.grid_block_size
        for off in range(0, len(msg.body), 8):
            (index,) = struct.unpack_from("<Q", msg.body, off)
            if index >= self.storage.layout.grid_block_count:
                continue
            raw = self.storage.read("grid", index * block_size, block_size)
            header = Header(
                command=Command.block, cluster=self.cluster,
                replica=self.replica_id, view=self.view, op=index)
            self.bus.send_to_replica(msg.header.replica,
                                     Message(header.finalize(raw), body=raw))

    def on_block(self, msg: Message) -> None:
        from .checksum import checksum as _checksum

        index = msg.header.op
        sync = self.syncing
        if sync is not None and index in sync["needed"]:
            kind, address, size, key_size = sync["needed"][index]
            # Per-block validation against the parent-held checksum — a
            # corrupt transfer is re-requested, never staged.
            if _checksum(msg.body[:size], domain=b"blk") != address.checksum:
                return
            del sync["needed"][index]
            sync["have"][index] = msg.body
            self._sync_expand(kind, msg.body[:size], key_size)
            if not sync["needed"]:
                self._sync_install()
            return
        # Scrub repair: a peer-provided copy of a corrupt block; install it
        # only if it satisfies the referring structure's checksum.
        fault = self.block_repair.get(index)
        if fault is not None:
            _, address, size = fault
            block_size = self.storage.layout.grid_block_size
            with self.tracer.span(Event.grid_repair_block):
                original = self.storage.read(
                    "grid", index * block_size, block_size)
                self.storage.write("grid", index * block_size, msg.body)
                try:
                    # Validate the repaired MEDIA bytes, not a cache.
                    self.durable.grid.read_block(address, size,
                                                 bypass_cache=True)
                except IOError:
                    self.storage.write(
                        "grid", index * block_size, original)
                    return
                del self.block_repair[index]
                self.scrubber.faults.pop(index, None)

    def _sync_install(self) -> None:
        from .durable import validate_staged_checkpoint

        sync = self.syncing
        block_size = self.storage.layout.grid_block_size
        try:
            # Validate the ENTIRE staged checkpoint before touching the live
            # grid: a bad transfer must not clobber our current (still
            # recoverable) checkpoint.
            root = sync["root"]
            forest_root, sessions_blob = _split_root(root)
            validate_staged_checkpoint(
                sync["have"], self.storage.layout, forest_root)
        except Exception:
            # Corrupted transfer or bad offer: drop and re-request later.
            self.syncing = None
            self.tracer.end(Event.state_sync)
            return
        sb = self.superblock
        # Staged install: persist the sync-progress record BEFORE the
        # first grid write. The incoming blocks may land on indices the
        # current checkpoint still references, so a crash mid-install
        # leaves a grid that belongs to NEITHER checkpoint — the nonzero
        # sync_op makes a normal open refuse the file (rebuild-only),
        # and the final store below clears it in the same flip that
        # adopts the installed checkpoint (atomic via the copy quorum).
        sb.sync_op = sync["target_op"]
        sb.store(self.storage)
        for index, raw in sorted(sync["have"].items()):
            self.storage.write("grid", index * block_size, raw)
        slot = 1 - sb.snapshot_slot
        self.storage.write(
            "snapshot", slot * self.storage.layout.snapshot_size_max, root)
        durable = DurableState(self.storage)
        state = durable.open(forest_root, load_events=False)
        self.sessions.restore(sessions_blob)
        self.durable = durable
        self.durable.grid.on_corrupt = self._note_missing_block
        self.scrubber = GridScrubber(
            self.durable.forest,
            origin_seed=self.replica_id * 2654435761, tracer=self.tracer)
        self.block_repair.clear()
        self.state_machine = self.state_machine_factory()
        self.state_machine.state = state
        self.state_machine.attach_durable(self.durable)
        sb.snapshot_slot = slot
        sb.snapshot_size = len(root)
        sb.snapshot_checksum = checksum(root, domain=b"ckptroot")
        sb.op_checkpoint = sync["target_op"]
        sb.commit_min = sync["target_op"]
        sb.commit_max = max(sb.commit_max, sync["commit_max"])
        # Stamp the release that checkpointed the synced root: a restart
        # must gate on the DATA's release, not on whatever we last wrote
        # (downgrade refusal would otherwise be bypassed for synced state).
        sb.release = sync["release"]
        sb.view = self.view
        sb.log_view = self.log_view
        sb.sync_op = 0  # install complete: clear the staged record
        sb.store(self.storage)
        if self.rebuilding:
            self._rebuild_synced = True
            self._rebuild_certified = False  # re-certify the new grid
        self.commit_min = sync["target_op"]
        self.commit_max = max(self.commit_max, sync["commit_max"])
        self.op = max(self.op, sync["target_op"])
        self.prepare_timestamp = max(
            self.prepare_timestamp,
            self.state_machine.state.commit_timestamp)
        for op in [o for o in self.repair_requested if o <= self.commit_min]:
            del self.repair_requested[op]
        self.syncing = None
        self.tracer.end(Event.state_sync)

    # --------------------------------------------------------- reply repair

    def _request_reply_repair(self, client: int) -> None:
        """Ask peers for the durable reply bytes we lack (reference:
        client_replies repair via request_reply / reply)."""
        entry = self.sessions.get(client)
        if entry is None or entry["reply"] is not None:
            return
        header = Header(
            command=Command.request_reply, cluster=self.cluster,
            replica=self.replica_id, view=self.view, client=client,
            context=entry["reply_checksum"])
        msg = Message(header.finalize())
        for r in range(self.peer_count):
            if r != self.replica_id:
                self.bus.send_to_replica(r, msg)

    def on_request_reply(self, msg: Message) -> None:
        entry = self.sessions.get(msg.header.client)
        if entry is None or entry["reply"] is None:
            return
        if entry["reply_checksum"] != msg.header.context:
            return  # we hold a different (older/newer) reply
        self.bus.send_to_replica(msg.header.replica, entry["reply"])

    def on_reply(self, msg: Message) -> None:
        """A peer answered our request_reply (replicas otherwise never
        receive reply messages)."""
        self.sessions.repair_reply(msg.header.client, msg)

    def _note_missing_block(self, address, size: int) -> None:
        """Grid read-path corruption callback: queue the block for peer
        repair (byte-identical grids make any peer a donor)."""
        self.block_repair[address.index] = ("read", address, size)

    def _repair(self, now: int) -> None:
        if now - self.last_repair_tick < self.options.repair_interval_ns:
            return
        self.last_repair_tick = now
        # Re-derive gaps below commit_max — INCLUDING ops beyond our own
        # log end: they are known-committed, and nothing else pulls them if
        # the original prepares were all lost (no retransmit path exists
        # once the primary's pipeline entry commits). Bounded by the WAL
        # window; older ops resolve via state sync.
        # slot_count - 1: op commit_min+slot_count would share a WAL slot
        # with op commit_min, clobbering the chain anchor the commit-time
        # tripwire validates against.
        repair_hi = min(self.commit_max,
                        self.commit_min + self.storage.layout.slot_count - 1)
        for op in range(self.commit_min + 1, repair_hi + 1):
            if self.journal.read_prepare(op) is None:
                self.repair_requested.setdefault(op, 0)
        for op in [o for o in self.canonical if o <= self.commit_min]:
            del self.canonical[op]
        # Primary: resend the oldest unacked prepare (reference
        # prepare_timeout, replica.zig:3567+ timeout battery).
        if self.is_primary:
            entry = self.pipeline.get(self.commit_min + 1)
            if entry is not None and now - entry.get("sent_at", 0) >= \
                    self.options.repair_interval_ns:
                entry["sent_at"] = now
                for r in range(self.replica_count):
                    if r != self.replica_id and r not in entry["oks"]:
                        self.bus.send_to_replica(r, entry["message"])
        for op, last in list(self.repair_requested.items()):
            held = self.journal.read_prepare(op)
            want_hdr = self.canonical.get(op)
            want = None if want_hdr is None else want_hdr.checksum
            below_floor = want is None and op < self.sync_floor
            # A chain suspicion is moot once the held prepare matches a
            # canonical header (the view-change quorum's truth needs no
            # chain proof) — without this, an already-correct suspect is
            # re-requested forever and starves the repair budget.
            if (op in self.chain_suspect and want is not None
                    and held is not None and held.header.checksum == want):
                self.chain_suspect.discard(op)
            # Forward-chain confirmation: a suspect whose SUCCESSOR is
            # trusted (canonical-matched or unsuspected) and whose
            # successor's parent pins our checksum is the true prepare —
            # this zips a quarantined rollback range down from the
            # canonical suffix one op per pass.
            if op in self.chain_suspect and held is not None:
                nxt = self.journal.read_prepare(op + 1)
                nxt_want = self.canonical.get(op + 1)
                nxt_trusted = nxt is not None and (
                    (nxt_want is not None
                     and nxt.header.checksum == nxt_want.checksum)
                    or (nxt_want is None
                        and (op + 1) not in self.chain_suspect))
                if nxt_trusted and nxt.header.parent == held.header.checksum:
                    # ...but NOT when the committed predecessor contradicts
                    # it: op+1 vouching for op while op-1 (executed)
                    # refuses it is a FORK between our executed prefix and
                    # the forward-chained suffix — without canonical truth
                    # neither side is provably right, so the suspicion
                    # persists and the resend/escalation path (stalled
                    # repair -> request_start_view) resolves it.
                    prev_ok = True
                    if op == self.commit_min + 1:
                        prev_c = self._prepare_checksum(self.commit_min)
                        prev_ok = (not prev_c
                                   or held.header.parent == prev_c)
                    if prev_ok:
                        self.chain_suspect.discard(op)
            satisfied = held is not None and (
                want is None or held.header.checksum == want) and \
                op not in self.chain_suspect and not below_floor
            if op <= self.commit_min or satisfied:
                del self.repair_requested[op]
                if op <= self.commit_min:
                    # Attempts stay sticky for merely-"satisfied" ops: a
                    # fork can ping-pong between forward confirmation and
                    # the backward tripwire (each neighbor vouching
                    # differently), and only an accumulating count ever
                    # reaches the start_view escalation that resolves it.
                    self._repair_attempts.pop(op, None)
                self.chain_suspect.discard(op)
                continue
            if now - last < self.options.repair_interval_ns:
                continue
            if not self.repair_budget.spend(now):
                break  # rate limit: repair must not starve the normal path
            self.repair_requested[op] = now
            attempts = self._repair_attempts.get(op, 0) + 1
            self._repair_attempts[op] = attempts
            if (attempts % 8 == 0 and self.status == "normal"
                    and not self.is_primary and want is None
                    and now - self._rsv_last
                    >= 8 * self.options.repair_interval_ns):
                # Repair is stalling without a canonical anchor: a stale
                # multi-op suffix (a deposed primary's prepares under ops
                # the cluster later committed differently) cannot be
                # replaced one-by-one, because each replacement's
                # hash-chain validation needs a true NEIGHBOR. Re-solicit
                # the CURRENT view's start_view: its canonical suffix pins
                # the checksums (canonical-match acceptance needs no
                # chaining), or — if the suffix base is beyond us — routes
                # to state sync via the sync-floor path.
                self._rsv_last = now
                self._request_start_view(self.view)
            # Below the sync floor a served prepare is untrustworthy —
            # solicit a state-sync offer instead (context=1).
            header = Header(
                command=Command.request_prepare, cluster=self.cluster,
                replica=self.replica_id, view=self.view, op=op,
                context=1 if below_floor else 0,
                parent=want or 0)  # canonical checksum (nack eligibility)
            msg = Message(header.finalize())
            for r in range(self.peer_count):
                if r != self.replica_id:
                    self.bus.send_to_replica(r, msg)
        # Rollback-recovery escalation: a quarantined op whose true
        # prepare no peer journal still holds can never zip down from the
        # canonical suffix — once it lingers past the horizon, fall back
        # to the state-sync path (peers checkpoint eventually, and their
        # checkpoint then covers us).
        horizon = 64 * self.options.repair_interval_ns
        for op, since in list(self._suspect_since.items()):
            if op <= self.commit_min or op not in self.chain_suspect:
                del self._suspect_since[op]
            elif now - since > horizon:
                self.sync_floor = max(self.sync_floor,
                                      max(self.commit_max, op) + 1)
                self.chain_suspect.discard(op)
                del self._suspect_since[op]
        self._try_start_view()  # a pending primary finalizes when complete
        self._sync_request_blocks(now)  # re-request lost sync blocks
        # Scrub repair: ask peers for fresh copies of corrupt blocks. A
        # queued address whose table was compacted away meanwhile is moot —
        # drop it rather than re-request forever.
        for index in [i for i, (_, a, _) in self.block_repair.items()
                      if not self.scrubber.still_referenced(a)]:
            del self.block_repair[index]
        if self.block_repair and self.syncing is None:
            # Batch size follows the budget: one token per 16-block
            # request, bursting up to the available tokens — the
            # post-rebuild certification can queue a whole grid's worth
            # of faults, and draining them one token per tick would
            # stretch the passive window needlessly.
            batches = min(self.repair_budget.available(now),
                          -(-len(self.block_repair) // 16))
            if batches and self.repair_budget.spend(now, batches):
                body = b"".join(
                    struct.pack("<Q", i)
                    for i in sorted(self.block_repair)[:16 * batches])
                header = Header(
                    command=Command.request_blocks, cluster=self.cluster,
                    replica=self.replica_id, view=self.view)
                msg = Message(header.finalize(body), body=body)
                for r in range(self.peer_count):
                    if r != self.replica_id:
                        self.bus.send_to_replica(r, msg)
        # Reply repair: refill missing client replies from peers.
        missing = self.sessions.missing_replies()
        if missing and now - self._reply_repair_last >= \
                4 * self.options.repair_interval_ns:
            self._reply_repair_last = now
            for client in missing[:8]:
                self._request_reply_repair(client)
        self._commit_journal(self.commit_max)

    # ---------------------------------------------------------------- time

    def on_ping(self, msg: Message) -> None:
        # Cluster-config fingerprint enforcement (reference:
        # ConfigCluster must match across the cluster, config.zig:153):
        # a peer built with different journal/message/batch geometry
        # would corrupt shared state — flag it; on_message drops all its
        # replica traffic while flagged. ONLY a MATCHING fingerprint
        # clears the flag: a fingerprint-less ping (legacy, or the
        # message bus's connection-handshake hello) is accepted but must
        # never un-gate a confirmed-mismatched peer, or every reconnect
        # would reopen the gate. The full 64-bit fingerprint rides the
        # ping's otherwise-unused u128 `context`.
        fp = msg.header.context
        if fp != 0 and fp != self._config_fp:
            self.tracer.count(Event.config_mismatch_peer, 1)
            self._config_mismatch.add(msg.header.replica)
            return
        if fp == self._config_fp:
            self._config_mismatch.discard(msg.header.replica)
        elif msg.header.replica in self._config_mismatch:
            return  # absent fingerprint: stay gated, no pong
        if msg.header.release == 0 and msg.header.timestamp == 0:
            # Bus-handshake hello (identification only): observing its
            # zero release would clobber the peer's real one, and the
            # pong echo would feed a degenerate (timestamp=0) clock
            # sample back to the sender.
            return
        self.releases.observe(msg.header.replica, msg.header.release)
        pong = Header(
            command=Command.pong, cluster=self.cluster,
            replica=self.replica_id, view=self.view, release=self.release,
            timestamp=self.time.realtime(), context=msg.header.timestamp)
        self.bus.send_to_replica(msg.header.replica, Message(pong.finalize()))

    def on_pong(self, msg: Message) -> None:
        """Clock sample: context echoes our ping's monotonic tx time
        (reference: clock sampling via ping/pong, src/vsr/clock.zig)."""
        self.releases.observe(msg.header.replica, msg.header.release)
        # Only ACTIVE replicas are clock-quorum sources: a standby's
        # agreeing clock must never let a primary call itself
        # synchronized without a replica quorum (clock.zig samples the
        # replica set only; standbys follow, they don't vouch).
        if msg.header.replica < self.replica_count:
            self.clock.learn(
                msg.header.replica, msg.header.context,
                msg.header.timestamp, self.time.monotonic())

    def tick(self) -> None:
        # Reap async WAL completions first: deferred prepare_oks / the
        # primary's self-acks fire here (sans-io: the engine never calls
        # back into the replica on its own threads).
        self.journal.poll_io()
        now = self.time.monotonic()
        if now - self.last_ping_tx >= self.options.heartbeat_interval_ns * 5:
            self.last_ping_tx = now
            ping = Header(
                command=Command.ping, cluster=self.cluster,
                replica=self.replica_id, view=self.view,
                release=self.release, timestamp=now,
                context=self._config_fp)
            msg = Message(ping.finalize())
            for r in range(self.peer_count):
                if r != self.replica_id:
                    self.bus.send_to_replica(r, msg)
        if self.status == "normal" and self.is_primary:
            if now - self.last_heartbeat_tx >= self.options.heartbeat_interval_ns:
                self.last_heartbeat_tx = now
                header = Header(
                    command=Command.commit, cluster=self.cluster,
                    replica=self.replica_id, view=self.view,
                    commit=self.commit_max)
                msg = Message(header.finalize())
                for r in range(self.peer_count):
                    if r != self.replica_id:
                        self.bus.send_to_replica(r, msg)
            # Self-issued expiry pulse (reference: replica.zig:4906-4910).
            if (not self.pipeline
                    and self.state_machine.pulse_needed(self.prepare_timestamp)):
                self._primary_prepare(Operation.pulse, b"")
        elif self.status == "normal":
            # Commit-progress watchdog (reference: replica_test.zig:479
            # "partition primary-all, send-only"): a primary whose SENDS
            # arrive but who receives nothing keeps heartbeating while
            # commit stalls — heartbeats alone must not renew its lease
            # when this replica holds uncommitted prepares that stopped
            # advancing.
            if (self.commit_max > self._progress_commit
                    or self.view > self._progress_view):
                # Progress, or a fresh view: give the (new) primary a
                # full window before suspecting it — a stale timer firing
                # right after an election would depose the new primary
                # before it can re-replicate the uncommitted suffix.
                self._progress_commit = self.commit_max
                self._progress_view = self.view
                self._progress_ts = now
            elif self.op <= self.commit_max:
                self._progress_ts = now  # nothing outstanding: no stall
            elif (not self.is_standby and not self.rebuilding
                  and now - self._progress_ts
                  >= 2 * self.options.view_change_timeout_ns):
                self._progress_ts = now
                self._start_view_change(self.view + 1)
                return
            # Adaptive liveness: the EWMA fault detector may suspect the
            # primary before the hard timeout (reference fault_detector +
            # timeout battery); the hard timeout stays as the ceiling.
            deadline = min(self.options.view_change_timeout_ns,
                           max(self.fault_detector.deadline_ns(),
                               2 * self.options.heartbeat_interval_ns))
            if now - self.last_heartbeat_rx >= deadline:
                if self.is_standby or self.rebuilding:
                    # Follow the electorate: probe every active replica for
                    # the current view instead of electing (whichever is
                    # primary answers with start_view).
                    self.last_heartbeat_rx = now
                    header = Header(
                        command=Command.request_start_view,
                        cluster=self.cluster, replica=self.replica_id,
                        view=self.view)
                    probe = Message(header.finalize())
                    for r in range(self.replica_count):
                        self.bus.send_to_replica(r, probe)
                else:
                    self._start_view_change(self.view + 1)
        elif self.status == "view_change":
            if now - self.last_heartbeat_rx >= 2 * self.options.view_change_timeout_ns:
                self.last_heartbeat_rx = now
                self._start_view_change(self.view + 1)
        if self.rebuilding:
            self._rebuild_tick(now)
        self._repair(now)
        # Background scrub: a few grid block validations per phase window
        # (reference: grid_scrubber.zig incremental tour); faults queue for
        # peer repair (grids are byte-identical across replicas).
        self._scrub_phase += 1
        if self._scrub_phase % 64 == 0:
            for name, address, size in self.scrubber.tick():
                self.block_repair[address.index] = (name, address, size)


def _split_root(root: bytes) -> tuple[bytes, bytes]:
    """Checkpoint root blob -> (forest root, sessions blob). Layout:
    forest-root || sessions-blob || u32 sessions length."""
    (slen,) = struct.unpack_from("<I", root, len(root) - 4)
    return root[:len(root) - 4 - slen], root[len(root) - 4 - slen:len(root) - 4]


def _reply_fits(operation: Operation, body_len: int,
                message_size_max: int) -> bool:
    """Admission bound: the worst-case reply for `body_len` request bytes
    must fit one message (and so the durable reply slot) — lookups amplify
    16-byte ids into 128-byte records (reference: batch_max accounts for
    both directions, src/state_machine.zig:336-380)."""
    from ..state_machine import OPERATION_SPECS

    spec = OPERATION_SPECS.get(operation)
    if spec is None or spec.event_size == 0 or \
            spec.result_size <= spec.event_size:
        return True
    worst = (body_len // spec.event_size) * spec.result_size
    return HEADER_SIZE + worst + body_len <= message_size_max


def _event_count(operation: Operation, body: bytes) -> int:
    """Number of logical events in a request body (drives timestamp
    assignment: each event gets a distinct timestamp below the prepare's)."""
    from .. import multi_batch
    from ..constants import BATCH_MAX
    from ..state_machine import OPERATION_SPECS

    if operation == Operation.pulse:
        # An expiry pulse may emit up to a full batch of expiry events, each
        # needing a distinct timestamp below the prepare's.
        return BATCH_MAX
    spec = OPERATION_SPECS.get(operation)
    if spec is None or spec.event_size == 0:
        return 1
    if operation.is_multi_batch():
        try:
            batches = multi_batch.decode(body, spec.event_size)
        except ValueError:
            return 1
        return max(1, sum(len(b) // spec.event_size for b in batches))
    return max(1, len(body) // spec.event_size)


def _unpack_headers(body: bytes) -> list[Header]:
    out = []
    for off in range(0, len(body), HEADER_SIZE):
        h = Header.unpack(body[off:off + HEADER_SIZE])
        if h.valid_checksum():
            out.append(h)
    return out
