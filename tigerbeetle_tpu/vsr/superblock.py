"""SuperBlock: the root of all durable state, quorum-replicated in-file.

reference: src/vsr/superblock.zig:53-120 + quorum picking in
src/vsr/superblock_quorums.zig. Four physical copies are written on every
update (sequence number bumped); startup reads all four and adopts the
highest sequence present in at least `read_quorum` identical valid copies.
A crash mid-update leaves a mix of old/new copies — the quorum rule makes
the flip atomic.

The superblock here references the current checkpoint snapshot (A/B slot,
size, checksum) and persists the VSR state the protocol must not forget
(view, log_view, commit_min/max, checkpoint id chain).

`sync_op` is the staged-install record (reference: the superblock's
vsr_state.sync_op_min/max brackets a state sync the same way): it is
persisted BEFORE a state-sync install writes its first grid block and
cleared in the same store that flips to the installed checkpoint. A
nonzero sync_op therefore proves the data file is mid-install — grid
bytes may be half-written — and a normal open must refuse it (recover
--from-cluster restarts the rebuild cleanly instead).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

from .checksum import checksum
from .storage import SUPERBLOCK_COPIES, SUPERBLOCK_COPY_SIZE, Storage

READ_QUORUM = 2  # of 4 copies (tolerates one torn write + one latent fault)

_FMT = struct.Struct("<16sQQQQQQQQQQQIIQ16s")


@dataclasses.dataclass
class SuperBlock:
    cluster: int = 0
    replica_id: int = 0
    replica_count: int = 1
    sequence: int = 0
    view: int = 0
    log_view: int = 0
    commit_min: int = 0  # checkpointed op (state snapshot covers <= this)
    commit_max: int = 0
    op_checkpoint: int = 0
    checkpoint_id: int = 0  # hash-chained across checkpoints
    # Staged-install record: target op of an in-progress state-sync
    # install (0 = none). Nonzero across a restart means the install was
    # torn — the grid is suspect and a normal open must refuse.
    sync_op: int = 0
    snapshot_slot: int = 0  # 0 or 1 (A/B)
    release: int = 0  # release that wrote this checkpoint (multiversion)
    snapshot_size: int = 0
    snapshot_checksum: int = 0

    def pack_copy(self) -> bytes:
        body = _FMT.pack(
            b"\x00" * 16,
            self.cluster, self.replica_id, self.replica_count,
            self.sequence, self.view, self.log_view,
            self.commit_min, self.commit_max, self.op_checkpoint,
            self.checkpoint_id & ((1 << 64) - 1),
            self.sync_op,
            self.snapshot_slot, self.release,
            self.snapshot_size,
            self.snapshot_checksum.to_bytes(16, "little"),
        )
        csum = checksum(body[16:], domain=b"sb")
        raw = csum.to_bytes(16, "little") + body[16:]
        return raw.ljust(SUPERBLOCK_COPY_SIZE, b"\x00")

    @classmethod
    def unpack_copy(cls, raw: bytes) -> Optional["SuperBlock"]:
        try:
            f = _FMT.unpack(raw[:_FMT.size])
        except struct.error:
            return None
        csum = int.from_bytes(raw[:16], "little")
        if csum != checksum(raw[16:_FMT.size], domain=b"sb"):
            return None
        return cls(
            cluster=f[1], replica_id=f[2], replica_count=f[3],
            sequence=f[4], view=f[5], log_view=f[6],
            commit_min=f[7], commit_max=f[8], op_checkpoint=f[9],
            checkpoint_id=f[10], sync_op=f[11],
            snapshot_slot=f[12], release=f[13], snapshot_size=f[14],
            snapshot_checksum=int.from_bytes(f[15], "little"),
        )

    # ----------------------------------------------------------------- io

    def store(self, storage: Storage) -> None:
        """Bump sequence and write all copies (atomic via quorum rule)."""
        self.sequence += 1
        raw = self.pack_copy()
        for copy in range(SUPERBLOCK_COPIES):
            storage.write("superblock", copy * SUPERBLOCK_COPY_SIZE, raw)
        storage.sync()

    @classmethod
    def load(cls, storage: Storage) -> Optional["SuperBlock"]:
        """Quorum-pick across the copies (reference:
        src/vsr/superblock_quorums.zig working-quorum selection)."""
        copies: list[SuperBlock] = []
        for copy in range(SUPERBLOCK_COPIES):
            raw = storage.read(
                "superblock", copy * SUPERBLOCK_COPY_SIZE, SUPERBLOCK_COPY_SIZE)
            sb = cls.unpack_copy(raw)
            if sb is not None:
                copies.append(sb)
        if not copies:
            return None
        by_seq: dict[int, list[SuperBlock]] = {}
        for sb in copies:
            by_seq.setdefault(sb.sequence, []).append(sb)
        for seq in sorted(by_seq, reverse=True):
            group = by_seq[seq]
            if len(group) >= READ_QUORUM:
                first = group[0]
                assert all(g == first for g in group[1:])
                return dataclasses.replace(first)
        return None
