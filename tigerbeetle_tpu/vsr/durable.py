"""DurableState: the LSM forest under the replica.

The incremental-checkpoint engine (replacing the round-1 whole-state
snapshots): state-machine objects are written through to LSM trees sharing
one copy-on-write grid in the data file's grid zone, compaction is paced
deterministically by op number, and a checkpoint serializes only manifests
plus the free set into one small root blob the superblock references.

reference mapping:
  grooves / object trees        src/lsm/groove.zig, forest.zig  -> Forest
  grid zone (CoW blocks)        src/vsr/grid.zig                -> lsm/grid.py
  checkpoint trailer (free set) src/vsr/checkpoint_trailer.zig  -> root blob
  write-through after commit    groove insert/update at commit

Determinism contract (load-bearing, like the reference's physical
determinism, docs/ARCHITECTURE.md:281-307): given an identical committed op
sequence, every replica produces byte-identical grid zones. Achieved by
(a) sorted dirty-set flush order, (b) op-derived compaction pacing, and
(c) deterministic grid allocation (cursor scan, reset at checkpoint).
"""

from __future__ import annotations

import struct
from typing import Optional

from ..lsm.forest import Forest
from ..lsm.grid import Grid
from ..lsm.scan import composite_key
from ..oracle.state_machine import AccountEventRecord, StateMachineOracle
from ..types import (Account, AccountFlags, Transfer, TransferFlags,
                     TransferPendingStatus)
from .storage import Storage

# Fixed-size AccountEventRecord row (reference: 256-byte AccountEvent,
# src/state_machine.zig:104-220; ours carries both full account snapshots).
_EVENT_SIZE = 8 + 2 + 1 + 1 + 128 + 128 + 16 + 16 + 128

SCHEMA = {
    "accounts": (16, 128),
    "transfers": (16, 128),
    "pending": (8, 1),
    "expiry": (8, 8),
    "orphaned": (16, 1),
    "events": (8, _EVENT_SIZE),
    # Secondary indexes (reference: the groove index trees,
    # src/state_machine.zig:45-90 — accounts: 9 trees, transfers: 14):
    # composite key = field prefix || timestamp (composite_key.zig);
    # timestamp trees map ts -> id for the object lookup hop
    # (scan_lookup.zig).
    "acct_by_ts": (8, 16),
    "acct_by_ud128": (24, 1),
    "acct_by_ud64": (16, 1),
    "acct_by_ud32": (12, 1),
    "acct_by_ledger": (12, 1),
    "acct_by_code": (10, 1),
    "xfer_by_ts": (8, 16),
    "xfer_by_dr": (24, 1),
    "xfer_by_cr": (24, 1),
    "xfer_by_pid": (24, 1),
    "xfer_by_ud128": (24, 1),
    "xfer_by_ud64": (16, 1),
    "xfer_by_ud32": (12, 1),
    "xfer_by_ledger": (12, 1),
    "xfer_by_code": (10, 1),
    # Flag indexes (reference: tree_ids 23-26 — presence-keyed; `closed`
    # and `closing` are the only mutable indexed attributes, maintained
    # put/remove on every dirty flush, which is deterministic and
    # idempotent across replicas):
    "acct_by_imported": (9, 1),
    "acct_by_closed": (9, 1),
    "xfer_by_amount": (24, 1),
    "xfer_by_imported": (9, 1),
    "xfer_by_closing": (9, 1),
    # account_events secondary trees (reference: tree_ids 27-33,
    # src/state_machine.zig:525-605 — account_timestamp put per
    # history-flagged side in account_event() :4452-4466; *_expired only
    # for expiry rows; prunable when neither side keeps history):
    "ev_by_acct_ts": (16, 1),
    "ev_by_pstat": (9, 1),
    "ev_by_dr_expired": (24, 1),
    "ev_by_cr_expired": (24, 1),
    "ev_by_pid_expired": (24, 1),
    "ev_by_ledger_expired": (12, 1),
    "ev_by_prunable": (8, 1),
}

_META_SIZE = 40  # scalars appended to the checkpoint root blob

_NO_PENDING = b"\x00" * 128
_FLAGS_NONE = 0xFFFF  # transfer_flags=None sentinel (expiry events)


def _k8(x: int) -> bytes:
    return x.to_bytes(8, "big")  # big-endian: lexicographic == numeric


def _k16(x: int) -> bytes:
    return x.to_bytes(16, "big")


def _pack_event(rec: AccountEventRecord) -> bytes:
    flags = _FLAGS_NONE if rec.transfer_flags is None else rec.transfer_flags
    return (struct.pack(
        "<QHBB", rec.timestamp, flags, int(rec.transfer_pending_status),
        1 if rec.transfer_pending is not None else 0)
        + rec.dr_account.pack() + rec.cr_account.pack()
        + rec.amount_requested.to_bytes(16, "little")
        + rec.amount.to_bytes(16, "little")
        + (rec.transfer_pending.pack() if rec.transfer_pending is not None
           else _NO_PENDING))


def _unpack_event(raw: bytes) -> AccountEventRecord:
    ts, flags, pstat, has_p = struct.unpack_from("<QHBB", raw)
    pos = 12
    dr = Account.unpack(raw[pos:pos + 128]); pos += 128
    cr = Account.unpack(raw[pos:pos + 128]); pos += 128
    amount_requested = int.from_bytes(raw[pos:pos + 16], "little"); pos += 16
    amount = int.from_bytes(raw[pos:pos + 16], "little"); pos += 16
    pending = Transfer.unpack(raw[pos:pos + 128]) if has_p else None
    return AccountEventRecord(
        timestamp=ts, dr_account=dr, cr_account=cr,
        transfer_flags=None if flags == _FLAGS_NONE else flags,
        transfer_pending_status=TransferPendingStatus(pstat),
        transfer_pending=pending,
        amount_requested=amount_requested, amount=amount)


def mirror_quiescent(state, events_persisted: int) -> bool:
    """True when the host mirror holds nothing the durable flush would
    have to serialize object-side: no dirty stores and every mirror
    event already persisted. The ONE predicate behind (a) the column
    flush contract, (b) the replica's drain-before-flush decision, and
    (c) commit-window formation — they must agree or the window path's
    per-op flush cadence silently diverges."""
    return not (
        state.accounts.dirty or state.transfers.dirty
        or state.pending_status.dirty or state.expiry.dirty
        or state.orphaned.dirty
        or events_persisted < (state.events_base
                               + len(state.account_events)))


def checkpoint_manifest(root_with_meta: bytes):
    """(manifest BlockAddress, manifest size) of a checkpoint root."""
    from ..lsm.grid import ADDRESS_SIZE, BlockAddress

    address = BlockAddress.unpack(root_with_meta[:ADDRESS_SIZE])
    (size,) = struct.unpack_from("<I", root_with_meta, ADDRESS_SIZE)
    return address, size


def manifest_children(manifest_raw: bytes) -> list:
    """(tree name, key_size, TableInfo) per table referenced by a forest
    manifest blob — the first expansion step of a checkpoint's block
    reachability graph (used by delta state sync)."""
    from ..lsm.table import TableInfo

    out = []
    (count,) = struct.unpack_from("<I", manifest_raw)
    pos = 4
    for _ in range(count):
        name_len, size = struct.unpack_from("<HI", manifest_raw, pos)
        pos += 6
        name = manifest_raw[pos:pos + name_len].decode()
        pos += name_len
        raw = manifest_raw[pos:pos + size]
        pos += size
        key_size = SCHEMA[name][0]
        # Tree blob: u64 beat, u8 level count (lsm.tree manifest_pack);
        # per level: u64 next_seq, u32 entry count.
        (n_levels,) = struct.unpack_from("<B", raw, 8)
        tpos = 9
        for _ in range(n_levels):
            (n_tables,) = struct.unpack_from("<I", raw, tpos + 8)
            tpos += 12
            for _ in range(n_tables):
                # Each entry: snapshot range + seq (3x u64,
                # lsm.manifest_level) then the TableInfo. History entries
                # (removed, unpruned) are reachable too — their blocks
                # stay allocated until the retention bar elapses.
                tpos += 24
                info, tpos = TableInfo.unpack(raw, tpos)
                out.append((name, key_size, info))
    return out


def index_children(index_raw: bytes, key_size: int) -> list:
    """(BlockAddress, size) of every value block an index block references
    (mirrors lsm.table.Table.__init__'s parse)."""
    from ..lsm.grid import ADDRESS_SIZE, BlockAddress
    from ..lsm.schema import BlockKind, unwrap

    index_raw = unwrap(index_raw, BlockKind.index)
    (count,) = struct.unpack_from("<I", index_raw)
    out = []
    pos = 4
    for _ in range(count):
        addr = BlockAddress.unpack(index_raw[pos:pos + ADDRESS_SIZE])
        pos += ADDRESS_SIZE
        (size,) = struct.unpack_from("<I", index_raw, pos)
        pos += 4 + key_size
        out.append((addr, size))
    return out


def allocated_blocks(root_with_meta: bytes) -> list[int]:
    """Grid block indices a checkpoint root reaches (the complement of its
    free set) — the exact transfer set for state sync."""
    from .. import ewah
    from ..lsm.grid import ADDRESS_SIZE

    root = root_with_meta[:-_META_SIZE]
    (free_size,) = struct.unpack_from("<I", root, ADDRESS_SIZE + 4)
    free_blob = root[ADDRESS_SIZE + 8:ADDRESS_SIZE + 8 + free_size]
    bits = ewah.decode_bitset(free_blob)
    return [i for i, free in enumerate(bits) if not free]


class _DictDevice:
    """Read-only staging device over a {block index: raw bytes} dict — used
    to validate state-synced blocks BEFORE they touch the live grid zone."""

    def __init__(self, blocks: dict, block_size: int):
        self.blocks = blocks
        self.block_size = block_size

    def read(self, off: int, size: int) -> bytes:
        idx, within = divmod(off, self.block_size)
        raw = self.blocks.get(idx, b"").ljust(self.block_size, b"\x00")
        return raw[within:within + size]

    def write(self, off: int, data: bytes) -> None:
        raise RuntimeError("staging device is read-only")


def validate_staged_checkpoint(blocks: dict, layout,
                               root_forest: bytes) -> StateMachineOracle:
    """Open a checkpoint root entirely from staged blocks; every read
    validates its parent-held checksum, so success proves the transfer is
    complete and uncorrupted. Raises on any fault — the caller must not
    have written anything to the live grid yet."""
    staged = DurableState.__new__(DurableState)
    staged.grid = Grid(
        _DictDevice(blocks, layout.grid_block_size),
        block_size=layout.grid_block_size,
        block_count=layout.grid_block_count)
    staged.forest = Forest(staged.grid, SCHEMA)
    staged.events_persisted = 0
    staged._indexed_accounts = set()
    staged._closed_indexed = set()
    return staged.open(root_forest)


class _ZoneDevice:
    """Adapter: a storage zone as the grid's flat byte device."""

    def __init__(self, storage: Storage, zone: str):
        self.storage = storage
        self.zone = zone

    def read(self, off: int, size: int) -> bytes:
        return self.storage.read(self.zone, off, size)

    def read_batch(self, reqs: list) -> list:
        return self.storage.read_batch(self.zone, reqs)

    def read_submit(self, reqs: list):
        return self.storage.read_submit(self.zone, reqs)

    def read_fetch(self, token, size: int) -> bytes:
        return self.storage.read_fetch(token, size)

    def write(self, off: int, data: bytes) -> None:
        self.storage.write(self.zone, off, data)


class DurableState:
    """Write-behind LSM persistence for one replica's state machine."""

    def __init__(self, storage: Storage):
        layout = storage.layout
        self.grid = Grid(
            _ZoneDevice(storage, "grid"),
            block_size=layout.grid_block_size,
            block_count=layout.grid_block_count)
        self.forest = Forest(self.grid, SCHEMA)
        self.events_persisted = 0
        # Accounts whose (immutable) index entries are already in the
        # trees: balance updates re-dirty accounts every batch, but only
        # the object row changes — index keys are written once.
        self._indexed_accounts: set[int] = set()
        # Accounts whose key is currently present in acct_by_closed —
        # the one mutable account index writes only on transitions
        # (rebuilt from the tree at open()).
        self._closed_indexed: set[int] = set()

    # ------------------------------------------------------------- writes

    def flush(self, state: StateMachineOracle, flush_columns=None):
        """Write every object mutated since the last flush into the trees
        (sorted key order: byte-deterministic across replicas). Returns
        (flushed account ids, flushed transfer ids) so the serving layer
        can write its bounded object caches through (state_machine.py
        cache_upsert).

        flush_columns: drained device-delta transfer columns
        (DeviceLedger.take_flush_columns). Transfers covered by them are
        flushed through the VECTORIZED path — values and index keys built
        in numpy passes instead of per-object int.to_bytes — and skipped
        by the object loop. Same puts, same bytes; memtable freeze sorts,
        so put order cannot affect the on-grid result."""
        trees = self.forest.trees
        vector_tids: list = []
        vector_aids: list = []
        if flush_columns:
            # Contract: the column path is only valid against a QUIESCENT
            # mirror — interleaved mirror writes (hard-regime handoffs,
            # account creations, expiries) carry ordering the two paths
            # cannot merge; the caller must drain and flush the object
            # path instead (vsr/replica.py does exactly that).
            assert mirror_quiescent(state, self.events_persisted), \
                "column flush with a dirty/unpersisted mirror: drain first"
        for (t_cols, e_cols, der_cols, n_new, abs_start,
             orphan_ids) in flush_columns or ():
            # Orphan puts are idempotent: flushed even for zero-create
            # chunks (transient failures poison ids without creating).
            for oid in orphan_ids:
                trees["orphaned"].put(_k16(oid), b"\x01")
            if n_new == 0:
                continue
            if abs_start + n_new <= self.events_persisted:
                # Stale chunk: an object-path flush (after a mirror
                # drain) already covered it — every put would be a
                # re-put of identical bytes.
                continue
            assert abs_start >= self.events_persisted, \
                "flush chunks must arrive whole and in order"
            vector_tids.extend(self._flush_transfer_columns(
                trees, t_cols, n_new))
            vector_aids.extend(self._flush_side_columns(
                trees, t_cols, e_cols, der_cols, n_new))
            self.events_persisted = abs_start + n_new
        # A dirty key absent from its dict was created then rolled back by a
        # linked-chain scope within one commit — it was never flushed, so
        # skip it (accounts/transfers/pending are never legitimately
        # removed; only expiry needs real tombstones).
        acc = state.accounts
        flushed_accounts = sorted(a for a in acc.dirty if a in acc)
        for aid in flushed_accounts:
            a = acc[aid]
            trees["accounts"].put(_k16(aid), a.pack())
            # `closed` is the one mutable indexed account attribute
            # (closing transfers set it; voiding them clears it) —
            # written only on transitions.
            closed = bool(a.flags & AccountFlags.closed)
            if closed != (aid in self._closed_indexed):
                closed_key = composite_key(1, a.timestamp, 1)
                if closed:
                    trees["acct_by_closed"].put(closed_key, b"\x01")
                    self._closed_indexed.add(aid)
                else:
                    trees["acct_by_closed"].remove(closed_key)
                    self._closed_indexed.discard(aid)
            if aid in self._indexed_accounts:
                continue  # balances changed; indexed fields immutable
            self._indexed_accounts.add(aid)
            ts = a.timestamp
            if a.flags & AccountFlags.imported:
                trees["acct_by_imported"].put(
                    composite_key(1, ts, 1), b"\x01")
            trees["acct_by_ts"].put(_k8(ts), _k16(aid))
            trees["acct_by_ud128"].put(
                composite_key(a.user_data_128, ts, 16), b"\x01")
            trees["acct_by_ud64"].put(
                composite_key(a.user_data_64, ts, 8), b"\x01")
            trees["acct_by_ud32"].put(
                composite_key(a.user_data_32, ts, 4), b"\x01")
            trees["acct_by_ledger"].put(
                composite_key(a.ledger, ts, 4), b"\x01")
            trees["acct_by_code"].put(
                composite_key(a.code, ts, 2), b"\x01")
        acc.dirty.clear()
        xfr = state.transfers
        xfr.dirty.difference_update(vector_tids)
        flushed_transfers = sorted(t for t in xfr.dirty if t in xfr)
        for tid in flushed_transfers:
            t = xfr[tid]
            ts = t.timestamp
            trees["transfers"].put(_k16(tid), t.pack())
            trees["xfer_by_ts"].put(_k8(ts), _k16(tid))
            trees["xfer_by_dr"].put(
                composite_key(t.debit_account_id, ts, 16), b"\x01")
            trees["xfer_by_cr"].put(
                composite_key(t.credit_account_id, ts, 16), b"\x01")
            if t.pending_id:
                # Zero means 'not a post/void' — never indexed
                # (reference: the pending_id tree likewise only holds
                # resolutions; ForestQuery.transfers_by_pending_id
                # reads it).
                trees["xfer_by_pid"].put(
                    composite_key(t.pending_id, ts, 16), b"\x01")
            trees["xfer_by_ud128"].put(
                composite_key(t.user_data_128, ts, 16), b"\x01")
            trees["xfer_by_ud64"].put(
                composite_key(t.user_data_64, ts, 8), b"\x01")
            trees["xfer_by_ud32"].put(
                composite_key(t.user_data_32, ts, 4), b"\x01")
            trees["xfer_by_ledger"].put(
                composite_key(t.ledger, ts, 4), b"\x01")
            trees["xfer_by_code"].put(
                composite_key(t.code, ts, 2), b"\x01")
            trees["xfer_by_amount"].put(
                composite_key(t.amount, ts, 16), b"\x01")
            if t.flags & TransferFlags.imported:
                trees["xfer_by_imported"].put(
                    composite_key(1, ts, 1), b"\x01")
            if t.flags & (TransferFlags.closing_debit
                          | TransferFlags.closing_credit):
                trees["xfer_by_closing"].put(
                    composite_key(1, ts, 1), b"\x01")
        xfr.dirty.clear()
        pend = state.pending_status
        for ts in sorted(pend.dirty):
            if ts in pend:
                trees["pending"].put(_k8(ts), bytes([int(pend[ts])]))
        pend.dirty.clear()
        exp = state.expiry
        for ts in sorted(exp.dirty):
            if ts in exp:
                trees["expiry"].put(_k8(ts), struct.pack("<Q", exp[ts]))
            else:
                trees["expiry"].remove(_k8(ts))
        exp.dirty.clear()
        orph = state.orphaned
        for oid in sorted(orph.dirty):
            trees["orphaned"].put(_k16(oid), b"\x01")
        orph.dirty.clear()
        for rec in state.account_events[self.events_persisted
                                        - state.events_base:]:
            ets = rec.timestamp
            trees["events"].put(_k8(ets), _pack_event(rec))
            if rec.dr_account.flags & AccountFlags.history:
                trees["ev_by_acct_ts"].put(
                    composite_key(rec.dr_account.timestamp, ets, 8), b"\x01")
            if rec.cr_account.flags & AccountFlags.history:
                trees["ev_by_acct_ts"].put(
                    composite_key(rec.cr_account.timestamp, ets, 8), b"\x01")
            trees["ev_by_pstat"].put(
                composite_key(int(rec.transfer_pending_status), ets, 1),
                b"\x01")
            if rec.transfer_pending_status == TransferPendingStatus.expired:
                trees["ev_by_dr_expired"].put(
                    composite_key(rec.dr_account.id, ets, 16), b"\x01")
                trees["ev_by_cr_expired"].put(
                    composite_key(rec.cr_account.id, ets, 16), b"\x01")
                trees["ev_by_pid_expired"].put(
                    composite_key(rec.transfer_pending.id, ets, 16), b"\x01")
                trees["ev_by_ledger_expired"].put(
                    composite_key(rec.dr_account.ledger, ets, 4), b"\x01")
            if not ((rec.dr_account.flags | rec.cr_account.flags)
                    & AccountFlags.history):
                trees["ev_by_prunable"].put(_k8(ets), b"\x01")
        # max(): with the drain deferred, the mirror's event list lags the
        # column watermark — never rewind it.
        self.events_persisted = max(
            self.events_persisted,
            state.events_base + len(state.account_events))
        return (flushed_accounts + vector_aids,
                flushed_transfers + vector_tids)

    def _flush_transfer_columns(self, trees, t, n: int) -> list:
        """Vectorized transfer flush from drained device columns: value
        bytes and every index key built in whole-column numpy passes; the
        per-row Python work is the memtable puts themselves. Returns the
        flushed transfer ids. Bit-identical to the object path (the wire
        codec IS the object pack format)."""
        import numpy as np

        from ..ops.batch import TRANSFER_WIRE
        from ..types import TransferFlags as TF

        # Closing and imported transfers come through the fast path now
        # (closing-native fixpoint tiers / the imported tiers), so the
        # column flush maintains their flag indexes exactly like the
        # object path does.
        flags = t["flags"][:n]
        closing_l = ((flags & np.uint32(int(TF.closing_debit
                                            | TF.closing_credit))) != 0
                     ).tolist()
        imported_l = ((flags & np.uint32(int(TF.imported))) != 0).tolist()

        rec = np.zeros(n, dtype=TRANSFER_WIRE)
        for f in ("id_lo", "id_hi", "dr_lo", "dr_hi", "cr_lo", "cr_hi",
                  "amt_lo", "amt_hi", "pid_lo", "pid_hi",
                  "ud128_lo", "ud128_hi", "ud64", "ud32", "timeout", "ts"):
            rec[f] = t[f][:n]
        rec["ledger"] = t["ledger"][:n]
        rec["code"] = t["code"][:n].astype(np.uint16)
        rec["flags"] = flags.astype(np.uint16)
        valb = rec.tobytes()

        def be(*cols):
            return np.ascontiguousarray(
                np.stack([c[:n] for c in cols], axis=1).astype(">u8")
            ).tobytes()

        ts = t["ts"]
        idb = be(t["id_hi"], t["id_lo"])                      # 16B rows
        ts8 = be(ts)                                          # 8B rows
        drk = be(t["dr_hi"], t["dr_lo"], ts)                  # 24B rows
        crk = be(t["cr_hi"], t["cr_lo"], ts)
        pidk = be(t["pid_hi"], t["pid_lo"], ts)
        ud128k = be(t["ud128_hi"], t["ud128_lo"], ts)
        amtk = be(t["amt_hi"], t["amt_lo"], ts)
        ud64k = be(t["ud64"], ts)
        ud32p = np.ascontiguousarray(t["ud32"][:n].astype(">u4")).tobytes()
        ledp = np.ascontiguousarray(t["ledger"][:n].astype(">u4")).tobytes()
        codep = np.ascontiguousarray(
            t["code"][:n].astype(np.uint16).astype(">u2")).tobytes()
        pid_live = ((t["pid_hi"][:n] != 0) | (t["pid_lo"][:n] != 0)).tolist()

        put_obj = trees["transfers"].put
        put_ts = trees["xfer_by_ts"].put
        put_dr = trees["xfer_by_dr"].put
        put_cr = trees["xfer_by_cr"].put
        put_pid = trees["xfer_by_pid"].put
        put_ud128 = trees["xfer_by_ud128"].put
        put_ud64 = trees["xfer_by_ud64"].put
        put_ud32 = trees["xfer_by_ud32"].put
        put_led = trees["xfer_by_ledger"].put
        put_code = trees["xfer_by_code"].put
        put_amt = trees["xfer_by_amount"].put
        put_closing = trees["xfer_by_closing"].put
        put_imported = trees["xfer_by_imported"].put
        ONE = b"\x01"
        tids = []
        for i in range(n):
            k16 = idb[16 * i:16 * i + 16]
            t8 = ts8[8 * i:8 * i + 8]
            tids.append(int.from_bytes(k16, "big"))
            put_obj(k16, valb[128 * i:128 * i + 128])
            put_ts(t8, k16)
            put_dr(drk[24 * i:24 * i + 24], ONE)
            put_cr(crk[24 * i:24 * i + 24], ONE)
            if pid_live[i]:
                put_pid(pidk[24 * i:24 * i + 24], ONE)
            put_ud128(ud128k[24 * i:24 * i + 24], ONE)
            put_ud64(ud64k[16 * i:16 * i + 16], ONE)
            put_ud32(ud32p[4 * i:4 * i + 4] + t8, ONE)
            put_led(ledp[4 * i:4 * i + 4] + t8, ONE)
            put_code(codep[2 * i:2 * i + 2] + t8, ONE)
            put_amt(amtk[24 * i:24 * i + 24], ONE)
            # Flag indexes (composite_key(1, ts, 1) == b"\x01" + ts_be).
            if closing_l[i]:
                put_closing(ONE + t8, ONE)
            if imported_l[i]:
                put_imported(ONE + t8, ONE)
        return tids

    def _flush_side_columns(self, trees, t, e, der, n: int) -> None:
        """Vectorized flush of one chunk's NON-transfer effects: the
        account_events rows (+ their index trees), the touched accounts'
        object rows, and the pending/expiry trees — all from device delta
        columns, so the flush does not require materializing the mirror.

        Immutable account metadata (user_data/ledger/code/timestamp) is
        spliced from the account's PREVIOUS tree value (the fast path
        never mutates it); the FLAGS word comes from the event columns,
        which carry the closing-native tiers' evolved closed bit — the
        closed-flag index transitions are maintained here exactly like
        the object path. Per-event balances come from the event columns.
        Byte-identical to the object path (oracle-exact snapshots either
        way)."""
        import numpy as np

        from ..types import AccountFlags as AF
        from ..types import TransferFlags as TF

        hist = int(AF.history)

        def le(*cols):
            return np.ascontiguousarray(
                np.stack([c[:n] for c in cols], axis=1).astype("<u8")
            ).tobytes()

        ets8 = np.ascontiguousarray(t["ts"][:n].astype(">u8")).tobytes()
        amt16 = le(e["amt_lo"], e["amt_hi"])
        areq16 = le(e["areq_lo"], e["areq_hi"])
        # Per-side account front half (id + four balances, wire LE).
        fronts = {}
        for side, idh, idl in (("dr", "dr_id_hi", "dr_id_lo"),
                               ("cr", "cr_id_hi", "cr_id_lo")):
            fronts[side] = le(
                der[idl], der[idh],
                e[f"{side}_dp_lo"], e[f"{side}_dp_hi"],
                e[f"{side}_dpos_lo"], e[f"{side}_dpos_hi"],
                e[f"{side}_cp_lo"], e[f"{side}_cp_hi"],
                e[f"{side}_cpos_lo"], e[f"{side}_cpos_hi"])
        flags2 = {
            side: np.ascontiguousarray(
                e[f"{side}_flags"][:n].astype("<u2")).tobytes()
            for side in ("dr", "cr")}
        idbe = {
            side: np.ascontiguousarray(np.stack(
                [der[f"{side}_id_hi"][:n], der[f"{side}_id_lo"][:n]],
                axis=1).astype(">u8")).tobytes()
            for side in ("dr", "cr")}
        pstat_l = e["pstat"][:n].tolist()
        p_row_l = e["p_row"][:n].tolist()
        tflags_l = e["tflags"][:n].tolist()
        side_flags_l = {side: e[f"{side}_flags"][:n].tolist()
                        for side in ("dr", "cr")}
        p_ts_l = der["p_ts"][:n].tolist()
        timeout_l = t["timeout"][:n].tolist()
        expires_l = t["expires"][:n].tolist()
        ts_l = t["ts"][:n].tolist()

        acct_tree = trees["accounts"]
        xfer_tree = trees["transfers"]
        by_ts = trees["xfer_by_ts"]
        put_ev = trees["events"].put
        put_ev_acct = trees["ev_by_acct_ts"].put
        put_ev_pstat = trees["ev_by_pstat"].put
        put_ev_prun = trees["ev_by_prunable"].put
        put_pending = trees["pending"].put
        put_expiry = trees["expiry"].put
        rm_expiry = trees["expiry"].remove
        ONE = b"\x01"
        meta_cache: dict = {}  # acct key16be -> (meta bytes, ts_be8)
        p_cache: dict = {}  # p_ts -> pending transfer value bytes
        acct_last: dict = {}  # acct key16be -> final account value bytes

        def acct_meta(k16):
            got = meta_cache.get(k16)
            if got is None:
                old = acct_tree.get(k16)
                assert old is not None, "account flushed before transfers"
                got = (old[80:118], old[120:128])
                meta_cache[k16] = got
            return got

        for i in range(n):
            pstat = pstat_l[i]
            assert 0 <= pstat <= 3, "expiry events never come from chunks"
            has_p = 1 if p_row_l[i] >= 0 else 0
            tflags = tflags_l[i]
            tflags16 = _FLAGS_NONE if tflags == 0xFFFFFFFF else tflags
            sides_bytes = {}
            for side in ("dr", "cr"):
                k16 = idbe[side][16 * i:16 * i + 16]
                meta, ts_le = acct_meta(k16)
                acct = (fronts[side][80 * i:80 * i + 80] + meta
                        + flags2[side][2 * i:2 * i + 2] + ts_le)
                sides_bytes[side] = acct
                acct_last[k16] = acct
            p_val = _NO_PENDING
            if has_p:
                pts = p_ts_l[i]
                p_val = p_cache.get(pts)
                if p_val is None:
                    ptid = by_ts.get(pts.to_bytes(8, "big"))
                    assert ptid is not None, "pending flushed before resolve"
                    p_val = xfer_tree.get(ptid)
                    p_cache[pts] = p_val
            ets = ets8[8 * i:8 * i + 8]
            put_ev(ets, struct.pack("<QHBB", ts_l[i], tflags16, pstat, has_p)
                   + sides_bytes["dr"] + sides_bytes["cr"]
                   + areq16[16 * i:16 * i + 16] + amt16[16 * i:16 * i + 16]
                   + p_val)
            dr_hist = side_flags_l["dr"][i] & hist
            cr_hist = side_flags_l["cr"][i] & hist
            if dr_hist:
                put_ev_acct(sides_bytes["dr"][120:128][::-1] + ets, ONE)
            if cr_hist:
                put_ev_acct(sides_bytes["cr"][120:128][::-1] + ets, ONE)
            if not (dr_hist or cr_hist):
                put_ev_prun(ets, ONE)
            put_ev_pstat(bytes([pstat]) + ets, ONE)
            # Pending-status + expiry effects (oracle semantics).
            if pstat == 1:
                put_pending(ets, ONE)
                if timeout_l[i]:
                    put_expiry(ets, struct.pack("<Q", expires_l[i]))
            elif pstat in (2, 3):
                pts = p_ts_l[i]
                pk8 = pts.to_bytes(8, "big")
                put_pending(pk8, bytes([pstat]))
                p_timeout = int.from_bytes(p_val[108:112], "little")
                if p_timeout:
                    rm_expiry(pk8)
        put_acct = acct_tree.put
        closed_bit = int(AF.closed)
        by_closed = trees["acct_by_closed"]
        for k16, val in acct_last.items():
            put_acct(k16, val)
            # `closed` transitions (closing-native tiers evolve it on
            # the fast path): same put/remove-on-transition contract as
            # the object flush, keyed by the account's timestamp.
            aid = int.from_bytes(k16, "big")
            closed = bool(val[118] & closed_bit)  # flags u16 LE low byte
            if closed != (aid in self._closed_indexed):
                a_ts = int.from_bytes(val[120:128], "little")
                ckey = composite_key(1, a_ts, 1)
                if closed:
                    by_closed.put(ckey, b"\x01")
                    self._closed_indexed.add(aid)
                else:
                    by_closed.remove(ckey)
                    self._closed_indexed.discard(aid)
        # The touched account ids: the caller invalidates their cache
        # entries (reads must never serve pre-chunk balances).
        return [int.from_bytes(k16, "big") for k16 in acct_last]

    def prune_events(self, before_ts: int) -> int:
        """Delete prunable (no-history) event rows older than `before_ts`
        (the CDC consumer watermark) — the cleanup job the reference's
        `prunable` index exists for (src/state_machine.zig:590-601).
        Returns the number of rows pruned. Deterministic: driven purely by
        tree contents and the argument, so replicas pruning at the same
        op produce byte-identical grids."""
        from ..lsm.scan import TreeScan

        trees = self.forest.trees
        doomed = [key for key, _ in TreeScan(
            trees["ev_by_prunable"], _k8(0), _k8(max(0, before_ts - 1)))]
        for key in doomed:
            raw = trees["events"].get(key)
            if raw is not None:  # groove delete: object + every index row
                rec = _unpack_event(raw)
                ets = rec.timestamp
                trees["ev_by_pstat"].remove(
                    composite_key(int(rec.transfer_pending_status), ets, 1))
                if (rec.transfer_pending_status
                        == TransferPendingStatus.expired):
                    trees["ev_by_dr_expired"].remove(
                        composite_key(rec.dr_account.id, ets, 16))
                    trees["ev_by_cr_expired"].remove(
                        composite_key(rec.cr_account.id, ets, 16))
                    trees["ev_by_pid_expired"].remove(
                        composite_key(rec.transfer_pending.id, ets, 16))
                    trees["ev_by_ledger_expired"].remove(
                        composite_key(rec.dr_account.ledger, ets, 4))
            trees["events"].remove(key)
            trees["ev_by_prunable"].remove(key)
        return len(doomed)

    def compact_beat(self, op: int) -> None:
        self.forest.compact_beat(op)

    def checkpoint(self, state: StateMachineOracle,
                   flush_columns=None) -> bytes:
        """Flush + forest checkpoint; returns the root blob to persist.
        The 40 scalar bytes (key maxes, pulse, commit timestamp, event
        count) ride in the root blob itself — they are only ever read at
        restore, so they don't belong in a tree (reference analog: the
        superblock's VSRState vs the checkpoint trailer)."""
        self.flush(state, flush_columns=flush_columns)
        meta = struct.pack(
            "<QQQQQ",
            state.accounts_key_max or 0, state.transfers_key_max or 0,
            state.pulse_next_timestamp, state.commit_timestamp,
            self.events_persisted)
        return self.forest.checkpoint() + meta

    # ------------------------------------------------------------- recover

    def open(self, root: Optional[bytes],
             load_events: bool = True) -> StateMachineOracle:
        """Restore the forest from a checkpoint root and rebuild the
        in-memory state (object dicts + derived timestamp indexes).

        load_events=False (the replica serving path) leaves the event
        history in the forest's events tree and starts the host list at
        events_base = the persisted count — bounded memory regardless of
        history size (history queries are forest-served)."""
        state = StateMachineOracle()
        if root is not None:
            meta = root[-_META_SIZE:]
            self.forest.open(root[:-_META_SIZE])
            trees = self.forest.trees
            lo16, hi16 = b"\x00" * 16, b"\xff" * 16
            lo8, hi8 = b"\x00" * 8, b"\xff" * 8
            for _, v in trees["accounts"].scan(lo16, hi16):
                a = Account.unpack(v)
                state.accounts[a.id] = a
                state.account_by_timestamp[a.timestamp] = a.id
                self._indexed_accounts.add(a.id)
            for _, v in trees["transfers"].scan(lo16, hi16):
                t = Transfer.unpack(v)
                state.transfers[t.id] = t
                state.transfer_by_timestamp[t.timestamp] = t.id
            for k, v in trees["pending"].scan(lo8, hi8):
                state.pending_status[int.from_bytes(k, "big")] = \
                    TransferPendingStatus(v[0])
            for k, v in trees["expiry"].scan(lo8, hi8):
                state.expiry[int.from_bytes(k, "big")] = \
                    struct.unpack("<Q", v)[0]
            for k, _ in trees["orphaned"].scan(lo16, hi16):
                state.orphaned.add(int.from_bytes(k, "big"))
            for k, _ in trees["acct_by_closed"].scan(
                    b"\x00" * 9, b"\xff" * 9):
                ats = int.from_bytes(k[-8:], "big")
                self._closed_indexed.add(state.account_by_timestamp[ats])
            if load_events:
                for _, v in trees["events"].scan(lo8, hi8):
                    state.account_events.append(_unpack_event(v))
            akm, tkm, pulse, commit_ts, events_len = struct.unpack("<QQQQQ", meta)
            state.accounts_key_max = akm or None
            state.transfers_key_max = tkm or None
            state.pulse_next_timestamp = pulse
            state.commit_timestamp = commit_ts
            if load_events:
                # prune_events removes rows from the events tree, but
                # events_len is the monotonic persisted COUNT — start the
                # host list past the pruned prefix so flush's
                # un-persisted-tail slice stays exact.
                assert events_len >= len(state.account_events)
                state.events_base = events_len - len(state.account_events)
            else:
                state.events_base = events_len
        # Everything just loaded is already durable.
        for container in (state.accounts, state.transfers,
                          state.pending_status, state.expiry, state.orphaned):
            container.dirty.clear()
        self.events_persisted = state.events_base + len(state.account_events)
        return state
