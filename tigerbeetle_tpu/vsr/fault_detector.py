"""Primary-liveness estimator (sans-io).

reference: src/vsr/fault_detector.zig:1-24 — the "traffic light" analogy:
estimate the primary's health from the inter-arrival rate of its protocol
progress (prepares/commits), not just a hard timeout. An EWMA of observed
inter-arrival intervals sets an adaptive expectation; the detector reports
`suspect` when the time since the last progress exceeds a multiple of that
expectation, and the replica's timeout battery escalates to a view change.

Sans-io: fed observations + queried with timestamps; owns no clock.
"""

from __future__ import annotations

MS = 1_000_000  # ns


class FaultDetector:
    def __init__(self, *, alpha: float = 0.125,
                 floor_ns: int = 50 * MS, ceil_ns: int = 1000 * MS,
                 suspect_multiplier: float = 8.0):
        self.alpha = alpha
        self.floor_ns = floor_ns
        self.ceil_ns = ceil_ns
        self.suspect_multiplier = suspect_multiplier
        self.ewma_ns: float = float(ceil_ns)
        self.last_progress_ns: int = 0

    def observe_progress(self, now_ns: int) -> None:
        """The primary made protocol progress (prepare/commit heartbeat
        received, view installed)."""
        if self.last_progress_ns:
            interval = now_ns - self.last_progress_ns
            self.ewma_ns += self.alpha * (interval - self.ewma_ns)
            self.ewma_ns = min(max(self.ewma_ns, self.floor_ns),
                               float(self.ceil_ns))
        self.last_progress_ns = now_ns

    def reset(self, now_ns: int) -> None:
        """View change installed a new primary: start fresh."""
        self.ewma_ns = float(self.ceil_ns)
        self.last_progress_ns = now_ns

    def deadline_ns(self) -> int:
        """Time-since-progress beyond which the primary is suspect."""
        return int(self.ewma_ns * self.suspect_multiplier)

    def suspect(self, now_ns: int) -> bool:
        if not self.last_progress_ns:
            return False
        return now_ns - self.last_progress_ns > self.deadline_ns()
