"""Checkpoint snapshot codec: serialize the whole state machine state.

Round-1 checkpointing strategy (stands in for the reference's incremental
copy-on-write LSM grid, docs/internals/data_file.md:30-44): at each
checkpoint the full state-machine state is serialized into one of two
alternating snapshot slots, then the superblock flips to reference it.
Replicas restore by loading the snapshot and replaying the WAL suffix —
determinism guarantees bit-identical reconstruction
(docs/internals/data_file.md:63-94). The LSM forest replaces this with
incremental checkpoints in a later round.

Format: little-endian sections, each `count` + packed fixed-size records.
"""

from __future__ import annotations

import struct

from ..oracle.state_machine import AccountEventRecord, StateMachineOracle
from ..types import Account, Transfer, TransferPendingStatus

_MAGIC = b"TBTPUSNAP1"


def _pack_u128(x: int) -> bytes:
    return x.to_bytes(16, "little")


def encode(state: StateMachineOracle) -> bytes:
    """Canonical encoding: containers are serialized in timestamp/key
    order, NOT dict iteration order — under the lazy mirror
    (ops/lazy_mirror.py) dict insertion order depends on each replica's
    READ history, while content must compare byte-identical across
    replicas (the StorageChecker doctrine)."""
    out = [_MAGIC]

    accounts = sorted(state.accounts.values(), key=lambda a: a.timestamp)
    out.append(struct.pack("<Q", len(accounts)))
    out.extend(a.pack() for a in accounts)

    transfers = sorted(state.transfers.values(), key=lambda t: t.timestamp)
    out.append(struct.pack("<Q", len(transfers)))
    out.extend(t.pack() for t in transfers)

    out.append(struct.pack("<Q", len(state.orphaned)))
    out.extend(_pack_u128(i) for i in sorted(state.orphaned))

    out.append(struct.pack("<Q", len(state.pending_status)))
    out.extend(struct.pack("<QB", ts, int(s))
               for ts, s in sorted(state.pending_status.items()))

    out.append(struct.pack("<Q", len(state.expiry)))
    out.extend(struct.pack("<QQ", ts, exp)
               for ts, exp in sorted(state.expiry.items()))

    out.append(struct.pack(
        "<QQQQ",
        state.accounts_key_max or 0, state.transfers_key_max or 0,
        state.pulse_next_timestamp, state.commit_timestamp))

    events = state.account_events
    out.append(struct.pack("<Q", state.events_base))
    out.append(struct.pack("<Q", len(events)))
    for rec in events:
        has_p = rec.transfer_pending is not None
        out.append(struct.pack(
            "<QHB?", rec.timestamp, rec.transfer_flags or 0,
            int(rec.transfer_pending_status), has_p))
        out.append(rec.dr_account.pack())
        out.append(rec.cr_account.pack())
        out.append(_pack_u128(rec.amount_requested))
        out.append(_pack_u128(rec.amount))
        if has_p:
            out.append(rec.transfer_pending.pack())
    return b"".join(out)


def decode(raw: bytes) -> StateMachineOracle:
    assert raw[:len(_MAGIC)] == _MAGIC, "bad snapshot magic"
    pos = len(_MAGIC)

    def take(n: int) -> bytes:
        nonlocal pos
        chunk = raw[pos:pos + n]
        assert len(chunk) == n, "truncated snapshot"
        pos += n
        return chunk

    def count() -> int:
        return struct.unpack("<Q", take(8))[0]

    state = StateMachineOracle()
    for _ in range(count()):
        a = Account.unpack(take(128))
        state.accounts[a.id] = a
        state.account_by_timestamp[a.timestamp] = a.id
    for _ in range(count()):
        t = Transfer.unpack(take(128))
        state.transfers[t.id] = t
        state.transfer_by_timestamp[t.timestamp] = t.id
    for _ in range(count()):
        state.orphaned.add(int.from_bytes(take(16), "little"))
    for _ in range(count()):
        ts, s = struct.unpack("<QB", take(9))
        state.pending_status[ts] = TransferPendingStatus(s)
    for _ in range(count()):
        ts, exp = struct.unpack("<QQ", take(16))
        state.expiry[ts] = exp
    (akm, tkm, pulse, commit_ts) = struct.unpack("<QQQQ", take(32))
    state.accounts_key_max = akm or None
    state.transfers_key_max = tkm or None
    state.pulse_next_timestamp = pulse
    state.commit_timestamp = commit_ts
    state.events_base = count()
    for _ in range(count()):
        ts, tflags, pstat, has_p = struct.unpack("<QHB?", take(12))
        dr = Account.unpack(take(128))
        cr = Account.unpack(take(128))
        amount_requested = int.from_bytes(take(16), "little")
        amount = int.from_bytes(take(16), "little")
        pending = Transfer.unpack(take(128)) if has_p else None
        state.account_events.append(AccountEventRecord(
            timestamp=ts, dr_account=dr, cr_account=cr,
            transfer_flags=tflags,
            transfer_pending_status=TransferPendingStatus(pstat),
            transfer_pending=pending,
            amount_requested=amount_requested, amount=amount))
    return state
