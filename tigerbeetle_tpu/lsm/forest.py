"""Forest: named trees sharing one grid, with atomic checkpoints.

reference: src/lsm/forest.zig (open/compact/checkpoint across all trees,
shared manifest log). A checkpoint serializes every tree's manifest plus
the grid free set into grid blocks and returns one root address blob —
the superblock-equivalent pointer a caller persists atomically."""

from __future__ import annotations

import struct

from .grid import ADDRESS_SIZE, BlockAddress, Grid
from .tree import Tree


class Forest:
    def __init__(self, grid: Grid, schema: dict[str, tuple[int, int]]):
        """schema: name -> (key_size, value_size), fixed at format time
        (the reference's comptime groove schema)."""
        self.grid = grid
        self.schema = dict(sorted(schema.items()))
        self.trees: dict[str, Tree] = {
            name: Tree(grid, key_size=k, value_size=v, name=name)
            for name, (k, v) in self.schema.items()}
        self._manifest_block: int = -1  # previous checkpoint's manifest

    def compact_beat(self, op=None) -> None:
        for tree in self.trees.values():
            tree.compact_beat(op)

    def checkpoint(self) -> bytes:
        """Flush + serialize everything; returns the root blob
        (manifest block address + free set). Pending grid frees are applied
        here — the atomic flip point."""
        manifests = {name: tree.manifest_pack()
                     for name, tree in self.trees.items()}
        parts = [struct.pack("<I", len(manifests))]
        for name, raw in manifests.items():
            nb = name.encode()
            parts.append(struct.pack("<HI", len(nb), len(raw)))
            parts.append(nb)
            parts.append(raw)
        manifest_blob = b"".join(parts)
        assert len(manifest_blob) <= self.grid.block_size, \
            "manifest exceeds one block (chain blocks in a later round)"
        # Free the previous checkpoint's manifest block (two-phase: it stays
        # intact on disk until this checkpoint's free set takes effect, so a
        # crash before the superblock flip still recovers the old root).
        if self._manifest_block >= 0:
            self.grid.release(self._manifest_block)
        address = self.grid.write_block(manifest_blob)
        self._manifest_block = address.index
        free_blob = self.grid.checkpoint_free_set()
        # The manifest block itself was just acquired; reflect that in the
        # free set by re-serializing after the write (acquire happened
        # before checkpoint_free_set, so it is already excluded).
        return (address.pack() + struct.pack("<I", len(manifest_blob))
                + struct.pack("<I", len(free_blob)) + free_blob)

    def open(self, root: bytes) -> None:
        """Restore from a checkpoint root blob."""
        address = BlockAddress.unpack(root[:ADDRESS_SIZE])
        (manifest_size,) = struct.unpack_from("<I", root, ADDRESS_SIZE)
        (free_size,) = struct.unpack_from("<I", root, ADDRESS_SIZE + 4)
        free_blob = root[ADDRESS_SIZE + 8:ADDRESS_SIZE + 8 + free_size]
        self.grid.restore_free_set(free_blob)
        self._manifest_block = address.index
        raw = self.grid.read_block(address, manifest_size)
        (count,) = struct.unpack_from("<I", raw)
        pos = 4
        for _ in range(count):
            name_len, size = struct.unpack_from("<HI", raw, pos)
            pos += 6
            name = raw[pos:pos + name_len].decode()
            pos += name_len
            self.trees[name].manifest_restore(raw[pos:pos + size])
            pos += size
