"""Forest: named trees sharing one grid, with atomic checkpoints.

reference: src/lsm/forest.zig (open/compact/checkpoint across all trees,
shared manifest log — a linked list of manifest blocks replayed at
startup, docs/internals/data_file.md:151-194). A checkpoint serializes
every tree's manifest into a CHAIN of grid blocks (head -> ... -> tail,
each block carrying the next block's address) plus the grid free set, and
returns one small root blob — the superblock-equivalent pointer a caller
persists atomically.
"""

from __future__ import annotations

import struct
from typing import Optional

from .grid import ADDRESS_SIZE, BlockAddress, Grid
from .tree import Tree

from .schema import BLOCK_HEADER_SIZE, BlockKind, unwrap, wrap

# Per-chain-block header (inside the unified block header,
# lsm/schema.py): next address (24) + next block size (4).
# next size == 0 marks the tail.
CHAIN_HEADER = ADDRESS_SIZE + 4


def chain_next(block_raw: bytes) -> Optional[tuple[BlockAddress, int]]:
    """(next address, next size) of a manifest chain block, or None."""
    inner = unwrap(block_raw, BlockKind.manifest)
    (next_size,) = struct.unpack_from("<I", inner, ADDRESS_SIZE)
    if next_size == 0:
        return None
    return BlockAddress.unpack(inner[:ADDRESS_SIZE]), next_size


def chain_payload(block_raw: bytes) -> bytes:
    return unwrap(block_raw, BlockKind.manifest)[CHAIN_HEADER:]


class Forest:
    def __init__(self, grid: Grid, schema: dict[str, tuple[int, int]]):
        """schema: name -> (key_size, value_size), fixed at format time
        (the reference's comptime groove schema)."""
        self.grid = grid
        self.schema = dict(sorted(schema.items()))
        # Deterministic tree ids (sorted-name order, 1-based; 0 means
        # standalone) — stamped into every block a tree writes.
        self.trees: dict[str, Tree] = {
            name: Tree(grid, key_size=k, value_size=v, name=name,
                       tree_id=i + 1)
            for i, (name, (k, v)) in enumerate(self.schema.items())}
        self._manifest_chain: list[int] = []  # previous checkpoint's blocks
        # (address, size) of the live chain — the scrubber's tour set.
        self.manifest_chain_blocks: list = []

    def compact_beat(self, op=None) -> None:
        for tree in self.trees.values():
            tree.compact_beat(op)

    def checkpoint(self) -> bytes:
        """Flush + serialize everything; returns the root blob (manifest
        chain head address + free set). Pending grid frees are applied
        here — the atomic flip point."""
        manifests = {name: tree.manifest_pack()
                     for name, tree in self.trees.items()}
        parts = [struct.pack("<I", len(manifests))]
        for name, raw in manifests.items():
            nb = name.encode()
            parts.append(struct.pack("<HI", len(nb), len(raw)))
            parts.append(nb)
            parts.append(raw)
        manifest_blob = b"".join(parts)
        # Free the previous checkpoint's manifest chain (two-phase: the
        # blocks stay intact on disk until this checkpoint's free set takes
        # effect, so a crash before the superblock flip still recovers the
        # old root).
        for index in self._manifest_chain:
            self.grid.release(index)
        # Write the chain tail-first so each block can embed its
        # successor's address.
        chunk_max = self.grid.block_size - CHAIN_HEADER - BLOCK_HEADER_SIZE
        chunks = [manifest_blob[off:off + chunk_max]
                  for off in range(0, len(manifest_blob), chunk_max)] or [b""]
        next_address: Optional[BlockAddress] = None
        next_size = 0
        chain: list[int] = []
        chain_blocks: list[tuple[BlockAddress, int]] = []
        for chunk in reversed(chunks):
            raw = wrap(
                BlockKind.manifest,
                (next_address.pack() if next_address is not None
                 else b"\x00" * ADDRESS_SIZE)
                + struct.pack("<I", next_size) + chunk)
            next_address = self.grid.write_block(raw)
            next_size = len(raw)
            chain.append(next_address.index)
            chain_blocks.append((next_address, next_size))
        # ONE canonical store, head-first; the release-index list is
        # derived (order is irrelevant for release). The scrubber tours
        # these: manifest blocks are reachable checkpoint state and must
        # be scrubbed/repairable like table blocks (reference
        # grid_scrubber tours the manifest log too).
        self.manifest_chain_blocks = list(reversed(chain_blocks))
        self._manifest_chain = [a.index
                                for a, _ in self.manifest_chain_blocks]
        head_address, head_size = next_address, next_size
        free_blob = self.grid.checkpoint_free_set()
        return (head_address.pack() + struct.pack("<I", head_size)
                + struct.pack("<I", len(free_blob)) + free_blob)

    def open(self, root: bytes) -> None:
        """Restore from a checkpoint root blob (walking the chain)."""
        address = BlockAddress.unpack(root[:ADDRESS_SIZE])
        (size,) = struct.unpack_from("<I", root, ADDRESS_SIZE)
        (free_size,) = struct.unpack_from("<I", root, ADDRESS_SIZE + 4)
        free_blob = root[ADDRESS_SIZE + 8:ADDRESS_SIZE + 8 + free_size]
        self.grid.restore_free_set(free_blob)
        payload_parts = []
        chain_blocks: list[tuple[BlockAddress, int]] = []
        link: Optional[tuple[BlockAddress, int]] = (address, size)
        while link is not None:
            block_address, block_size = link
            raw = self.grid.read_block(block_address, block_size)
            chain_blocks.append((block_address, block_size))
            payload_parts.append(chain_payload(raw))
            link = chain_next(raw)
        # Head-first, matching checkpoint() — one canonical order.
        self.manifest_chain_blocks = chain_blocks
        self._manifest_chain = [a.index for a, _ in chain_blocks]
        raw = b"".join(payload_parts)
        (count,) = struct.unpack_from("<I", raw)
        pos = 4
        for _ in range(count):
            name_len, size = struct.unpack_from("<HI", raw, pos)
            pos += 6
            name = raw[pos:pos + name_len].decode()
            pos += name_len
            self.trees[name].manifest_restore(raw[pos:pos + size])
            pos += size
