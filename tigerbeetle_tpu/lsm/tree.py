"""One LSM tree: memtable + leveled immutable tables with deterministic
compaction.

reference: src/lsm/tree.zig (mutable/immutable memtables, 7 levels, growth
factor 8 — src/config.zig:162-163), src/lsm/compaction.zig (incremental
merge paced in bars/beats; deterministic pacing is load-bearing for
replica-identical data files), src/lsm/manifest.zig (least-overlap table
selection, docs/internals/lsm.md:93-108).

Pacing model here (incremental, VERDICT r1 #5 — reference:
src/lsm/compaction.zig:289, docs/internals/lsm.md:37-138): `compact_beat()`
is called once per committed op (the reference's beat). At each bar
boundary the mutable memtable FREEZES (mutable/immutable swap,
tree.zig:543) and one compaction JOB is scheduled per over-budget level;
both kinds of work then spread evenly across the bar's remaining beats.
The frozen memtable streams value blocks to the grid each beat but its
tables INSTALL only at completion; compaction merges in memory and writes
only at its completing beat. Either way no manifest ever references
partial state: checkpoints drain in-flight work first (manifest_pack),
and blocks written by an abandoned mid-bar job are unreferenced (freed at
the next checkpoint). The last beat of the bar drains whatever remains,
so a bar always ends with its scheduled work installed. All decisions are
pure functions of the op sequence — byte-deterministic across replicas
(tested), including across a crash/replay."""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

from .grid import Grid
from .manifest_level import SNAPSHOT_LATEST, ManifestLevel
from .table import (
    Table,
    TableInfo,
    TOMBSTONE,
    release_table,
    table_block_bound,
    table_entry_max,
    value_block_entry_max,
    write_index_block,
    write_tables,
    write_value_block,
)

LSM_LEVELS = 7
GROWTH_FACTOR = 8
BAR_LENGTH = 32  # ops per bar (reference: lsm_compaction_ops)
L0_TABLES_MAX = 4


@dataclasses.dataclass
class _FlushJob:
    """The frozen (immutable) memtable being written out incrementally
    (reference: the mutable/immutable memtable pair, src/lsm/tree.zig +
    table_memory.zig — the immutable side streams to disk across the
    bar's beats while staying readable)."""

    entries: list  # sorted (key, value)
    snapshot: int  # freeze op: installed tables carry this snapshot_min
    pos: int = 0
    # Current table's completed value blocks: (address, size, first_key).
    blocks: list = dataclasses.field(default_factory=list)
    infos: list = dataclasses.field(default_factory=list)
    # Worst-case grid reservation claimed at freeze (free_set.zig:28-35).
    reservation: object = None


@dataclasses.dataclass
class _CompactionJob:
    """One level's in-flight incremental merge: input tables captured at
    schedule time, merge advanced a bounded number of entries per beat,
    output written + installed only at completion."""

    level: int
    table: Table
    overlapping: list[Table]
    total: int  # input entries (pacing estimate)
    merged: dict = dataclasses.field(default_factory=dict)
    streams: list = dataclasses.field(default_factory=list)
    stream_i: int = 0
    # Worst-case grid reservation claimed at schedule (free_set.zig:28-35).
    reservation: object = None

    def advance(self, budget: Optional[int]):
        """Merge up to `budget` INPUT entries (None = drain). Returns
        (done, used): done when the inputs are exhausted (caller
        finalizes); used = entries consumed, which the caller charges
        against the beat budget (NOT merged-dict growth — duplicate-key
        merges consume entries without growing the dict)."""
        used = 0
        while self.stream_i < len(self.streams):
            stream = self.streams[self.stream_i]
            for k, v in stream:
                self.merged[k] = v
                used += 1
                if budget is not None and used >= budget:
                    return False, used
            self.stream_i += 1
        return True, used


class Tree:
    def __init__(self, grid: Grid, *, key_size: int, value_size: int,
                 name: str = "tree", tree_id: int = 0):
        self.grid = grid
        self.key_size = key_size
        self.value_size = value_size
        self.name = name
        # Stamped into every block this tree writes (lsm/schema.py);
        # 0 = standalone. The forest assigns deterministic ids.
        self.tree_id = tree_id
        self.memtable: dict[bytes, bytes] = {}
        # Frozen previous memtable: readable while its flush job streams
        # it into level-0 tables across the bar's beats.
        self.immutable_map: dict[bytes, bytes] = {}
        self._flush: Optional[_FlushJob] = None
        self._flush_per_beat = 0
        # Per-level manifest structures over (key range x snapshot range)
        # (reference: src/lsm/manifest_level.zig). L0 tables overlap
        # (insertion order, recency decides); deeper levels are disjoint
        # per snapshot (key_min order, binary-searched).
        self.levels: list[ManifestLevel] = [
            ManifestLevel(keep_sorted=(i > 0)) for i in range(LSM_LEVELS)]
        self.beat = 0
        # In-flight incremental compaction jobs (scheduled at bar start,
        # advanced per beat, drained by bar end).
        self._jobs: list[_CompactionJob] = []
        self._per_beat = 0

    # ------------------------------------------------------------- updates

    def put(self, key: bytes, value: bytes) -> None:
        assert len(key) == self.key_size and len(value) == self.value_size
        self.memtable[key] = value

    def remove(self, key: bytes) -> None:
        assert len(key) == self.key_size
        self.memtable[key] = TOMBSTONE * self.value_size

    def get(self, key: bytes,
            snapshot: Optional[int] = None) -> Optional[bytes]:
        """Point lookup. snapshot=None serves the latest state (memtable
        included); snapshot=s reads the table set visible at op s — a
        point-in-time view that stays consistent while compaction installs
        and removes tables around it (valid within the tree's one-bar
        retention window; reference: manifest snapshot queries,
        src/lsm/manifest_level.zig)."""
        value = self.memtable.get(key) if snapshot is None else None
        if value is None and self._frozen_visible(snapshot):
            # The frozen memtable became logically table-visible at its
            # freeze op: snapshots at or past it must read it even while
            # the flush job is still streaming it out (otherwise the same
            # (key, snapshot) would answer differently before and after
            # the install).
            value = self.immutable_map.get(key)
        if value is None:
            # L0 tables may overlap: newest-first probe; deeper levels
            # yield at most one candidate per snapshot (binary-searched on
            # the live set for the latest snapshot).
            for level in self.levels:
                for table in level.lookup(key, snapshot):
                    value = table.get(key)
                    if value is not None:
                        break
                if value is not None:
                    break
        if value is None or value == TOMBSTONE * self.value_size:
            return None
        return value

    def get_many(self, keys, snapshot: Optional[int] = None) -> dict:
        """Batched point lookups: per level, every unresolved key's value
        block is issued in ONE concurrent fan-out (Grid.read_blocks),
        then resolved in place — a cold cache costs one round trip per
        level touched, not one per key (reference: the prefetch fan-out,
        src/lsm/groove.zig:996,1339). Returns {key: value} for keys
        found live (tombstoned/missing keys are absent)."""
        found: dict = {}
        remaining = []
        for key in keys:
            value = self.memtable.get(key) if snapshot is None else None
            if value is None and self._frozen_visible(snapshot):
                value = self.immutable_map.get(key)
            if value is not None:
                found[key] = value
            else:
                remaining.append(key)
        plans: dict = {}  # key -> [(table, blk)] planned by the lookahead
        for li, level in enumerate(self.levels):
            if not remaining:
                break
            # Per-key candidate queues (L0 may yield several overlapping
            # tables, newest first; deeper levels at most one). The
            # previous level's lookahead already planned (table, block)
            # pairs for this level — reuse them instead of re-probing.
            active = []
            for key in remaining:
                cand = plans.get(key)
                if cand is None:
                    cand = [(t, t.block_for(key))
                            for t in level.lookup(key, snapshot)]
                if cand:
                    active.append((key, cand))
            # Overlap: submit the NEXT level's candidate blocks (planned
            # read-free) while THIS level's fan-out resolves — a superset
            # read-ahead (keys resolved here waste their submit) bounded
            # by the grid's in-flight cap; no-op on synchronous devices.
            plans = {}
            if li + 1 < len(self.levels) and active:
                lookahead = []
                for key, _ in active:
                    cand2 = [(t, t.block_for(key)) for t in
                             self.levels[li + 1].lookup(key, snapshot)]
                    if cand2:
                        plans[key] = cand2
                        lookahead.extend(
                            b for _, b in cand2 if b is not None)
                if lookahead:
                    self.grid.prefetch_async(lookahead)
            while active:
                reqs, slots, nxt = [], [], []
                for key, cand in active:
                    blk = None
                    while cand and blk is None:
                        table, blk = cand.pop(0)
                    if blk is None:
                        continue
                    reqs.append(blk)
                    slots.append((key, table, cand))
                if not reqs:
                    break
                for (key, table, cand), raw in zip(
                        slots, self.grid.read_blocks(reqs)):
                    value = table.get_in_block(key, raw)
                    if value is not None:
                        found[key] = value  # tombstones shadow deeper levels
                    elif cand:
                        nxt.append((key, cand))
                active = nxt
            remaining = [k for k in remaining if k not in found]
        dead = TOMBSTONE * self.value_size
        return {k: v for k, v in found.items() if v != dead}

    def scan(self, key_min: bytes, key_max: bytes,
             snapshot: Optional[int] = None) -> list[tuple[bytes, bytes]]:
        """Merged range scan, newest version wins (streaming k-way merge
        over memtable + levels — reference: scan_tree.zig; the lazy
        iterator API is lsm/scan.py's TreeScan)."""
        from .scan import TreeScan

        return list(TreeScan(self, key_min, key_max, snapshot=snapshot))

    # ---------------------------------------------------------- compaction

    def compact_beat(self, op: Optional[int] = None) -> None:
        """One beat. At a bar boundary: flush the memtable and SCHEDULE one
        compaction job per over-budget level; on every beat, advance the
        in-flight jobs by a bounded number of merged entries (total work /
        remaining beats), deferring grid writes to each job's completion;
        the bar's last beat drains the rest. Deterministic in the op
        sequence (no clocks, no randomness). When `op` is given, the bar
        phase is derived from the op number itself so a restarted replica
        replaying the WAL suffix hits the exact same flush and merge
        points as one that never crashed (the reference derives compaction
        pacing from op % lsm_compaction_ops the same way,
        docs/internals/lsm.md:37-91)."""
        self.beat = self.beat + 1 if op is None else op
        phase = self.beat % BAR_LENGTH
        if phase == 0:
            self._drain_flush()  # defensive: the previous freeze is done
            self._freeze_memtable()
            self._drain_jobs()  # defensive: a bar never leaves work behind
            # Physically release tables removed at least one full bar ago
            # (snapshot reads within the retention window stay valid; a
            # pure function of the op sequence, so every replica frees the
            # identical block set — physical determinism).
            self._prune(self.beat - BAR_LENGTH)
            self._schedule_jobs()
        if self._flush is not None:
            if phase == BAR_LENGTH - 1:
                self._drain_flush()
            else:
                self._advance_flush(self._flush_per_beat)
        if self._jobs:
            if phase == BAR_LENGTH - 1:
                self._drain_jobs()
            else:
                self._advance_jobs(self._per_beat)

    def flush_memtable(self) -> None:
        """Synchronous freeze + drain (checkpoints and callers that need
        every row table-resident NOW; the beat path streams instead)."""
        self._freeze_memtable()
        self._drain_flush()

    # -------------------------------------------------- memtable flushing

    def _frozen_visible(self, snapshot: Optional[int]) -> bool:
        """Is the frozen memtable part of the view at `snapshot`?"""
        if snapshot is None:
            return True
        return self._flush is not None and snapshot >= self._flush.snapshot

    def _freeze_memtable(self) -> None:
        """Swap mutable -> immutable (reference tree.zig:543): the frozen
        rows stay readable from `immutable_map` while a flush job streams
        them into level-0 tables across the bar's beats."""
        if not self.memtable:
            return
        self._drain_flush()  # at most one frozen memtable at a time
        # Reserve BEFORE the swap: a "grid full" reserve failure must
        # leave the tree unchanged (a post-swap failure would strand the
        # frozen rows with no flush job and lose them at the next freeze).
        entries = sorted(self.memtable.items())
        reservation = self.grid.reserve(table_block_bound(
            self.grid, len(entries), self.key_size, self.value_size))
        self.immutable_map = self.memtable
        self.memtable = {}
        self._flush = _FlushJob(
            entries=entries,
            snapshot=self.beat,
            reservation=reservation)
        self._flush_per_beat = max(
            1, -(-len(self._flush.entries) // (BAR_LENGTH - 1)))

    def _advance_flush(self, budget: Optional[int]) -> None:
        """Write up to `budget` entries (whole value blocks; None = all).
        Value blocks hit the grid each beat, but tables INSTALL only at
        job completion: the mid-bar blocks stay unreferenced from any
        manifest, and checkpoints drain the job first (manifest_pack ->
        flush_memtable), so no checkpoint ever references a partial
        table."""
        job = self._flush
        if job is None:
            return
        per_block = value_block_entry_max(self.grid, self.key_size,
                                          self.value_size)
        cap = table_entry_max(self.grid, self.key_size, self.value_size)
        while job.pos < len(job.entries):
            if budget is not None and budget <= 0:
                return
            table_end = min(len(job.entries),
                            (job.pos // cap + 1) * cap)
            chunk = job.entries[job.pos:min(job.pos + per_block, table_end)]
            job.blocks.append(write_value_block(
                self.grid, chunk, reservation=job.reservation,
                tree_id=self.tree_id))
            job.pos += len(chunk)
            if budget is not None:
                budget -= len(chunk)
            if job.pos == table_end:
                job.infos.append(self._finish_flush_table(job, cap))
        # All entries written: install every produced table.
        for info in job.infos:
            self.levels[0].insert(
                Table(self.grid, info, self.key_size, self.value_size),
                snapshot=job.snapshot)
        if job.reservation is not None:
            self.grid.forfeit(job.reservation)
        self.immutable_map = {}
        self._flush = None

    def _finish_flush_table(self, job: _FlushJob, cap: int) -> TableInfo:
        index_addr, index_size = write_index_block(
            self.grid, job.blocks, reservation=job.reservation,
            tree_id=self.tree_id)
        first_key = job.blocks[0][2]
        # job.pos sits at this table's end; recover its entry range.
        start = (job.pos - 1) // cap * cap
        info = TableInfo(
            index_address=index_addr, index_size=index_size,
            key_min=first_key, key_max=job.entries[job.pos - 1][0],
            entry_count=job.pos - start)
        job.blocks = []
        return info

    def _drain_flush(self) -> None:
        self._advance_flush(None)

    def _prune(self, snapshot_oldest: int) -> None:
        for level in self.levels:
            for table in level.prune(snapshot_oldest):
                release_table(self.grid, table)

    def _level_budget(self, level: int) -> int:
        if level == 0:
            return L0_TABLES_MAX
        return GROWTH_FACTOR ** level

    def _schedule_jobs(self) -> None:
        """One job per over-budget level, inputs captured now (they stay
        installed and readable until the job completes). A level whose
        pick or overlap set intersects an earlier job's captured tables
        is SKIPPED this bar (adjacent over-budget levels would otherwise
        double-release a shared level-(L+1) table); it reschedules next
        bar — deterministic either way."""
        assert not self._jobs
        jobs: list[_CompactionJob] = []
        claimed: set[int] = set()  # id() of captured Table objects
        for level in range(LSM_LEVELS - 1):
            if len(self.levels[level]) > self._level_budget(level):
                table = self._pick_table(level)
                overlapping = [
                    t for t in self.levels[level + 1]
                    if not (t.info.key_max < table.info.key_min
                            or t.info.key_min > table.info.key_max)]
                touched = [table, *overlapping]
                if any(id(t) in claimed for t in touched):
                    continue
                claimed.update(id(t) for t in touched)
                total = (table.info.entry_count
                         + sum(t.info.entry_count for t in overlapping))
                job = _CompactionJob(
                    level=level, table=table,
                    overlapping=overlapping, total=total,
                    reservation=self.grid.reserve(table_block_bound(
                        self.grid, total, self.key_size, self.value_size)))
                # Older tables first so the newer input wins the merge.
                job.streams = [t.iter_entries() for t in overlapping]
                job.streams.append(table.iter_entries())
                # Warm the first input block of every stream now: the
                # device reads run during the beats before the job's
                # first advance (iter_entries read-ahead covers the
                # rest of each table).
                self.grid.prefetch_async(
                    [(t.block_addresses[0], t.block_sizes[0])
                     for t in touched if t.block_addresses])
                jobs.append(job)
        self._jobs = jobs
        total = sum(j.total for j in jobs)
        self._per_beat = max(1, -(-total // (BAR_LENGTH - 1)))

    def _advance_jobs(self, budget: int) -> None:
        while budget > 0 and self._jobs:
            job = self._jobs[0]
            done, used = job.advance(budget)
            if done:
                self._finalize_job(job)
                self._jobs.pop(0)
            budget -= max(1, used)

    def _drain_jobs(self) -> None:
        for job in self._jobs:
            done, _ = job.advance(None)
            assert done
            self._finalize_job(job)
        self._jobs = []

    def _finalize_job(self, job: _CompactionJob) -> None:
        """Write output tables, install, logically remove inputs — the
        only beat that touches the grid (mid-bar checkpoints therefore
        never see a partially-written compaction). Inputs move to the
        manifest's history (snapshot_max = this op) and stay readable for
        snapshots taken before this beat; their blocks are freed by
        `_prune` a bar later."""
        level = job.level
        self.levels[level].remove(job.table, snapshot=self.beat)
        next_level = self.levels[level + 1]
        for t in job.overlapping:
            next_level.remove(t, snapshot=self.beat)
        last_level = level + 1 == LSM_LEVELS - 1
        dead = TOMBSTONE * self.value_size
        entries = sorted(
            (k, v) for k, v in job.merged.items()
            if not (last_level and v == dead))  # tombstones die at the bottom
        if entries:
            # A merge output exceeding one table's capacity splits into
            # several disjoint tables (all still inside next_level's range).
            for info in write_tables(self.grid, entries, self.key_size,
                                     self.value_size,
                                     reservation=job.reservation,
                                     tree_id=self.tree_id):
                next_level.insert(Table(
                    self.grid, info, self.key_size, self.value_size),
                    snapshot=self.beat)
        if job.reservation is not None:
            self.grid.forfeit(job.reservation)

    def _pick_table(self, level: int) -> Table:
        """Selection policy: L0 tables overlap each other, so only the
        OLDEST may move down (a newer table would otherwise be shadowed by
        stale data left behind). Deeper levels are disjoint; pick by least
        overlap with the next level, ties on smallest key_min for
        determinism (reference: docs/internals/lsm.md:93-108)."""
        if level == 0:
            return self.levels[0][0]

        def overlap(table: Table) -> int:
            return sum(
                1 for t in self.levels[level + 1]
                if not (t.info.key_max < table.info.key_min
                        or t.info.key_min > table.info.key_max))

        return min(self.levels[level],
                   key=lambda t: (overlap(t), t.info.key_min))

    # ------------------------------------------------------------ manifest

    def manifest_pack(self) -> bytes:
        """Serialize the level structure AND any in-flight compaction
        jobs (reference: manifest log replay). Persisting the job plans
        is load-bearing for physical determinism: a mid-bar checkpoint
        precedes the bar-end install, so a replica restarting from it
        must resume the SAME merges (same inputs, same install beat) a
        never-crashed replica completes — the merge output is a pure
        function of the inputs, so the grids stay byte-identical even
        though the restarted replica redoes the merge work."""
        self.flush_memtable()
        # The beat is persisted so a restored tree keeps stamping snapshots
        # and pruning on the same op clock (a reset-to-zero beat would
        # invert level-0 recency for post-restore flushes and re-extend
        # the retention window).
        parts = [struct.pack("<QB", self.beat, LSM_LEVELS)]
        for level in self.levels:
            entries = list(level.live) + list(level.history)
            # next_seq is persisted (not re-derived from surviving
            # entries): once the max-seq entry is pruned, a re-derived
            # counter would diverge from never-restarted replicas and
            # break byte-identical checkpoints.
            parts.append(struct.pack("<QI", level.next_seq, len(entries)))
            for e in entries:
                parts.append(struct.pack("<QQQ", e.snapshot_min,
                                         e.snapshot_max, e.seq))
                parts.append(e.table.info.pack())
        parts.append(struct.pack("<I", len(self._jobs)))
        for job in self._jobs:
            parts.append(struct.pack("<BI", job.level, len(job.overlapping)))
            parts.append(job.table.info.pack())
            for t in job.overlapping:
                parts.append(t.info.pack())
        return b"".join(parts)

    def manifest_restore(self, raw: bytes) -> None:
        from .manifest_level import LevelEntry

        beat, n_levels = struct.unpack_from("<QB", raw)
        assert n_levels == LSM_LEVELS
        self.beat = beat
        pos = 9
        self.levels = [ManifestLevel(keep_sorted=(i > 0))
                       for i in range(LSM_LEVELS)]
        for level in range(n_levels):
            next_seq, count = struct.unpack_from("<QI", raw, pos)
            pos += 12
            for _ in range(count):
                snap_min, snap_max, seq = struct.unpack_from(
                    "<QQQ", raw, pos)
                pos += 24
                info, pos = TableInfo.unpack(raw, pos)
                table = Table(self.grid, info, self.key_size,
                              self.value_size)
                if snap_max == SNAPSHOT_LATEST:
                    self.levels[level].insert(table, snapshot=snap_min,
                                              seq=seq)
                else:
                    self.levels[level].history.append(LevelEntry(
                        table=table, snapshot_min=snap_min,
                        snapshot_max=snap_max, seq=seq))
            self.levels[level].next_seq = next_seq
        self.memtable.clear()
        self.immutable_map = {}
        self._flush = None
        # Rebuild in-flight jobs against the RESTORED Table objects
        # (identity matters: finalize removes job tables from the level
        # lists by identity). Merge progress restarts from zero — the
        # output is input-deterministic, so only pacing differs.
        self._jobs = []
        if pos < len(raw):
            (n_jobs,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            for _ in range(n_jobs):
                level, n_over = struct.unpack_from("<BI", raw, pos)
                pos += 5
                t_info, pos = TableInfo.unpack(raw, pos)
                over_infos = []
                for _ in range(n_over):
                    info, pos = TableInfo.unpack(raw, pos)
                    over_infos.append(info)

                def resident(lvl: int, info: TableInfo) -> Table:
                    for t in self.levels[lvl]:
                        if (t.info.index_address == info.index_address
                                and t.info.index_size == info.index_size):
                            return t
                    raise AssertionError(
                        f"job table missing from restored level {lvl}")

                table = resident(level, t_info)
                overlapping = [resident(level + 1, i) for i in over_infos]
                total = (table.info.entry_count
                         + sum(t.info.entry_count for t in overlapping))
                job = _CompactionJob(
                    level=level, table=table,
                    overlapping=overlapping, total=total,
                    reservation=self.grid.reserve(table_block_bound(
                        self.grid, total, self.key_size, self.value_size)))
                job.streams = [t.iter_entries() for t in overlapping]
                job.streams.append(table.iter_entries())
                self._jobs.append(job)
            total = sum(j.total for j in self._jobs)
            self._per_beat = max(1, -(-total // (BAR_LENGTH - 1)))


