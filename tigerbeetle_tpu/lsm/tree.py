"""One LSM tree: memtable + leveled immutable tables with deterministic
compaction.

reference: src/lsm/tree.zig (mutable/immutable memtables, 7 levels, growth
factor 8 — src/config.zig:162-163), src/lsm/compaction.zig (incremental
merge paced in bars/beats; deterministic pacing is load-bearing for
replica-identical data files), src/lsm/manifest.zig (least-overlap table
selection, docs/internals/lsm.md:93-108).

Pacing model here: `compact_beat()` is called once per committed op (the
reference's beat); every `bar_length` beats the mutable memtable flushes to
level 0 and one compaction step runs per level that exceeds its budget.
All decisions are pure functions of the op sequence — byte-deterministic
across replicas (tested)."""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

from .grid import Grid
from .table import (
    Table,
    TableInfo,
    TOMBSTONE,
    release_table,
    write_tables,
)

LSM_LEVELS = 7
GROWTH_FACTOR = 8
BAR_LENGTH = 32  # ops per bar (reference: lsm_compaction_ops)
L0_TABLES_MAX = 4


class Tree:
    def __init__(self, grid: Grid, *, key_size: int, value_size: int,
                 name: str = "tree"):
        self.grid = grid
        self.key_size = key_size
        self.value_size = value_size
        self.name = name
        self.memtable: dict[bytes, bytes] = {}
        self.levels: list[list[Table]] = [[] for _ in range(LSM_LEVELS)]
        self.beat = 0

    # ------------------------------------------------------------- updates

    def put(self, key: bytes, value: bytes) -> None:
        assert len(key) == self.key_size and len(value) == self.value_size
        self.memtable[key] = value

    def remove(self, key: bytes) -> None:
        assert len(key) == self.key_size
        self.memtable[key] = TOMBSTONE * self.value_size

    def get(self, key: bytes) -> Optional[bytes]:
        value = self.memtable.get(key)
        if value is None:
            for level in self.levels:
                # Newest-first within a level (L0 tables may overlap).
                for table in reversed(level):
                    value = table.get(key)
                    if value is not None:
                        break
                if value is not None:
                    break
        if value is None or value == TOMBSTONE * self.value_size:
            return None
        return value

    def scan(self, key_min: bytes, key_max: bytes) -> list[tuple[bytes, bytes]]:
        """Merged range scan, newest version wins (streaming k-way merge
        over memtable + levels — reference: scan_tree.zig; the lazy
        iterator API is lsm/scan.py's TreeScan)."""
        from .scan import TreeScan

        return list(TreeScan(self, key_min, key_max))

    # ---------------------------------------------------------- compaction

    def compact_beat(self, op: Optional[int] = None) -> None:
        """One beat; at each bar boundary, flush + rebalance one step.
        Deterministic in the op sequence (no clocks, no randomness). When
        `op` is given, the bar phase is derived from the op number itself so
        a restarted replica replaying the WAL suffix hits the exact same
        flush points as one that never crashed (the reference derives
        compaction pacing from op % lsm_compaction_ops the same way,
        docs/internals/lsm.md:37-91)."""
        self.beat = self.beat + 1 if op is None else op
        if self.beat % BAR_LENGTH == 0:
            self.flush_memtable()
            self._compact_levels()

    def flush_memtable(self) -> None:
        if not self.memtable:
            return
        entries = sorted(self.memtable.items())
        for info in write_tables(self.grid, entries, self.key_size,
                                 self.value_size):
            self.levels[0].append(
                Table(self.grid, info, self.key_size, self.value_size))
        self.memtable.clear()

    def _level_budget(self, level: int) -> int:
        if level == 0:
            return L0_TABLES_MAX
        return GROWTH_FACTOR ** level

    def _compact_levels(self) -> None:
        for level in range(LSM_LEVELS - 1):
            if len(self.levels[level]) > self._level_budget(level):
                self._compact_one(level)

    def _pick_table(self, level: int) -> Table:
        """Selection policy: L0 tables overlap each other, so only the
        OLDEST may move down (a newer table would otherwise be shadowed by
        stale data left behind). Deeper levels are disjoint; pick by least
        overlap with the next level, ties on smallest key_min for
        determinism (reference: docs/internals/lsm.md:93-108)."""
        if level == 0:
            return self.levels[0][0]

        def overlap(table: Table) -> int:
            return sum(
                1 for t in self.levels[level + 1]
                if not (t.info.key_max < table.info.key_min
                        or t.info.key_min > table.info.key_max))

        return min(self.levels[level],
                   key=lambda t: (overlap(t), t.info.key_min))

    def _compact_one(self, level: int) -> None:
        table = self._pick_table(level)
        self.levels[level].remove(table)
        next_level = self.levels[level + 1]
        overlapping = [
            t for t in next_level
            if not (t.info.key_max < table.info.key_min
                    or t.info.key_min > table.info.key_max)]
        for t in overlapping:
            next_level.remove(t)

        merged: dict[bytes, bytes] = {}
        for t in overlapping:  # older
            for k, v in t.iter_entries():
                merged[k] = v
        for k, v in table.iter_entries():  # newer wins
            merged[k] = v
        last_level = level + 1 == LSM_LEVELS - 1
        dead = TOMBSTONE * self.value_size
        entries = sorted(
            (k, v) for k, v in merged.items()
            if not (last_level and v == dead))  # tombstones die at the bottom
        if entries:
            # A merge output exceeding one table's capacity splits into
            # several disjoint tables (all still inside next_level's range).
            for info in write_tables(self.grid, entries, self.key_size,
                                     self.value_size):
                bisect_insert(next_level, Table(
                    self.grid, info, self.key_size, self.value_size))
        release_table(self.grid, table)
        for t in overlapping:
            release_table(self.grid, t)

    # ------------------------------------------------------------ manifest

    def manifest_pack(self) -> bytes:
        """Serialize level structure (reference: manifest log replay)."""
        self.flush_memtable()
        parts = [struct.pack("<B", LSM_LEVELS)]
        for level in self.levels:
            parts.append(struct.pack("<I", len(level)))
            for table in level:
                parts.append(table.info.pack())
        return b"".join(parts)

    def manifest_restore(self, raw: bytes) -> None:
        (n_levels,) = struct.unpack_from("<B", raw)
        assert n_levels == LSM_LEVELS
        pos = 1
        self.levels = [[] for _ in range(LSM_LEVELS)]
        for level in range(n_levels):
            (count,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            for _ in range(count):
                info, pos = TableInfo.unpack(raw, pos)
                self.levels[level].append(Table(
                    self.grid, info, self.key_size, self.value_size))
        self.memtable.clear()


def bisect_insert(level: list[Table], table: Table) -> None:
    """Keep levels ordered by key_min (disjoint above L0)."""
    i = 0
    while i < len(level) and level[i].info.key_min < table.info.key_min:
        i += 1
    level.insert(i, table)
