"""Scan trees: ordered, seekable range scans over LSM trees, with union
and intersection combinators.

reference: src/lsm/scan_tree.zig (per-tree merge of memtable + every
on-disk level), scan_merge.zig (k-way union / zig-zag intersection across
scans), scan_builder.zig (composing index conditions), scan_lookup.zig
(resolving matched keys to objects). composite_key.zig's encoding lives in
`composite_key` here: secondary index keys are (field prefix ||
timestamp), so one prefix's matches are a contiguous, timestamp-ordered
key range.
"""

from __future__ import annotations

import bisect
from typing import Iterator, Optional

from .k_way_merge import k_way_merge
from .table import TOMBSTONE
from .tree import Tree
from .zig_zag_merge import zig_zag_intersect


def composite_key(prefix: int, timestamp: int, prefix_size: int) -> bytes:
    """(field value, timestamp) -> big-endian index key (reference:
    src/lsm/composite_key.zig — prefix-major so one field value's matches
    sort by timestamp)."""
    return (prefix.to_bytes(prefix_size, "big")
            + timestamp.to_bytes(8, "big"))


def composite_key_timestamp(key: bytes) -> int:
    return int.from_bytes(key[-8:], "big")


class TreeScan:
    """Seekable ascending scan of one tree over [key_min, key_max].

    Sources: the memtable plus every table whose range intersects; merged
    lazily with newest-first dedupe; tombstones are filtered. Implements
    the SeekableStream protocol for zig-zag intersection."""

    def __init__(self, tree: Tree, key_min: bytes, key_max: bytes,
                 snapshot: Optional[int] = None):
        self.tree = tree
        self.key_min = key_min
        self.key_max = key_max
        # snapshot=None scans the latest state (memtable included);
        # snapshot=s scans the table set visible at op s — the view stays
        # consistent while compaction mutates the levels mid-scan
        # (reference: scans pin a snapshot in manifest_level.zig).
        self.snapshot = snapshot
        self._head: Optional[tuple] = None
        self._exhausted = False
        self._iter = self._merged(key_min)
        self._advance()

    def _sources(self, start: bytes):
        sources = []
        if self.snapshot is None:
            sources.append(sorted(
                (k, v) for k, v in self.tree.memtable.items()
                if start <= k <= self.key_max))
        if self.tree._frozen_visible(self.snapshot):
            # The frozen memtable is table-visible from its freeze op on,
            # even while its flush job is still streaming it out.
            sources.append(sorted(
                (k, v) for k, v in self.tree.immutable_map.items()
                if start <= k <= self.key_max))
        # Levels newest-first; within L0, newest table first (L0 overlaps).
        for level_i, level in enumerate(self.tree.levels):
            entries = level.visible(self.snapshot)
            tables = [e.table for e in
                      (reversed(entries) if level_i == 0 else entries)]
            for table in tables:
                if (table.info.key_max < start
                        or table.info.key_min > self.key_max):
                    continue
                sources.append(_table_range(table, start, self.key_max))
        return sources

    def _merged(self, start: bytes) -> Iterator[tuple]:
        dead = TOMBSTONE * self.tree.value_size
        for key, value in k_way_merge(self._sources(start)):
            if value != dead:
                yield key, value

    def _advance(self) -> None:
        self._head = next(self._iter, None)
        if self._head is None:
            self._exhausted = True

    # ------------------------------------------------- SeekableStream API

    def peek(self) -> Optional[bytes]:
        return self._head[0] if self._head is not None else None

    def peek_value(self) -> Optional[bytes]:
        return self._head[1] if self._head is not None else None

    def next(self) -> None:
        self._advance()

    def seek(self, key: bytes) -> None:
        """Advance to the first key >= `key` (zig-zag leapfrog). Rebuilds
        the merge from the target — each source binary-searches, so a seek
        is O(sources * log n), not a linear drain. Seek only moves forward:
        an exhausted scan stays exhausted (SeekableStream contract)."""
        if self._exhausted or (self._head is not None
                               and self._head[0] >= key):
            return
        self._iter = self._merged(key)
        self._advance()

    def __iter__(self) -> Iterator[tuple]:
        while self._head is not None:
            item = self._head
            self._advance()
            yield item


def _table_range(table, key_min: bytes, key_max: bytes) -> Iterator[tuple]:
    """Lazy (key, value) stream of one table clipped to [key_min, key_max]
    (binary search to the starting block, reference: binary_search.zig)."""
    start_block = max(
        0, bisect.bisect_right(table.block_first_keys, key_min) - 1)
    for i in range(start_block, len(table.block_addresses)):
        if table.block_first_keys[i] > key_max:
            return
        keys, values = table._block_entries(i)
        j = bisect.bisect_left(keys, key_min)
        for key, value in zip(keys[j:], values[j:]):
            if key > key_max:
                return
            yield key, value


def union_scans(scans: list[TreeScan]) -> Iterator[tuple]:
    """Ascending union (OR) of scans, deduplicated by key (reference:
    scan_merge.zig k-way union — e.g. debits OR credits)."""
    return k_way_merge([iter(s) for s in scans])


def intersect_scans(scans: list[TreeScan]) -> Iterator[bytes]:
    """Ascending intersection (AND) via zig-zag leapfrog."""
    return zig_zag_intersect(scans)


def intersect_by_suffix(scans: list[TreeScan]) -> Iterator[int]:
    """Intersect composite-key scans on their TIMESTAMP suffix: each scan
    covers one field prefix's contiguous range, so the suffix stream stays
    ascending and zig-zag applies (reference: multi-index queries join on
    timestamp, src/lsm/scan_builder.zig)."""

    class _Suffix:
        def __init__(self, scan: TreeScan):
            self.scan = scan

        def peek(self):
            head = self.scan.peek()
            return None if head is None else head[-8:]

        def next(self):
            self.scan.next()

        def seek(self, suffix: bytes) -> None:
            head = self.scan.peek()
            if head is None:
                return
            self.scan.seek(head[:-8] + suffix)

    for suffix in zig_zag_intersect([_Suffix(s) for s in scans]):
        yield int.from_bytes(suffix, "big")
