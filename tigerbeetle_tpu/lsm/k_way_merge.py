"""Streaming k-way merge of sorted (key, value) iterators.

reference: src/lsm/k_way_merge.zig — the merge engine under compaction and
scans. Sources are ordered by precedence (lower index = newer): when
several sources yield the same key, the newest wins and the rest are
consumed (the reference's deduplication for mutable-beats-immutable).
"""

from __future__ import annotations

import heapq
from typing import Iterable, Iterator, Optional


def k_way_merge(sources: list[Iterable], *,
                reverse: bool = False) -> Iterator[tuple]:
    """Merge sorted (key, value) streams; on duplicate keys the
    lowest-index source wins. `reverse=True` merges descending streams."""
    heap: list = []
    iters = [iter(s) for s in sources]
    sign = -1 if reverse else 1

    def push(i: int) -> None:
        for key, value in iters[i]:
            heapq.heappush(heap, (_Key(key, sign), i, value))
            return

    for i in range(len(iters)):
        push(i)
    last_key: Optional[bytes] = None
    while heap:
        wrapped, i, value = heapq.heappop(heap)
        push(i)
        if last_key is not None and wrapped.key == last_key:
            continue  # older duplicate: newest already emitted
        last_key = wrapped.key
        yield wrapped.key, value


class _Key:
    """Orders keys ascending or descending under one heap."""

    __slots__ = ("key", "sign")

    def __init__(self, key, sign: int):
        self.key = key
        self.sign = sign

    def __lt__(self, other: "_Key") -> bool:
        if self.sign > 0:
            return self.key < other.key
        return self.key > other.key

    def __eq__(self, other) -> bool:
        return self.key == other.key
