"""In-memory per-level manifest structure over (key range x snapshot range).

reference: src/lsm/manifest_level.zig (the two-dimensional table index a
tree's manifest keeps per level) + src/lsm/manifest.zig TableInfo's
snapshot_min/snapshot_max lifecycle. Every table entry carries the op at
which it became visible (snapshot_min) and the op at which compaction
removed it (snapshot_max, SNAPSHOT_LATEST while live). Removal keeps the
entry queryable for older snapshots: a scan or lookup pinned to snapshot s
sees exactly the tables with snapshot_min <= s < snapshot_max, so an
iterator opened before a compaction installs its outputs keeps reading a
consistent table set while the level mutates around it.

Physical block release is decoupled from logical removal (the reference
frees a removed table's blocks only once no live snapshot can reference
it): `prune(snapshot_oldest)` pops entries whose snapshot_max has fallen
behind the oldest snapshot the caller still serves, and the caller releases
their grid blocks. The tree prunes at bar boundaries with a one-bar
retention window — a pure function of the op sequence, so replicas release
byte-identical block sets (physical determinism, the load-bearing property
of docs/internals/lsm.md:37-91).

Containers are Python lists ordered by key_min (live set) — the by-design
substitution for the reference's segmented arrays (src/lsm/segmented_array
.zig); the history set (removed, unpruned) is small by construction (at
most one bar of removals) and scanned linearly.
"""

from __future__ import annotations

import bisect
import dataclasses
from typing import Iterator, Optional

SNAPSHOT_LATEST = (1 << 64) - 1


@dataclasses.dataclass
class LevelEntry:
    """One table's manifest entry (reference: manifest.zig TableInfo —
    address/checksum live in lsm/table.py's TableInfo; this adds the
    snapshot dimension). `seq` is the level-local insertion sequence:
    recency for overlapping level-0 tables is decided by it, never by
    snapshot_min (two tables can share a snapshot — e.g. a bar-boundary
    flush plus a checkpoint-time flush at the same op — and a restore
    must not re-derive recency from op numbers)."""

    table: object  # lsm.table.Table
    snapshot_min: int
    snapshot_max: int = SNAPSHOT_LATEST
    seq: int = 0

    @property
    def key_min(self) -> bytes:
        return self.table.info.key_min

    @property
    def key_max(self) -> bytes:
        return self.table.info.key_max

    def visible(self, snapshot: int) -> bool:
        return self.snapshot_min <= snapshot < self.snapshot_max


class ManifestLevel:
    """One level's table index.

    The LIVE set (snapshot_max == SNAPSHOT_LATEST) answers the serving
    path: kept sorted by key_min for disjoint levels (binary-searched
    lookups), in insertion order for level 0 (newest last — L0 tables
    overlap and recency decides). The HISTORY set holds removed entries
    until `prune`; snapshot queries merge both.

    The sequence protocol (len/iter/getitem/reversed) exposes live TABLES
    so existing consumers (scans, scrubber, tests) read the level as
    before.
    """

    def __init__(self, keep_sorted: bool):
        self.keep_sorted = keep_sorted
        self.live: list[LevelEntry] = []
        self.history: list[LevelEntry] = []
        self.next_seq = 0

    # ------------------------------------------------------------ mutation

    def insert(self, table, snapshot: int,
               seq: Optional[int] = None) -> None:
        if seq is None:
            seq = self.next_seq
        self.next_seq = max(self.next_seq, seq + 1)
        entry = LevelEntry(table=table, snapshot_min=snapshot, seq=seq)
        if self.keep_sorted:
            i = bisect.bisect_left(self.live, entry.key_min,
                                   key=lambda e: e.key_min)
            self.live.insert(i, entry)
        else:
            self.live.append(entry)

    def remove(self, table, snapshot: int) -> None:
        """Logical removal: the entry moves to history, visible to
        snapshots < `snapshot`, until pruned."""
        for i, e in enumerate(self.live):
            if e.table is table:
                e.snapshot_max = snapshot
                self.history.append(e)
                del self.live[i]
                return
        raise AssertionError("table not present in level")

    def prune(self, snapshot_oldest: int) -> list:
        """Pop history entries no snapshot >= snapshot_oldest can see;
        returns their tables for physical release."""
        dead = [e for e in self.history if e.snapshot_max <= snapshot_oldest]
        self.history = [e for e in self.history
                        if e.snapshot_max > snapshot_oldest]
        return [e.table for e in dead]

    # ------------------------------------------------------------- queries

    def visible(self, snapshot: Optional[int]) -> list[LevelEntry]:
        """Entries a snapshot sees, ordered like the live set (history
        entries merge in key order / recency order)."""
        if snapshot is None:
            return list(self.live)
        out = [e for e in self.live if e.visible(snapshot)]
        out.extend(e for e in self.history if e.visible(snapshot))
        if self.keep_sorted:
            out.sort(key=lambda e: e.key_min)
        else:
            out.sort(key=lambda e: e.seq)  # oldest first (scan reverses)
        return out

    def lookup(self, key: bytes, snapshot: Optional[int] = None):
        """Tables possibly containing `key`, newest-first. Disjoint levels
        at the latest snapshot binary-search the live set (the hot path);
        everything else filters linearly."""
        if snapshot is None and self.keep_sorted:
            i = bisect.bisect_right(self.live, key,
                                    key=lambda e: e.key_min) - 1
            if i >= 0 and key <= self.live[i].key_max:
                return [self.live[i].table]
            return []
        cands = [e for e in self.visible(snapshot)
                 if e.key_min <= key <= e.key_max]
        cands.sort(key=lambda e: -e.seq)  # newest insertion first
        return [e.table for e in cands]

    def query(self, key_min: bytes, key_max: bytes,
              snapshot: Optional[int] = None) -> list:
        """Tables intersecting [key_min, key_max] at `snapshot`, in the
        level's serving order."""
        return [e.table for e in self.visible(snapshot)
                if not (e.key_max < key_min or e.key_min > key_max)]

    # ------------------------------------------ sequence protocol (live)

    def __len__(self) -> int:
        return len(self.live)

    def __iter__(self) -> Iterator:
        return (e.table for e in self.live)

    def __reversed__(self) -> Iterator:
        return (e.table for e in reversed(self.live))

    def __getitem__(self, i):
        return self.live[i].table

    def entry_for(self, table) -> LevelEntry:
        for e in self.live:
            if e.table is table:
                return e
        raise AssertionError("table not present in level")
