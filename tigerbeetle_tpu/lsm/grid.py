"""Block grid: copy-on-write checksummed block store + free set.

reference: src/vsr/grid.zig (block addressing, cache) + src/vsr/free_set.zig
(EWAH-compressed allocation bitset with reserve/acquire determinism) +
docs/internals/data_file.md:30-44 (addresses are (index, checksum) pairs;
blocks are immutable once written — updates write NEW blocks and free the
old ones at checkpoint, which is what makes checkpoints atomic).

Simplification vs the reference: the block checksum is stored alongside the
address by the referring structure (same contract — a block is only
readable through its address+checksum pair), and block size defaults to
64 KiB (the reference uses 512 KiB; both are config)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from .. import ewah
from ..vsr.checksum import checksum

BLOCK_SIZE_DEFAULT = 64 * 1024


@dataclasses.dataclass(frozen=True)
class BlockAddress:
    index: int
    checksum: int

    def pack(self) -> bytes:
        return self.index.to_bytes(8, "little") + self.checksum.to_bytes(16, "little")

    @classmethod
    def unpack(cls, raw: bytes) -> "BlockAddress":
        return cls(int.from_bytes(raw[:8], "little"),
                   int.from_bytes(raw[8:24], "little"))


ADDRESS_SIZE = 24


class GridReservation:
    """A pre-claimed run of grid blocks (see Grid.reserve)."""

    __slots__ = ("grid", "indices", "cursor", "closed")

    def __init__(self, grid: "Grid", indices: list):
        self.grid = grid
        self.indices = indices
        self.cursor = 0
        self.closed = False

    def next_index(self) -> int:
        assert not self.closed, "reservation already forfeited"
        assert self.cursor < len(self.indices), \
            "reservation exhausted: worst-case bound was wrong"
        idx = self.indices[self.cursor]
        self.cursor += 1
        return idx

    def unused(self) -> list:
        return self.indices[self.cursor:]


class Grid:
    """Block store over a flat byte device (file or memory).

    Two-phase allocation like the reference free set (:28-35): blocks freed
    during a checkpoint interval stay unavailable until `checkpoint()` so
    crash recovery never sees a block overwritten mid-interval."""

    def __init__(self, device, block_size: int = BLOCK_SIZE_DEFAULT,
                 block_count: int = 4096, cache_sets: int = 64,
                 cache_ways: int = 8):
        from .cache_map import ObjectCache

        self.device = device  # .read(off, size) / .write(off, data)
        self.block_size = block_size
        self.block_count = block_count
        self.free: list[bool] = [True] * block_count
        # Bounded block cache (reference: the set-associative grid block
        # cache, src/vsr/grid.zig:30). Keys are (checksum, index), so a
        # freed-and-reused index can never serve stale bytes — blocks
        # are immutable under copy-on-write, making entries forever valid.
        self.cache = ObjectCache(sets=cache_sets, ways=cache_ways)
        # Standing missing-block hook (reference: grid_blocks_missing,
        # src/vsr/grid_blocks_missing.zig:24): the replica wires this to
        # its repair queue so ANY corrupt read — serving path included,
        # not just the scrubber's tour — queues a peer repair.
        self.on_corrupt = None
        self.freed_pending: list[int] = []  # released at next checkpoint
        self.acquire_cursor = 0
        # Live reservations (reserve() .. forfeit()): their unwritten
        # blocks are excluded from checkpointed free sets — a crash mid-
        # job must not leak them (the restored job re-reserves afresh).
        self._reservations: set = set()
        # Read-ahead in flight: key -> (device token, size). Submitted by
        # prefetch_async (compaction input lookahead), consumed by the
        # next read of the same block — the IO runs while the replica
        # keeps computing (reference: all reads are issued concurrently
        # through io_uring and the event loop continues,
        # src/storage.zig:177 + src/io/linux.zig).
        self._inflight: dict[int, tuple] = {}  # key -> (token, size);
        # dict insertion order IS submission order (oldest first).
        self._discard_pending: list[tuple] = []  # evicted, not yet freed
        self.prefetch_inflight_max = 256
        self.prefetched = 0  # blocks submitted, lifetime
        self.prefetch_hits = 0  # reads served from a VALIDATED read-ahead
        self.prefetch_evicted = 0  # dead entries discarded to make room

    # ------------------------------------------------------------ alloc

    def acquire(self) -> int:
        """Deterministic first-free-from-cursor allocation."""
        for _ in range(self.block_count):
            idx = self.acquire_cursor % self.block_count
            self.acquire_cursor += 1
            if self.free[idx]:
                self.free[idx] = False
                return idx
        raise RuntimeError("grid full")

    # Two-stage reserve/acquire (reference: src/vsr/free_set.zig:28-35):
    # a long-running job claims its WORST-CASE block count up front, then
    # acquires from its reservation as it writes, and forfeits the unused
    # remainder at completion. Guarantees (a) a job can never die of
    # "grid full" mid-write, and (b) allocation stays deterministic no
    # matter how concurrent jobs interleave their writes.

    def reserve(self, count: int) -> "GridReservation":
        indices = []
        try:
            for _ in range(count):
                indices.append(self.acquire())
        except RuntimeError:
            for idx in indices:  # all-or-nothing
                self.free[idx] = True
            raise RuntimeError(
                f"grid cannot reserve {count} blocks (full)")
        res = GridReservation(self, indices)
        self._reservations.add(res)
        return res

    def forfeit(self, reservation: "GridReservation") -> None:
        """Return a reservation's unwritten blocks to the free set (they
        were never written, so immediate reuse is crash-safe)."""
        for idx in reservation.unused():
            assert not self.free[idx]
            self.free[idx] = True
        reservation.closed = True
        self._reservations.discard(reservation)

    def release(self, index: int) -> None:
        """Free a block at the NEXT checkpoint (two-phase, crash-safe)."""
        assert not self.free[index]
        self.freed_pending.append(index)

    def checkpoint_free_set(self) -> bytes:
        """Apply pending frees and serialize the free set (EWAH). Live
        reservations serialize as FREE in their entirety — an incomplete
        job's blocks (written or not) are referenced by no manifest
        (tables install and manifests pack only after a job drains), so
        a crash must not leak them: the restored job re-reserves and
        rewrites from scratch."""
        for idx in self.freed_pending:
            self.free[idx] = True
        self.freed_pending.clear()
        self.acquire_cursor = 0
        bits = list(self.free)
        for res in self._reservations:
            for idx in res.indices:
                assert not bits[idx]
                bits[idx] = True
        return ewah.encode_bitset(bits)

    def restore_free_set(self, blob: bytes) -> None:
        bits = ewah.decode_bitset(blob)
        assert len(bits) == self.block_count
        self.free = bits
        self.freed_pending.clear()
        self.acquire_cursor = 0
        self._reservations.clear()

    # ------------------------------------------------------------- blocks

    def write_block(self, data: bytes,
                    reservation: "GridReservation" = None) -> BlockAddress:
        assert len(data) <= self.block_size
        index = (self.acquire() if reservation is None
                 else reservation.next_index())
        self.device.write(index * self.block_size, data)
        address = BlockAddress(index, checksum(data, domain=b"blk"))
        self.cache.put((address.checksum << 64) | index, data)
        return address

    def prefetch_async(self, reqs: list) -> int:
        """Fire-and-continue block read-ahead: submit device reads for
        the cache-missing blocks in `reqs` [(address, size)] and return
        immediately; a later read_block/read_blocks of the same block
        collects the completed data instead of touching the device.
        No-ops (returns 0) on devices without read_submit — the
        deterministic simulator stays strictly synchronous."""
        submit = getattr(self.device, "read_submit", None)
        if submit is None:
            return 0
        wanted = []
        seen: set = set()
        for address, size in reqs:
            key = (address.checksum << 64) | address.index
            # Dedupe within the call too: many lookup keys map to ONE
            # value block; a duplicate submit would orphan the first
            # token in the engine forever.
            if key in self._inflight or key in seen:
                continue
            if len(wanted) >= self.prefetch_inflight_max:
                break
            cached = self.cache.get(key)
            if cached is not None and len(cached) == size:
                continue
            seen.add(key)
            wanted.append((key, address, size))
        if not wanted:
            return 0
        # Make room by discarding the OLDEST in-flight entries (fetched
        # and dropped, so the engine record is freed): superset
        # lookaheads for keys that resolved early would otherwise pin
        # dead entries until the cap silently disabled read-ahead.
        overflow = len(self._inflight) + len(wanted) \
            - self.prefetch_inflight_max
        if overflow > 0:
            self._evict_inflight(overflow)
        tokens = submit([(a.index * self.block_size, s)
                         for _, a, s in wanted])
        if tokens is None:
            return 0
        for (key, _, size), token in zip(wanted, tokens):
            self._inflight[key] = (token, size)
        self.prefetched += len(wanted)
        return len(wanted)

    def _evict_inflight(self, count: int) -> None:
        """Drop the OLDEST in-flight entries (dict order = submission
        order). Their engine records are freed LATER, at the next
        collect (which already blocks on a fetch by nature) — the
        submit path stays fire-and-continue even when an evicted
        entry's IO hasn't completed yet."""
        import itertools

        for key in list(itertools.islice(self._inflight, count)):
            self._discard_pending.append(self._inflight.pop(key))
            self.prefetch_evicted += 1
        # Backstop: if collects never run (all read-ahead went dead),
        # don't let deferred discards pin unbounded engine records.
        if len(self._discard_pending) >= self.prefetch_inflight_max:
            self._drain_discards()

    def _drain_discards(self) -> None:
        """Free engine records of evicted entries. Called right after a
        blocking collect: by then the (older) evicted reads have almost
        always completed, so the fetch-and-drop rarely waits."""
        while self._discard_pending:
            # FIFO: the oldest eviction was submitted earliest and is
            # the most likely to have completed — freeing it first
            # keeps this drain (on the collect path) from waiting on
            # the freshest in-flight read.
            token, sz = self._discard_pending.pop(0)
            try:
                self.device.read_fetch(token, sz)
            except OSError:
                pass

    def _take_inflight(self, key: int, address: BlockAddress, size: int):
        """Collect a completed, CHECKSUM-VALIDATED read-ahead for `key`,
        or None (caller reads synchronously). A stale buffer — the
        extent was freed and rewritten after submit — fails validation
        here and the sync re-read takes over; correctness never rests
        on the read-ahead, and only validated data counts as a hit."""
        entry = self._inflight.pop(key, None)
        if entry is None:
            return None
        token, sz = entry
        if sz != size:
            self._discard_pending.append((token, sz))
            self._drain_discards()
            return None
        try:
            data = self.device.read_fetch(token, sz)
        except OSError:
            return None
        finally:
            self._drain_discards()
        if len(data) != size or \
                checksum(data, domain=b"blk") != address.checksum:
            return None
        self.prefetch_hits += 1
        return data

    def read_block(self, address: BlockAddress, size: int,
                   bypass_cache: bool = False) -> bytes:
        """bypass_cache: the scrubber's latent-fault tour must touch the
        MEDIA, not the cache (reference: scrub reads skip the block
        cache so cached copies can't mask sector rot)."""
        key = (address.checksum << 64) | address.index
        if not bypass_cache:
            cached = self.cache.get(key)
            if cached is not None and len(cached) == size:
                return cached
            data = self._take_inflight(key, address, size)
            if data is not None:
                self.cache.put(key, data)
                return data
        data = self.device.read(address.index * self.block_size, size)
        if checksum(data, domain=b"blk") != address.checksum:
            if self.on_corrupt is not None:
                self.on_corrupt(address, size)
            raise IOError(f"grid block {address.index} corrupt")
        self.cache.put(key, data)
        return data

    def read_blocks(self, reqs: list) -> list:
        """Batched point reads: all cache misses are issued as ONE
        concurrent fan-out to the device (reference: the prefetch
        fan-out, src/lsm/groove.zig:996,1339). reqs: [(address, size)];
        returns the block bytes in request order."""
        out: list = [None] * len(reqs)
        # Requesters per unique missing block (a clustered key batch maps
        # many keys to ONE value block — read it once, not per key).
        misses: dict = {}
        for i, (address, size) in enumerate(reqs):
            key = (address.checksum << 64) | address.index
            cached = self.cache.get(key)
            if cached is not None and len(cached) == size:
                out[i] = cached
                continue
            if (address, size) not in misses:
                data = self._take_inflight(key, address, size)
                if data is not None:
                    self.cache.put(key, data)
                    out[i] = data
                    continue
            misses.setdefault((address, size), []).append(i)
        if misses:
            unique = list(misses)
            batch = getattr(self.device, "read_batch", None)
            extents = [(address.index * self.block_size, size)
                       for address, size in unique]
            datas = (batch(extents) if batch is not None else
                     [self.device.read(off, size) for off, size in extents])
            for (address, size), data in zip(unique, datas):
                if checksum(data, domain=b"blk") != address.checksum:
                    if self.on_corrupt is not None:
                        self.on_corrupt(address, size)
                    raise IOError(f"grid block {address.index} corrupt")
                self.cache.put((address.checksum << 64) | address.index, data)
                for i in misses[(address, size)]:
                    out[i] = data
        return out


class MemoryDevice:
    def __init__(self, size: int):
        self.data = bytearray(size)

    def read(self, off: int, size: int) -> bytes:
        return bytes(self.data[off:off + size])

    def write(self, off: int, data: bytes) -> None:
        self.data[off:off + len(data)] = data


class FileDevice:
    def __init__(self, path: str, create: bool = False):
        import os

        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self.fd = os.open(path, flags, 0o644)

    def read(self, off: int, size: int) -> bytes:
        import os

        data = os.pread(self.fd, size, off)
        return data + b"\x00" * (size - len(data))

    def write(self, off: int, data: bytes) -> None:
        import os

        os.pwrite(self.fd, data, off)

    def close(self) -> None:
        import os

        os.close(self.fd)
