"""Unified grid-block header (reference: src/lsm/schema.zig:624 — every
grid block is self-describing, so inspect/repair tooling can classify
any block from its bytes alone, and a reader that follows a wrong
address fails LOUDLY on the kind check instead of misparsing).

Layout (16 bytes, little-endian), before the block payload:

    magic       u32   0x54424C4B ("TBLK")
    kind        u8    BlockKind
    version     u8    format version (1)
    tree_id     u16   owning tree (0 = none/standalone)
    payload_len u32   exact payload byte length
    reserved    u32   zero

The block checksum (BlockAddress.checksum, keyed BLAKE2b over the FULL
block including this header) remains the integrity boundary; the header
is the classification boundary.
"""

from __future__ import annotations

import enum
import struct

MAGIC = 0x54424C4B  # "TBLK"
VERSION = 1
BLOCK_HEADER_SIZE = 16
_FMT = struct.Struct("<IBBHII")
assert _FMT.size == BLOCK_HEADER_SIZE


class BlockKind(enum.IntEnum):
    value = 1      # sorted (key, value) entries (lsm/table.py)
    index = 2      # a table's value-block directory (lsm/table.py)
    manifest = 3   # checkpoint manifest chain link (lsm/forest.py)


def wrap(kind: BlockKind, payload: bytes, tree_id: int = 0) -> bytes:
    return _FMT.pack(MAGIC, int(kind), VERSION, tree_id,
                     len(payload), 0) + payload


def unwrap(raw: bytes, kind: BlockKind) -> bytes:
    """Validate the header and return the payload. Raises ValueError on
    any mismatch — a misdirected or misclassified block must never be
    silently misparsed."""
    if len(raw) < BLOCK_HEADER_SIZE:
        raise ValueError(f"block shorter than header ({len(raw)} B)")
    magic, got_kind, version, _tree_id, payload_len, _ = _FMT.unpack_from(raw)
    if magic != MAGIC:
        raise ValueError(f"bad block magic {magic:#x}")
    if version != VERSION:
        raise ValueError(f"unknown block version {version}")
    if got_kind != int(kind):
        raise ValueError(
            f"block kind {got_kind} where {int(kind)} expected")
    if BLOCK_HEADER_SIZE + payload_len > len(raw):
        raise ValueError("block payload_len exceeds block bytes")
    return raw[BLOCK_HEADER_SIZE:BLOCK_HEADER_SIZE + payload_len]


def classify(raw: bytes):
    """(BlockKind, tree_id, payload_len) of any block, or None if the
    bytes carry no valid header (inspect/devhub tooling)."""
    if len(raw) < BLOCK_HEADER_SIZE:
        return None
    magic, kind, version, tree_id, payload_len, _ = _FMT.unpack_from(raw)
    if magic != MAGIC or version != VERSION:
        return None
    if BLOCK_HEADER_SIZE + payload_len > len(raw):
        return None  # torn header: length does not fit the block
    try:
        return BlockKind(kind), tree_id, payload_len
    except ValueError:
        return None
