"""Bounded set-associative object cache.

The hot-object cache in front of the forest's object trees (reference:
src/lsm/set_associative_cache.zig:1 + src/lsm/cache_map.zig:1): a fixed
sets × ways grid of entries, LRU within each set. Memory is bounded by
construction — at most `sets * ways` cached objects, ever — which is the
static-allocation doctrine applied to the read path
(docs/ARCHITECTURE.md:189-230): serving state no longer needs to fit in
host RAM; misses fall through to the LSM.

Write discipline (reference: the groove object cache is written THROUGH
at commit, src/lsm/groove.zig:1770): mutated objects are upserted after
every durable flush, so a cached entry is always the current value —
reads never need invalidation logic.

Deliberate non-port: the reference CacheMap pairs the cache with a
"stash" map holding entries evicted mid-bar whose mutations are not yet
in the LSM, plus scope open/persist/discard for linked-chain rollback
(src/lsm/cache_map.zig:1-40). Here neither exists by design: mutations
reach this cache only AFTER the durable flush (the LSM below already
holds the truth, so an evicted entry is always re-readable), and
rollback scopes are resolved on device before anything is applied
(ops/create_kernels.py undo log) — there is no mid-bar mutable window
to stash.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

__all__ = ["ObjectCache"]

# Fibonacci hashing spreads sequential ids across sets
# (reference: set_associative_cache.zig uses a permuted tag hash).
_GOLDEN = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


class ObjectCache:
    """sets × ways bounded cache: key (u128 int) -> object."""

    def __init__(self, sets: int = 1024, ways: int = 8):
        assert sets > 0 and ways > 0
        self.n_sets = sets
        self.ways = ways
        self._sets: list[OrderedDict] = [OrderedDict() for _ in range(sets)]
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def capacity(self) -> int:
        return self.n_sets * self.ways

    def __len__(self) -> int:
        return sum(len(s) for s in self._sets)

    def _set_for(self, key: int) -> OrderedDict:
        h = ((key ^ (key >> 64)) * _GOLDEN) & _MASK64
        return self._sets[h % self.n_sets]

    def get(self, key: int):
        s = self._set_for(key)
        value = s.get(key)
        if value is None:
            self.misses += 1
            return None
        s.move_to_end(key)  # LRU within the set
        self.hits += 1
        return value

    def put(self, key: int, value) -> None:
        s = self._set_for(key)
        if key in s:
            s[key] = value
            s.move_to_end(key)
            return
        if len(s) >= self.ways:
            s.popitem(last=False)  # evict set-LRU
            self.evictions += 1
        s[key] = value

    def remove(self, key: int) -> None:
        self._set_for(key).pop(key, None)

    def clear(self) -> None:
        for s in self._sets:
            s.clear()

    def stats(self) -> dict:
        return dict(hits=self.hits, misses=self.misses,
                    evictions=self.evictions, size=len(self),
                    capacity=self.capacity)
