"""Zig-zag intersection of sorted key streams.

reference: src/lsm/zig_zag_merge.zig — multi-index query AND: instead of
materializing each index's matches, the streams leapfrog each other (each
seeks to the maximum head key), touching only O(result + seeks) entries.
Streams must expose `peek() -> key | None` and `seek(key)` (advance to the
first key >= target).
"""

from __future__ import annotations

from typing import Iterator, Protocol


class SeekableStream(Protocol):
    def peek(self): ...
    def seek(self, key) -> None: ...
    def next(self) -> None: ...


def zig_zag_intersect(streams: list) -> Iterator:
    """Yield keys present in EVERY stream, ascending."""
    if not streams:
        return
    while True:
        heads = []
        for stream in streams:
            head = stream.peek()
            if head is None:
                return  # any exhausted stream ends the intersection
            heads.append(head)
        target = max(heads)
        if all(h == target for h in heads):
            yield target
            for stream in streams:
                stream.next()
        else:
            for stream, head in zip(streams, heads):
                if head < target:
                    stream.seek(target)
