"""Forest query engine: secondary-index scans resolved to objects.

reference: src/lsm/scan_builder.zig (composing index conditions into
union/intersection scans) + scan_lookup.zig (resolving matched timestamps
to objects) as used by get_account_transfers / get_account_balances
(src/state_machine.zig:1737-1831). This is the on-disk query path over the
durable forest — it must return exactly what the state machine's in-memory
indexes return (differential-tested).
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..constants import TIMESTAMP_MAX
from ..types import (
    Account,
    AccountBalance,
    AccountFilter,
    AccountFilterFlags,
    AccountFlags,
    Operation,
    QueryFilter,
    QueryFilterFlags,
    Transfer,
)
from .forest import Forest
from .k_way_merge import k_way_merge
from .scan import TreeScan, composite_key

# QueryFilter condition fields -> (index tree suffix, prefix byte width).
_QUERY_FIELDS = (
    ("user_data_128", "ud128", 16),
    ("user_data_64", "ud64", 8),
    ("user_data_32", "ud32", 4),
    ("ledger", "ledger", 4),
    ("code", "code", 2),
)


def _transfer_matches(f: AccountFilter, t: Transfer) -> bool:
    """AccountFilter residual predicate (the conditions not served by the
    debits/credits index scan) — shared by transfers and balances."""
    if f.user_data_128 and t.user_data_128 != f.user_data_128:
        return False
    if f.user_data_64 and t.user_data_64 != f.user_data_64:
        return False
    if f.user_data_32 and t.user_data_32 != f.user_data_32:
        return False
    if f.code and t.code != f.code:
        return False
    return True


class ForestQuery:
    def __init__(self, forest: Forest):
        self.forest = forest

    # ---------------------------------------------------------- primitives

    def _index_scan(self, tree_name: str, prefix: int,
                    ts_min: int, ts_max: int) -> TreeScan:
        tree = self.forest.trees[tree_name]
        return TreeScan(
            tree,
            composite_key(prefix, ts_min, 16),
            composite_key(prefix, ts_max, 16))

    def transfer_by_timestamp(self, timestamp: int) -> Optional[Transfer]:
        tid = self.forest.trees["xfer_by_ts"].get(
            timestamp.to_bytes(8, "big"))
        if tid is None:
            return None
        raw = self.forest.trees["transfers"].get(tid)
        return None if raw is None else Transfer.unpack(raw)

    def account_by_timestamp(self, timestamp: int) -> Optional[Account]:
        aid = self.forest.trees["acct_by_ts"].get(
            timestamp.to_bytes(8, "big"))
        if aid is None:
            return None
        raw = self.forest.trees["accounts"].get(aid)
        return None if raw is None else Account.unpack(raw)

    # ------------------------------------------------------------- queries

    def account_transfer_timestamps(self, f: AccountFilter) -> Iterator[int]:
        """Ascending matching timestamps for an AccountFilter's
        debits/credits index conditions (the OR side; user_data/code
        predicates apply at lookup)."""
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        scans = []
        if f.flags & AccountFilterFlags.debits:
            scans.append(self._index_scan(
                "xfer_by_dr", f.account_id, ts_min, ts_max))
        if f.flags & AccountFilterFlags.credits:
            scans.append(self._index_scan(
                "xfer_by_cr", f.account_id, ts_min, ts_max))
        # Union on the timestamp suffix (dr and cr scans share the same
        # account prefix, so suffix order == key order).
        suffix_streams = [
            ((key[-8:], None) for key, _ in scan) for scan in scans]
        for suffix, _ in k_way_merge(suffix_streams):
            yield int.from_bytes(suffix, "big")

    def get_account_transfers(self, f: AccountFilter,
                              limit_cap: int = 0) -> list[Transfer]:
        """The reference query (src/state_machine.zig:3294-3310) served
        from the forest: filter validation -> index scan -> object lookup
        -> residual filters -> direction/limit. Must return exactly what
        the host-index path returns (differential-tested)."""
        from ..state_machine import OPERATION_SPECS, StateMachine

        if not StateMachine._account_filter_valid(f):
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_account_transfers].result_max()
        limit = min(f.limit, limit_cap)
        reverse = bool(f.flags & AccountFilterFlags.reversed)
        matches: list[Transfer] = []
        for timestamp in self.account_transfer_timestamps(f):
            t = self.transfer_by_timestamp(timestamp)
            if t is None or not _transfer_matches(f, t):
                continue
            matches.append(t)
            if not reverse and len(matches) >= limit:
                break  # ascending: the limit cuts the front of the stream
        if reverse:
            matches.reverse()
        return matches[:limit]

    def get_account_balances(self, f: AccountFilter,
                             limit_cap: int = 0) -> list[AccountBalance]:
        """Balance history from the events tree (reference:
        src/state_machine.zig:1568-1666 — the same transfer scan mapped
        through account_events rows; history-flagged accounts only)."""
        from ..state_machine import OPERATION_SPECS, StateMachine
        from ..vsr.durable import _unpack_event

        if not StateMachine._account_filter_valid(f):
            return []
        raw = self.forest.trees["accounts"].get(
            f.account_id.to_bytes(16, "big"))
        if raw is None:
            return []
        account = Account.unpack(raw)
        if not (account.flags & AccountFlags.history):
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_account_balances].result_max()
        limit = min(f.limit, limit_cap)
        events = self.forest.trees["events"]
        reverse = bool(f.flags & AccountFilterFlags.reversed)

        def balances():
            for timestamp in self.account_transfer_timestamps(f):
                raw_event = events.get(timestamp.to_bytes(8, "big"))
                if raw_event is None:
                    continue
                t = self.transfer_by_timestamp(timestamp)
                if t is None or not _transfer_matches(f, t):
                    continue
                rec = _unpack_event(raw_event)
                if rec.dr_account.id == f.account_id:
                    side = rec.dr_account
                elif rec.cr_account.id == f.account_id:
                    side = rec.cr_account
                else:
                    continue
                yield AccountBalance(
                    debits_pending=side.debits_pending,
                    debits_posted=side.debits_posted,
                    credits_pending=side.credits_pending,
                    credits_posted=side.credits_posted,
                    timestamp=timestamp,
                )

        if reverse:
            # The host path reverses the full match stream, then cuts.
            out = list(balances())
            out.reverse()
            return out[:limit]
        out = []
        for balance in balances():
            out.append(balance)
            if len(out) >= limit:
                break
        return out

    def _query_objects(self, f: QueryFilter, groove: str):
        """Matching objects for a QueryFilter over one groove, ascending
        (reference: src/state_machine.zig:2054-2124 — walk one condition
        index, or the timestamp tree when unconditioned; verify residual
        conditions on the object)."""
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        conds = [(attr, suffix, width)
                 for attr, suffix, width in _QUERY_FIELDS
                 if getattr(f, attr) != 0]
        prefix = "acct" if groove == "accounts" else "xfer"
        lookup = (self.account_by_timestamp if groove == "accounts"
                  else self.transfer_by_timestamp)
        if conds:
            attr, suffix, width = conds[0]
            tree = self.forest.trees[f"{prefix}_by_{suffix}"]
            scan = TreeScan(
                tree,
                composite_key(getattr(f, attr), ts_min, width),
                composite_key(getattr(f, attr), ts_max, width))
            candidates = (int.from_bytes(key[-8:], "big")
                          for key, _ in scan)
        else:
            tree = self.forest.trees[f"{prefix}_by_ts"]
            scan = TreeScan(tree, ts_min.to_bytes(8, "big"),
                            ts_max.to_bytes(8, "big"))
            candidates = (int.from_bytes(key, "big") for key, _ in scan)
        for timestamp in candidates:
            obj = lookup(timestamp)
            if obj is None:
                continue
            if any(getattr(obj, attr) != getattr(f, attr)
                   for attr, _, _ in conds):
                continue
            yield obj

    def _query(self, f: QueryFilter, groove: str, operation: Operation):
        from ..state_machine import OPERATION_SPECS, StateMachine

        if not StateMachine._query_filter_valid(f):
            return []
        limit = min(f.limit, OPERATION_SPECS[operation].result_max())
        if f.flags & QueryFilterFlags.reversed:
            matches = list(self._query_objects(f, groove))
            matches.reverse()
            return matches[:limit]
        matches = []
        for obj in self._query_objects(f, groove):
            matches.append(obj)
            if len(matches) >= limit:
                break  # ascending: stop at limit (host path does too)
        return matches

    def query_accounts(self, f: QueryFilter) -> list[Account]:
        return self._query(f, "accounts", Operation.query_accounts)

    def query_transfers(self, f: QueryFilter) -> list[Transfer]:
        return self._query(f, "transfers", Operation.query_transfers)

    def get_change_events(self, f, limit_cap: int = 0) -> list:
        """CDC query served from the forest's events tree (reference:
        src/state_machine.zig:3395-3528): range-scan account_events by
        timestamp, join transfer + both accounts from their object trees.
        Must return exactly what the host-index path returns."""
        from ..constants import TIMESTAMP_MAX as TS_MAX
        from ..state_machine import (
            OPERATION_SPECS,
            build_change_event,
        )
        from ..vsr.durable import _unpack_event

        valid = (
            f.limit != 0
            and (f.timestamp_min == 0 or 1 <= f.timestamp_min <= TS_MAX)
            and (f.timestamp_max == 0 or 1 <= f.timestamp_max <= TS_MAX)
            and (f.timestamp_max == 0 or f.timestamp_min <= f.timestamp_max)
        )
        if not valid:
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_change_events].result_max()
        limit = min(f.limit, limit_cap)
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TS_MAX
        scan = TreeScan(self.forest.trees["events"],
                        ts_min.to_bytes(8, "big"), ts_max.to_bytes(8, "big"))

        def account_by_id(aid: int) -> Account:
            raw = self.forest.trees["accounts"].get(aid.to_bytes(16, "big"))
            assert raw is not None, aid
            return Account.unpack(raw)

        out = []
        for _, value in scan:
            rec = _unpack_event(value)
            out.append(build_change_event(
                rec, self.transfer_by_timestamp, account_by_id))
            if len(out) >= limit:
                break
        return out

    def account_history_events(self, account_timestamp: int,
                               ts_min: int = 1,
                               ts_max: int = TIMESTAMP_MAX,
                               limit: int = 8190,
                               reverse: bool = False) -> list:
        """Balance-history rows of one history-flagged account, by the
        account_timestamp event index (reference: tree id 27,
        src/state_machine.zig:534-538 — "balance as-of" / "last time
        account=X was updated" queries). Returns AccountBalance rows of
        the requested side."""
        from ..types import AccountBalance
        from ..vsr.durable import _unpack_event

        events = self.forest.trees["events"]
        scan = TreeScan(
            self.forest.trees["ev_by_acct_ts"],
            composite_key(account_timestamp, ts_min, 8),
            composite_key(account_timestamp, ts_max, 8))
        # Index keys are cheap ints; unpack only the `limit` rows served.
        # (History rows are never prunable — both sides' flags gate the
        # prunable index — so every index key resolves to a row.)
        # Reverse keeps the LAST `limit` ascending keys via a bounded
        # deque: O(range) scan but O(limit) memory.
        from collections import deque

        keys = deque(maxlen=limit) if reverse else []
        for key, _ in scan:
            keys.append(int.from_bytes(key[-8:], "big"))
            if not reverse and len(keys) >= limit:
                break
        if reverse:
            keys = list(reversed(keys))
        rows = []
        for ets in keys:
            raw = events.get(ets.to_bytes(8, "big"))
            assert raw is not None, ets
            rec = _unpack_event(raw)
            side = (rec.dr_account
                    if rec.dr_account.timestamp == account_timestamp
                    else rec.cr_account)
            rows.append(AccountBalance(
                debits_pending=side.debits_pending,
                debits_posted=side.debits_posted,
                credits_pending=side.credits_pending,
                credits_posted=side.credits_posted,
                timestamp=ets))
        return rows

    def expiry_event_of_pending(self, pending_id: int):
        """The expiry event of a pending transfer, if it expired
        (reference: transfer_pending_id_expired index, tree id 31 —
        "when transfer=X has expired")."""
        from ..vsr.durable import _unpack_event

        scan = TreeScan(
            self.forest.trees["ev_by_pid_expired"],
            composite_key(pending_id, 1, 16),
            composite_key(pending_id, TIMESTAMP_MAX, 16))
        for key, _ in scan:
            raw = self.forest.trees["events"].get(key[-8:])
            if raw is not None:
                return _unpack_event(raw)
        return None

    def expired_events_by_account(self, account_id: int,
                                  side: str = "dr",
                                  limit: int = 8190) -> list:
        """Expiry events touching an account on the given side
        (reference: dr/cr_account_id_expired indexes, tree ids 29-30 —
        "all expired debits where account=X")."""
        from ..vsr.durable import _unpack_event

        assert side in ("dr", "cr")
        scan = TreeScan(
            self.forest.trees[f"ev_by_{side}_expired"],
            composite_key(account_id, 1, 16),
            composite_key(account_id, TIMESTAMP_MAX, 16))
        out = []
        for key, _ in scan:
            raw = self.forest.trees["events"].get(key[-8:])
            if raw is not None:
                out.append(_unpack_event(raw))
                if len(out) >= limit:
                    break
        return out

    def transfers_by_pending_id(self, pending_id: int) -> list[Transfer]:
        """Resolutions (posts/voids) of a pending transfer, ascending —
        served by the pending_id index tree (reference: the transfers
        groove's pending_id index)."""
        scan = TreeScan(
            self.forest.trees["xfer_by_pid"],
            composite_key(pending_id, 1, 16),
            composite_key(pending_id, TIMESTAMP_MAX, 16))
        out = []
        for key, _ in scan:
            t = self.transfer_by_timestamp(int.from_bytes(key[-8:], "big"))
            if t is not None:
                out.append(t)
        return out
