"""Forest query engine: secondary-index scans resolved to objects.

reference: src/lsm/scan_builder.zig (composing index conditions into
union/intersection scans) + scan_lookup.zig (resolving matched timestamps
to objects) as used by get_account_transfers / get_account_balances
(src/state_machine.zig:1737-1831). This is the on-disk query path over the
durable forest — it must return exactly what the state machine's in-memory
indexes return (differential-tested).
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..constants import TIMESTAMP_MAX
from ..types import AccountFilter, AccountFilterFlags, Operation, Transfer
from .forest import Forest
from .k_way_merge import k_way_merge
from .scan import TreeScan, composite_key


class ForestQuery:
    def __init__(self, forest: Forest):
        self.forest = forest

    # ---------------------------------------------------------- primitives

    def _index_scan(self, tree_name: str, prefix: int,
                    ts_min: int, ts_max: int) -> TreeScan:
        tree = self.forest.trees[tree_name]
        return TreeScan(
            tree,
            composite_key(prefix, ts_min, 16),
            composite_key(prefix, ts_max, 16))

    def transfer_by_timestamp(self, timestamp: int) -> Optional[Transfer]:
        tid = self.forest.trees["xfer_by_ts"].get(
            timestamp.to_bytes(8, "big"))
        if tid is None:
            return None
        raw = self.forest.trees["transfers"].get(tid)
        return None if raw is None else Transfer.unpack(raw)

    # ------------------------------------------------------------- queries

    def account_transfer_timestamps(self, f: AccountFilter) -> Iterator[int]:
        """Ascending matching timestamps for an AccountFilter's
        debits/credits index conditions (the OR side; user_data/code
        predicates apply at lookup)."""
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        scans = []
        if f.flags & AccountFilterFlags.debits:
            scans.append(self._index_scan(
                "xfer_by_dr", f.account_id, ts_min, ts_max))
        if f.flags & AccountFilterFlags.credits:
            scans.append(self._index_scan(
                "xfer_by_cr", f.account_id, ts_min, ts_max))
        # Union on the timestamp suffix (dr and cr scans share the same
        # account prefix, so suffix order == key order).
        suffix_streams = [
            ((key[-8:], None) for key, _ in scan) for scan in scans]
        for suffix, _ in k_way_merge(suffix_streams):
            yield int.from_bytes(suffix, "big")

    def get_account_transfers(self, f: AccountFilter,
                              limit_cap: int = 0) -> list[Transfer]:
        """The reference query (src/state_machine.zig:3294-3310) served
        from the forest: filter validation -> index scan -> object lookup
        -> residual filters -> direction/limit. Must return exactly what
        the host-index path returns (differential-tested)."""
        from ..state_machine import OPERATION_SPECS, StateMachine

        if not StateMachine._account_filter_valid(f):
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_account_transfers].result_max()
        limit = min(f.limit, limit_cap)
        reverse = bool(f.flags & AccountFilterFlags.reversed)
        matches: list[Transfer] = []
        for timestamp in self.account_transfer_timestamps(f):
            t = self.transfer_by_timestamp(timestamp)
            if t is None:
                continue
            if f.user_data_128 and t.user_data_128 != f.user_data_128:
                continue
            if f.user_data_64 and t.user_data_64 != f.user_data_64:
                continue
            if f.user_data_32 and t.user_data_32 != f.user_data_32:
                continue
            if f.code and t.code != f.code:
                continue
            matches.append(t)
            if not reverse and len(matches) >= limit:
                break  # ascending: the limit cuts the front of the stream
        if reverse:
            matches.reverse()
        return matches[:limit]
