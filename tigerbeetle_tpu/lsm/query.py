"""Forest query engine: secondary-index scans resolved to objects.

reference: src/lsm/scan_builder.zig (composing index conditions into
union/intersection scans) + scan_lookup.zig (resolving matched timestamps
to objects) as used by get_account_transfers / get_account_balances
(src/state_machine.zig:1737-1831). This is the on-disk query path over the
durable forest — it must return exactly what the state machine's in-memory
indexes return (differential-tested).
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..constants import TIMESTAMP_MAX
from ..types import (
    Account,
    AccountBalance,
    AccountFilter,
    AccountFilterFlags,
    AccountFlags,
    Operation,
    QueryFilter,
    QueryFilterFlags,
    Transfer,
)
from .forest import Forest
from .k_way_merge import k_way_merge
from .scan import TreeScan, composite_key

# QueryFilter condition fields -> (index tree suffix, prefix byte width).
_QUERY_FIELDS = (
    ("user_data_128", "ud128", 16),
    ("user_data_64", "ud64", 8),
    ("user_data_32", "ud32", 4),
    ("ledger", "ledger", 4),
    ("code", "code", 2),
)


def _transfer_matches(f: AccountFilter, t: Transfer) -> bool:
    """AccountFilter residual predicate (the conditions not served by the
    debits/credits index scan) — shared by transfers and balances."""
    if f.user_data_128 and t.user_data_128 != f.user_data_128:
        return False
    if f.user_data_64 and t.user_data_64 != f.user_data_64:
        return False
    if f.user_data_32 and t.user_data_32 != f.user_data_32:
        return False
    if f.code and t.code != f.code:
        return False
    return True


class ForestQuery:
    def __init__(self, forest: Forest):
        self.forest = forest

    # ---------------------------------------------------------- primitives

    def _index_scan(self, tree_name: str, prefix: int,
                    ts_min: int, ts_max: int) -> TreeScan:
        tree = self.forest.trees[tree_name]
        return TreeScan(
            tree,
            composite_key(prefix, ts_min, 16),
            composite_key(prefix, ts_max, 16))

    def transfer_by_timestamp(self, timestamp: int) -> Optional[Transfer]:
        tid = self.forest.trees["xfer_by_ts"].get(
            timestamp.to_bytes(8, "big"))
        if tid is None:
            return None
        raw = self.forest.trees["transfers"].get(tid)
        return None if raw is None else Transfer.unpack(raw)

    def account_by_timestamp(self, timestamp: int) -> Optional[Account]:
        aid = self.forest.trees["acct_by_ts"].get(
            timestamp.to_bytes(8, "big"))
        if aid is None:
            return None
        raw = self.forest.trees["accounts"].get(aid)
        return None if raw is None else Account.unpack(raw)

    # ------------------------------------------------------------- queries

    def account_transfer_timestamps(self, f: AccountFilter) -> Iterator[int]:
        """Ascending matching timestamps for an AccountFilter's
        debits/credits index conditions (the OR side; user_data/code
        predicates apply at lookup)."""
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        scans = []
        if f.flags & AccountFilterFlags.debits:
            scans.append(self._index_scan(
                "xfer_by_dr", f.account_id, ts_min, ts_max))
        if f.flags & AccountFilterFlags.credits:
            scans.append(self._index_scan(
                "xfer_by_cr", f.account_id, ts_min, ts_max))
        # Union on the timestamp suffix (dr and cr scans share the same
        # account prefix, so suffix order == key order).
        suffix_streams = [
            ((key[-8:], None) for key, _ in scan) for scan in scans]
        for suffix, _ in k_way_merge(suffix_streams):
            yield int.from_bytes(suffix, "big")

    def get_account_transfers(self, f: AccountFilter,
                              limit_cap: int = 0) -> list[Transfer]:
        """The reference query (src/state_machine.zig:3294-3310) served
        from the forest: filter validation -> index scan -> object lookup
        -> residual filters -> direction/limit. Must return exactly what
        the host-index path returns (differential-tested)."""
        from ..state_machine import OPERATION_SPECS, StateMachine

        if not StateMachine._account_filter_valid(f):
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_account_transfers].result_max()
        limit = min(f.limit, limit_cap)
        reverse = bool(f.flags & AccountFilterFlags.reversed)
        matches: list[Transfer] = []
        for timestamp in self.account_transfer_timestamps(f):
            t = self.transfer_by_timestamp(timestamp)
            if t is None or not _transfer_matches(f, t):
                continue
            matches.append(t)
            if not reverse and len(matches) >= limit:
                break  # ascending: the limit cuts the front of the stream
        if reverse:
            matches.reverse()
        return matches[:limit]

    def get_account_balances(self, f: AccountFilter,
                             limit_cap: int = 0) -> list[AccountBalance]:
        """Balance history from the events tree (reference:
        src/state_machine.zig:1568-1666 — the same transfer scan mapped
        through account_events rows; history-flagged accounts only)."""
        from ..state_machine import OPERATION_SPECS, StateMachine
        from ..vsr.durable import _unpack_event

        if not StateMachine._account_filter_valid(f):
            return []
        raw = self.forest.trees["accounts"].get(
            f.account_id.to_bytes(16, "big"))
        if raw is None:
            return []
        account = Account.unpack(raw)
        if not (account.flags & AccountFlags.history):
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_account_balances].result_max()
        limit = min(f.limit, limit_cap)
        events = self.forest.trees["events"]
        reverse = bool(f.flags & AccountFilterFlags.reversed)

        def balances():
            for timestamp in self.account_transfer_timestamps(f):
                raw_event = events.get(timestamp.to_bytes(8, "big"))
                if raw_event is None:
                    continue
                t = self.transfer_by_timestamp(timestamp)
                if t is None or not _transfer_matches(f, t):
                    continue
                rec = _unpack_event(raw_event)
                if rec.dr_account.id == f.account_id:
                    side = rec.dr_account
                elif rec.cr_account.id == f.account_id:
                    side = rec.cr_account
                else:
                    continue
                yield AccountBalance(
                    debits_pending=side.debits_pending,
                    debits_posted=side.debits_posted,
                    credits_pending=side.credits_pending,
                    credits_posted=side.credits_posted,
                    timestamp=timestamp,
                )

        if reverse:
            # The host path reverses the full match stream, then cuts.
            out = list(balances())
            out.reverse()
            return out[:limit]
        out = []
        for balance in balances():
            out.append(balance)
            if len(out) >= limit:
                break
        return out

    def _query_objects(self, f: QueryFilter, groove: str):
        """Matching objects for a QueryFilter over one groove, ascending
        (reference: src/state_machine.zig:2054-2124 — walk one condition
        index, or the timestamp tree when unconditioned; verify residual
        conditions on the object)."""
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TIMESTAMP_MAX
        conds = [(attr, suffix, width)
                 for attr, suffix, width in _QUERY_FIELDS
                 if getattr(f, attr) != 0]
        prefix = "acct" if groove == "accounts" else "xfer"
        lookup = (self.account_by_timestamp if groove == "accounts"
                  else self.transfer_by_timestamp)
        if conds:
            attr, suffix, width = conds[0]
            tree = self.forest.trees[f"{prefix}_by_{suffix}"]
            scan = TreeScan(
                tree,
                composite_key(getattr(f, attr), ts_min, width),
                composite_key(getattr(f, attr), ts_max, width))
            candidates = (int.from_bytes(key[-8:], "big")
                          for key, _ in scan)
        else:
            tree = self.forest.trees[f"{prefix}_by_ts"]
            scan = TreeScan(tree, ts_min.to_bytes(8, "big"),
                            ts_max.to_bytes(8, "big"))
            candidates = (int.from_bytes(key, "big") for key, _ in scan)
        for timestamp in candidates:
            obj = lookup(timestamp)
            if obj is None:
                continue
            if any(getattr(obj, attr) != getattr(f, attr)
                   for attr, _, _ in conds):
                continue
            yield obj

    def _query(self, f: QueryFilter, groove: str, operation: Operation):
        from ..state_machine import OPERATION_SPECS, StateMachine

        if not StateMachine._query_filter_valid(f):
            return []
        limit = min(f.limit, OPERATION_SPECS[operation].result_max())
        if f.flags & QueryFilterFlags.reversed:
            matches = list(self._query_objects(f, groove))
            matches.reverse()
            return matches[:limit]
        matches = []
        for obj in self._query_objects(f, groove):
            matches.append(obj)
            if len(matches) >= limit:
                break  # ascending: stop at limit (host path does too)
        return matches

    def query_accounts(self, f: QueryFilter) -> list[Account]:
        return self._query(f, "accounts", Operation.query_accounts)

    def query_transfers(self, f: QueryFilter) -> list[Transfer]:
        return self._query(f, "transfers", Operation.query_transfers)

    def get_change_events(self, f, limit_cap: int = 0) -> list:
        """CDC query served from the forest's events tree (reference:
        src/state_machine.zig:3395-3528): range-scan account_events by
        timestamp, join transfer + both accounts from their object trees.
        Must return exactly what the host-index path returns."""
        from ..constants import TIMESTAMP_MAX as TS_MAX
        from ..state_machine import (
            OPERATION_SPECS,
            build_change_event,
        )
        from ..vsr.durable import _unpack_event

        valid = (
            f.limit != 0
            and (f.timestamp_min == 0 or 1 <= f.timestamp_min <= TS_MAX)
            and (f.timestamp_max == 0 or 1 <= f.timestamp_max <= TS_MAX)
            and (f.timestamp_max == 0 or f.timestamp_min <= f.timestamp_max)
        )
        if not valid:
            return []
        if not limit_cap:
            limit_cap = OPERATION_SPECS[
                Operation.get_change_events].result_max()
        limit = min(f.limit, limit_cap)
        ts_min = f.timestamp_min or 1
        ts_max = f.timestamp_max or TS_MAX
        scan = TreeScan(self.forest.trees["events"],
                        ts_min.to_bytes(8, "big"), ts_max.to_bytes(8, "big"))

        def account_by_id(aid: int) -> Account:
            raw = self.forest.trees["accounts"].get(aid.to_bytes(16, "big"))
            assert raw is not None, aid
            return Account.unpack(raw)

        out = []
        for _, value in scan:
            rec = _unpack_event(value)
            out.append(build_change_event(
                rec, self.transfer_by_timestamp, account_by_id))
            if len(out) >= limit:
                break
        return out

    def transfers_by_pending_id(self, pending_id: int) -> list[Transfer]:
        """Resolutions (posts/voids) of a pending transfer, ascending —
        served by the pending_id index tree (reference: the transfers
        groove's pending_id index)."""
        scan = TreeScan(
            self.forest.trees["xfer_by_pid"],
            composite_key(pending_id, 1, 16),
            composite_key(pending_id, TIMESTAMP_MAX, 16))
        out = []
        for key, _ in scan:
            t = self.transfer_by_timestamp(int.from_bytes(key[-8:], "big"))
            if t is not None:
                out.append(t)
        return out
