"""Immutable sorted tables serialized into grid blocks.

reference: src/lsm/table.zig (index block + value blocks) +
src/lsm/table_memory.zig. A table is one sorted run of fixed-size
(key, value) entries: value blocks hold the entries, the index block holds
each value block's first key + address. Lookups binary-search the index
then the block (reference: src/lsm/binary_search.zig — here Python's
bisect over in-memory key arrays)."""

from __future__ import annotations

import bisect
import dataclasses
import struct

from .grid import ADDRESS_SIZE, BlockAddress, Grid
from .schema import BLOCK_HEADER_SIZE, BlockKind, unwrap, wrap

TOMBSTONE = b"\xff"  # value prefix marking a deletion


@dataclasses.dataclass
class TableInfo:
    """Manifest entry (reference: manifest TableInfo)."""

    index_address: BlockAddress
    index_size: int
    key_min: bytes
    key_max: bytes
    entry_count: int

    def pack(self) -> bytes:
        return (self.index_address.pack()
                + struct.pack("<IHHI", self.index_size, len(self.key_min),
                              len(self.key_max), self.entry_count)
                + self.key_min + self.key_max)

    @classmethod
    def unpack(cls, raw: bytes, offset: int = 0) -> tuple["TableInfo", int]:
        addr = BlockAddress.unpack(raw[offset:offset + ADDRESS_SIZE])
        offset += ADDRESS_SIZE
        size, kmin_len, kmax_len, count = struct.unpack_from("<IHHI", raw, offset)
        offset += 12
        kmin = raw[offset:offset + kmin_len]
        offset += kmin_len
        kmax = raw[offset:offset + kmax_len]
        offset += kmax_len
        return cls(addr, size, kmin, kmax, count), offset


class Table:
    """Reader over one on-grid table: index loaded, blocks read on demand."""

    def __init__(self, grid: Grid, info: TableInfo, key_size: int,
                 value_size: int):
        self.grid = grid
        self.info = info
        self.key_size = key_size
        self.value_size = value_size
        raw = unwrap(grid.read_block(info.index_address, info.index_size),
                     BlockKind.index)
        (count,) = struct.unpack_from("<I", raw)
        self.block_first_keys: list[bytes] = []
        self.block_addresses: list[BlockAddress] = []
        self.block_sizes: list[int] = []
        pos = 4
        for _ in range(count):
            addr = BlockAddress.unpack(raw[pos:pos + ADDRESS_SIZE])
            pos += ADDRESS_SIZE
            (size,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            first = raw[pos:pos + key_size]
            pos += key_size
            self.block_addresses.append(addr)
            self.block_sizes.append(size)
            self.block_first_keys.append(first)

    def _block_entries(self, i: int) -> tuple[list[bytes], list[bytes]]:
        raw = unwrap(self.grid.read_block(self.block_addresses[i],
                                          self.block_sizes[i]),
                     BlockKind.value)
        (n,) = struct.unpack_from("<I", raw)
        pos = 4
        entry = self.key_size + self.value_size
        keys = [raw[pos + j * entry: pos + j * entry + self.key_size]
                for j in range(n)]
        vals = [raw[pos + j * entry + self.key_size: pos + (j + 1) * entry]
                for j in range(n)]
        return keys, vals

    def get(self, key: bytes):
        blk = self.block_for(key)
        if blk is None:
            return None
        address, size = blk
        return self.get_in_block(key, self.grid.read_block(address, size))

    def block_for(self, key: bytes):
        """(address, size) of the one value block that could hold `key`,
        or None — the read-free planning half of a point lookup (the
        batched prefetch fan-out plans ALL of a batch's reads first)."""
        if not (self.info.key_min <= key <= self.info.key_max):
            return None
        i = bisect.bisect_right(self.block_first_keys, key) - 1
        if i < 0:
            return None
        return self.block_addresses[i], self.block_sizes[i]

    def get_in_block(self, key: bytes, raw: bytes):
        """Binary-search `key` inside a fetched value block."""
        raw = unwrap(raw, BlockKind.value)
        (n,) = struct.unpack_from("<I", raw)
        entry = self.key_size + self.value_size
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            if raw[4 + mid * entry: 4 + mid * entry + self.key_size] < key:
                lo = mid + 1
            else:
                hi = mid
        if lo < n and raw[4 + lo * entry: 4 + lo * entry + self.key_size] == key:
            return raw[4 + lo * entry + self.key_size: 4 + (lo + 1) * entry]
        return None

    def iter_entries(self):
        # Read-ahead: block i+1's device read runs while block i's
        # entries are merged (compaction input no longer stalls the
        # replica loop per block — reference: compaction reads are
        # pipelined through io_uring, src/storage.zig:177 +
        # docs/internals/lsm.md pipelined compaction). No-op on
        # synchronous devices (the deterministic simulator).
        n = len(self.block_addresses)
        for i in range(n):
            if i + 1 < n:
                self.grid.prefetch_async(
                    [(self.block_addresses[i + 1],
                      self.block_sizes[i + 1])])
            keys, vals = self._block_entries(i)
            yield from zip(keys, vals)


def value_block_entry_max(grid: Grid, key_size: int,
                          value_size: int) -> int:
    """Entries per value block (block header + u32 count + k||v rows)."""
    return max(1, (grid.block_size - BLOCK_HEADER_SIZE - 4)
               // (key_size + value_size))


def table_entry_max(grid: Grid, key_size: int, value_size: int) -> int:
    """Largest entry count whose index still fits one block (reference:
    tables have a fixed value_count_max per comptime layout)."""
    per_block = value_block_entry_max(grid, key_size, value_size)
    index_entries_max = ((grid.block_size - BLOCK_HEADER_SIZE - 4)
                         // (ADDRESS_SIZE + 4 + key_size))
    return per_block * index_entries_max


def write_value_block(grid: Grid, chunk: list[tuple[bytes, bytes]],
                      reservation=None, tree_id: int = 0):
    """One value block; returns (address, size, first_key) — the index
    entry triple. The SINGLE encoder for the value-block layout (shared
    by whole-table writes and the incremental memtable flush)."""
    raw = wrap(BlockKind.value,
               struct.pack("<I", len(chunk)) + b"".join(
                   k + v for k, v in chunk),
               tree_id=tree_id)
    addr = grid.write_block(raw, reservation=reservation)
    return addr, len(raw), chunk[0][0]


def write_index_block(grid: Grid, blocks: list,
                      reservation=None,
                      tree_id: int = 0) -> tuple[BlockAddress, int]:
    """The table's index block over (address, size, first_key) triples."""
    index_raw = wrap(
        BlockKind.index,
        struct.pack("<I", len(blocks)) + b"".join(
            addr.pack() + struct.pack("<I", size) + first
            for addr, size, first in blocks),
        tree_id=tree_id)
    assert len(index_raw) <= grid.block_size, "table too large for one index"
    return grid.write_block(index_raw, reservation=reservation), len(index_raw)


def table_block_bound(grid: Grid, n_entries: int, key_size: int,
                      value_size: int) -> int:
    """Worst-case grid blocks (value + index) for writing `n_entries` as
    tables — the reservation bound for flush/compaction jobs (reference:
    compactions reserve their worst case, src/vsr/free_set.zig:28-35)."""
    per_block = value_block_entry_max(grid, key_size, value_size)
    cap = table_entry_max(grid, key_size, value_size)
    n = max(1, n_entries)
    tables = -(-n // cap)
    # Value blocks: ceil(n/per_block) plus one possible short block per
    # table boundary; one index block per table.
    return -(-n // per_block) + 2 * tables


def write_tables(grid: Grid, entries: list[tuple[bytes, bytes]],
                 key_size: int, value_size: int,
                 reservation=None, tree_id: int = 0) -> list["TableInfo"]:
    """Serialize a sorted run as one or more bounded tables (a single merge
    output may exceed one table's index capacity — split, like the
    reference's compaction emitting multiple output tables)."""
    cap = table_entry_max(grid, key_size, value_size)
    return [write_table(grid, entries[i:i + cap], key_size, value_size,
                        reservation=reservation, tree_id=tree_id)
            for i in range(0, len(entries), cap)]


def write_table(grid: Grid, entries: list[tuple[bytes, bytes]],
                key_size: int, value_size: int,
                reservation=None, tree_id: int = 0) -> TableInfo:
    """Serialize one sorted run (caller guarantees sort order + unique keys)."""
    assert entries
    per_block = value_block_entry_max(grid, key_size, value_size)
    blocks = [write_value_block(grid, entries[base:base + per_block],
                                reservation=reservation, tree_id=tree_id)
              for base in range(0, len(entries), per_block)]
    index_addr, index_size = write_index_block(grid, blocks,
                                               reservation=reservation,
                                               tree_id=tree_id)
    return TableInfo(
        index_address=index_addr, index_size=index_size,
        key_min=entries[0][0], key_max=entries[-1][0],
        entry_count=len(entries))


def release_table(grid: Grid, table: Table) -> None:
    """Free all of a table's blocks (effective at next checkpoint)."""
    for addr in table.block_addresses:
        grid.release(addr.index)
    grid.release(table.info.index_address.index)
