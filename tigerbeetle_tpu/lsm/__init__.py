"""LSM storage engine (reference: src/lsm/, SURVEY §2.2).

A log-structured merge forest over a copy-on-write block grid:

- grid.py    — block allocator/store with checksummed blocks and an
               EWAH-persisted free set (reference: src/vsr/grid.zig +
               src/vsr/free_set.zig)
- table.py   — immutable sorted runs serialized into grid blocks
               (reference: src/lsm/table.zig)
- tree.py    — memtable + leveled tables, growth factor 8, deterministic
               least-overlap compaction (reference: src/lsm/tree.zig,
               compaction.zig, manifest.zig)
- forest.py  — named trees sharing one grid; checkpoint/open
               (reference: src/lsm/forest.zig)

Round-1 scope: the engine is standalone and fully tested (including
byte-determinism across runs); wiring it under the replica's checkpoint
path (replacing snapshot checkpoints) is the next round's work.
"""

from .forest import Forest
from .grid import Grid
from .tree import Tree

__all__ = ["Forest", "Grid", "Tree"]
