"""Shared SPMD plumbing for the sharded modules.

Two things live here because BOTH parallel/full_sharded.py (replicated
state, sharded per-event stage) and parallel/partitioned.py (sharded
state, exchange-assembled per-event stage) need them and must agree:

  - `get_shard_map()`: the jax.shard_map / jax.experimental.shard_map
    import fallback, previously duplicated per module;
  - `shard_of_id()`: the ownership function — which mesh shard owns a
    128-bit object id. The device kernels, the host packers
    (partitioned_from_oracle), and the oracle-side digest pack
    (state_epoch.pack_oracle_state_partitioned) all route through this
    ONE definition, so device and host can never disagree about
    ownership (the partitioned digest comparison depends on it).

Elastic shards (ISSUE 19) extend the base map with an *overlay*: a
tiny, generation-tagged table of hash ranges mid-migration. An overlay
entry `(lo, hi, src, dst, mode)` says: ids whose 64-bit ownership hash
falls in [lo, hi] (inclusive, so the full range is representable) AND
whose base owner is `src` are being moved to `dst`. `mode` is
OVERLAY_DOUBLE_WRITE (reads still served by src; writes applied by
BOTH src and dst — the copy-catchup stage) or OVERLAY_MIGRATED (reads
and writes owned by dst; src's copy awaits retirement). The overlay is
consulted bit-identically on host (`owner_read_int`) and device
(`owner_read` / `writes_here`): both derive the same `mix_id` hash and
walk the same static entry tuple, so a flip can never tear between the
packers and the kernels. An EMPTY overlay lowers to exactly the code
that existed before elastic shards — the serving op budgets and
jaxhound signatures see byte-identical HLO.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# The same splitmix64-style constants the two-choice hash table uses
# (ops/hash_table.py) — a different finalization order, so shard
# assignment and bucket choice stay decorrelated.
_C1 = 0x9E3779B97F4A7C15
_C2 = 0xBF58476D1CE4E5B9
_C3 = 0x94D049BB133111EB
_M64 = (1 << 64) - 1


def get_shard_map():
    """Resolve shard_map across jax versions (>=0.5 exports it from the
    top-level namespace; older jax keeps it under experimental)."""
    try:
        from jax import shard_map
    except ImportError:  # pre-0.5 jax
        from jax.experimental.shard_map import shard_map
    return shard_map


def mix_id(k_hi, k_lo):
    """The full 64-bit ownership hash of a 128-bit id (array form;
    jnp or numpy u64 arrays). `shard_of_id` is its low bits; overlay
    ranges and the range digest fold are defined over the whole hash."""
    u64 = np.uint64
    h = (k_lo ^ (k_hi * u64(_C1))) * u64(_C2)
    h = (h ^ (h >> u64(31))) * u64(_C3)
    h = h ^ (h >> u64(29))
    return h


def mix_int(id128: int) -> int:
    """Host-side `mix_id` over a python 128-bit int. Bit-identical."""
    k_hi = (id128 >> 64) & _M64
    k_lo = id128 & _M64
    h = ((k_lo ^ (k_hi * _C1 & _M64)) * _C2) & _M64
    h = ((h ^ (h >> 31)) * _C3) & _M64
    return h ^ (h >> 29)


def shard_of_id(k_hi, k_lo, n_shards: int):
    """Owning shard of a 128-bit id (account, transfer, or orphan key).

    Pure function of the id: a splitmix-style 64-bit mix of the two
    limbs, masked to `n_shards` (power of two — mesh sizes are). Works
    on jnp arrays (traced, wrapping uint64), numpy arrays, and — via
    `shard_of_int` — python ints, producing identical assignments.
    """
    assert n_shards & (n_shards - 1) == 0, n_shards
    u64 = np.uint64
    return (mix_id(k_hi, k_lo) & u64(n_shards - 1)).astype(np.int32)


def shard_of_int(id128: int, n_shards: int) -> int:
    """Host-side shard_of_id over a python 128-bit int (oracle
    partitioning / digest packs). Bit-identical to the array form."""
    assert n_shards & (n_shards - 1) == 0, n_shards
    return mix_int(id128) & (n_shards - 1)


# ------------------------------------------------------------- overlay
# Migration modes an overlay entry can be in. Membership of an id in an
# entry is always tested against the BASE map (`base_owner == src`), so
# an entry's meaning never depends on other entries:
#
#   DOUBLE_WRITE  forward copy-catchup: src answers reads, BOTH src and
#                 dst apply writes (dst's copy stays current while the
#                 bulk copy streams).
#   MIGRATED      post-flip steady state: dst owns reads and writes;
#                 src's copy is stale (zeroed at retire). The entry
#                 persists as the collapsed base override — the base
#                 map is a pure hash, so "collapse" means the entry
#                 simply stops being part of any in-flight migration.
#   RETURNING     backward copy-catchup (merge home): dst still answers
#                 reads, both apply writes; the flip that completes it
#                 DROPS the entry, returning the range to the base map.
OVERLAY_DOUBLE_WRITE = 1
OVERLAY_MIGRATED = 2
OVERLAY_RETURNING = 3


def _validate_overlay(entries: tuple, n_shards: int) -> None:
    spans: list = []
    for e in entries:
        lo, hi, src, dst, mode = e
        assert 0 <= lo <= hi <= _M64, e
        assert 0 <= src < n_shards and 0 <= dst < n_shards, e
        assert src != dst, e
        assert mode in (OVERLAY_DOUBLE_WRITE, OVERLAY_MIGRATED,
                        OVERLAY_RETURNING), e
        for (plo, phi, psrc) in spans:
            if psrc == src and not (hi < plo or lo > phi):
                raise AssertionError(
                    f"overlapping overlay ranges for shard {src}")
        spans.append((lo, hi, src))


def owner_read(k_hi, k_lo, n_shards: int, overlay: tuple = ()):
    """READ owner of an id under an (optionally empty) overlay: the
    shard whose copy of the object is authoritative right now. With an
    empty overlay this IS `shard_of_id` — same lowering, same budget."""
    base = shard_of_id(k_hi, k_lo, n_shards)
    if not overlay:
        return base
    import jax.numpy as jnp
    u64 = np.uint64
    h = mix_id(k_hi, k_lo)
    owner = base
    for (lo, hi, src, dst, mode) in overlay:
        if mode == OVERLAY_DOUBLE_WRITE:
            continue  # copy-catchup ranges still read from src == base
        inr = (h >= u64(lo)) & (h <= u64(hi)) & (base == np.int32(src))
        owner = jnp.where(inr, np.int32(dst), owner)
    return owner


def writes_here(k_hi, k_lo, n_shards: int, me, overlay: tuple = ()):
    """Boolean per id: does shard `me` apply writes for it. Equals
    `owner_read(...) == me` except during copy-catchup, where the
    non-reading owner writes too (DOUBLE_WRITE: dst; RETURNING: src)."""
    w = owner_read(k_hi, k_lo, n_shards, overlay) == me
    if not overlay:
        return w
    u64 = np.uint64
    h = mix_id(k_hi, k_lo)
    base = shard_of_id(k_hi, k_lo, n_shards)
    for (lo, hi, src, dst, mode) in overlay:
        if mode == OVERLAY_MIGRATED:
            continue
        other = dst if mode == OVERLAY_DOUBLE_WRITE else src
        inr = (h >= u64(lo)) & (h <= u64(hi)) & (base == np.int32(src))
        w = w | (inr & (me == np.int32(other)))
    return w


def owner_read_int(id128: int, n_shards: int, overlay: tuple = ()) -> int:
    """Host-side `owner_read` over a python int — the packers' and the
    oracle digest's view of the same overlay. Bit-identical."""
    h = mix_int(id128)
    base = h & (n_shards - 1)
    for (lo, hi, src, dst, mode) in overlay:
        if (mode != OVERLAY_DOUBLE_WRITE and lo <= h <= hi
                and base == src):
            return dst
    return base


def write_owners_int(id128: int, n_shards: int,
                     overlay: tuple = ()) -> tuple:
    """Host-side write-owner set of an id (1 shard normally, 2 while
    its range is in copy-catchup)."""
    h = mix_int(id128)
    base = h & (n_shards - 1)
    owners = [owner_read_int(id128, n_shards, overlay)]
    for (lo, hi, src, dst, mode) in overlay:
        if mode == OVERLAY_MIGRATED or not (lo <= h <= hi
                                            and base == src):
            continue
        other = dst if mode == OVERLAY_DOUBLE_WRITE else src
        if other not in owners:
            owners.append(other)
    return tuple(sorted(owners))


@dataclass(frozen=True)
class OwnershipTable:
    """The host-side ownership authority: base map (splitmix over
    `n_shards`) plus the generation-tagged overlay. The controller
    mutates ownership ONLY by swapping in a new table with a bumped
    generation; traced step functions bake `entries` in as static
    closure constants, so a generation bump is what forces the router
    to select (or trace) the matching step."""
    n_shards: int
    generation: int = 0
    entries: tuple = ()

    def __post_init__(self):
        assert self.n_shards & (self.n_shards - 1) == 0, self.n_shards
        _validate_overlay(self.entries, self.n_shards)

    @property
    def active(self) -> bool:
        return bool(self.entries)

    def owner_read_int(self, id128: int) -> int:
        return owner_read_int(id128, self.n_shards, self.entries)

    def write_owners_int(self, id128: int) -> tuple:
        return write_owners_int(id128, self.n_shards, self.entries)

    def with_entry(self, lo: int, hi: int, src: int, dst: int,
                   mode: int) -> "OwnershipTable":
        return OwnershipTable(
            self.n_shards, self.generation + 1,
            self.entries + ((lo, hi, src, dst, mode),))

    def transition(self, entry: tuple, mode: int) -> "OwnershipTable":
        """The same range, next stage (e.g. DOUBLE_WRITE -> MIGRATED
    at a forward flip, MIGRATED -> RETURNING when a merge-home copy
    begins)."""
        lo, hi, src, dst, _m = entry
        out = tuple((lo, hi, src, dst, mode) if e[:4] == (lo, hi, src, dst)
                    else e for e in self.entries)
        table = OwnershipTable(self.n_shards, self.generation + 1, out)
        assert any(e[:4] == (lo, hi, src, dst) for e in out), entry
        return table

    def without_entry(self, entry: tuple) -> "OwnershipTable":
        """Drop a range from the overlay: the abort revert of an
        un-flipped migration, or the completing flip of a RETURNING
        merge (either way, ids in the range route by the base map
        again)."""
        out = tuple(e for e in self.entries if e[:4] != entry[:4])
        assert out != self.entries, (entry, self.entries)
        return OwnershipTable(self.n_shards, self.generation + 1, out)
