"""Shared SPMD plumbing for the sharded modules.

Two things live here because BOTH parallel/full_sharded.py (replicated
state, sharded per-event stage) and parallel/partitioned.py (sharded
state, exchange-assembled per-event stage) need them and must agree:

  - `get_shard_map()`: the jax.shard_map / jax.experimental.shard_map
    import fallback, previously duplicated per module;
  - `shard_of_id()`: the ownership function — which mesh shard owns a
    128-bit object id. The device kernels, the host packers
    (partitioned_from_oracle), and the oracle-side digest pack
    (state_epoch.pack_oracle_state_partitioned) all route through this
    ONE definition, so device and host can never disagree about
    ownership (the partitioned digest comparison depends on it).
"""

from __future__ import annotations

import numpy as np

# The same splitmix64-style constants the two-choice hash table uses
# (ops/hash_table.py) — a different finalization order, so shard
# assignment and bucket choice stay decorrelated.
_C1 = 0x9E3779B97F4A7C15
_C2 = 0xBF58476D1CE4E5B9
_C3 = 0x94D049BB133111EB
_M64 = (1 << 64) - 1


def get_shard_map():
    """Resolve shard_map across jax versions (>=0.5 exports it from the
    top-level namespace; older jax keeps it under experimental)."""
    try:
        from jax import shard_map
    except ImportError:  # pre-0.5 jax
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_of_id(k_hi, k_lo, n_shards: int):
    """Owning shard of a 128-bit id (account, transfer, or orphan key).

    Pure function of the id: a splitmix-style 64-bit mix of the two
    limbs, masked to `n_shards` (power of two — mesh sizes are). Works
    on jnp arrays (traced, wrapping uint64), numpy arrays, and — via
    `shard_of_int` — python ints, producing identical assignments.
    """
    assert n_shards & (n_shards - 1) == 0, n_shards
    u64 = np.uint64
    h = (k_lo ^ (k_hi * u64(_C1))) * u64(_C2)
    h = (h ^ (h >> u64(31))) * u64(_C3)
    h = h ^ (h >> u64(29))
    return (h & u64(n_shards - 1)).astype(np.int32)


def shard_of_int(id128: int, n_shards: int) -> int:
    """Host-side shard_of_id over a python 128-bit int (oracle
    partitioning / digest packs). Bit-identical to the array form."""
    assert n_shards & (n_shards - 1) == 0, n_shards
    k_hi = (id128 >> 64) & _M64
    k_lo = id128 & _M64
    h = ((k_lo ^ (k_hi * _C1 & _M64)) * _C2) & _M64
    h = ((h ^ (h >> 31)) * _C3) & _M64
    h = h ^ (h >> 29)
    return h & (n_shards - 1)
