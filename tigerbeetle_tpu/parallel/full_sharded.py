"""Full-semantics SPMD create_transfers over a device mesh — deep tiers.

The multi-chip form of the single-chip kernel stack
(ops/fast_kernels.py), with FULL semantics — eligibility E1-E7, chains,
idempotency, two-phase post/void, event-ring snapshots — across EVERY
kernel tier: plain, limit-fixpoint (closing-native, in-window pending
refs), balancing, and imported.

Decomposition (reference mapping: the batch axis of
docs/ARCHITECTURE.md:358-362 sharded over ICI):

  1. per-event stage (SHARDED): each device takes its slice of the
     batch and runs per_event_status() — the 5 hash probes and the ~50
     order-independent checks — against the REPLICATED ledger state.
     This is where the per-event FLOPs are; it scales linearly with
     devices. The imported tier's batch context (homogeneity flag,
     commit timestamp, account-ts collision) is computed replicated and
     fed in sliced.
  2. all_gather (ICI): the compact per-event bundle (status, resolved
     amount, touched rows — ~50 B/event) is gathered so every device
     holds the full batch's results.
  3. global tail (REPLICATED): eligibility reductions, the in-window
     join + substitution fixup (fixpoint tiers), the K-round
     limit/closing/balancing/imported fixpoint, the chain first-failure
     broadcast, row planning, and state application run identically on
     every device over the gathered bundle — a few O(N log N) sorts on
     compact arrays. Determinism makes the replicated ledger state
     bit-identical across the mesh, the SPMD restatement of the
     reference's determinism doctrine (docs/ARCHITECTURE.md:281-307).

Exactness: each sharded step returns bit-identical (new_state, out) to
its single-chip sibling, which is itself bit-exact vs the sequential
oracle under eligibility (tests/test_full_sharded.py runs the
differentials on an 8-device CPU mesh).

`ShardedRouter` is the host-side driver: per-batch flag routing to the
matching tier (the SPMD analog of DeviceLedger's pre-route), on-device
escalation (plain -> fixpoint), and per-cause fallback counters — a
mixed balancing+imported+closing window executes with ZERO per-shard
host fallbacks, and that is a measured number, not an assumption.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.fast_kernels import (
    LIMIT_FIXPOINT_ROUNDS_WINDOW_DEEP,
    create_transfers_fast,
    imported_batch_ctx,
    per_event_status,
)
from ..trace import Event, NullTracer
from .shard_utils import get_shard_map

__all__ = ["make_sharded_create_transfers", "shard_batch", "ShardedRouter",
           "MODES"]

MODES = ("plain", "fixpoint", "balancing", "imported")

# Tail kwargs per tier — the SAME static flags the single-chip jit
# entries use, so the sharded step IS the single-chip kernel with the
# per-event stage plugged in.
_MODE_KWARGS = {
    "plain": {},
    "fixpoint": dict(limit_rounds=LIMIT_FIXPOINT_ROUNDS_WINDOW_DEEP),
    "balancing": dict(limit_rounds=LIMIT_FIXPOINT_ROUNDS_WINDOW_DEEP,
                      balancing_mode=True),
    "imported": dict(limit_rounds=LIMIT_FIXPOINT_ROUNDS_WINDOW_DEEP,
                     imported_mode=True),
}


def make_sharded_create_transfers(mesh: Mesh, axis: str = "batch",
                                  mode: str = "plain"):
    """Build the jitted full-semantics SPMD step over `mesh` for one
    kernel tier (`mode` in MODES).

    Returns step(state, ev, timestamp, n) -> (new_state, out), the same
    contract as the matching single-chip jit entry. `ev` arrays must be
    divisible by the mesh axis size (pad_transfer_events' N_PAD=8192
    divides any power-of-two mesh)."""
    shard_map = get_shard_map()

    assert mode in MODES, mode
    n_dev = mesh.shape[axis]
    # The imported tier's after_regress_codes is a STATIC tuple derived
    # inside per_event_status from its literal check lists; it cannot
    # ride the shard_map outputs (arrays only), so the traced body
    # captures it here and the tail re-attaches it.
    static_codes: list = []

    def step(state, ev, timestamp, n):
        N = ev["id_lo"].shape[0]
        assert N % n_dev == 0, (N, n_dev)
        shard = N // n_dev
        idxs = jnp.arange(N, dtype=jnp.int32)
        ts_full = (timestamp - n.astype(jnp.uint64)
                   + idxs.astype(jnp.uint64) + jnp.uint64(1))
        if mode == "imported":
            # Batch context replicated (global reductions + one sorted-
            # column membership probe), then sliced into the shards;
            # key_max stays a replicated scalar.
            ctx_full = imported_batch_ctx(state, ev, ts_full,
                                          ev["valid"], idxs)
            key_max = ctx_full.pop("key_max")
        else:
            ctx_full = key_max = None

        def per_event_shard(state, ev_shard, *ctx_args):
            # Global event positions for this shard: the event timestamp
            # ts_event = timestamp - n + i + 1 depends on the global index.
            dev = jax.lax.axis_index(axis)
            sh_idx = (dev * shard
                      + jnp.arange(shard, dtype=jnp.int32)).astype(
                          jnp.uint64)
            ts_event = (timestamp - n.astype(jnp.uint64) + sh_idx
                        + jnp.uint64(1))
            ictx = None
            if mode == "imported":
                (ctx_shard,) = ctx_args
                ictx = dict(ctx_shard, key_max=key_max)
            pe = per_event_status(state, ev_shard, ts_event,
                                  imported_ctx=ictx)
            codes = pe.pop("after_regress_codes", None)
            if codes is not None and not static_codes:
                static_codes.append(codes)
            # all_gather(tiled): every device ends with the full batch's
            # compact bundle, concatenated in device order == batch order.
            return {k: jax.lax.all_gather(v, axis, tiled=True)
                    for k, v in pe.items()}

        state_spec = jax.tree.map(lambda _: P(), state)
        ev_spec = {k: P(axis) for k in ev}
        # out_specs derived programmatically from the per-event pytree
        # (never a hardcoded key set): eval_shape the shard body's
        # bundle and map every leaf to the replicated spec.
        def _pe_struct(state, ev):
            ev_s = {k: v[:shard] for k, v in ev.items()}
            ictx = None
            if mode == "imported":
                ictx = dict({k: v[:shard] for k, v in ctx_full.items()},
                            key_max=key_max)
            pe = per_event_status(state, ev_s, ts_full[:shard],
                                  imported_ctx=ictx)
            pe.pop("after_regress_codes", None)
            return pe

        pe_struct = jax.eval_shape(_pe_struct, state, ev)
        out_specs = jax.tree.map(lambda _: P(), pe_struct)
        args = (state, ev)
        in_specs = (state_spec, ev_spec)
        if mode == "imported":
            args = args + ({k: v for k, v in ctx_full.items()},)
            in_specs = in_specs + ({k: P(axis) for k in ctx_full},)
        try:
            smapped = shard_map(
                per_event_shard, mesh=mesh,
                in_specs=in_specs, out_specs=out_specs, check_vma=False)
        except TypeError:  # pre-0.5 jax spells the kwarg check_rep
            smapped = shard_map(
                per_event_shard, mesh=mesh,
                in_specs=in_specs, out_specs=out_specs, check_rep=False)
        pe = smapped(*args)
        if mode == "imported":
            pe["after_regress_codes"] = static_codes[0]
        # Global tail on the gathered bundle: replicated, deterministic,
        # bit-exact vs the single-chip tier (it IS the single-chip
        # kernel with the per-event stage plugged in; the fixpoint
        # tiers additionally compute the in-window join here and
        # re-apply the substitution to the bundle).
        return create_transfers_fast(state, ev, timestamp, n,
                                     per_event=pe, **_MODE_KWARGS[mode])

    # Donate the replicated ledger buffers like every single-chip tier
    # (jaxhound's donation audit checks the lowered artifact): callers
    # consume the RETURNED state only — on fallback the masked writes
    # leave it bit-identical, so the escalation/replay contract is
    # unchanged. Platforms without donation support simply ignore it.
    return jax.jit(step, donate_argnums=0)


def shard_batch(mesh: Mesh, ev: dict, axis: str = "batch"):
    """Place a padded event dict with the batch axis sharded over `mesh`
    and return it (state stays replicated via P())."""
    sharding = NamedSharding(mesh, P(axis))
    return {k: jax.device_put(v, sharding) for k, v in ev.items()}


class ShardedRouter:
    """Host-side tier router over the sharded steps — the SPMD analog of
    DeviceLedger's flag pre-route. Inspects each batch's flags, runs the
    matching sharded step, redispatches device-resolvable escalations
    (plain -> fixpoint, exactly the single-chip limit_only contract:
    the failed kernel leaves donated state untouched), and accumulates
    per-cause host-fallback counters so "zero fallbacks on a mixed
    balancing+imported+closing window" is a measured invariant."""

    def __init__(self, mesh: Mesh, axis: str = "batch", tracer=None):
        self.mesh = mesh
        self.axis = axis
        self.tracer = tracer if tracer is not None else NullTracer()
        self._steps: dict = {}
        self._single_steps: dict = {}
        self.batches = 0
        self.escalations = 0
        self.host_fallbacks = 0
        self.fallback_causes: dict = {}
        # Chaos/degraded mode: mesh devices marked lost. While any
        # device is lost, every batch re-routes to the single-chip step
        # (the SAME create_transfers_fast math without the shard_map) —
        # results stay bit-exact, throughput degrades, and the reroute
        # is a counted event (testing/chaos.py injects the loss).
        self.lost_devices: set = set()
        self.shard_loss_reroutes = 0

    def drop_device(self, device) -> None:
        """Mark one mesh device lost (simulated ICI/host failure). The
        replicated ledger state means ANY surviving chip — or the
        single-chip path — can serve; we take the single-chip path
        until restore_devices() (re-meshing is a driver concern).

        This reroute is a REPLICATED-state privilege: the partitioned
        sibling (parallel/partitioned.PartitionedRouter.drop_device)
        cannot take it — a lost shard takes its account range with it —
        and resyncs from the oracle instead (`shard_resync` cause)."""
        self.lost_devices.add(device)

    def restore_devices(self) -> None:
        """The mesh healed: route back to the sharded steps."""
        self.lost_devices.clear()

    def _step(self, mode: str):
        fn = self._steps.get(mode)
        if fn is None:
            fn = self._steps[mode] = make_sharded_create_transfers(
                self.mesh, self.axis, mode=mode)
        return fn

    def _single_step(self, mode: str):
        """Single-chip sibling of the sharded step: the same
        create_transfers_fast tail with the same static tier kwargs, no
        mesh — the degraded-mode target when a shard is lost."""
        fn = self._single_steps.get(mode)
        if fn is None:
            import functools

            fn = self._single_steps[mode] = jax.jit(
                functools.partial(create_transfers_fast,
                                  **_MODE_KWARGS[mode]),
                donate_argnums=0)
        return fn

    @staticmethod
    def route(ev: dict) -> str:
        """Flag-derived tier for one (padded or raw) event dict. Same
        precedence as DeviceLedger: imported > balancing > closing;
        limit breaches and in-batch pending refs are invisible to flags
        and escalate from the plain step instead."""
        from ..types import TransferFlags as TF

        flags = np.asarray(ev["flags"])
        if (flags & np.uint32(int(TF.imported))).any():
            return "imported"
        if (flags & np.uint32(int(TF.balancing_debit
                                  | TF.balancing_credit))).any():
            return "balancing"
        if (flags & np.uint32(int(TF.closing_debit
                                  | TF.closing_credit))).any():
            return "fixpoint"
        return "plain"

    def step(self, state, ev: dict, timestamp: int, n: int):
        """Run one padded batch. Returns (new_state, out, fell_back).
        On fell_back=True the state is untouched (masked writes) and the
        caller owns the exact-path replay."""
        self.batches += 1
        mode = self.route(ev)
        degraded = bool(self.lost_devices)
        if degraded:
            self.shard_loss_reroutes += 1
            self.tracer.count(Event.router_reroute)
        pick = self._single_step if degraded else self._step
        # Route observability: the same catalog counter the serving
        # supervisor emits per window, so sharded and single-chip
        # dispatch routes read off one metric.
        self.tracer.count(
            Event.dispatch_route,
            route=("single_chip_" if degraded else "sharded_") + mode)
        with self.tracer.span(Event.router_step, mode=mode,
                              degraded=int(degraded)):
            new_state, out = pick(mode)(
                state, ev, np.uint64(timestamp), np.int32(n))
            fallback, limit_only = (bool(x) for x in jax.device_get(
                (out["fallback"], out["limit_only"])))
            if fallback and limit_only and mode == "plain":
                # Breach / collision / closing: resolvable on the
                # sharded fixpoint step (the plain kernel left state
                # untouched).
                self.escalations += 1
                new_state, out = pick("fixpoint")(
                    new_state, ev, np.uint64(timestamp), np.int32(n))
                fallback = bool(jax.device_get(out["fallback"]))
        if fallback:
            self.host_fallbacks += 1
            for k, v in jax.device_get(out["fb_causes"]).items():
                if bool(v):
                    self.fallback_causes[k] = (
                        self.fallback_causes.get(k, 0) + 1)
                    self.tracer.count(Event.router_fallback, cause=k)
        return new_state, out, fallback

    def stats(self) -> dict:
        return {
            "batches": self.batches,
            "escalations": self.escalations,
            "host_fallbacks": self.host_fallbacks,
            "causes": dict(self.fallback_causes),
            "lost_devices": len(self.lost_devices),
            "shard_loss_reroutes": self.shard_loss_reroutes,
        }
