"""Full-semantics SPMD create_transfers over a device mesh.

The multi-chip form of the single-chip fast kernel
(ops/fast_kernels.py), with FULL semantics — eligibility E1-E7, chains,
idempotency, two-phase post/void, event-ring snapshots.

Decomposition (reference mapping: the batch axis of
docs/ARCHITECTURE.md:358-362 sharded over ICI):

  1. per-event stage (SHARDED): each device takes its slice of the
     batch and runs per_event_status() — the 5 hash probes and the ~50
     order-independent checks — against the REPLICATED ledger state.
     This is where the per-event FLOPs are; it scales linearly with
     devices.
  2. all_gather (ICI): the compact per-event bundle (status, resolved
     amount, touched rows — ~50 B/event) is gathered so every device
     holds the full batch's results.
  3. global tail (REPLICATED): eligibility reductions, the chain
     first-failure broadcast, row planning, and state application run
     identically on every device over the gathered bundle — a few
     O(N log N) sorts on compact arrays. Determinism makes the
     replicated ledger state bit-identical across the mesh, the SPMD
     restatement of the reference's determinism doctrine
     (docs/ARCHITECTURE.md:281-307).

Exactness: the sharded step returns bit-identical (new_state, out) to
the single-chip create_transfers_fast, which is itself bit-exact vs the
sequential oracle under eligibility (tests/test_full_sharded.py runs
the differential on an 8-device CPU mesh).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.fast_kernels import create_transfers_fast, per_event_status

__all__ = ["make_sharded_create_transfers", "shard_batch"]


def make_sharded_create_transfers(mesh: Mesh, axis: str = "batch"):
    """Build the jitted full-semantics SPMD step over `mesh`.

    Returns step(state, ev, timestamp, n) -> (new_state, out), the same
    contract as create_transfers_fast. `ev` arrays must be divisible by
    the mesh axis size (pad_transfer_events' N_PAD=8192 divides any
    power-of-two mesh)."""
    from jax import shard_map

    n_dev = mesh.shape[axis]

    def step(state, ev, timestamp, n):
        N = ev["id_lo"].shape[0]
        assert N % n_dev == 0, (N, n_dev)
        shard = N // n_dev

        def per_event_shard(state, ev_shard):
            # Global event positions for this shard: the event timestamp
            # ts_event = timestamp - n + i + 1 depends on the global index.
            dev = jax.lax.axis_index(axis)
            idxs = (dev * shard
                    + jnp.arange(shard, dtype=jnp.int32)).astype(jnp.uint64)
            ts_event = timestamp - n.astype(jnp.uint64) + idxs + jnp.uint64(1)
            pe = per_event_status(state, ev_shard, ts_event)
            # all_gather(tiled): every device ends with the full batch's
            # compact bundle, concatenated in device order == batch order.
            return {k: jax.lax.all_gather(v, axis, tiled=True)
                    for k, v in pe.items()}

        state_spec = jax.tree.map(lambda _: P(), state)
        ev_spec = {k: P(axis) for k in ev}
        pe = shard_map(
            per_event_shard, mesh=mesh,
            in_specs=(state_spec, ev_spec),
            out_specs={k: P() for k in (
                "status_pre", "ts_pre", "amt_res_hi", "amt_res_lo",
                "dr_row", "cr_row", "p_row",
                "dr_found", "cr_found", "p_found")},
            check_vma=False,
        )(state, ev)
        # Global tail on the gathered bundle: replicated, deterministic,
        # bit-exact vs the single-chip kernel (it IS the single-chip
        # kernel with the per-event stage plugged in).
        return create_transfers_fast(state, ev, timestamp, n, per_event=pe)

    return jax.jit(step)


def shard_batch(mesh: Mesh, ev: dict, axis: str = "batch"):
    """Place a padded event dict with the batch axis sharded over `mesh`
    and return it (state stays replicated via P())."""
    sharding = NamedSharding(mesh, P(axis))
    return {k: jax.device_put(v, sharding) for k, v in ev.items()}
