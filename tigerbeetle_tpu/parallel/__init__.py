"""Device-mesh parallelism for batch validation.

The reference's only multi-node axis is state-machine replication (VSR,
SURVEY §2.5); its intra-batch axis is the 8190-event hot loop
(reference: docs/ARCHITECTURE.md:358-362). On TPU the intra-batch axis maps
to SPMD over a `jax.sharding.Mesh`: events are sharded across devices,
account-balance deltas are combined with `psum` over ICI, and the account
cache stays replicated (it is the small, hot working set).
"""

from .sharded import make_sharded_validate, sharded_demo_inputs

__all__ = ["make_sharded_validate", "sharded_demo_inputs"]
