"""Device-mesh parallelism for batch validation.

The reference's only multi-node axis is state-machine replication (VSR,
SURVEY §2.5); its intra-batch axis is the 8190-event hot loop
(reference: docs/ARCHITECTURE.md:358-362). On TPU the intra-batch axis maps
to SPMD over a `jax.sharding.Mesh`: the FULL create_transfers kernel runs
sharded — per-event validation on each device's slice of the batch,
a compact per-event bundle all-gathered over ICI, and the deterministic
global tail replicated (parallel/full_sharded.py).
"""

from .full_sharded import make_sharded_create_transfers, shard_batch

__all__ = ["make_sharded_create_transfers", "shard_batch"]
