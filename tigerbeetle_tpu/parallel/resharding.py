"""Elastic shards: crash-safe live resharding with staged handoff.

The partitioned layout (parallel/partitioned.py) fixes each object's
shard with a pure hash — perfect balance for uniform traffic, no answer
when one hash range runs hot or a mesh grows. This module moves an
account-hash range between shards UNDER LIVE TRAFFIC with a five-stage
protocol whose every irreversible step is gated by a digest witness:

  1. SNAPSHOT  — quiesce (caller contract: no in-flight windows),
     fetch the source shard's stores to host, filter the range's rows,
     and verify the filtered pack's position-independent range digest
     (ops/state_epoch.partitioned_range_digest) against the device fold
     — and against the oracle's, when the driver holds one. From here
     the range is FROZEN: the controller treats any window touching it
     as a conflict until double-write activates.
  2. COPY      — stream the snapshot's account/transfer/ring rows to
     the target in bounded chunks (a jitted scatter-append at the
     target's live counts; capacity pre-checked host-side because
     dynamic starts clamp rather than trap). Staged rows are NOT in the
     target's hash tables yet — lookups cannot see a half-copied range.
     The source keeps serving all non-range traffic; a window that
     conflicts with the frozen range drains the remaining chunks
     synchronously (a bounded stall) instead of deferring the window —
     deferral would reorder history against the oracle.
  3. DOUBLE-WRITE — after the last chunk, one finalize kernel restores
     the target shard's canonical row order (argsort by timestamp —
     the shard-then-sort contract the epoch digest pins), REMAPS the
     existing table values through the permutation (bucket choice
     depends only on the key, so values can move without a rebuild),
     and inserts the staged keys + the range's orphan markers. Then
     the ownership overlay activates (shard_utils.OVERLAY_DOUBLE_WRITE)
     and traffic resumes: reads still come from the source, writes
     apply to BOTH copies (owner-masked write-back under `writes_here`),
     so the two copies advance in lockstep for at least
     `min_double_write_windows` commit windows.
  4. FLIP      — at a window boundary (quiesced again), ownership
     switches to the target ONLY if the source and target range digests
     (content + row counts) are bit-equal at the same epoch — plus the
     oracle's, when available. A mismatch aborts: the overlay entry is
     reverted, the staged copy is evicted from the target, and the
     flight recorder freezes a FLIGHT_*_reshard_* artifact. The flip
     itself is one host-side ownership-table swap (generation bump) —
     the routers' step caches key on the overlay entries, so the next
     window simply selects the post-flip lowering.
  5. RETIRE    — immediately after a clean flip, the source's copy of
     the range is evicted (keep-compaction into zeros, table keys
     dropped with per-bucket slot re-compaction, surviving values
     remapped). The overlay entry persists as OVERLAY_MIGRATED — the
     base map is a pure hash, so the entry IS the collapsed override.
     A later `merge_back` runs the same protocol in reverse
     (OVERLAY_RETURNING; its completing flip DROPS the entry).

Crash safety: every stage before FLIP is invisible to ownership — a
crash recovers by reverting the overlay entry (if any) and rebuilding
from the oracle (`PartitionedRouter.resync`), the `reshard_abort`
recovery cause. A crash after FLIP keeps the MIGRATED entry: the resync
packer places the range on the target, so the pre-retire stale source
copy never resurfaces. There is no window in which a crash can lose or
double-apply a committed write: double-write keeps both copies current,
and the flip's digest gate proves it before ownership moves.

Known non-goals, by design:
  - Ring rows carry no object ids, so the device snapshot cannot
    attribute them to a range: they are copied only when the driver
    passes an oracle (packed from its account_events with dump
    pointers — row pointers are non-canonical scope), and the retired
    source's ring rows remain as scratch (the ring is excluded from
    every digest and recycled by serving).
  - The whole-state epoch digest is NOT comparable mid-copy (staged
    rows bump the target's counts): epoch verification must complete
    or abort the migration first (ServingSupervisor does).
  - Stored dr_row/cr_row pointer words go stale when finalize re-sorts
    account rows; they are non-canonical scope — every consumer
    re-derives them from id columns (see partitioned.py docstring).

The HotRangeDetector turns the router's per-shard telemetry into split
proposals (propose-only: enacting is the driver's `--auto-reshard`
decision), including the degenerate verdict — a single account so hot
that no hash range smaller than the whole shard isolates it is
`unsplittable` (the fix is AT2-style lane parallelism WITHIN the
account's commit lane, not placement; see ARCHITECTURE.md).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.ev_layout import AC_NCOLS, AC_U64_IDX, EV_NCOLS, XF_NCOLS, \
    XF_U64_IDX
from ..ops.hash_table import ORPHAN_VAL, SLOTS, ht_lookup, ht_plan, \
    ht_write
from ..ops.state_epoch import _range_digest_components, \
    partitioned_range_digest
from ..trace import Event, NullTracer
from .shard_utils import (
    OVERLAY_DOUBLE_WRITE, OVERLAY_MIGRATED, OVERLAY_RETURNING,
    mix_id, mix_int,
)

__all__ = ["ReshardPlan", "ReshardController", "HotRangeDetector",
           "MigrationAborted"]

_U64_MAX = (1 << 64) - 1
_AC_TS = AC_U64_IDX["ts"]
_XF_TS = XF_U64_IDX["ts"]


class MigrationAborted(RuntimeError):
    """A migration aborted pre-flip (digest mismatch, capacity, table
    overflow, recovery). Ownership is already reverted and the staged
    copy evicted when this raises; the range serves from its pre-
    migration owner, bit-identically."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(reason + (f": {detail}" if detail else ""))
        self.reason = reason
        self.detail = detail


@dataclass(frozen=True)
class ReshardPlan:
    """One range move: ids whose ownership hash (shard_utils.mix_id)
    falls in [lo, hi] (inclusive) AND whose base owner is `src` migrate
    to `dst`. `kind` is 'migrate'/'split' (forward; split is a migrate
    proposed by the hot-range detector) or 'merge_back' (reverse an
    earlier migration — requires its OVERLAY_MIGRATED entry)."""

    lo: int
    hi: int
    src: int
    dst: int
    kind: str = "migrate"

    def __post_init__(self):
        assert 0 <= self.lo <= self.hi <= _U64_MAX, (self.lo, self.hi)
        assert self.src != self.dst, self
        assert self.kind in ("migrate", "split", "merge_back"), self.kind

    def in_range(self, id128: int, n_shards: int) -> bool:
        h = mix_int(id128)
        return (self.lo <= h <= self.hi
                and (h & (n_shards - 1)) == self.src)


# ------------------------------------------------------ device kernels
# Host-driven control-plane kernels over the stacked partitioned state.
# All are module-level jits (one trace per shape family), donate the
# state, and keep the serving lowerings untouched — resharding never
# adds an op to any window dispatch.


@functools.partial(jax.jit, donate_argnums=0)
def _install_chunk(stacked, shard, a_u64, a_bal, a_n, x_u64, x_n,
                   e_u64, e_n):
    """Scatter-append one copy chunk at the receiving shard's live
    counts (chunks are zero-padded to a fixed row count; pad lanes land
    zeros on the dump row, which is scratch by contract). Counts bump
    by the valid sub-counts only. Capacity is the CALLER's pre-check:
    scatter indices past the dump row would corrupt live rows."""
    out = jax.tree.map(lambda x: x, stacked)

    def append(u64, cnt_vec, rows, n):
        cap = u64.shape[1]
        iota = jnp.arange(rows.shape[0], dtype=jnp.int32)
        idx = jnp.where(iota < n, cnt_vec[shard] + iota,
                        jnp.int32(cap - 1))
        return u64.at[shard, idx].set(rows), cnt_vec.at[shard].add(n)

    acc, xfr, evr = out["accounts"], out["transfers"], out["events"]
    au, a_cnt = append(acc["u64"], acc["count"], a_u64, a_n)
    iota_a = jnp.arange(a_u64.shape[0], dtype=jnp.int32)
    idx_a = jnp.where(iota_a < a_n,
                      acc["count"][shard] + iota_a,
                      jnp.int32(acc["bal"].shape[1] - 1))
    ab = acc["bal"].at[shard, idx_a].set(a_bal)
    xu, x_cnt = append(xfr["u64"], xfr["count"], x_u64, x_n)
    eu, e_cnt = append(evr["u64"], evr["count"], e_u64, e_n)
    out["accounts"] = dict(u64=au, bal=ab, count=a_cnt)
    out["transfers"] = dict(u64=xu, count=x_cnt)
    out["events"] = dict(u64=eu, count=e_cnt)
    return out


def _remap_table_vals(packed, newpos):
    """Remap every live row-index value in a packed table through the
    row permutation (bucket choice depends only on the key, so values
    move without touching the structure). Orphan markers (< 0) and
    empty slots pass through."""
    kh = packed[:, :SLOTS]
    kl = packed[:, SLOTS:2 * SLOTS]
    v = packed[:, 2 * SLOTS:].astype(jnp.int32)
    nonempty = (kh != 0) | (kl != 0)
    liveval = nonempty & (v >= 0)
    cap = newpos.shape[0]
    v2 = jnp.where(liveval,
                   newpos[jnp.clip(v, 0, cap - 1)].astype(jnp.int32), v)
    return jnp.concatenate(
        [kh, kl, v2.astype(jnp.uint64)], axis=1)


def _sort_store(u64, count, ts_col):
    """Canonical re-sort of one store's live rows by timestamp (commit
    timestamps are unique per store). Returns (sorted u64 with the tail
    zeroed, newpos: old row -> new row)."""
    cap = u64.shape[0]
    iota = jnp.arange(cap, dtype=jnp.uint64)
    live = iota < jnp.asarray(count).astype(jnp.uint64)
    # Tie-break dead rows by original index: fully deterministic order
    # without relying on sort stability.
    key = jnp.where(live, u64[:, ts_col], jnp.uint64(_U64_MAX))
    perm = jnp.lexsort((iota, key)).astype(jnp.int32)
    newpos = jnp.zeros(cap, jnp.int32).at[perm].set(
        jnp.arange(cap, dtype=jnp.int32))
    sorted_u64 = jnp.where(jnp.arange(cap)[:, None] < count,
                           u64[perm], jnp.uint64(0))
    return sorted_u64, perm, newpos


def _insert_missing(table, u64, count, orphan_val=None):
    """Insert every live row id absent from `table` with its row index
    as value (the staged rows finalize pass). Returns (table, ok)."""
    cap = u64.shape[0]
    k_hi, k_lo = u64[:, 0], u64[:, 1]
    iota = jnp.arange(cap, dtype=jnp.int32)
    valid = iota < count
    found, _ = ht_lookup(table, k_hi, k_lo)
    ins = valid & ~found
    pos, ok = ht_plan(table, k_hi, k_lo, ins)
    table = ht_write(table, pos, k_hi, k_lo, iota, ins & ok)
    return table, ok


@functools.partial(jax.jit, donate_argnums=0)
def _finalize_shard(stacked, shard, o_hi, o_lo, o_n):
    """Post-copy finalize of the receiving shard: canonical row order
    restored (appended chunks interleave by timestamp with the shard's
    own rows), existing table values remapped through the permutation,
    staged keys inserted at their new positions, and the range's orphan
    markers carried over. Returns (stacked, ok) — ok False means a
    table overflowed and the caller must abort (nothing else checks)."""
    out = jax.tree.map(lambda x: x, stacked)
    acc, xfr = out["accounts"], out["transfers"]

    au = acc["u64"][shard]
    ab = acc["bal"][shard]
    a_cnt = acc["count"][shard]
    au_s, a_perm, a_newpos = _sort_store(au, a_cnt, _AC_TS)
    cap_a = au.shape[0]
    ab_s = jnp.where(jnp.arange(cap_a)[:, None] < a_cnt,
                     ab[a_perm], jnp.uint64(0))
    aht = {"packed": _remap_table_vals(out["acct_ht"]["packed"][shard],
                                       a_newpos)}
    aht, ok_a = _insert_missing(aht, au_s, a_cnt)

    xu = xfr["u64"][shard]
    x_cnt = xfr["count"][shard]
    xu_s, _x_perm, x_newpos = _sort_store(xu, x_cnt, _XF_TS)
    xht = {"packed": _remap_table_vals(out["xfer_ht"]["packed"][shard],
                                       x_newpos)}
    xht, ok_x = _insert_missing(xht, xu_s, x_cnt)
    # The range's orphan markers (transiently-failed ids with no row):
    # unique, absent from the target, valued ORPHAN_VAL forever.
    o_iota = jnp.arange(o_hi.shape[0], dtype=jnp.int32)
    o_ins = o_iota < o_n
    o_pos, ok_o = ht_plan(xht, o_hi, o_lo, o_ins)
    xht = ht_write(xht, o_pos, o_hi, o_lo,
                   jnp.full(o_hi.shape[0], ORPHAN_VAL, jnp.int32),
                   o_ins & ok_o)

    out["accounts"] = dict(u64=acc["u64"].at[shard].set(au_s),
                           bal=acc["bal"].at[shard].set(ab_s),
                           count=acc["count"])
    out["transfers"] = dict(u64=xfr["u64"].at[shard].set(xu_s),
                            count=xfr["count"])
    out["acct_ht"] = {"packed": out["acct_ht"]["packed"].at[shard].set(
        aht["packed"])}
    out["xfer_ht"] = {"packed": out["xfer_ht"]["packed"].at[shard].set(
        xht["packed"])}
    return out, ok_a & ok_x & ok_o


def _drop_range_keys(packed, lo, hi, base_shard, n_shards):
    """Zero every table slot whose key's ownership hash is in [lo, hi]
    with base owner `base_shard` (catches orphan markers — they have
    keys but no rows), then re-compact each bucket's slots to a leading
    non-empty prefix (the planner's occupancy invariant)."""
    kh = packed[:, :SLOTS]
    kl = packed[:, SLOTS:2 * SLOTS]
    v = packed[:, 2 * SLOTS:]
    h = mix_id(kh, kl)
    nonempty = (kh != 0) | (kl != 0)
    inr = ((h >= jnp.asarray(lo).astype(jnp.uint64))
           & (h <= jnp.asarray(hi).astype(jnp.uint64))
           & ((h & jnp.uint64(n_shards - 1)).astype(jnp.int32)
              == base_shard))
    drop = nonempty & inr
    kh = jnp.where(drop, jnp.uint64(0), kh)
    kl = jnp.where(drop, jnp.uint64(0), kl)
    v = jnp.where(drop, jnp.uint64(0), v)
    empty = (kh == 0) & (kl == 0)
    slot_iota = jnp.arange(SLOTS, dtype=jnp.int32)[None, :]
    # Unique per-slot keys (empty flag major, slot index minor): any
    # sort gives the same order, no stability assumption.
    order = jnp.argsort(
        empty.astype(jnp.int32) * jnp.int32(SLOTS) + slot_iota, axis=1)
    kh = jnp.take_along_axis(kh, order, axis=1)
    kl = jnp.take_along_axis(kl, order, axis=1)
    v = jnp.take_along_axis(v, order, axis=1)
    return jnp.concatenate([kh, kl, v], axis=1)


@functools.partial(jax.jit, donate_argnums=0, static_argnums=(4,))
def _evict_range(stacked, shard, lo, hi, n_shards, base_shard):
    """Evict a hash range from one shard's stores and tables: retire
    (shard = the migration source) and abort (shard = the receiver —
    staged rows carry the same base owner, so one kernel serves both).
    Kept rows compact preserving canonical order, dropped and tail rows
    zero, table keys drop with per-bucket re-compaction, surviving
    values remap. The ring is untouched (no id columns — documented
    scratch)."""
    out = jax.tree.map(lambda x: x, stacked)
    acc, xfr = out["accounts"], out["transfers"]

    def evict_store(u64, count):
        cap = u64.shape[0]
        iota = jnp.arange(cap, dtype=jnp.int32)
        live = iota < count
        h = mix_id(u64[:, 0], u64[:, 1])
        inr = ((h >= jnp.asarray(lo).astype(jnp.uint64))
               & (h <= jnp.asarray(hi).astype(jnp.uint64))
               & ((h & jnp.uint64(n_shards - 1)).astype(jnp.int32)
                  == base_shard))
        keep = live & ~inr
        # Kept rows first, in their original (canonical) order.
        key = jnp.where(keep, iota, jnp.int32(cap) + iota)
        perm = jnp.argsort(key).astype(jnp.int32)
        new_count = jnp.sum(keep, dtype=jnp.int32)
        new_u64 = jnp.where(iota[:, None] < new_count, u64[perm],
                            jnp.uint64(0))
        newpos = jnp.zeros(cap, jnp.int32).at[perm].set(iota)
        return new_u64, new_count, perm, newpos

    au, a_cnt2, a_perm, a_newpos = evict_store(acc["u64"][shard],
                                               acc["count"][shard])
    ab = jnp.where(jnp.arange(au.shape[0])[:, None] < a_cnt2,
                   acc["bal"][shard][a_perm], jnp.uint64(0))
    xu, x_cnt2, _xp, x_newpos = evict_store(xfr["u64"][shard],
                                            xfr["count"][shard])

    aht = _drop_range_keys(out["acct_ht"]["packed"][shard], lo, hi,
                           base_shard, n_shards)
    aht = _remap_table_vals(aht, a_newpos)
    xht = _drop_range_keys(out["xfer_ht"]["packed"][shard], lo, hi,
                           base_shard, n_shards)
    xht = _remap_table_vals(xht, x_newpos)

    out["accounts"] = dict(
        u64=acc["u64"].at[shard].set(au),
        bal=acc["bal"].at[shard].set(ab),
        count=acc["count"].at[shard].set(a_cnt2))
    out["transfers"] = dict(
        u64=xfr["u64"].at[shard].set(xu),
        count=xfr["count"].at[shard].set(x_cnt2))
    out["acct_ht"] = {"packed": out["acct_ht"]["packed"].at[shard].set(
        aht)}
    out["xfer_ht"] = {"packed": out["xfer_ht"]["packed"].at[shard].set(
        xht)}
    return out


# --------------------------------------------------------- controller

def _digest_eq(a: dict, b: dict) -> bool:
    return all(int(a[k]) == int(b[k]) for k in a)


class ReshardController:
    """The five-stage migration state machine over a PartitionedRouter.

    Driver contract: construct, `begin(state, plan)` while quiesced,
    then call `on_window(state, batches)` once per commit window BEFORE
    dispatching it (the controller advances one copy chunk per window,
    drains on a range conflict, activates double-write when the copy
    completes, and flips + retires — quiesced, at that same boundary —
    once `min_double_write_windows` windows ran under double-write).
    Every method that touches device state takes and returns the
    stacked state pytree; the caller (DeviceLedger attach mode, or a
    test driving the router directly) owns threading it.

    `batches` may be Transfer-object window batches or SoA ev dicts —
    conflict detection hashes ids either way, bit-identically with the
    device (shard_utils.mix_int / mix_id).

    Aborts raise MigrationAborted AFTER restoring the pre-migration
    world: overlay reverted, staged copy evicted, flight artifact
    frozen (FLIGHT_*_reshard_*). `on_recovery()` is the crash path —
    no device work (the resync rebuild supersedes it), just the
    ownership revert and the `reshard_abort` bookkeeping."""

    STAGES = ("snapshot", "copy", "double_write", "flip", "retire")

    def __init__(self, router, *, tracer=None, chunk_rows: int = 256,
                 min_double_write_windows: int = 2,
                 capacity_margin: int = 8):
        self.router = router
        self.tracer = tracer if tracer is not None \
            else getattr(router, "tracer", None) or NullTracer()
        self.chunk_rows = int(chunk_rows)
        self.min_double_write_windows = int(min_double_write_windows)
        self.capacity_margin = int(capacity_margin)
        self.plan: ReshardPlan | None = None
        self.stage = "idle"
        self.rows_copied = 0
        self.dw_windows = 0
        self.migrations: list = []   # completed-migration records
        self.aborts: list = []       # abort records
        # Test hook: when armed, the next transfer chunk's rows are
        # bit-flipped before install — the flip digest gate must catch
        # it and abort pre-flip (the gate's negative arm).
        self.corrupt_next_chunk = False
        self._snap = None
        self._cursors = None
        self._t0 = None
        self._entry = None

    # -------------------------------------------------------- queries

    @property
    def active(self) -> bool:
        return self.stage in ("copy", "double_write")

    def _pred(self):
        p = self.plan
        n = self.router.n_shards
        lo, hi, src = p.lo, p.hi, p.src
        mask = n - 1

        def inr(id128):
            h = mix_int(id128)
            return lo <= h <= hi and (h & mask) == src

        return inr

    def conflicts(self, batches) -> bool:
        """True if any id a window touches (transfer, pending, debit,
        credit) lies in the frozen range — only meaningful in the copy
        stage (afterwards double-write serves the range live)."""
        if self.stage != "copy" or not batches:
            return False
        inr = self._pred()
        for b in batches:
            if isinstance(b, dict):   # SoA ev dict
                for k in ("id", "pid", "dr", "cr"):
                    hi = np.asarray(b[f"{k}_hi"], dtype=np.uint64)
                    lo = np.asarray(b[f"{k}_lo"], dtype=np.uint64)
                    nz = (hi | lo) != 0
                    h = mix_id(hi[nz], lo[nz])
                    if bool(np.any(
                            (h >= np.uint64(self.plan.lo))
                            & (h <= np.uint64(self.plan.hi))
                            & ((h & np.uint64(self.router.n_shards - 1))
                               == np.uint64(self.plan.src)))):
                        return True
            else:                     # Transfer objects
                for t in b:
                    for i in (t.id, t.pending_id or 0,
                              t.debit_account_id or 0,
                              t.credit_account_id or 0):
                        if i and inr(i):
                            return True
        return False

    # ---------------------------------------------------------- begin

    def begin(self, state, plan: ReshardPlan, oracle=None):
        """SNAPSHOT: verify, freeze, and stage the copy. Returns the
        (unchanged) state. Call quiesced. `oracle` (optional) adds the
        oracle leg to the digest witness and supplies the range's ring
        rows (unattributable from device state alone)."""
        assert self.stage in ("idle", "done", "aborted"), self.stage
        r = self.router
        assert 0 <= plan.src < r.n_shards and 0 <= plan.dst < r.n_shards
        self.plan = plan
        self._t0 = time.monotonic()
        reverse = plan.kind == "merge_back"
        auth = plan.dst if reverse else plan.src   # authoritative copy
        recv = plan.src if reverse else plan.dst   # receiving shard
        if reverse:
            self._entry = self._find_entry(OVERLAY_MIGRATED)
            assert self._entry is not None, \
                "merge_back requires the range's OVERLAY_MIGRATED entry"
        with self.tracer.span(Event.reshard_stage, stage="snapshot",
                              outcome="ok"):
            snap = self._take_snapshot(state, auth, oracle)
            got = partitioned_range_digest(state, plan.lo, plan.hi,
                                           plan.src)[auth]
            if not _digest_eq(got, snap["digest"]):
                self._abort_noop("snapshot_digest",
                                 f"device {got} != snapshot pack")
            if oracle is not None:
                from ..ops.state_epoch import oracle_range_digest
                want = oracle_range_digest(oracle, r.a_cap, plan.lo,
                                           plan.hi, plan.src,
                                           r.n_shards)
                if not _digest_eq(got, want):
                    self._abort_noop("snapshot_oracle_digest",
                                     f"device {got} != oracle {want}")
            self._check_capacity(state, recv, snap)
        self._snap = snap
        self._cursors = dict(a=0, x=0, e=0)
        self.rows_copied = 0
        self.dw_windows = 0
        self.stage = "copy"
        return state

    def _find_entry(self, mode):
        p = self.plan
        for e in self.router.ownership.entries:
            if e[:4] == (p.lo, p.hi, p.src, p.dst) and e[4] == mode:
                return e
        return None

    def _take_snapshot(self, state, auth: int, oracle) -> dict:
        """Fetch the authoritative shard's stores and filter the
        range's rows host-side (canonical order preserved — the source
        store is canonical and the filter is order-stable)."""
        p = self.plan
        n = self.router.n_shards
        sub = jax.device_get(jax.tree.map(lambda x: x[auth], state))

        def sel(u64, count):
            h = mix_id(np.asarray(u64[:, 0], dtype=np.uint64),
                       np.asarray(u64[:, 1], dtype=np.uint64))
            live = np.arange(u64.shape[0]) < int(count)
            inr = ((h >= np.uint64(p.lo)) & (h <= np.uint64(p.hi))
                   & ((h & np.uint64(n - 1)) == np.uint64(p.src)))
            return live & inr

        a_sel = sel(sub["accounts"]["u64"], sub["accounts"]["count"])
        x_sel = sel(sub["transfers"]["u64"], sub["transfers"]["count"])
        a_rows = np.asarray(sub["accounts"]["u64"])[a_sel]
        a_bal = np.asarray(sub["accounts"]["bal"])[a_sel]
        x_rows = np.asarray(sub["transfers"]["u64"])[x_sel]
        # Orphan markers ride the transfer table only (no rows): pull
        # them straight out of the fetched packed matrix.
        packed = np.asarray(sub["xfer_ht"]["packed"])[:-1]
        kh = packed[:, :SLOTS].reshape(-1)
        kl = packed[:, SLOTS:2 * SLOTS].reshape(-1)
        v = packed[:, 2 * SLOTS:].reshape(-1).astype(
            np.int64).astype(np.int32)
        h = mix_id(kh, kl)
        o_sel = (((kh != 0) | (kl != 0)) & (v < 0)
                 & (h >= np.uint64(p.lo)) & (h <= np.uint64(p.hi))
                 & ((h & np.uint64(n - 1)) == np.uint64(p.src)))
        e_rows = np.zeros((0, EV_NCOLS), dtype=np.uint64)
        if oracle is not None:
            e_rows = self._pack_range_events(oracle)
        digest = {k: int(v2) for k, v2 in _range_digest_components(
            dict(accounts=dict(u64=a_rows, bal=a_bal,
                               count=np.int32(len(a_rows))),
                 transfers=dict(u64=x_rows,
                                count=np.int32(len(x_rows)))),
            np.uint64(p.lo), np.uint64(p.hi), np.uint64(p.src), n,
            np).items()}
        return dict(a_u64=a_rows, a_bal=a_bal, x_u64=x_rows,
                    e_u64=e_rows, o_hi=kh[o_sel], o_lo=kl[o_sel],
                    digest=digest)

    def _pack_range_events(self, sm) -> np.ndarray:
        """The range's account-event ring rows, packed from the oracle
        with dump row pointers (non-canonical scope)."""
        from ..ops.ledger import _pack_event_rows
        from .partitioned import _record_owner_id
        inr = self._pred()
        recs = [rec for rec in sm.account_events
                if inr(_record_owner_id(sm, rec))]
        if not recs:
            return np.zeros((0, EV_NCOLS), dtype=np.uint64)
        a_cap_s = self.router.a_cap // self.router.n_shards
        return _pack_event_rows(recs, {}, {}, a_cap_s)["u64"]

    def _check_capacity(self, state, recv: int, snap: dict) -> None:
        """dynamic scatter starts clamp instead of trapping: the whole
        copy's room on the receiver must be proven BEFORE the first
        chunk (margin covers double-write appends while staged)."""
        counts = jax.device_get(dict(
            a=state["accounts"]["count"], x=state["transfers"]["count"],
            e=state["events"]["count"]))
        caps = dict(a=state["accounts"]["u64"].shape[1] - 1,
                    x=state["transfers"]["u64"].shape[1] - 1,
                    e=state["events"]["u64"].shape[1] - 1)
        need = dict(a=len(snap["a_u64"]), x=len(snap["x_u64"]),
                    e=len(snap["e_u64"]))
        for k in ("a", "x", "e"):
            have = caps[k] - int(np.asarray(counts[k])[recv])
            if need[k] + self.capacity_margin > have:
                self._abort_noop(
                    "capacity",
                    f"store {k}: need {need[k]}+{self.capacity_margin} "
                    f"margin, have {have} on shard {recv}")

    # ----------------------------------------------------------- copy

    def on_window(self, state, batches=None, oracle=None):
        """The per-window tick (call BEFORE dispatching the window,
        quiesced at that boundary). Copy stage: one chunk — or a full
        drain when the window conflicts with the frozen range. Double-
        write stage: count the boundary; flip + retire at the
        threshold. Idle/terminal stages: no-op."""
        if self.stage == "copy":
            if self.conflicts(batches):
                while self.stage == "copy":
                    state = self.copy_chunk(state)
            else:
                state = self.copy_chunk(state)
        elif self.stage == "double_write":
            self.dw_windows += 1
            if self.dw_windows >= self.min_double_write_windows:
                state = self.flip(state, oracle=oracle)
        return state

    def drain(self, state, oracle=None):
        """Run the in-flight migration to completion (or abort): the
        epoch-verify gate and shutdown paths call this — the whole-
        state digest is not comparable while a copy is staged."""
        while self.stage == "copy":
            state = self.copy_chunk(state)
        if self.stage == "double_write":
            state = self.flip(state, oracle=oracle)
        return state

    def copy_chunk(self, state):
        """Install the next bounded chunk; on the last one, finalize
        the receiver and activate double-write."""
        assert self.stage == "copy", self.stage
        p, snap, cur = self.plan, self._snap, self._cursors
        C = self.chunk_rows
        recv = p.src if p.kind == "merge_back" else p.dst

        def take(mat, key, ncols):
            k = min(C, len(mat) - cur[key])
            rows = np.zeros((C, ncols), dtype=np.uint64)
            if k > 0:
                rows[:k] = mat[cur[key]:cur[key] + k]
            cur[key] += k
            return rows, k

        with self.tracer.span(Event.reshard_stage, stage="copy",
                              outcome="ok"):
            a_rows, a_k = take(snap["a_u64"], "a", AC_NCOLS)
            a_bal = np.zeros((C, snap["a_bal"].shape[1]),
                             dtype=np.uint64)
            if a_k > 0:
                a_bal[:a_k] = snap["a_bal"][cur["a"] - a_k:cur["a"]]
            x_rows, x_k = take(snap["x_u64"], "x", XF_NCOLS)
            e_rows, e_k = take(snap["e_u64"], "e", EV_NCOLS)
            if self.corrupt_next_chunk and x_k > 0:
                # Fault injection: flip amount bits in the staged rows
                # only — the source stays correct, so the flip gate
                # sees source != target and must abort.
                x_rows[:x_k, XF_U64_IDX["amt_lo"]] ^= np.uint64(0xA5)
                self.corrupt_next_chunk = False
            state = _install_chunk(
                state, np.int32(recv), a_rows, a_bal, np.int32(a_k),
                x_rows, np.int32(x_k), e_rows, np.int32(e_k))
            copied = a_k + x_k + e_k
            self.rows_copied += copied
            if copied:
                self.tracer.count(Event.reshard_rows_copied,
                                  value=copied)
        done = (cur["a"] >= len(snap["a_u64"])
                and cur["x"] >= len(snap["x_u64"])
                and cur["e"] >= len(snap["e_u64"]))
        if done:
            state = self._activate_double_write(state)
        return state

    def _activate_double_write(self, state):
        """Finalize the receiver and swap in the copy-catchup overlay
        (forward: DOUBLE_WRITE appended; merge-back: the MIGRATED entry
        transitions to RETURNING). Traffic on the range resumes —
        writes now land on BOTH copies."""
        p, snap = self.plan, self._snap
        recv = p.src if p.kind == "merge_back" else p.dst
        o_cap = max(1, 1 << int(np.ceil(np.log2(
            max(1, len(snap["o_hi"]))))))
        o_hi = np.zeros(o_cap, dtype=np.uint64)
        o_lo = np.zeros(o_cap, dtype=np.uint64)
        o_hi[:len(snap["o_hi"])] = snap["o_hi"]
        o_lo[:len(snap["o_lo"])] = snap["o_lo"]
        state, ok = _finalize_shard(state, np.int32(recv), o_hi, o_lo,
                                    np.int32(len(snap["o_hi"])))
        if not bool(jax.device_get(ok)):
            return self._abort_device("table_capacity",
                                      f"receiver shard {recv}", state)
        r = self.router
        if p.kind == "merge_back":
            table = r.ownership.transition(self._entry,
                                           OVERLAY_RETURNING)
            self._entry = (p.lo, p.hi, p.src, p.dst, OVERLAY_RETURNING)
        else:
            table = r.ownership.with_entry(p.lo, p.hi, p.src, p.dst,
                                           OVERLAY_DOUBLE_WRITE)
            self._entry = (p.lo, p.hi, p.src, p.dst,
                           OVERLAY_DOUBLE_WRITE)
        r.set_ownership(table)
        self.tracer.gauge(Event.reshard_overlay_active,
                          len(table.entries))
        self.stage = "double_write"
        return state

    # ----------------------------------------------------------- flip

    def flip(self, state, oracle=None):
        """The witness-gated ownership switch (call quiesced, at a
        window boundary). Source and target range digests — content
        AND row counts — must be bit-equal; the oracle's too when the
        driver holds one. Clean: ownership moves and the stale copy
        retires in the same boundary. Mismatch: abort (overlay
        reverted, staged copy evicted, artifact frozen)."""
        assert self.stage == "double_write", self.stage
        p, r = self.plan, self.router
        comps = partitioned_range_digest(state, p.lo, p.hi, p.src)
        src_d, dst_d = comps[p.src], comps[p.dst]
        if not _digest_eq(src_d, dst_d):
            with self.tracer.span(Event.reshard_stage, stage="flip",
                                  outcome="abort"):
                return self._abort_device(
                    "digest_mismatch",
                    f"src {src_d} != dst {dst_d}", state)
        if oracle is not None:
            from ..ops.state_epoch import oracle_range_digest
            want = oracle_range_digest(oracle, r.a_cap, p.lo, p.hi,
                                       p.src, r.n_shards)
            if not _digest_eq(src_d, want):
                with self.tracer.span(Event.reshard_stage,
                                      stage="flip", outcome="abort"):
                    return self._abort_device(
                        "oracle_digest_mismatch",
                        f"device {src_d} != oracle {want}", state)
        with self.tracer.span(Event.reshard_stage, stage="flip",
                              outcome="ok"):
            if p.kind == "merge_back":
                table = r.ownership.without_entry(self._entry)
            else:
                table = r.ownership.transition(self._entry,
                                               OVERLAY_MIGRATED)
            r.set_ownership(table)
            self.tracer.gauge(
                Event.reshard_overlay_active,
                sum(1 for e in table.entries
                    if e[4] != OVERLAY_MIGRATED))
        return self._retire(state)

    def _retire(self, state):
        """Evict the now-stale copy (source forward, receiver's old
        authority on merge-back) in the same quiesced boundary as the
        flip — no window ever sees both copies as readable."""
        p = self.plan
        stale = p.dst if p.kind == "merge_back" else p.src
        with self.tracer.span(Event.reshard_stage, stage="retire",
                              outcome="ok"):
            state = _evict_range(state, np.int32(stale),
                                 np.uint64(p.lo), np.uint64(p.hi),
                                 self.router.n_shards,
                                 np.int32(p.src))
        self.migrations.append(dict(
            kind=p.kind, lo=p.lo, hi=p.hi, src=p.src, dst=p.dst,
            rows_copied=self.rows_copied,
            double_write_windows=self.dw_windows,
            duration_s=round(time.monotonic() - self._t0, 6)))
        self._reset("done")
        return state

    # ---------------------------------------------------------- abort

    def _abort_noop(self, reason: str, detail: str):
        """Abort before anything was staged on device."""
        self._record_abort(reason, detail)
        raise MigrationAborted(reason, detail)

    def _abort_device(self, reason: str, detail: str, state):
        """Abort with staged rows on the receiver: revert the overlay
        (a RETURNING merge-back reverts to MIGRATED — the pre-copy
        owner), evict the staged copy, freeze the artifact, raise."""
        p, r = self.plan, self.router
        recv = p.src if p.kind == "merge_back" else p.dst
        if self._entry is not None \
                and self._entry in r.ownership.entries:
            if p.kind == "merge_back":
                table = r.ownership.transition(self._entry,
                                               OVERLAY_MIGRATED)
            else:
                table = r.ownership.without_entry(self._entry)
            r.set_ownership(table)
            self.tracer.gauge(
                Event.reshard_overlay_active,
                sum(1 for e in table.entries
                    if e[4] != OVERLAY_MIGRATED))
        state = _evict_range(state, np.int32(recv), np.uint64(p.lo),
                             np.uint64(p.hi), r.n_shards,
                             np.int32(p.src))
        self._record_abort(reason, detail)
        err = MigrationAborted(reason, detail)
        err.state = state
        raise err

    def _record_abort(self, reason: str, detail: str) -> None:
        self.aborts.append(dict(reason=reason, detail=detail[:200],
                                stage=self.stage,
                                rows_copied=self.rows_copied))
        self.router.flight.record(
            window=getattr(self.router, "_window_seq", 0),
            route="reshard_abort", reason=reason, detail=detail[:200],
            stage=self.stage)
        self.router.flight.dump(f"reshard_abort_{reason}")
        self.tracer.count(Event.serving_recoveries,
                          cause="reshard_abort")
        self._reset("aborted")

    def on_recovery(self) -> None:
        """Crash/quarantine mid-migration: revert the overlay entry (a
        pre-flip migration serves from its old owner again) WITHOUT
        device eviction — the caller rebuilds the whole sharded state
        from the oracle (`PartitionedRouter.resync`), which places
        every range by the reverted table. Post-flip there is nothing
        to revert (the MIGRATED entry is the collapsed base override
        and the rebuild honors it)."""
        if not self.active:
            return
        r = self.router
        if self._entry is not None \
                and self._entry in r.ownership.entries:
            if self.plan.kind == "merge_back":
                table = r.ownership.transition(self._entry,
                                               OVERLAY_MIGRATED)
            else:
                table = r.ownership.without_entry(self._entry)
            r.set_ownership(table)
        self._record_abort("recovery", "crash/quarantine mid-migration")

    def _reset(self, terminal: str) -> None:
        self.stage = terminal
        self._snap = None
        self._cursors = None
        self._entry = None
        self.plan = None


# --------------------------------------------------- hot-range detector

@dataclass
class HotRangeDetector:
    """Propose-only split planner: folds per-shard routed-event counts
    (the router's device-telemetry `events_owned` words) and a decayed
    per-account hash histogram into either a split proposal for the
    hottest shard or the degenerate `unsplittable` verdict — ONE
    account carrying the load, which no hash range smaller than the
    whole shard isolates (anti-thrash: no proposal is emitted, the
    verdict names the account hash; the remedy is AT2 lane parallelism
    within the account's commit lane, not placement).

    Enacting a proposal is the driver's decision (`--auto-reshard`);
    the detector never mutates ownership."""

    n_shards: int
    hot_ratio: float = 2.0
    top_frac: float = 0.5
    decay: float = 0.5
    min_events: int = 64
    max_tracked: int = 4096
    cooldown_windows: int = 4
    _loads: np.ndarray = field(default=None, repr=False)
    _hashes: dict = field(default_factory=dict, repr=False)
    _cooldown: int = 0

    def __post_init__(self):
        assert self.n_shards & (self.n_shards - 1) == 0, self.n_shards
        self._loads = np.zeros(self.n_shards, dtype=np.float64)

    def observe_window(self, evs) -> None:
        """Fold one window's account traffic (SoA ev dicts or Transfer
        object batches): every touched account hash lands in the
        per-shard load vector and the hash histogram."""
        hs = []
        for b in evs:
            if isinstance(b, dict):
                for k in ("dr", "cr"):
                    hs.append(mix_id(
                        np.asarray(b[f"{k}_hi"], dtype=np.uint64),
                        np.asarray(b[f"{k}_lo"], dtype=np.uint64)))
            else:
                hs.append(np.array(
                    [mix_int(i) for t in b
                     for i in (t.debit_account_id,
                               t.credit_account_id) if i],
                    dtype=np.uint64))
        if not hs:
            return
        h = np.concatenate([x[x != mix_int(0)] if x.size else x
                            for x in hs])
        if h.size == 0:
            return
        shards = (h & np.uint64(self.n_shards - 1)).astype(np.int64)
        self._loads *= self.decay
        np.add.at(self._loads, shards, 1.0)
        for k in self._hashes:
            self._hashes[k] *= self.decay
        uniq, cnt = np.unique(h, return_counts=True)
        for hv, c in zip(uniq.tolist(), cnt.tolist()):
            self._hashes[hv] = self._hashes.get(hv, 0.0) + c
        if len(self._hashes) > self.max_tracked:
            keep = sorted(self._hashes.items(), key=lambda kv: -kv[1])
            self._hashes = dict(keep[:self.max_tracked // 2])
        if self._cooldown > 0:
            self._cooldown -= 1

    def propose(self) -> dict | None:
        """None while balanced (or cooling down / under-sampled); else
        {"verdict": "split", "plan": ReshardPlan, ...} or
        {"verdict": "unsplittable", ...}."""
        total = float(self._loads.sum())
        if total < self.min_events or self._cooldown > 0:
            return None
        mean = total / self.n_shards
        hot = int(self._loads.argmax())
        if self._loads[hot] < self.hot_ratio * mean:
            return None
        shard_hashes = sorted(
            (hv, w) for hv, w in self._hashes.items()
            if (hv & (self.n_shards - 1)) == hot)
        shard_w = sum(w for _, w in shard_hashes)
        if not shard_hashes or shard_w <= 0:
            return None
        top_hash, top_w = max(shard_hashes, key=lambda kv: kv[1])
        self._cooldown = self.cooldown_windows
        if top_w / shard_w >= self.top_frac:
            return dict(verdict="unsplittable", shard=hot,
                        hot_hash=int(top_hash),
                        fraction=round(top_w / shard_w, 4),
                        note="single hot account dominates: no hash "
                             "range isolates it — needs AT2 lane "
                             "parallelism, not placement")
        # Split at the weighted median hash: ~half the observed load
        # moves. dst = the coldest shard.
        acc = 0.0
        mid = shard_hashes[-1][0]
        for hv, w in shard_hashes:
            acc += w
            if acc >= shard_w / 2:
                mid = hv
                break
        dst = int(self._loads.argmin())
        if dst == hot:
            return None
        plan = ReshardPlan(lo=0, hi=int(mid), src=hot, dst=dst,
                           kind="split")
        return dict(verdict="split", shard=hot, plan=plan,
                    load=float(self._loads[hot]), mean=mean)
