"""SPMD-sharded batch validation over a device mesh.

Events are sharded along the batch axis; the account cache (the hot working
set, equivalent of the reference's groove object cache —
src/lsm/groove.zig:885) is replicated. Each device validates its slice of
events and produces a dense per-account balance-delta tensor; deltas are
summed with `psum` over ICI and applied identically on every device, so the
replicated account state stays bit-identical across the mesh — the SPMD
restatement of the reference's determinism doctrine
(docs/ARCHITECTURE.md:281-307).

Carry-exactness across the mesh: u64 limbs are split into 32-bit halves and
the halves are psum'd BEFORE recombining, so neither intra-device segment
sums nor the cross-device reduction can drop a carry (each 32-bit half sum
stays far below 2^64 for any batch/mesh size).

This module implements the *order-independent* subset of the
create_transfers checks (the full sequential semantics live in
ops/create_kernels.py; the single-chip vectorized fast path in
ops/fast_kernels.py). It is the multi-chip scaling skeleton: the same
shard_map layout carries the fast-path kernel across chips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import u128
from ..ops.create_kernels import (
    _CREATED,
    _TF_PADDING,
    _TS,
    _first_failure,
)

_F_PENDING = jnp.uint32(1 << 1)
_A_CLOSED = jnp.uint32(1 << 5)


def _validate_shard(ev, acct):
    """Validate one shard of events against the replicated account cache.

    Returns (status, delta_parts) where delta_parts holds four u64 arrays of
    32-bit half sums per balance limb field — recombined only after psum.
    """
    dr = {k: acct[k][ev["dr_idx"]] for k in acct}
    cr = {k: acct[k][ev["cr_idx"]] for k in acct}
    pending = (ev["flags"] & _F_PENDING) != 0

    checks = [
        ((ev["flags"] & _TF_PADDING) != 0, _TS["reserved_flag"]),
        (u128.is_zero(ev["id_hi"], ev["id_lo"]), _TS["id_must_not_be_zero"]),
        (u128.is_max(ev["id_hi"], ev["id_lo"]), _TS["id_must_not_be_int_max"]),
        (u128.is_zero(ev["dr_hi"], ev["dr_lo"]), _TS["debit_account_id_must_not_be_zero"]),
        (u128.is_max(ev["dr_hi"], ev["dr_lo"]), _TS["debit_account_id_must_not_be_int_max"]),
        (u128.is_zero(ev["cr_hi"], ev["cr_lo"]), _TS["credit_account_id_must_not_be_zero"]),
        (u128.is_max(ev["cr_hi"], ev["cr_lo"]), _TS["credit_account_id_must_not_be_int_max"]),
        (u128.eq(ev["dr_hi"], ev["dr_lo"], ev["cr_hi"], ev["cr_lo"]),
         _TS["accounts_must_be_different"]),
        (~u128.is_zero(ev["pid_hi"], ev["pid_lo"]), _TS["pending_id_must_be_zero"]),
        (~pending & (ev["timeout"] != 0), _TS["timeout_reserved_for_pending_transfer"]),
        (ev["ledger"] == 0, _TS["ledger_must_not_be_zero"]),
        (ev["code"] == 0, _TS["code_must_not_be_zero"]),
        (~dr["exists"], _TS["debit_account_not_found"]),
        (~cr["exists"], _TS["credit_account_not_found"]),
        (dr["ledger"] != cr["ledger"], _TS["accounts_must_have_the_same_ledger"]),
        (ev["ledger"] != dr["ledger"], _TS["transfer_must_have_the_same_ledger_as_accounts"]),
        ((dr["flags"] & _A_CLOSED) != 0, _TS["debit_account_already_closed"]),
        ((cr["flags"] & _A_CLOSED) != 0, _TS["credit_account_already_closed"]),
    ]
    status = jnp.where(ev["valid"], _first_failure(checks), jnp.uint32(0))
    created = status == _CREATED

    A = acct["exists"].shape[0]

    def seg_sum_parts(idx, hi, lo, mask):
        """Per-account sums as four 32-bit half-sum arrays (u64 lanes)."""
        hi = jnp.where(mask, hi, jnp.uint64(0))
        lo = jnp.where(mask, lo, jnp.uint64(0))
        parts = []
        for limb in (lo, hi):
            lo32 = limb & jnp.uint64(0xFFFFFFFF)
            hi32 = limb >> jnp.uint64(32)
            parts.append(jax.ops.segment_sum(lo32, idx, num_segments=A))
            parts.append(jax.ops.segment_sum(hi32, idx, num_segments=A))
        return parts

    delta_parts = dict(
        dpos=seg_sum_parts(ev["dr_idx"], ev["amt_hi"], ev["amt_lo"],
                           created & ~pending),
        cpos=seg_sum_parts(ev["cr_idx"], ev["amt_hi"], ev["amt_lo"],
                           created & ~pending),
        dp=seg_sum_parts(ev["dr_idx"], ev["amt_hi"], ev["amt_lo"],
                         created & pending),
        cp=seg_sum_parts(ev["cr_idx"], ev["amt_hi"], ev["amt_lo"],
                         created & pending),
    )
    return status, delta_parts


def _recombine(parts):
    """Four psum'd 32-bit half sums -> exact (hi, lo) u128 delta."""
    p0, p1, p2, p3 = parts
    add_hi32 = p1 << jnp.uint64(32)
    lo = p0 + add_hi32
    carry = (p1 >> jnp.uint64(32)) + jnp.where(
        lo < add_hi32, jnp.uint64(1), jnp.uint64(0))
    hi = p2 + (p3 << jnp.uint64(32)) + carry
    return hi, lo


def make_sharded_validate(mesh: Mesh, axis: str = "batch"):
    """Build the jitted SPMD validation step over `mesh`.

    Returns step(events, acct) -> (statuses, new_acct) with events sharded on
    `axis`, account state replicated, and balance deltas combined via psum.
    """

    def step(ev, acct):
        def shard_fn(ev, acct):
            status, delta_parts = _validate_shard(ev, acct)
            # One psum per 32-bit half-sum leaf: carry-safe, and plain sum
            # all-reduces lower on every backend.
            delta_parts = {
                k: [jax.lax.psum(p, axis) for p in parts]
                for k, parts in delta_parts.items()
            }
            new_acct = dict(acct)
            for field, parts in delta_parts.items():
                d_hi, d_lo = _recombine(parts)
                hi, lo, _ = u128.add(
                    acct[f"{field}_hi"], acct[f"{field}_lo"], d_hi, d_lo)
                new_acct[f"{field}_hi"] = hi
                new_acct[f"{field}_lo"] = lo
            return status, new_acct

        ev_spec = {k: P(axis) for k in ev}
        acct_spec = {k: P() for k in acct}
        return shard_map(
            shard_fn, mesh=mesh,
            in_specs=(ev_spec, acct_spec),
            out_specs=(P(axis), acct_spec),
            check_rep=False,
        )(ev, acct)

    return jax.jit(step)


def sharded_demo_inputs(n_devices: int, events_per_device: int = 16, n_accounts: int = 8):
    """Tiny deterministic inputs for the multi-chip dryrun."""
    import numpy as np

    N = n_devices * events_per_device
    A = n_accounts
    ids = np.arange(1, N + 1, dtype=np.uint64)
    dr_idx = (np.arange(N) % (A - 1) + 1).astype(np.int32)
    cr_idx = ((np.arange(N) + 1) % (A - 1) + 1).astype(np.int32)
    # Make dr != cr everywhere (wraparound can collide).
    cr_idx = np.where(cr_idx == dr_idx, ((cr_idx % (A - 1)) + 1).astype(np.int32), cr_idx)
    z64 = np.zeros(N, dtype=np.uint64)
    ev = dict(
        valid=np.ones(N, dtype=bool),
        id_hi=z64, id_lo=ids,
        dr_hi=z64, dr_lo=dr_idx.astype(np.uint64),
        cr_hi=z64, cr_lo=cr_idx.astype(np.uint64),
        amt_hi=z64, amt_lo=np.full(N, 10, dtype=np.uint64),
        pid_hi=z64, pid_lo=z64,
        ud128_hi=z64, ud128_lo=z64,
        ud64=z64, ud32=np.zeros(N, dtype=np.uint32),
        timeout=np.zeros(N, dtype=np.uint32),
        ledger=np.ones(N, dtype=np.uint32),
        code=np.ones(N, dtype=np.uint32),
        flags=np.zeros(N, dtype=np.uint32),
        ts=z64,
        dr_idx=dr_idx, cr_idx=cr_idx,
    )
    za = np.zeros(A, dtype=np.uint64)
    acct = dict(
        exists=np.ones(A, dtype=bool),
        dp_hi=za.copy(), dp_lo=za.copy(),
        dpos_hi=za.copy(), dpos_lo=za.copy(),
        cp_hi=za.copy(), cp_lo=za.copy(),
        cpos_hi=za.copy(), cpos_lo=za.copy(),
        ledger=np.ones(A, dtype=np.uint32),
        code=np.ones(A, dtype=np.uint32),
        flags=np.zeros(A, dtype=np.uint32),
        ts=np.arange(A, dtype=np.uint64),
    )
    acct["exists"][0] = False
    return ev, acct
