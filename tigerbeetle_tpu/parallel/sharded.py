"""SPMD-sharded batch validation over a device mesh.

Events are sharded along the batch axis; the account cache (the hot working
set, equivalent of the reference's groove object cache —
src/lsm/groove.zig:885) is replicated. Each device validates its slice of
events and produces a dense per-account balance-delta tensor; deltas are
summed with `psum` over ICI and applied identically on every device, so the
replicated account state stays bit-identical across the mesh — the SPMD
restatement of the reference's determinism doctrine
(docs/ARCHITECTURE.md:281-307).

This module intentionally implements the *order-independent* subset of the
create_transfers checks (the full sequential semantics live in
ops/create_kernels.py; the single-chip vectorized fast path in
ops/fast_kernels.py). It is the multi-chip scaling skeleton: the same
shard_map layout carries the fast-path kernel across chips.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import u128

_CREATED = jnp.uint32(0xFFFFFFFF)

# Wire codes (types.CreateTransferStatus values), kept in check order.
_CODES = dict(
    reserved_flag=4,
    id_must_not_be_zero=5,
    id_must_not_be_int_max=6,
    debit_account_id_must_not_be_zero=8,
    debit_account_id_must_not_be_int_max=9,
    credit_account_id_must_not_be_zero=10,
    credit_account_id_must_not_be_int_max=11,
    accounts_must_be_different=12,
    pending_id_must_be_zero=13,
    timeout_reserved_for_pending_transfer=17,
    ledger_must_not_be_zero=19,
    code_must_not_be_zero=20,
    debit_account_not_found=21,
    credit_account_not_found=22,
    accounts_must_have_the_same_ledger=23,
    transfer_must_have_the_same_ledger_as_accounts=24,
    debit_account_already_closed=65,
    credit_account_already_closed=66,
)

_F_PENDING = jnp.uint32(1 << 1)
_TF_PADDING = jnp.uint32(0xFFFF & ~0x1FF)
_A_CLOSED = jnp.uint32(1 << 5)


def _first_failure(checks):
    status = _CREATED
    for cond, code in reversed(checks):
        status = jnp.where(cond, jnp.uint32(code), status)
    return status


def _validate_shard(ev, acct, n_events, timestamp):
    """Validate one shard of events against the replicated account cache."""
    dr = {k: acct[k][ev["dr_idx"]] for k in acct}
    cr = {k: acct[k][ev["cr_idx"]] for k in acct}
    pending = (ev["flags"] & _F_PENDING) != 0

    checks = [
        ((ev["flags"] & _TF_PADDING) != 0, _CODES["reserved_flag"]),
        (u128.is_zero(ev["id_hi"], ev["id_lo"]), _CODES["id_must_not_be_zero"]),
        (u128.is_max(ev["id_hi"], ev["id_lo"]), _CODES["id_must_not_be_int_max"]),
        (u128.is_zero(ev["dr_hi"], ev["dr_lo"]), _CODES["debit_account_id_must_not_be_zero"]),
        (u128.is_max(ev["dr_hi"], ev["dr_lo"]), _CODES["debit_account_id_must_not_be_int_max"]),
        (u128.is_zero(ev["cr_hi"], ev["cr_lo"]), _CODES["credit_account_id_must_not_be_zero"]),
        (u128.is_max(ev["cr_hi"], ev["cr_lo"]), _CODES["credit_account_id_must_not_be_int_max"]),
        (u128.eq(ev["dr_hi"], ev["dr_lo"], ev["cr_hi"], ev["cr_lo"]),
         _CODES["accounts_must_be_different"]),
        (~u128.is_zero(ev["pid_hi"], ev["pid_lo"]), _CODES["pending_id_must_be_zero"]),
        (~pending & (ev["timeout"] != 0), _CODES["timeout_reserved_for_pending_transfer"]),
        (ev["ledger"] == 0, _CODES["ledger_must_not_be_zero"]),
        (ev["code"] == 0, _CODES["code_must_not_be_zero"]),
        (~dr["exists"], _CODES["debit_account_not_found"]),
        (~cr["exists"], _CODES["credit_account_not_found"]),
        (dr["ledger"] != cr["ledger"], _CODES["accounts_must_have_the_same_ledger"]),
        (ev["ledger"] != dr["ledger"], _CODES["transfer_must_have_the_same_ledger_as_accounts"]),
        ((dr["flags"] & _A_CLOSED) != 0, _CODES["debit_account_already_closed"]),
        ((cr["flags"] & _A_CLOSED) != 0, _CODES["credit_account_already_closed"]),
    ]
    status = jnp.where(ev["valid"], _first_failure(checks), jnp.uint32(0))
    created = status == _CREATED

    # Dense per-account delta tensors, carry-exact: u64 limbs are split into
    # 32-bit halves so segment sums cannot wrap, then recombined.
    A = acct["exists"].shape[0]

    def seg_sum_u128(idx, hi, lo, mask):
        hi = jnp.where(mask, hi, jnp.uint64(0))
        lo = jnp.where(mask, lo, jnp.uint64(0))
        parts = []
        for limb in (lo, hi):
            lo32 = limb & jnp.uint64(0xFFFFFFFF)
            hi32 = limb >> jnp.uint64(32)
            parts.append(jax.ops.segment_sum(lo32, idx, num_segments=A))
            parts.append(jax.ops.segment_sum(hi32, idx, num_segments=A))
        add_hi32 = parts[1] << jnp.uint64(32)
        s_lo = parts[0] + add_hi32
        carry = (parts[1] >> jnp.uint64(32)) + jnp.where(
            s_lo < add_hi32, jnp.uint64(1), jnp.uint64(0))
        s_hi = parts[2] + (parts[3] << jnp.uint64(32)) + carry
        return s_hi, s_lo

    d_dpos_hi, d_dpos_lo = seg_sum_u128(
        ev["dr_idx"], ev["amt_hi"], ev["amt_lo"], created & ~pending)
    d_cpos_hi, d_cpos_lo = seg_sum_u128(
        ev["cr_idx"], ev["amt_hi"], ev["amt_lo"], created & ~pending)
    d_dp_hi, d_dp_lo = seg_sum_u128(
        ev["dr_idx"], ev["amt_hi"], ev["amt_lo"], created & pending)
    d_cp_hi, d_cp_lo = seg_sum_u128(
        ev["cr_idx"], ev["amt_hi"], ev["amt_lo"], created & pending)

    deltas = dict(
        dpos_hi=d_dpos_hi, dpos_lo=d_dpos_lo,
        cpos_hi=d_cpos_hi, cpos_lo=d_cpos_lo,
        dp_hi=d_dp_hi, dp_lo=d_dp_lo,
        cp_hi=d_cp_hi, cp_lo=d_cp_lo,
    )
    return status, deltas


def make_sharded_validate(mesh: Mesh, axis: str = "batch"):
    """Build the jitted SPMD validation step over `mesh`.

    Returns step(events, acct, n_events, timestamp) ->
    (statuses, new_acct) with events sharded on `axis`, account state
    replicated, and balance deltas combined via psum over the mesh.
    """

    def step(ev, acct, n_events, timestamp):
        def shard_fn(ev, acct, n_events, timestamp):
            status, deltas = _validate_shard(ev, acct, n_events, timestamp)
            # One psum per leaf: some backends lower only plain sum
            # all-reduces, not tuple-combined ones.
            deltas = {k: jax.lax.psum(v, axis) for k, v in deltas.items()}
            new_acct = dict(acct)
            for field in ("dp", "dpos", "cp", "cpos"):
                hi, lo, _ = u128.add(
                    acct[f"{field}_hi"], acct[f"{field}_lo"],
                    deltas[f"{field}_hi"], deltas[f"{field}_lo"])
                new_acct[f"{field}_hi"] = hi
                new_acct[f"{field}_lo"] = lo
            return status, new_acct

        ev_spec = {k: P(axis) for k in ev}
        acct_spec = {k: P() for k in acct}
        return shard_map(
            shard_fn, mesh=mesh,
            in_specs=(ev_spec, acct_spec, P(), P()),
            out_specs=({k: P(axis) for k in ev}["id_lo"], acct_spec),
            check_rep=False,
        )(ev, acct, n_events, timestamp)

    return jax.jit(step)


def sharded_demo_inputs(n_devices: int, events_per_device: int = 16, n_accounts: int = 8):
    """Tiny deterministic inputs for the multi-chip dryrun."""
    import numpy as np

    N = n_devices * events_per_device
    A = n_accounts
    ids = np.arange(1, N + 1, dtype=np.uint64)
    dr_idx = (np.arange(N) % (A - 1) + 1).astype(np.int32)
    cr_idx = ((np.arange(N) + 1) % (A - 1) + 1).astype(np.int32)
    # Make dr != cr everywhere (wraparound can collide).
    cr_idx = np.where(cr_idx == dr_idx, ((cr_idx % (A - 1)) + 1).astype(np.int32), cr_idx)
    z64 = np.zeros(N, dtype=np.uint64)
    ev = dict(
        valid=np.ones(N, dtype=bool),
        id_hi=z64, id_lo=ids,
        dr_hi=z64, dr_lo=dr_idx.astype(np.uint64),
        cr_hi=z64, cr_lo=cr_idx.astype(np.uint64),
        amt_hi=z64, amt_lo=np.full(N, 10, dtype=np.uint64),
        pid_hi=z64, pid_lo=z64,
        ud128_hi=z64, ud128_lo=z64,
        ud64=z64, ud32=np.zeros(N, dtype=np.uint32),
        timeout=np.zeros(N, dtype=np.uint32),
        ledger=np.ones(N, dtype=np.uint32),
        code=np.ones(N, dtype=np.uint32),
        flags=np.zeros(N, dtype=np.uint32),
        ts=z64,
        dr_idx=dr_idx, cr_idx=cr_idx,
    )
    za = np.zeros(A, dtype=np.uint64)
    acct = dict(
        exists=np.ones(A, dtype=bool),
        dp_hi=za.copy(), dp_lo=za.copy(),
        dpos_hi=za.copy(), dpos_lo=za.copy(),
        cp_hi=za.copy(), cp_lo=za.copy(),
        cpos_hi=za.copy(), cpos_lo=za.copy(),
        ledger=np.ones(A, dtype=np.uint32),
        code=np.ones(A, dtype=np.uint32),
        flags=np.zeros(A, dtype=np.uint32),
        ts=np.arange(A, dtype=np.uint64),
    )
    acct["exists"][0] = False
    return ev, acct
