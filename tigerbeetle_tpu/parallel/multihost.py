"""Multi-host mesh: jax.distributed initialization + the 2-process leg.

The partitioned route shards ledger state by account/transfer range
over a device mesh; nothing in the route cares whether those devices
hang off one host. This module supplies the multi-controller plumbing
that stretches the mesh 8 -> 8xN:

  - ``init_multihost``: idempotent ``jax.distributed.initialize``
    wrapper (coordinator address + process count + process id from
    args or the standard env vars). Every process runs the SAME
    program; after init, ``jax.devices()`` is the GLOBAL device list
    and a mesh built over it spans hosts — shard_map + psum inside it
    become cross-host collectives with no change to the partitioned
    step itself.
  - ``global_mesh``: the 1-D partitioned mesh over the global device
    list.
  - ``two_process_smoke``: the gate's local multi-controller leg — two
    coordinator-connected processes on this host, each owning half the
    virtual CPU mesh, drive one fused partitioned-chain window and
    check oracle parity on the replicated results. Environments
    without multi-process support (no distributed runtime, no CPU
    cross-process collectives) SKIP gracefully: only a parity break is
    a red, never a missing capability.

Production deployment (one process per TPU host, coordinator =
host 0) is documented in docs/operating/cluster.md "Multi-host mesh".
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time

_INITIALIZED = False


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> bool:
    """Bring up the multi-controller runtime. Returns True when
    distributed init succeeded (or already ran), False when the
    runtime is unavailable in this environment — callers treat False
    as "single-host mesh", not an error. Arguments default to the
    standard JAX env vars (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES,
    JAX_PROCESS_ID); with none present and no args, this is a no-op
    single-process True."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        # Single-process: nothing to initialize, the local mesh IS the
        # global mesh.
        return True
    try:
        import jax

        # CPU cross-process collectives need an explicit impl (gloo)
        # where supported; harmless no-op elsewhere.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=(num_processes
                           if num_processes is not None else
                           int(os.environ.get("JAX_NUM_PROCESSES", 1))),
            process_id=(process_id if process_id is not None else
                        int(os.environ.get("JAX_PROCESS_ID", 0))))
        _INITIALIZED = True
        return True
    except Exception as e:  # runtime absent / backend refuses: skip
        print(f"[multihost] distributed init unavailable: {e!r}",
              flush=True)
        return False


def global_mesh(axis: str = "batch"):
    """The 1-D partitioned mesh over the GLOBAL device list (after
    init_multihost, that spans every connected process's devices)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis,))


# ------------------------------------------------ 2-process local leg

_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); coord = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4").strip()
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
try:
    from tigerbeetle_tpu.parallel import multihost
    if not multihost.init_multihost(coord, nproc, pid):
        print("MULTIHOST_SKIP: distributed init unavailable",
              flush=True)
        sys.exit(0)
    import jax
    import numpy as np
    if len(jax.devices()) != 4 * nproc:
        print(f"MULTIHOST_SKIP: global device list is "
              f"{len(jax.devices())}, expected {4 * nproc}", flush=True)
        sys.exit(0)
    from tigerbeetle_tpu.oracle import StateMachineOracle
    from tigerbeetle_tpu.ops.batch import transfers_to_arrays
    from tigerbeetle_tpu.parallel.partitioned import PartitionedRouter
    from tigerbeetle_tpu.types import Account, Transfer

    mesh = multihost.global_mesh()
    oracle = StateMachineOracle()
    oracle.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 17)], 50)
    router = PartitionedRouter(mesh, a_cap=1 << 8, t_cap=1 << 9)
    state = router.from_oracle(oracle)
    rng = np.random.default_rng(31)
    nid, ts = 10 ** 6, 10 ** 9
    window, tss = [], []
    for _ in range(2):  # W=2: one fused cross-host dispatch
        evs = []
        for _ in range(6):
            dr, cr = (int(x) for x in rng.choice(
                np.arange(1, 17), 2, replace=False))
            evs.append(Transfer(id=nid, debit_account_id=dr,
                                credit_account_id=cr,
                                amount=int(rng.integers(1, 20)),
                                ledger=1, code=1))
            nid += 1
        ts += 300
        window.append(evs)
        tss.append(ts)
    state, results = router.step_window(
        state, [transfers_to_arrays(e) for e in window], tss)
except AssertionError:
    raise  # parity breaks are a RED, not a skip
except Exception as e:
    print(f"MULTIHOST_SKIP: {e!r}"[:300], flush=True)
    sys.exit(0)
# The route and parity asserts run OUTSIDE the skip net: once the
# runtime is up, a wrong answer must fail the leg.
assert router.window_routes.get("partitioned_chain") == 1, \
    router.window_routes
assert router.host_fallbacks == 0, router.stats()
for evs, t, (st, rts) in zip(window, tss, results):
    want = oracle.create_transfers(evs, t)
    got = [(int(rts[i]), int(st[i])) for i in range(len(evs))]
    assert got == [(r.timestamp, int(r.status)) for r in want], got
print(f"MULTIHOST_OK process={pid}", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _smoke_attempt(timeout: float) -> str:
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(pid), "2", coord],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(2)]
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        left = max(1.0, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=left)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            # A wedged coordinator handshake is an environment
            # limitation, not a ledger bug: skip, loudly.
            return "skipped: 2-process leg timed out (coordinator " \
                   "handshake unavailable?)"
        outs.append(out or "")
    for p, out in zip(procs, outs):
        if p.returncode != 0:
            if "AssertionError" in out:
                # A parity/route break with the runtime UP: a real red.
                raise RuntimeError(
                    f"multihost 2-process leg RED "
                    f"(rc={p.returncode}):\n" + out[-2000:])
            # Transport-layer crashes (the CPU gloo backend aborts on a
            # TCP race now and then) are an environment limitation.
            return ("skipped: worker crashed in the multi-process "
                    f"runtime (rc={p.returncode}): " + out[-200:])
    if all("MULTIHOST_OK" in o for o in outs):
        return "ok"
    reason = next((line for o in outs for line in o.splitlines()
                   if line.startswith("MULTIHOST_SKIP")),
                  "MULTIHOST_SKIP: no marker")
    return "skipped: " + reason.split(":", 1)[-1].strip()


def two_process_smoke(timeout: float = 300.0, attempts: int = 2) -> str:
    """Run the 2-process multi-controller leg on this host: two
    processes, 4 virtual CPU devices each, one coordinator, one fused
    partitioned-chain window over the 8-device GLOBAL mesh. Returns
    "ok" (route green across processes) or "skipped: <reason>"
    (multi-process init/collectives unavailable here — flaky transport
    crashes retry once before skipping). Raises on a parity red."""
    last = "skipped: not attempted"
    for _ in range(attempts):
        last = _smoke_attempt(timeout)
        if last == "ok":
            return last
    return last


if __name__ == "__main__":
    print(f"[multihost] {two_process_smoke()}")
