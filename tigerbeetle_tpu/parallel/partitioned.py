"""Partitioned ledger state: account-hash sharding with an on-device
event exchange and owner-masked write-back.

parallel/full_sharded.py scales the per-event FLOPs but replicates the
WHOLE ledger on every chip — state size is clamped to one device's HBM.
This module removes that clamp: every store (accounts, transfer rows,
the two hash tables, the event ring) is sharded over the mesh axis by a
deterministic id hash (shard_utils.shard_of_id), so per-device resident
state is ~1/n_shards of the replicated route.

The semantic license is AT2's (PAPERS.md): transfer ordering only
matters per account, so cross-shard coordination is only needed for the
compact per-event bundle — never for state. One `shard_map` body runs
the whole step:

  1. PROBE + EXCHANGE (phase 1, transfers): every shard looks up the
     batch's transfer ids and pending ids in its LOCAL table and
     contributes (encoded hit, masked row) lanes to ONE dense `psum`.
     The partitioned-storage invariant — each key lives on exactly one
     shard — makes the sum a select: afterwards every shard holds the
     global lookup result and the owning shard's row for every lane.
  2. PROBE + EXCHANGE (phase 2, accounts): same exchange for the 4N
     account keys the batch can touch (ev.dr, ev.cr, and the pending
     rows' dr/cr from phase 1), carrying the packed account row and the
     balance limbs.
  3. ASSEMBLE: the exchanged rows are deduplicated (first-occurrence
     over the 128-bit keys) into a replicated O(batch) MINI-STATE —
     init_state-shaped, with its own small hash tables — whose row
     pointers are rewritten mini-locally. This is the narrow two-phase
     join: cross-shard transfers resolve against the assembled bundle,
     not against remote state.
  4. JUDGE: the UNMODIFIED single-chip kernel stack
     (per_event_status + create_transfers_fast, any tier) runs on the
     mini-state, replicated. Bit-exactness vs the single-chip route is
     inherited, not re-proved: the kernel sees exactly the rows it
     would have gathered from the full store.
  5. WRITE-BACK: each shard applies the mini's changes to the rows it
     owns — appended transfer rows and ring rows land at the local
     counts, pending-status flips rewrite the (alone-in-its-column)
     pstat word, touched accounts write back the full packed row +
     limbs, and the new ids plan/write into the local hash table. All
     writes are masked by a psum-combined ok (kernel fallback, local
     capacity, exchange overflow): a failed batch leaves every shard
     bit-identical, preserving the escalation/replay contract.

The five steps above are one prepare's worth of work
(_partitioned_batch_body). Two dispatch forms share it:

  * PER BATCH (make_partitioned_create_transfers): one shard_map
    dispatch per prepare — the escalation unit, and the replay path
    for a window's fallen-back suffix.
  * CHAIN (make_partitioned_chain_create_transfers, the DEFAULT window
    route): the W prepares of a commit window run as a `lax.scan`
    carry over the donated sharded state INSIDE one shard_map
    dispatch, with a rolling poison scalar in the carry — the
    single-chip chain kernel's transitive-poison contract
    (ops/fast_kernels.py _create_transfers_chain), composed with the
    exchange. Collectives run inside the scan body; jaxhound's
    scan_body_census budgets them (body ops == the per-batch
    partitioned tier, whole-program ops flat in W —
    perf/opbudget_r09.json).

Non-canonical columns: transfer `dr_row`/`cr_row` and the ring's row
pointers are SHARD-LOCAL (or mini-scope, for ring rows) under the
partitioned layout. They were already excluded from the state-epoch
digest and re-derived by every consumer (the exchange rewrites them
from the id columns on assembly), so bit-comparability is unaffected.

Fallback/overflow: the exchange has a static per-shard capacity (the
mini-state caps and the per-shard table/row headroom). A breach is a
per-cause host fallback exactly like the replicated router's —
`shard_capacity` / `exchange_overflow` ride out["fb_causes"].
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ev_layout import (
    AC_NCOLS, EV_NCOLS, EV_P32_POS, XF_NCOLS, XF_U64_IDX, XF_P32_POS,
    pack32,
)
from ..ops.fast_kernels import (
    _CREATED,
    _TRANSIENT_CODES,
    _cumsum,
    create_transfers_fast,
    imported_batch_ctx,
    per_event_status,
)
from ..ops.hash_table import (
    ORPHAN_VAL, ht_init, ht_insert, ht_lookup, ht_plan, ht_write,
)
from ..ops.ledger import (
    N_PAD, _delta_gather_body, _pad_bucket, pad_transfer_events,
)
from ..trace import Event, FlightRecorder, Histogram, NullTracer
from .full_sharded import MODES, _MODE_KWARGS, ShardedRouter
from .shard_utils import (
    OwnershipTable, get_shard_map, owner_read, owner_read_int,
    shard_of_id, shard_of_int, writes_here,
)

__all__ = ["make_partitioned_create_transfers",
           "make_partitioned_chain_create_transfers",
           "stack_partitioned_window", "partitioned_from_oracle",
           "partitioned_state_bytes", "PartitionedRouter", "MODES",
           "TEL_WORDS", "TEL_LAYOUT", "TEL_CAUSES", "decode_telemetry"]

_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
_XF_DRROW_COL = XF_P32_POS["dr_row"][0]   # ("dr_row","cr_row") word
_XF_PSTAT_COL = XF_P32_POS["pstat"][0]    # pstat lives ALONE (flips)
_EV_PROW_COL = EV_P32_POS["p_row"][0]     # ("pstat","p_row") word
_EV_TFLAGS_COL = EV_P32_POS["tflags"][0]  # ("tflags","dr_flags") word


def _uniq_rows(k_hi, k_lo, active):
    """First-occurrence dedupe of 128-bit keys over the exchange lanes.

    Returns (first: bool[N] — the one lane per distinct active key that
    builds the mini row, row: int32[N] — that key's dense mini row on
    EVERY lane carrying it (-1 on inactive lanes), n: int32 — number of
    distinct active keys). Inactive lanes sort to a MAX-key block at
    the end (valid object ids are never 2^128-1), so active runs get
    the dense rank prefix."""
    n = k_hi.shape[0]
    kh = jnp.where(active, k_hi, _U64_MAX)
    kl = jnp.where(active, k_lo, _U64_MAX)
    perm = jnp.lexsort((kl, kh))  # stable: primary kh, secondary kl
    khs, kls = kh[perm], kl[perm]
    first_s = jnp.concatenate([
        jnp.ones((1,), bool),
        (khs[1:] != khs[:-1]) | (kls[1:] != kls[:-1])])
    act_s = active[perm]
    run = _cumsum(first_s.astype(jnp.int32)) - 1
    n_uniq = jnp.sum((first_s & act_s).astype(jnp.int32))
    first = jnp.zeros(n, bool).at[perm].set(first_s & act_s)
    row = jnp.zeros(n, jnp.int32).at[perm].set(run)
    return first, jnp.where(active, row, jnp.int32(-1)), n_uniq


# ------------------------------------------------------- telemetry plane
#
# A fixed-layout u32 block per (shard, prepare), built from values the
# exchange body already computes — elementwise packing only, so the
# heavy-op identity "chain body == per-batch partitioned tier" survives
# (gate-pinned in perf/opbudget_r*.json). Words 0-6 and 10 are
# REPLICATED (equal on every shard: they summarize the replicated mini
# judgment / exchange); words 7-9 and 11 are PER-SHARD.
TEL_LAYOUT = (
    "fix_rounds",            # 0  fixpoint rounds consumed (0 = plain)
    "poison_cause",          # 1  priority-encoded cause, 0 = clean
    "xchg1_occupancy",       # 2  live transfer rows in the 2N phase-1 lanes
    "xchg1_capacity",        # 3  phase-1 lane capacity (2N)
    "xchg2_occupancy",       # 4  distinct account keys in the 4N phase-2 lanes
    "xchg2_capacity",        # 5  phase-2 mini capacity (4N)
    "cross_shard_transfers",  # 6  created transfers whose dr/cr shards differ
    "ring_occupancy",        # 7  event-ring rows after write-back (per shard)
    "writeback_transfers",   # 8  owner-masked rows written (per shard)
    "events_owned",          # 9  valid events routed to this shard
    "exchange_overflow",     # 10 0/1: a phase capacity breached
    "shard_capacity_hit",    # 11 0/1: THIS shard's store/ring/plan capacity
)
TEL_WORDS = len(TEL_LAYOUT)

# poison_cause codes: index+1 into this tuple; the EARLIEST listed cause
# that fired wins (a forced/transitive poison only shows when no
# intrinsic cause explains the prepare). Mirrors out["fb_causes"] plus
# the exchange breaches.
TEL_CAUSES = (
    "e1_hard_flags", "e2_collision", "e3_limit", "e4_overflow",
    "e5_void_closing", "closing", "capacity", "forced",
    "shard_capacity", "exchange_overflow",
)


@functools.partial(jax.jit, inline=False)
def _telemetry_pack(*words):
    """Pack the telemetry words into one u32 vector. Kept as a NAMED
    nested jit (inline=False) so the call survives as a pjit equation
    in the lowered jaxpr: jaxhound.telemetry_census finds it by name
    and counts its input lanes against the committed lane budget."""
    return jnp.stack([jnp.asarray(w).astype(jnp.uint32) for w in words])


def _telemetry_block(out, fbc, *, n_lanes, n_a, n_live, xchg_bad,
                     bad_l, cross_shard, ring_count, n_mine_ok, owned):
    """Assemble the per-(shard, prepare) telemetry vector from the
    body's existing intermediates. Elementwise ops + the pack only —
    zero heavy-op delta, no extra collectives (per-shard words ride the
    sh subtree the shard_map already returns)."""
    cause = jnp.uint32(0)
    for i, name in reversed(list(enumerate(TEL_CAUSES, start=1))):
        cause = jnp.where(fbc[name], jnp.uint32(i), cause)
    return _telemetry_pack(
        out["fix_rounds"], cause,
        n_live, jnp.int32(2 * n_lanes),
        n_a, jnp.int32(4 * n_lanes),
        cross_shard, ring_count, n_mine_ok, owned,
        xchg_bad, bad_l)


def decode_telemetry(tel) -> dict:
    """Host-side decode: [..., TEL_WORDS] u32 -> {name: int array}.
    The leading axes are whatever the harvest kept (shard, or
    shard x W for the fused chain)."""
    arr = np.asarray(tel, dtype=np.uint32)
    assert arr.shape[-1] == TEL_WORDS, arr.shape
    return {name: arr[..., i].astype(np.int64)
            for i, name in enumerate(TEL_LAYOUT)}


def _partitioned_batch_body(sub, ev, timestamp, n, *, axis, n_dev,
                            mode, force_fallback=None, telemetry=True,
                            overlay=()):
    """One prepare against the per-shard state `sub` (UNSTACKED
    leaves): the full exchange -> mini-state -> judge -> write-back
    anatomy of the module docstring, shared VERBATIM by the per-batch
    shard_map body and the chain route's lax.scan body (one scan
    iteration == one per-batch dispatch's ops — the budget identity
    perf/opbudget_r*.json pins).

    `force_fallback` is the chain's rolling poison scalar: threaded
    into the judge it aborts the batch unconditionally, the masked
    write-back leaves every shard bit-identical, and the poison rides
    out through rep["fallback"] — the single-chip chain kernel's
    transitive-poison contract. Returns (new_sub, rep, events_owned,
    tel) where rep is the replicated out dict, events_owned the
    per-shard routed-event count, and tel the TEL_WORDS u32 telemetry
    vector (None when `telemetry` is off — the overhead-probe
    baseline).

    `overlay` is the elastic-shards ownership override table
    (shard_utils OwnershipTable.entries), baked in as a static closure
    constant. The exchange's "each key lives on exactly one shard"
    invariant — which makes each psum a select — breaks while a range
    is mid-migration (its rows exist on BOTH owners), so with a
    non-empty overlay every probe CONTRIBUTION is masked by
    read-ownership (only the authoritative copy feeds the psum) and
    every write-back mask generalizes from `shard_of_id == me` to
    `writes_here` (the copy-catchup owner applies the same rows at its
    own local positions). An EMPTY overlay takes the original code
    paths verbatim — byte-identical lowering, so the pinned op budgets
    and jaxhound signatures never see elastic shards unless one is
    actually live."""
    N = ev["id_lo"].shape[0]
    me = jax.lax.axis_index(axis)
    idxs = jnp.arange(N, dtype=jnp.int32)
    ts_full = (timestamp - n.astype(jnp.uint64)
               + idxs.astype(jnp.uint64) + jnp.uint64(1))
    acc, xfr, evr = (sub["accounts"], sub["transfers"],
                     sub["events"])
    a_dump_l = acc["u64"].shape[0] - 1
    t_dump_l = xfr["u64"].shape[0] - 1
    e_cap_l = evr["u64"].shape[0] - 1

    # ---- phase 1: transfer-key probe + exchange (2N lanes:
    # [ev.id | ev.pid]). Encoding in lane 0 of the exchanged
    # row: 0 = absent, 1 = orphan (ht_lookup reports stored
    # ORPHAN_VAL as val=-1), r+2 = live owner-local row r.
    xk_hi = jnp.concatenate([ev["id_hi"], ev["pid_hi"]])
    xk_lo = jnp.concatenate([ev["id_lo"], ev["pid_lo"]])
    xf_raw, xv_l = ht_lookup(sub["xfer_ht"], xk_hi, xk_lo)
    if overlay:
        # Mid-migration a range's rows exist on BOTH owners: only the
        # READ owner's copy may feed the psum, or the "sum is a
        # select" exchange invariant breaks.
        read_mine_x = owner_read(xk_hi, xk_lo, n_dev, overlay) == me
        xf_l = xf_raw & read_mine_x
    else:
        xf_l = xf_raw
    x_live_l = xf_l & (xv_l >= 0)
    enc_l = jnp.where(
        xf_l, (xv_l + 2).astype(jnp.uint64), jnp.uint64(0))
    xrow_l = jnp.where(x_live_l, xv_l, t_dump_l)
    xdata_l = jnp.where(x_live_l[:, None],
                        xfr["u64"][xrow_l], jnp.uint64(0))
    g = jax.lax.psum(
        jnp.concatenate([enc_l[:, None], xdata_l], axis=1), axis)
    g_enc, g_rows = g[:, 0], g[:, 1:]
    x_active = g_enc > 0
    x_live = g_enc >= 2

    # ---- phase 2: account-key probe + exchange (4N lanes:
    # [ev.dr | ev.cr | p.dr | p.cr]; the pending rows' account
    # ids come off the phase-1 exchange). Encoding: 0 = absent,
    # r+1 = owner-local row r. Zero keys (padded lanes, absent
    # pendings) hit the hash table's empty sentinel -> absent.
    p_rows_g = g_rows[N:]
    ak_hi = jnp.concatenate([
        ev["dr_hi"], ev["cr_hi"],
        p_rows_g[:, XF_U64_IDX["dr_hi"]],
        p_rows_g[:, XF_U64_IDX["cr_hi"]]])
    ak_lo = jnp.concatenate([
        ev["dr_lo"], ev["cr_lo"],
        p_rows_g[:, XF_U64_IDX["dr_lo"]],
        p_rows_g[:, XF_U64_IDX["cr_lo"]]])
    af_raw, ar_l = ht_lookup(sub["acct_ht"], ak_hi, ak_lo)
    if overlay:
        read_mine_a = owner_read(ak_hi, ak_lo, n_dev, overlay) == me
        af_l = af_raw & read_mine_a
    else:
        af_l = af_raw
    aenc_l = jnp.where(
        af_l, (ar_l + 1).astype(jnp.uint64), jnp.uint64(0))
    arow_g_l = jnp.where(af_l, ar_l, a_dump_l)
    au_l = jnp.where(af_l[:, None],
                     acc["u64"][arow_g_l], jnp.uint64(0))
    ab_l = jnp.where(af_l[:, None],
                     acc["bal"][arow_g_l], jnp.uint64(0))
    ga = jax.lax.psum(
        jnp.concatenate([aenc_l[:, None], au_l, ab_l], axis=1),
        axis)
    g_aenc = ga[:, 0]
    g_au = ga[:, 1:1 + AC_NCOLS]
    g_ab = ga[:, 1 + AC_NCOLS:]
    a_active = g_aenc > 0

    # ---- assemble the replicated mini-state (O(batch) caps).
    MA, MT, ME = 4 * N, 3 * N, N
    afirst, amrow, n_a = _uniq_rows(ak_hi, ak_lo, a_active)
    mini_au = jnp.zeros((MA + 1, AC_NCOLS), jnp.uint64).at[
        jnp.where(afirst, amrow, MA)].set(g_au).at[MA].set(
        jnp.uint64(0))
    mini_ab = jnp.zeros((MA + 1, 16), jnp.uint64).at[
        jnp.where(afirst, amrow, MA)].set(g_ab).at[MA].set(
        jnp.uint64(0))
    ht_a, ok_a = ht_insert(
        ht_init(8 * N), ak_hi, ak_lo, amrow, afirst)

    xfirst, _, _ = _uniq_rows(xk_hi, xk_lo, x_active)
    lfirst, lrow, n_live = _uniq_rows(xk_hi, xk_lo, x_live)
    mini_xu = jnp.zeros((MT + 1, XF_NCOLS), jnp.uint64).at[
        jnp.where(lfirst, lrow, MT)].set(g_rows).at[MT].set(
        jnp.uint64(0))
    # Mini-local row pointers: rewrite each exchanged row's
    # (dr_row, cr_row) word from its OWN id columns through the
    # mini account table (absent -> mini dump row). Only the
    # pending rows' pointers are ever dereferenced, and their
    # dr/cr are in the phase-2 key set by construction.
    mdr_hi = mini_xu[:, XF_U64_IDX["dr_hi"]]
    mdr_lo = mini_xu[:, XF_U64_IDX["dr_lo"]]
    mcr_hi = mini_xu[:, XF_U64_IDX["cr_hi"]]
    mcr_lo = mini_xu[:, XF_U64_IDX["cr_lo"]]
    fdr, rdr = ht_lookup(ht_a, mdr_hi, mdr_lo)
    fcr, rcr = ht_lookup(ht_a, mcr_hi, mcr_lo)
    has_ids = (mdr_hi | mdr_lo) != 0
    ptr_word = pack32(jnp.where(fdr, rdr, MA),
                      jnp.where(fcr, rcr, MA))
    mini_xu = mini_xu.at[:, _XF_DRROW_COL].set(
        jnp.where(has_ids, ptr_word,
                  mini_xu[:, _XF_DRROW_COL]))
    ht_x, ok_x = ht_insert(
        ht_init(8 * N), xk_hi, xk_lo,
        jnp.where(x_live, lrow, jnp.int32(ORPHAN_VAL)), xfirst)
    xchg_bad = (~ok_a) | (~ok_x) | (n_a > MA) | (n_live > 2 * N)

    # Ring prefill (p_row=-1 / tflags=0xFFFFFFFF) built ON
    # DEVICE by column sets — never as a host closure constant.
    mini_ev = jnp.zeros((ME + 1, EV_NCOLS), jnp.uint64)
    mini_ev = mini_ev.at[:, _EV_PROW_COL].set(
        jnp.uint64(0xFFFFFFFF) << jnp.uint64(32))
    mini_ev = mini_ev.at[:, _EV_TFLAGS_COL].set(
        jnp.uint64(0xFFFFFFFF))

    mini = dict(
        accounts=dict(u64=mini_au, bal=mini_ab, count=n_a),
        transfers=dict(u64=mini_xu, count=n_live),
        events=dict(u64=mini_ev, count=jnp.int32(0)),
        acct_ht=ht_a,
        xfer_ht=ht_x,
        # Scalars are stored per shard but hold GLOBAL values.
        acct_key_max=sub["acct_key_max"],
        xfer_key_max=sub["xfer_key_max"],
        pulse_next=sub["pulse_next"],
        commit_ts=sub["commit_ts"],
    )

    # ---- judge: the unmodified single-chip kernel on the
    # mini-state, replicated. The imported tier's account-ts
    # collision is the only batch-context piece that needs the
    # FULL table: each shard probes its sorted local column and
    # the memberships OR-combine over the mesh.
    ictx = None
    if mode == "imported":
        ctx_l = imported_batch_ctx(sub, ev, ts_full,
                                   ev["valid"], idxs)
        ictx = dict(ctx_l)
        ictx["acct_ts_collision"] = jax.lax.psum(
            ctx_l["acct_ts_collision"].astype(jnp.int32),
            axis) > 0
    pe = per_event_status(mini, ev, ts_full, imported_ctx=ictx)
    mini_t0 = n_live
    kw = dict(_MODE_KWARGS[mode])
    if force_fallback is not None:
        kw["force_fallback"] = force_fallback
    new_mini, out = create_transfers_fast(
        mini, ev, timestamp, n, per_event=pe, **kw)

    # ---- per-shard write-back plan + combined ok.
    status = out["r_status"]
    created = ev["valid"] & (status == _CREATED)
    transient = jnp.zeros_like(created)
    for code in _TRANSIENT_CODES:
        transient = transient | (status == code)
    orphan_new = ev["valid"] & transient
    ins_mask = created | orphan_new
    if overlay:
        owner_ev = owner_read(ev["id_hi"], ev["id_lo"], n_dev, overlay)
        wr_ev = writes_here(ev["id_hi"], ev["id_lo"], n_dev, me,
                            overlay)
    else:
        owner_ev = shard_of_id(ev["id_hi"], ev["id_lo"], n_dev)
        wr_ev = owner_ev == me
    mine = created & wr_ev
    ins_mine = ins_mask & wr_ev
    n_mine = jnp.sum(mine.astype(jnp.int32))
    local_rank = _cumsum(mine.astype(jnp.int32)) - mine
    pos, ok_pl = ht_plan(sub["xfer_ht"], ev["id_hi"],
                         ev["id_lo"], ins_mine)
    bad_l = ((xfr["count"] + n_mine > t_dump_l)
             | (evr["count"] + n_mine > e_cap_l)
             | ~ok_pl)
    bad = jax.lax.psum(bad_l.astype(jnp.int32), axis) > 0
    g_ok = (~out["fallback"]) & (~bad) & (~xchg_bad)

    # ---- write-back (every write masked by g_ok; the dump
    # rows absorb masked lanes, exactly the kernel's idiom).
    row_off = _cumsum(created.astype(jnp.int32)) - created
    mini_trow = jnp.clip(mini_t0 + row_off, 0, MT)
    dest_t = jnp.where(mine & g_ok,
                       xfr["count"] + local_rank, t_dump_l)
    new_rows = new_mini["transfers"]["u64"][mini_trow]
    # Stored row pointers become SHARD-LOCAL: resolve the new
    # row's dr/cr against the local table (remote -> dump).
    fdr2, rdr2 = ht_lookup(sub["acct_ht"],
                           ev["dr_hi"], ev["dr_lo"])
    fcr2, rcr2 = ht_lookup(sub["acct_ht"],
                           ev["cr_hi"], ev["cr_lo"])
    new_rows = new_rows.at[:, _XF_DRROW_COL].set(
        pack32(jnp.where(fdr2, rdr2, a_dump_l),
               jnp.where(fcr2, rcr2, a_dump_l)))
    xu_new = xfr["u64"].at[dest_t].set(new_rows)
    # Pending-status flips on existing owned rows: the pstat
    # word is alone in its column, so the flip cannot clobber a
    # neighbor. Unchanged rows rewrite their own value.
    if overlay:
        # Copy-catchup owners flip their OWN copy's row: the read
        # owner's row index is the exchanged encoding, the other
        # write owner's is its local lookup (absent-here rows — a
        # key outside this shard's tables — mask to the dump row).
        wr_xk = writes_here(xk_hi, xk_lo, n_dev, me, overlay)
        flip = lfirst & wr_xk
        row_here = jnp.where(
            read_mine_x, (g_enc - jnp.uint64(2)).astype(jnp.int32),
            xv_l)
        has_here = read_mine_x | (xf_raw & (xv_l >= 0))
        dest_p = jnp.where(flip & g_ok & has_here, row_here, t_dump_l)
    else:
        owner_xk = shard_of_id(xk_hi, xk_lo, n_dev)
        flip = lfirst & (owner_xk == me)
        dest_p = jnp.where(flip & g_ok,
                           (g_enc - jnp.uint64(2)).astype(jnp.int32),
                           t_dump_l)
    pword = new_mini["transfers"]["u64"][
        jnp.where(x_live, lrow, MT), _XF_PSTAT_COL]
    xu_new = xu_new.at[dest_p, _XF_PSTAT_COL].set(pword)

    if overlay:
        wr_ak = writes_here(ak_hi, ak_lo, n_dev, me, overlay)
        wb_a = afirst & wr_ak
        arow_here = jnp.where(
            read_mine_a, (g_aenc - jnp.uint64(1)).astype(jnp.int32),
            ar_l)
        dest_a = jnp.where(wb_a & g_ok & (read_mine_a | af_raw),
                           arow_here, a_dump_l)
    else:
        owner_ak = shard_of_id(ak_hi, ak_lo, n_dev)
        wb_a = afirst & (owner_ak == me)
        dest_a = jnp.where(wb_a & g_ok,
                           (g_aenc - jnp.uint64(1)).astype(jnp.int32),
                           a_dump_l)
    amrow_c = jnp.where(afirst, amrow, MA)
    au_new = acc["u64"].at[dest_a].set(
        new_mini["accounts"]["u64"][amrow_c])
    ab_new = acc["bal"].at[dest_a].set(
        new_mini["accounts"]["bal"][amrow_c])

    dest_e = jnp.where(mine & g_ok,
                       evr["count"] + local_rank, e_cap_l)
    ring_rows = new_mini["events"]["u64"][
        jnp.clip(row_off, 0, ME)]
    eu_new = evr["u64"].at[dest_e].set(ring_rows)

    vals = jnp.where(created, xfr["count"] + local_rank,
                     jnp.int32(ORPHAN_VAL))
    ht_new = ht_write(sub["xfer_ht"], pos, ev["id_hi"],
                      ev["id_lo"], vals, ins_mine & g_ok)

    # int32 pinned: jnp.sum promotes to int64 under x64, and the scan
    # carry requires the counts' dtype to be a fixpoint.
    n_mine_ok = jnp.where(g_ok, n_mine, 0).astype(jnp.int32)

    def adopt(new_v, old_v):
        return jnp.where(g_ok, new_v, old_v)

    new_sub = dict(
        accounts=dict(u64=au_new, bal=ab_new,
                      count=acc["count"]),
        transfers=dict(u64=xu_new,
                       count=xfr["count"] + n_mine_ok),
        events=dict(u64=eu_new,
                    count=evr["count"] + n_mine_ok),
        acct_ht=sub["acct_ht"],
        xfer_ht=ht_new,
        acct_key_max=adopt(new_mini["acct_key_max"],
                           sub["acct_key_max"]),
        xfer_key_max=adopt(new_mini["xfer_key_max"],
                           sub["xfer_key_max"]),
        pulse_next=adopt(new_mini["pulse_next"],
                         sub["pulse_next"]),
        commit_ts=adopt(new_mini["commit_ts"],
                        sub["commit_ts"]),
    )

    # ---- amended out dict: the shard/exchange breaches are
    # host fallbacks (state untouched), never escalations.
    xb = bad | xchg_bad
    rep = dict(out)
    rep["r_status"] = jnp.where(xb, jnp.zeros_like(status),
                                status)
    rep["r_ts"] = jnp.where(xb, jnp.zeros_like(out["r_ts"]),
                            out["r_ts"])
    rep["fallback"] = out["fallback"] | xb
    rep["limit_only"] = out["limit_only"] & ~xb
    rep["created_count"] = jnp.where(xb, 0,
                                     out["created_count"])
    fbc = dict(out["fb_causes"])
    fbc["shard_capacity"] = bad
    fbc["exchange_overflow"] = xchg_bad
    rep["fb_causes"] = fbc
    # Durable flush rides the mini: the appended rows' slice
    # plus the id/p_ts derivations, all mini-resolved (the
    # canonical columns are bit-exact vs the single-chip
    # gather; row-pointer columns are non-canonical scope).
    rep["flush"] = _delta_gather_body(new_mini, mini_t0, 0,
                                      N, N)
    if overlay:
        owner_dr = owner_read(ev["dr_hi"], ev["dr_lo"], n_dev, overlay)
        owner_cr = owner_read(ev["cr_hi"], ev["cr_lo"], n_dev, overlay)
    else:
        owner_dr = shard_of_id(ev["dr_hi"], ev["dr_lo"], n_dev)
        owner_cr = shard_of_id(ev["cr_hi"], ev["cr_lo"], n_dev)
    rep["cross_shard_transfers"] = jnp.sum(
        (created & (owner_dr != owner_cr)).astype(jnp.int32))
    rep["exchange_overflow"] = xchg_bad
    owned = jnp.sum(
        (ev["valid"] & (owner_ev == me)).astype(jnp.int32))
    tel = None
    if telemetry:
        tel = _telemetry_block(
            out, fbc, n_lanes=N, n_a=n_a, n_live=n_live,
            xchg_bad=xchg_bad, bad_l=bad_l,
            cross_shard=rep["cross_shard_transfers"],
            ring_count=evr["count"] + n_mine_ok,
            n_mine_ok=n_mine_ok, owned=owned)
    return new_sub, rep, owned, tel


def make_partitioned_create_transfers(mesh: Mesh, axis: str = "batch",
                                      mode: str = "plain",
                                      telemetry: bool = True,
                                      overlay: tuple = ()):
    """Build the jitted partitioned-state SPMD step over `mesh` for one
    kernel tier (`mode` in MODES).

    Returns step(stacked_state, ev, timestamp, n) -> (new_state, out).
    `stacked_state` is the pytree from partitioned_from_oracle: every
    leaf carries a leading shard axis sharded P(axis); `ev` is the full
    padded batch, replicated. `out` is the single-chip out dict plus
    `flush` (the delta gather of the appended rows, replicated),
    `cross_shard_transfers`, `exchange_overflow`, and
    `shard_stats.events_owned` (per-shard routed-event counts). With
    `telemetry` (the default) `shard_stats.tel` carries the
    [n_shards, TEL_WORDS] device telemetry block; `telemetry=False` is
    the overhead-probe baseline. `overlay` (elastic shards) is the
    static ownership-override tuple baked into the lowering; () — the
    default — lowers byte-identically to the pre-overlay artifact."""
    shard_map = get_shard_map()
    assert mode in MODES, mode
    n_dev = mesh.shape[axis]

    def step(state, ev, timestamp, n):
        def body(stacked, ev):
            sub = jax.tree.map(lambda x: x[0], stacked)
            new_sub, rep, owned, tel = _partitioned_batch_body(
                sub, ev, timestamp, n, axis=axis, n_dev=n_dev,
                mode=mode, telemetry=telemetry, overlay=overlay)
            sh = dict(events_owned=owned[None])
            if tel is not None:
                sh["tel"] = tel[None]
            new_stacked = jax.tree.map(lambda x: jnp.asarray(x)[None],
                                       new_sub)
            return new_stacked, {"rep": rep, "sh": sh}

        try:
            smapped = shard_map(
                body, mesh=mesh, in_specs=(P(axis), P()),
                out_specs=(P(axis), {"rep": P(), "sh": P(axis)}),
                check_vma=False)
        except TypeError:  # pre-0.5 jax spells the kwarg check_rep
            smapped = shard_map(
                body, mesh=mesh, in_specs=(P(axis), P()),
                out_specs=(P(axis), {"rep": P(), "sh": P(axis)}),
                check_rep=False)
        new_state, out2 = smapped(state, ev)
        out = dict(out2["rep"])
        out["shard_stats"] = out2["sh"]
        return new_state, out

    # Donation preserved: the sharded buffers are consumed in place
    # (jaxhound's donation audit checks the lowered artifact).
    return jax.jit(step, donate_argnums=0)


def make_partitioned_chain_create_transfers(mesh: Mesh,
                                            axis: str = "batch",
                                            mode: str = "plain",
                                            telemetry: bool = True,
                                            overlay: tuple = ()):
    """Build the FUSED window step: the W prepares of a commit window
    run as a `lax.scan` over the per-batch body INSIDE one shard_map
    dispatch, with the donated sharded state and a rolling poison
    scalar in the scan carry.

    Returns step(stacked_state, ev_stack, ts_stack, n_stack,
    force_fallback) -> (new_state, out). The stacks come from
    stack_partitioned_window: every ev leaf is [W, n_pad] (replicated),
    ts_stack/n_stack are the per-prepare commit timestamp and event
    count. `force_fallback` seeds the poison carry (None = clean), so
    pipelined drivers chain windows exactly like the single-chip chain
    route (DeviceLedger.submit_window).

    Per-prepare fallback granularity is PRESERVED: scan iteration k's
    rep["fallback"] poisons iterations k+1.. (masked writes — their
    shards stay bit-identical), so the clean prefix commits inside the
    one dispatch and out["fallback"] ([W], replicated) tells the host
    which suffix to re-window. Every out leaf gains a leading W axis;
    `shard_stats.events_owned` is [n_shards, W] and (with `telemetry`,
    the default) `shard_stats.tel` is [n_shards, W, TEL_WORDS] — the
    whole window's per-prepare device telemetry harvested in the SAME
    dispatch as the results.

    Why this exists: the per-batch route pays PERF.md's bottleneck #1
    (per-dispatch fixed cost) once per prepare; here the whole window
    is ONE dispatch whose whole-program op count is flat in W (the
    scan body is censused once — partitioned_chain tiers in
    perf/opbudget_r09.json)."""
    shard_map = get_shard_map()
    assert mode in MODES, mode
    n_dev = mesh.shape[axis]

    def step(state, ev_stack, ts_stack, n_stack, force_fallback):
        def body(stacked, ev_stack, ts_stack, n_stack):
            sub = jax.tree.map(lambda x: x[0], stacked)
            poisoned0 = (jnp.bool_(False) if force_fallback is None
                         else force_fallback)

            def scan_step(carry, xs):
                st, poisoned = carry
                ev_k, ts_k, n_k = xs
                new_st, rep, owned, tel = _partitioned_batch_body(
                    st, ev_k, ts_k, n_k, axis=axis, n_dev=n_dev,
                    mode=mode, force_fallback=poisoned,
                    telemetry=telemetry, overlay=overlay)
                ys = ((rep, owned, tel) if telemetry
                      else (rep, owned))
                return (new_st, rep["fallback"]), ys

            (new_sub, _), ys_w = jax.lax.scan(
                scan_step, (sub, poisoned0),
                (ev_stack, ts_stack, n_stack))
            if telemetry:
                reps, owned_w, tel_w = ys_w
            else:
                reps, owned_w = ys_w
            sh = dict(events_owned=owned_w[None])
            if telemetry:
                sh["tel"] = tel_w[None]
            new_stacked = jax.tree.map(lambda x: jnp.asarray(x)[None],
                                       new_sub)
            return new_stacked, {"rep": reps, "sh": sh}

        specs = (P(axis), P(), P(), P())
        try:
            smapped = shard_map(
                body, mesh=mesh, in_specs=specs,
                out_specs=(P(axis), {"rep": P(), "sh": P(axis)}),
                check_vma=False)
        except TypeError:  # pre-0.5 jax spells the kwarg check_rep
            smapped = shard_map(
                body, mesh=mesh, in_specs=specs,
                out_specs=(P(axis), {"rep": P(), "sh": P(axis)}),
                check_rep=False)
        new_state, out2 = smapped(state, ev_stack, ts_stack, n_stack)
        out = dict(out2["rep"])
        out["shard_stats"] = out2["sh"]
        return new_state, out

    return jax.jit(step, donate_argnums=0)


def stack_partitioned_window(evs: list[dict], timestamps: list[int],
                             n_pad: int = N_PAD):
    """W prepares -> the chain step's stacked inputs: each unpadded
    transfers_to_arrays SoA dict padded to n_pad and stacked on a
    leading W axis, plus the per-prepare commit-timestamp and
    valid-count vectors the scan body consumes (the partitioned
    sibling of ops/ledger.stack_chain_window — per-prepare (ts, n)
    scalars instead of seg lanes, because the exchange body judges one
    whole prepare per iteration)."""
    assert len(evs) == len(timestamps) and evs
    padded = [pad_transfer_events(e, n_pad) for e in evs]
    ev_stack = {k: np.stack([p[k] for p in padded]) for k in padded[0]}
    ts_stack = np.asarray([int(t) for t in timestamps], dtype=np.uint64)
    n_stack = np.asarray([len(e["id_lo"]) for e in evs],
                         dtype=np.int32)
    return ev_stack, ts_stack, n_stack


# --------------------------------------------------------------- host side

def _chunk_insert(table, keys_vals, n_pad):
    """from_host's batch_insert, shared shape: chunked ht_insert of
    (id, val) pairs with a hard overflow assert."""
    table = jax.tree.map(jnp.asarray, table)
    for lo_i in range(0, len(keys_vals), n_pad):
        chunk = keys_vals[lo_i:lo_i + n_pad]
        hi = np.array([k >> 64 for k, _ in chunk], dtype=np.uint64)
        lo = np.array([k & (1 << 64) - 1 for k, _ in chunk],
                      dtype=np.uint64)
        vals = np.array([v for _, v in chunk], dtype=np.int32)
        table, ok = ht_insert(
            table, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(vals),
            jnp.ones(len(chunk), dtype=bool))
        assert bool(ok), "hash rebuild overflow: raise capacities"
    return table


def _record_owner_id(sm, rec) -> int:
    """The id that decides a ring row's shard: the creating transfer's
    (commit-timestamp keyed), else the pending transfer's, else the
    debit account's (expiry rows without a commit entry)."""
    tid = sm.transfer_by_timestamp.get(rec.timestamp)
    if tid is not None:
        return tid
    if rec.transfer_pending is not None:
        return rec.transfer_pending.id
    return rec.dr_account.id


def partitioned_from_oracle(sm, mesh: Mesh, axis: str = "batch",
                            a_cap: int = 1 << 12, t_cap: int = 1 << 14,
                            e_cap: int | None = None,
                            overlay: tuple = ()):
    """Build the device-sharded state pytree from a host oracle.

    The partitioned sibling of DeviceLedger.from_host: objects are
    assigned to shards by shard_of_int over the SAME ownership hash the
    kernels use, then packed per shard in the canonical order
    (accounts by applied timestamp, transfers in commit order — the
    shard-then-sort contract the epoch digest pins). Every leaf gains a
    leading shard axis and lands with NamedSharding P(axis); per-shard
    caps are the global caps / n_shards, so per-device resident bytes
    scale ~1/n_shards.

    `overlay` (elastic shards): placement follows the READ owner under
    the override table, so a rebuild mid-overlay (recovery after a
    flip) lands every range on its authoritative shard. Rebuilding
    DURING copy-catchup is a controller bug — the ReshardController
    always reverts (or completes) the in-flight entry before a resync,
    so a double-write range never reaches this packer."""
    from ..ops.ledger import (
        N_PAD, _pack_account_rows, _pack_event_rows, _pack_transfer_rows,
        init_state,
    )
    from ..types import TransferPendingStatus

    n_shards = mesh.shape[axis]
    assert a_cap % n_shards == 0 and t_cap % n_shards == 0, \
        (a_cap, t_cap, n_shards)
    if e_cap is None:
        e_cap = t_cap
    a_cap_s = a_cap // n_shards
    t_cap_s = t_cap // n_shards
    e_cap_s = max(e_cap // n_shards, 1)
    # The replicated default keeps a 2^16 orphan floor for load safety;
    # per shard the floor scales too, keeping the AGGREGATE table the
    # same size (the 1/n_shards byte assertion depends on it).
    orphan_cap_s = max((1 << 16) // n_shards, t_cap_s)

    acct_all = sorted(sm.accounts.values(), key=lambda a: a.timestamp)
    xfer_all = [sm.transfers[tid]
                for tid in sm.transfer_by_timestamp.values()]
    orphan_all = sorted(sm.orphaned)

    def shard_of(id128):
        return owner_read_int(id128, n_shards, overlay)

    subs = []
    for s in range(n_shards):
        accounts = [a for a in acct_all if shard_of(a.id) == s]
        transfers = [t for t in xfer_all if shard_of(t.id) == s]
        orphans = [o for o in orphan_all if shard_of(o) == s]
        records = [r for r in sm.account_events
                   if shard_of(_record_owner_id(sm, r)) == s]
        assert len(accounts) <= a_cap_s and len(transfers) <= t_cap_s \
            and len(records) <= e_cap_s, "shard capacity exceeded"
        st = jax.tree.map(lambda x: np.array(x), init_state(
            a_cap_s, t_cap_s, orphan_cap=orphan_cap_s, e_cap=e_cap_s))

        acct_row = {a.id: r for r, a in enumerate(accounts)}
        xfer_row = {t.id: r for r, t in enumerate(transfers)}
        a_u64, a_bal = _pack_account_rows(accounts)
        st["accounts"]["u64"][:len(accounts)] = a_u64
        st["accounts"]["bal"][:len(accounts)] = a_bal
        st["accounts"]["count"] = np.int32(len(accounts))
        st["acct_ht"] = jax.tree.map(np.asarray, _chunk_insert(
            st["acct_ht"],
            [(a.id, r) for r, a in enumerate(accounts)], N_PAD))

        u64m = _pack_transfer_rows(
            transfers,
            lambda o: int(sm.pending_status.get(
                o.timestamp, TransferPendingStatus.none)),
            lambda aid, dump: acct_row.get(aid, dump),
            a_cap_s)
        st["transfers"]["u64"][:len(transfers)] = u64m
        st["transfers"]["count"] = np.int32(len(transfers))
        st["xfer_ht"] = jax.tree.map(np.asarray, _chunk_insert(
            st["xfer_ht"],
            [(t.id, r) for r, t in enumerate(transfers)]
            + [(o, ORPHAN_VAL) for o in orphans], N_PAD))

        ecols = _pack_event_rows(records, acct_row, xfer_row, a_cap_s)
        st["events"]["u64"][:len(records)] = ecols["u64"]
        st["events"]["count"] = np.int32(len(records))

        # Scalars hold GLOBAL values on every shard (the mini-state and
        # the write-back adopt/replicate them each step).
        st["acct_key_max"] = np.uint64(sm.accounts_key_max or 0)
        st["xfer_key_max"] = np.uint64(sm.transfers_key_max or 0)
        st["pulse_next"] = np.uint64(sm.pulse_next_timestamp)
        st["commit_ts"] = np.uint64(sm.commit_timestamp)
        subs.append(st)

    stacked = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *subs)
    return jax.device_put(stacked, NamedSharding(mesh, P(axis)))


def _host_local(x):
    """device_get that tolerates a multi-host mesh: a leaf sharded over
    the global device list cannot be fetched whole from one process, so
    fall back to the ADDRESSABLE shards — each process accounts the
    rows it hosts (remote rows read as zero here and accumulate on
    their own host's router). Replicated leaves fetch whole either
    way."""
    try:
        return np.asarray(jax.device_get(x))
    except RuntimeError:
        out = np.zeros(x.shape, dtype=x.dtype)
        for s in x.addressable_shards:
            out[s.index] = np.asarray(s.data)
        return out


def partitioned_state_bytes(stacked) -> int:
    """Per-device resident state bytes of a stacked partitioned pytree
    (every leaf's leading dim is the shard axis)."""
    leaves = jax.tree.leaves(stacked)
    n = leaves[0].shape[0]
    total = sum(int(np.prod(x.shape)) * x.dtype.itemsize
                for x in leaves)
    return total // n


def replicated_state_bytes(a_cap: int, t_cap: int,
                           e_cap: int | None = None) -> int:
    """Per-device resident bytes of the REPLICATED route at the same
    caps (every device holds the whole pytree) — the comparison base
    for the ~1/n_shards assertion. Shape-only (eval_shape): nothing is
    allocated."""
    from ..ops.ledger import init_state

    shapes = jax.eval_shape(lambda: init_state(a_cap, t_cap,
                                               e_cap=e_cap))
    return sum(int(np.prod(x.shape)) * x.dtype.itemsize
               for x in jax.tree.leaves(shapes))


class PartitionedRouter:
    """Host-side tier router over the partitioned steps — the sharded-
    state sibling of ShardedRouter. Same flag pre-route, same
    plain -> fixpoint escalation, same per-cause fallback counters,
    plus the exchange diagnostics (events routed per shard, cross-shard
    transfer counts, exchange overflows).

    Window dispatch (step_window) defaults to the PARTITIONED CHAIN:
    one fused shard_map+scan dispatch per eligible commit window, with
    per-prepare fallback — the clean prefix stays committed inside the
    dispatch, the first ineligible prepare replays through the
    per-batch step (which escalates plain -> fixpoint on device), and
    the remainder re-windows. Route counters ride
    stats()["routes"] in the same shape as
    DeviceLedger.fallback_stats()["routes"].

    Shard loss differs STRUCTURALLY from the replicated router: no
    surviving chip holds the lost range, so a single-chip reroute
    cannot serve. Loss quarantines the router until `resync(oracle)`
    rebuilds the sharded state from the last verified oracle — the
    ServingSupervisor recovery path's bounded-replay contract, counted
    under the `shard_resync` recovery cause."""

    def __init__(self, mesh: Mesh, axis: str = "batch", tracer=None,
                 a_cap: int = 1 << 12, t_cap: int = 1 << 14,
                 e_cap: int | None = None, telemetry: bool = True,
                 flight_recorder=None):
        self.mesh = mesh
        self.axis = axis
        self.tracer = tracer if tracer is not None else NullTracer()
        self.a_cap = a_cap
        self.t_cap = t_cap
        self.e_cap = e_cap
        self.n_shards = mesh.shape[axis]
        # Elastic shards: the generation-tagged ownership authority.
        # Step caches key on (mode, overlay entries) — an overlay swap
        # SELECTS a different compiled artifact, it never mutates one.
        self.ownership = OwnershipTable(self.n_shards)
        self._staging_host = None  # DeviceLedger.attach_partitioned
        self._steps: dict = {}
        self._chain_steps: dict = {}
        self.batches = 0
        self.escalations = 0
        self.host_fallbacks = 0
        self.fallback_causes: dict = {}
        self.lost_devices: set = set()
        self.shard_resyncs = 0
        self.cross_shard_transfers = 0
        self.exchange_overflows = 0
        self.events_owned = np.zeros(self.n_shards, dtype=np.int64)
        self.window_routes: dict = {}
        self.chain_batch_fallbacks: dict = {}
        # Device telemetry plane: `telemetry` is a MAKE-TIME switch (it
        # selects which compiled artifact the factories build — the
        # call signatures never change), the aggregates below are what
        # the decoded blocks accumulate into between stats() reads.
        self.telemetry = bool(telemetry)
        self.flight = flight_recorder if flight_recorder is not None \
            else FlightRecorder(pid=jax.process_index(),
                                tracer=self.tracer)
        self._tel_hist = Histogram()    # exchange occupancy, pct
        self._tel_rounds = Histogram()  # fixpoint rounds per prepare
        self.device_poison_causes: dict = {}
        self.writeback_rows = 0
        self.shard_capacity_hits = 0
        self._window_seq = 0

    # Same flag-derived tier precedence as the replicated router.
    route = staticmethod(ShardedRouter.route)

    def from_oracle(self, sm):
        """Build the router's sharded state from a host oracle (under
        the current ownership table — migrated ranges land on their
        read owner)."""
        return partitioned_from_oracle(sm, self.mesh, self.axis,
                                       self.a_cap, self.t_cap,
                                       self.e_cap,
                                       overlay=self.ownership.entries)

    def set_ownership(self, table: OwnershipTable) -> None:
        """Swap in a new ownership table (reshard stage transitions).
        Purely a host-side selection change: the next dispatch picks
        (or traces) the step keyed by the new overlay entries."""
        assert table.n_shards == self.n_shards, table
        assert table.generation >= self.ownership.generation, table
        self.ownership = table

    def _step(self, mode: str):
        key = (mode, self.ownership.entries)
        fn = self._steps.get(key)
        if fn is None:
            fn = self._steps[key] = make_partitioned_create_transfers(
                self.mesh, self.axis, mode=mode,
                telemetry=self.telemetry,
                overlay=self.ownership.entries)
        return fn

    def _chain_step(self, mode: str):
        key = (mode, self.ownership.entries)
        fn = self._chain_steps.get(key)
        if fn is None:
            fn = self._chain_steps[key] = \
                make_partitioned_chain_create_transfers(
                    self.mesh, self.axis, mode=mode,
                    telemetry=self.telemetry,
                    overlay=self.ownership.entries)
        return fn

    def drop_device(self, device, oracle=None):
        """Mark one mesh device lost. The lost range exists NOWHERE
        else on the mesh (partitioned state), so — unlike
        ShardedRouter.drop_device — there is no single-chip reroute:
        the router refuses to serve until resynced. Passing `oracle`
        runs the resync immediately and returns the rebuilt state.

        Quarantine is a flight-recorder dump point: the ring's tail is
        the last-N windows BEFORE the loss — exactly the post-mortem
        question — so freeze it now, while the evidence is fresh."""
        self.lost_devices.add(device)
        self.flight.record(window=self._window_seq, route="quarantined",
                           lost_devices=len(self.lost_devices))
        self.flight.dump("shard_loss_quarantine")
        if oracle is not None:
            return self.resync(oracle)
        return None

    def resync(self, oracle):
        """Bounded oracle-replay resync of the lost range(s): rebuild
        the sharded state from the last verified oracle through the
        supervisor recovery path's event taxonomy (`shard_resync`
        cause). Returns the fresh stacked state.

        Staging is torn down FIRST: a pack staged under the
        pre-quarantine ownership map could otherwise be consumed by
        identity against the rebuilt state (ISSUE 19 satellite fix —
        the staged window's route and pad bucket would match while its
        placement assumptions no longer do)."""
        host = self._staging_host
        if host is not None:
            host.shutdown_staging()
        self.flight.dump("shard_resync")
        with self.tracer.span(Event.serving_recovery_replay,
                              cause="shard_resync"):
            state = self.from_oracle(oracle)
        self.tracer.count(Event.serving_recoveries,
                          cause="shard_resync")
        self.shard_resyncs += 1
        self.lost_devices.clear()
        return state

    def restore_devices(self) -> None:
        """The mesh healed WITHOUT state loss (transient link flap):
        nothing to rebuild."""
        self.lost_devices.clear()

    def _require_serving(self) -> None:
        if self.lost_devices:
            raise RuntimeError(
                "partitioned shard lost: resync(oracle) required — the "
                "single-chip reroute cannot serve a lost range")

    def _absorb_telemetry(self, tel):
        """Decode one harvested telemetry block ([n_shards, W,
        TEL_WORDS] or [n_shards, TEL_WORDS], host-local rows) into
        tracer emissions + the router aggregates, returning the
        per-window summary dict the flight recorder rings (None when
        empty). Replicated words were psum'd on device, so every LOCAL
        shard row carries the same value — max over the shard axis
        recovers them on multi-host meshes where remote rows read zero
        (_host_local); per-shard words stay per shard."""
        tel = np.asarray(tel)
        if tel.ndim == 2:
            tel = tel[:, None, :]
        if tel.shape[1] == 0:
            return None
        d = decode_telemetry(tel)
        rep = {k: d[k].max(axis=0) for k in (
            "fix_rounds", "poison_cause",
            "xchg1_occupancy", "xchg1_capacity",
            "xchg2_occupancy", "xchg2_capacity",
            "cross_shard_transfers", "exchange_overflow")}
        W = tel.shape[1]
        occ_pct = []
        causes = []
        for w in range(W):
            self.tracer.observe(Event.device_fixpoint_rounds,
                                int(rep["fix_rounds"][w]))
            self._tel_rounds.record(float(rep["fix_rounds"][w]))
            for phase, occ, cap in (
                    ("transfers", rep["xchg1_occupancy"][w],
                     rep["xchg1_capacity"][w]),
                    ("accounts", rep["xchg2_occupancy"][w],
                     rep["xchg2_capacity"][w])):
                pct = (100.0 * float(occ) / float(cap)) if cap else 0.0
                pct = round(pct, 3)
                occ_pct.append(pct)
                self.tracer.observe(Event.device_exchange_occupancy,
                                    pct, phase=phase)
                self._tel_hist.record(pct)
            code = int(rep["poison_cause"][w])
            cause = (TEL_CAUSES[code - 1]
                     if 0 < code <= len(TEL_CAUSES)
                     else (f"code_{code}" if code else None))
            causes.append(cause)
            if cause is not None:
                self.device_poison_causes[cause] = (
                    self.device_poison_causes.get(cause, 0) + 1)
                self.tracer.count(Event.device_poison_cause,
                                  cause=cause)
        for s in range(tel.shape[0]):
            for w in range(W):
                self.tracer.observe(Event.device_ring_occupancy,
                                    int(d["ring_occupancy"][s, w]))
        wb = int(d["writeback_transfers"].sum())
        if wb:
            self.writeback_rows += wb
            self.tracer.count(Event.device_writeback_rows, value=wb)
        self.shard_capacity_hits += int(d["shard_capacity_hit"].sum())
        return {
            "prepares": W,
            "fix_rounds": [int(x) for x in rep["fix_rounds"]],
            "poison_causes": causes,
            "exchange_occupancy_pct": occ_pct,
            "cross_shard_transfers": int(
                rep["cross_shard_transfers"].sum()),
            "exchange_overflows": int(rep["exchange_overflow"].sum()),
            "shard_capacity_hits": int(d["shard_capacity_hit"].sum()),
            "writeback_rows": wb,
            "events_owned": [int(x)
                             for x in d["events_owned"].sum(axis=1)],
            "ring_occupancy": [int(x)
                               for x in d["ring_occupancy"][:, -1]],
        }

    def step(self, state, ev: dict, timestamp: int, n: int):
        """Run one padded batch. Returns (new_state, out, fell_back).
        On fell_back=True the state is untouched (masked writes on
        every shard) and the caller owns the exact-path replay."""
        self._require_serving()
        self.batches += 1
        mode = self.route(ev)
        self.tracer.count(Event.dispatch_route,
                          route="partitioned_" + mode)
        with self.tracer.span(Event.shard_exchange, mode=mode):
            new_state, out = self._step(mode)(
                state, ev, np.uint64(timestamp), np.int32(n))
            fallback, limit_only = (bool(x) for x in jax.device_get(
                (out["fallback"], out["limit_only"])))
            if fallback and limit_only and mode == "plain":
                self.escalations += 1
                mode = "fixpoint"
                new_state, out = self._step("fixpoint")(
                    new_state, ev, np.uint64(timestamp), np.int32(n))
                fallback = bool(jax.device_get(out["fallback"]))
        if self.telemetry:
            # The harvested block IS the probe (satellite contract: no
            # host-side recomputation of shard balance) — the shard
            # diagnostics below decode from the same device words the
            # tracer events and the flight recorder see.
            tel = _host_local(out["shard_stats"]["tel"])
            d = decode_telemetry(tel)
            xs = int(d["cross_shard_transfers"].max())
            ov = int(d["exchange_overflow"].max())
            owned = d["events_owned"]
            summary = self._absorb_telemetry(tel)
            self.flight.record(window=self._window_seq,
                               route="partitioned_" + mode,
                               telemetry=summary)
        else:
            xs, ov = (int(x) for x in jax.device_get(
                (out["cross_shard_transfers"],
                 out["exchange_overflow"])))
            owned = _host_local(out["shard_stats"]["events_owned"])
        if int(xs):
            self.cross_shard_transfers += int(xs)
            self.tracer.count(Event.cross_shard_transfers,
                              value=int(xs))
        self.exchange_overflows += int(bool(ov))
        self.events_owned += np.asarray(owned, dtype=np.int64)
        if fallback:
            self.host_fallbacks += 1
            for k, v in jax.device_get(out["fb_causes"]).items():
                if bool(v):
                    self.fallback_causes[k] = (
                        self.fallback_causes.get(k, 0) + 1)
                    self.tracer.count(Event.router_fallback, cause=k)
        return new_state, out, fallback

    # ---- fused window dispatch (the default partitioned route) ----

    def _count_window(self, route: str) -> None:
        self.window_routes[route] = (
            self.window_routes.get(route, 0) + 1)
        self._window_seq += 1

    def stage_operands(self, evs: list[dict], timestamps: list[int],
                       n_pad: int):
        """Pack one fused window's stacked operands and start their
        REPLICATED device transfer (the chain step's in_specs are
        P() for ev_stack/ts_stack/n_stack — state is the only sharded
        input) as a single pytree put. Pure host work + transfer, no
        router state touched: DeviceLedger's background stager calls
        this off the dispatch thread so the pack/transfer overlaps the
        in-flight window; chain_dispatch(staged=...) consumes the
        result."""
        return jax.device_put(
            stack_partitioned_window(evs, timestamps, n_pad),
            NamedSharding(self.mesh, P()))

    def chain_dispatch(self, state, evs: list[dict],
                       timestamps: list[int], n_pad: int | None = None,
                       force_fallback=None, staged=None):
        """ONE fused shard_map+scan dispatch over a whole window,
        UNRESOLVED (every out leaf stays on device with a leading W
        axis). Pipelined drivers (DeviceLedger.submit_window) thread
        out["fallback"][-1] into the next window's force_fallback and
        resolve later; synchronous callers use step_window. Counts the
        window under the partitioned_chain route. `staged` is an
        optional pre-staged (ev_stack, ts_stack, n_stack) payload from
        stage_operands — already packed and resident replicated, so
        the dispatch skips the inline pack entirely."""
        self._require_serving()
        if staged is not None:
            ev_stack, ts_stack, n_stack = staged
        else:
            ns = [len(e["id_lo"]) for e in evs]
            if n_pad is None:
                n_pad = _pad_bucket(max(ns))
            ev_stack, ts_stack, n_stack = stack_partitioned_window(
                evs, timestamps, n_pad)
        self._count_window("partitioned_chain")
        self.tracer.count(Event.dispatch_route,
                          route="partitioned_chain")
        with self.tracer.span(Event.shard_exchange, mode="chain"):
            new_state, out = self._chain_step("plain")(
                state, ev_stack, ts_stack, n_stack, force_fallback)
        return new_state, out

    def absorb_chain_prefix(self, out, k: int, n_prepares: int) -> None:
        """Accumulate one fused dispatch's committed-prefix counters
        ([0, k) prepares) and, when k < n_prepares, the per-prepare
        fallback causes at iteration k (later iterations only carry
        the transitive poison). The replayed suffix counts itself
        through the per-batch step.

        With telemetry on, every counter here decodes from the
        harvested device block — the cross-shard/ownership words, the
        committed prefix's per-prepare rounds and occupancies (tracer
        histograms), and iteration k's poison cause — and the window
        lands one flight-recorder record."""
        self.batches += k
        tel = None
        if self.telemetry and "tel" in out.get("shard_stats", {}):
            tel = _host_local(out["shard_stats"]["tel"])
        if k:
            if tel is not None:
                d = decode_telemetry(tel[:, :k])
                xs = int(d["cross_shard_transfers"].max(axis=0).sum())
                owned = d["events_owned"].sum(axis=1)
                self.exchange_overflows += int(
                    d["exchange_overflow"].max(axis=0).sum())
            else:
                xs = int(np.asarray(jax.device_get(
                    out["cross_shard_transfers"]))[:k].sum())
                owned = _host_local(
                    out["shard_stats"]["events_owned"])[:, :k].sum(
                        axis=1)
            if xs:
                self.cross_shard_transfers += xs
                self.tracer.count(Event.cross_shard_transfers,
                                  value=xs)
            self.events_owned += np.asarray(owned, dtype=np.int64)
        if tel is not None:
            # Emit the committed prefix's per-prepare telemetry; when
            # the window poisoned at k, fold iteration k in too — its
            # decoded cause code is the post-mortem headline (later
            # iterations only carry the transitive `forced` poison).
            upto = min(k + 1, n_prepares) if k < n_prepares else k
            summary = self._absorb_telemetry(tel[:, :upto])
            self.flight.record(
                window=self._window_seq, route="partitioned_chain",
                telemetry=summary, prepares=n_prepares,
                committed_prefix=k)
        if k < n_prepares:
            for cause, v in jax.device_get(out["fb_causes"]).items():
                if bool(np.asarray(v)[k]):
                    self.chain_batch_fallbacks[cause] = (
                        self.chain_batch_fallbacks.get(cause, 0) + 1)

    def _window_per_batch(self, state, evs, timestamps, n_pad,
                          count_route=True):
        """The per-batch window ladder: one shard_map dispatch per
        prepare through step() (plain -> fixpoint escalation on
        device). The replay path for a chain window's fallen-back
        prepare, and the pre-route for windows carrying flags the
        plain chain body cannot serve."""
        if count_route:
            self._count_window("partitioned_per_batch")
        results = []
        for ev, ts in zip(evs, timestamps):
            n_b = len(ev["id_lo"])
            pe = pad_transfer_events(ev, n_pad)
            state, out, _fb = self.step(state, pe, ts, n_b)
            st, rts = jax.device_get((out["r_status"], out["r_ts"]))
            results.append((np.asarray(st)[:n_b],
                            np.asarray(rts)[:n_b]))
        return state, results

    def step_window(self, state, evs: list[dict],
                    timestamps: list[int], n_pad: int | None = None):
        """Commit one window of W prepares (each an UNPADDED
        transfers_to_arrays SoA dict). Returns (new_state, results)
        with one (status u32[n_b], ts u64[n_b]) pair per prepare.

        DEFAULT route: the partitioned CHAIN — ONE fused
        shard_map+lax.scan dispatch for the whole window when every
        prepare pre-routes plain (imported/balancing/closing windows
        take the per-batch ladder, whose steps escalate tiers
        per-flag). Per-prepare fallback preserves PR 6's window
        semantics: the clean prefix [0, k) committed inside the
        dispatch and its results stand; prepare k replays through the
        per-batch step (plain -> fixpoint escalation on device); the
        remainder re-windows recursively."""
        W = len(evs)
        if W == 0:
            return state, []
        self._require_serving()
        ns = [len(e["id_lo"]) for e in evs]
        if n_pad is None:
            n_pad = _pad_bucket(max(ns))
        if W < 2 or any(self.route(e) != "plain" for e in evs):
            return self._window_per_batch(state, evs, timestamps,
                                          n_pad)
        new_state, out = self.chain_dispatch(state, evs, timestamps,
                                             n_pad)
        fb = np.asarray(jax.device_get(out["fallback"]))
        k = int(np.argmax(fb)) if fb.any() else W
        self.absorb_chain_prefix(out, k, W)
        st_all, ts_all = (np.asarray(x) for x in jax.device_get(
            (out["r_status"], out["r_ts"])))
        results = [(st_all[b, :ns[b]], ts_all[b, :ns[b]])
                   for b in range(k)]
        if k == W:
            return new_state, results
        # Prepare k replays per-batch (the device escalation ladder
        # serves limit cascades without a host fallback); the poisoned
        # suffix — whose shards are bit-identical to the prefix state —
        # re-windows through the full ladder.
        new_state, res_k = self._window_per_batch(
            new_state, evs[k:k + 1], timestamps[k:k + 1], n_pad,
            count_route=False)
        results.extend(res_k)
        if k + 1 < W:
            new_state, rest = self.step_window(
                new_state, evs[k + 1:], timestamps[k + 1:], n_pad)
            results.extend(rest)
        return new_state, results

    def stats(self) -> dict:
        total = int(self.events_owned.sum())
        return {
            "batches": self.batches,
            "escalations": self.escalations,
            "host_fallbacks": self.host_fallbacks,
            "causes": dict(self.fallback_causes),
            "lost_devices": len(self.lost_devices),
            "shard_resyncs": self.shard_resyncs,
            "cross_shard_transfers": self.cross_shard_transfers,
            "exchange_overflows": self.exchange_overflows,
            "events_owned": [int(x) for x in self.events_owned],
            "cross_shard_fraction": (
                self.cross_shard_transfers / total if total else 0.0),
            # Dispatch-route record, DeviceLedger.fallback_stats()
            # shape: windows per route (partitioned_chain = the fused
            # default) + per-cause prepares that fell out of a chain
            # window (the prefix stayed committed).
            "routes": {
                "windows": dict(self.window_routes),
                "chain_batch_fallbacks": dict(
                    self.chain_batch_fallbacks),
            },
            # Device telemetry plane: everything below decodes from the
            # fixed-layout u32 block harvested with the outputs —
            # measured on device, never host-side guesswork. The
            # exchange-occupancy histogram dict is what the SLO
            # engine's exchange-headroom burn objective reads
            # (trace/slo.py evaluate_bench_record).
            "telemetry": None if not self.telemetry else {
                "device_poison_causes": dict(self.device_poison_causes),
                "writeback_rows": int(self.writeback_rows),
                "shard_capacity_hits": int(self.shard_capacity_hits),
                "exchange_occupancy": self._tel_hist.to_dict(),
                "fixpoint_rounds": self._tel_rounds.summary(),
                "flight_windows": self.flight.seq,
                "flight_dumps": self.flight.dumps,
            },
        }
