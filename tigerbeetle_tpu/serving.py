"""Serving supervisor: chaos-hardened orchestration around DeviceLedger.

The VSR/LSM layer already treats faults as repairable events (checksums
detect, peers heal, the VOPR proves it under a seed). This module gives
the TPU serving path the same property, in three parts:

1. **Bounded retry with backoff** — every device dispatch runs under a
   retry policy (exponential backoff with seeded jitter, a bounded
   attempt count, and a per-window deadline checked between attempts).
   Transient dispatch faults (`TransientDispatchError`, the class the
   chaos harness injects at the dispatch boundary) retry; exhaustion
   escalates to recovery instead of crashing or silently dropping the
   window.

2. **Verified state epochs** — every `epoch_interval` windows the
   supervisor quiesces the pipeline (resolve + drain), replays the
   epoch's logged inputs through the ORACLE engine (the pure-Python
   exact semantics — unreachable by device corruption), and checks
   three invariants: (a) the device-returned results match the oracle
   replay bit-for-bit, (b) the on-device state digest
   (ops/state_epoch.py — one tiny jitted fold, never part of a serving
   lowering) matches the digest of the replayed oracle state, and
   (c) the write-through mirror matches the replayed oracle object for
   object. A clean epoch advances the verified base (the replayed
   oracle IS the next epoch's replay source, so verification costs no
   extra snapshotting); any divergence quarantines the device state.

3. **Bounded replay recovery** — on quarantine (digest mismatch, result
   divergence, mirror divergence, retry exhaustion), the supervisor
   replays AT MOST the windows since the last verified epoch (asserted)
   through the oracle, revises the authoritative result history with
   the oracle's answers, rebuilds a fresh mirror + device state from
   the recovered oracle (`from_host`, the same path a restart takes),
   and resumes kernel serving. Per-cause recovery counters surface
   through `DeviceLedger.fallback_stats()["recovery"]`, bench.py's
   ``##bench`` line, and the devhub dashboard.

Fault model, detection latency, and the reproduction workflow are
documented in ARCHITECTURE.md ("Fault model & recovery"); the seeded
injection harness lives in testing/chaos.py and runs as
``python -m tigerbeetle_tpu cfo --kind chaos --seed <seed>``.
"""

from __future__ import annotations

import copy
import dataclasses
import random
import time
from dataclasses import dataclass

from .ops.ledger import DeviceLedger, MirrorDivergence, default_recovery_stats
from .oracle.state_machine import StateMachineOracle
from .trace import Event, FlightRecorder, NullTracer, fmt_trace_id


class TransientDispatchError(RuntimeError):
    """A device dispatch failed in a way worth retrying (the chaos
    harness's injected dispatch failures subclass this; a real backend
    wrapper would translate transient PJRT/tunnel errors into it)."""


class DispatchTimeout(TransientDispatchError):
    """A dispatch exceeded its deadline (injected or wrapped)."""


class RecoveryNeeded(RuntimeError):
    """Internal escalation: the serving pipeline must quarantine device
    state and replay from the last verified epoch."""

    def __init__(self, cause: str, detail: str = ""):
        super().__init__(cause + (f": {detail}" if detail else ""))
        self.cause = cause
        self.detail = detail


@dataclass
class RetryPolicy:
    """Bounded-retry parameters for one device dispatch. Backoff is
    exponential from base_delay_s, capped at max_delay_s, with
    multiplicative seeded jitter in [1, 1+jitter); deadline_s bounds the
    whole attempt sequence (checked between attempts — a dispatch
    blocked inside the runtime cannot be preempted, only not retried)."""

    max_retries: int = 3
    base_delay_s: float = 0.01
    max_delay_s: float = 1.0
    deadline_s: float = 30.0
    jitter: float = 0.25

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        base = min(self.max_delay_s,
                   self.base_delay_s * (2.0 ** max(0, attempt - 1)))
        return base * (1.0 + self.jitter * rng.random())

    def clamped(self, deadline_s: float | None) -> "RetryPolicy":
        """This policy with deadline_s tightened to a caller's remaining
        admission budget — never loosened. The admission plane threads a
        request's remaining deadline through the window dispatch so the
        whole retry sequence (attempts + backoff sleeps) is bounded by
        the budget the request was admitted under, instead of the
        policy's static per-window deadline."""
        if deadline_s is None or deadline_s >= self.deadline_s:
            return self
        return dataclasses.replace(self, deadline_s=max(0.0, deadline_s))


# Structural faults while consuming device-produced bytes (the drain
# materializes fetched delta chunks into the mirror): an unknown
# account/transfer id, an invalid enum code, or a bad index there is
# DETECTED corruption — corrupted device rows fed the chunk — so it
# routes to quarantine+replay, never to a retry or a raw crash.
_STRUCTURAL_FAULTS = (KeyError, IndexError, ValueError)


def call_with_retries(fn, policy: RetryPolicy, rng: random.Random,
                      counters: dict, *, sleep=time.sleep,
                      clock=time.monotonic, tracer=None):
    """Run `fn()` under `policy`. Transient faults retry with backoff;
    exhaustion (attempts or deadline) raises RecoveryNeeded, as do a
    MirrorDivergence and the structural drain faults (retrying cannot
    fix divergent state). Counters accumulate into the shared
    recovery-stats dict."""
    if tracer is None:
        tracer = NullTracer()
    t0 = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except MirrorDivergence as e:
            raise RecoveryNeeded("mirror_divergence", str(e)) from e
        except _STRUCTURAL_FAULTS as e:
            raise RecoveryNeeded("drain_fault", repr(e)) from e
        except TransientDispatchError as e:
            attempt += 1
            counters["retries"] += 1
            tracer.count(Event.serving_retries)
            if attempt > policy.max_retries:
                raise RecoveryNeeded(
                    "dispatch_exhausted",
                    f"{attempt} attempts: {e!r}") from e
            remaining = policy.deadline_s - (clock() - t0)
            if remaining <= 0:
                raise RecoveryNeeded(
                    "dispatch_deadline",
                    f"deadline {policy.deadline_s}s: {e!r}") from e
            # The backoff sleep itself is capped by the remaining
            # deadline budget: under saturation, exponential backoff
            # must not stack the attempt sequence past the deadline the
            # caller (per-window or admission) is holding the line on.
            delay = min(policy.delay_s(attempt, rng), remaining)
            counters["backoff_s"] = round(
                counters["backoff_s"] + delay, 6)
            sleep(delay)


class ServingSupervisor:
    """Owns a write-through DeviceLedger and supervises its serving
    loop: retries, verified epochs, and bounded replay recovery.

    The caller submits Transfer/Account OBJECT batches (the supervisor
    keeps them as the epoch's replayable log); device dispatch uses the
    ledger's array paths underneath. `history` is the authoritative
    normalized result record — one entry per submitted op, revised with
    the oracle's answers whenever a recovery replays a suffix."""

    def __init__(self, a_cap: int = 1 << 17, t_cap: int = 1 << 21, *,
                 epoch_interval: int = 8, retry: RetryPolicy | None = None,
                 seed: int = 0, mirror_audit: str = "full",
                 fault_hook=None, sleep=time.sleep, tracer=None,
                 flight_recorder=None, pipeline_depth: int = 2,
                 profiler=None, memwatch=None, alert_engine=None):
        assert mirror_audit in ("full", "spot", "off")
        self.tracer = tracer if tracer is not None else NullTracer()
        # Flight recorder: every window's route decision and every
        # verified epoch digest ring here; any recovery — including
        # retry exhaustion (dispatch_exhausted / dispatch_deadline) —
        # freezes the ring into a post-mortem artifact.
        self.flight = flight_recorder if flight_recorder is not None \
            else FlightRecorder(tracer=self.tracer)
        # Performance observatory (ISSUE 20): all three hooks are
        # optional and None by default — the unobserved serving path
        # pays nothing. The profiler samples window dispatches, the
        # memwatch ticks at every verified epoch (the natural quiesce
        # point), and the alert engine ticks once per committed window
        # in the same tracer + flight-recorder universe as everything
        # else (a page-severity firing dumps OUR flight ring).
        self.profiler = profiler
        self.memwatch = memwatch
        self.alert_engine = alert_engine
        if alert_engine is not None:
            alert_engine.bind(self.tracer, self.flight)
        self.a_cap = a_cap
        self.t_cap = t_cap
        self.epoch_interval = epoch_interval
        self.retry = retry or RetryPolicy()
        self.rng = random.Random(seed)
        self.mirror_audit = mirror_audit
        # Chaos-injection point: called as hook(window_index, what) at
        # every dispatch attempt; raising TransientDispatchError /
        # DispatchTimeout injects a dispatch fault (testing/chaos.py).
        self.fault_hook = fault_hook
        self._sleep = sleep
        self.counters = default_recovery_stats()
        # The last VERIFIED epoch's state: a pure oracle advanced only
        # by replaying logged inputs — device corruption cannot reach
        # it. After each clean epoch it equals the live state.
        self.epoch_base = StateMachineOracle()
        self.log: list = []       # ops since the last verified epoch
        self.history: list = []   # normalized results, one per op ever
        self.last_recovery: dict | None = None
        self._windows_since_epoch = 0
        self.windows_total = 0
        # Overlapped serving (submit_transfers_window): in-flight
        # pipelined window records, oldest first. pipeline_depth bounds
        # how many stay unresolved — at depth the oldest resolves
        # before the next submit, and window k+1's host staging (the
        # ledger's background stager) overlaps exactly that blocking
        # resolve plus the in-flight dispatch. The synchronous
        # create_transfers_window path never populates this.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._pending: list = []
        # Trace ids of requests whose windows landed since the last
        # verified epoch: a recovery affects exactly these requests, so
        # tail retention force-keeps them (ISSUE 15) and the flight
        # artifact names them for cross-reference.
        self._epoch_trace_ids: list[str] = []
        # Elastic shards (ISSUE 19): set by attach_partitioned — the
        # sharded-state backend's router and the live-resharding
        # controller whose migrations interleave with commit windows.
        self.part_router = None
        self.resharder = None
        self._attach(DeviceLedger(a_cap, t_cap,
                                  write_through=StateMachineOracle()))

    def _attach(self, led: DeviceLedger) -> None:
        self.led = led
        # The ledger surfaces OUR counters through fallback_stats() so
        # bench/devhub records carry them next to the fallback causes.
        led.recovery_stats = self.counters
        # And OUR tracer flows down so window_stage spans + the
        # host-stall gauge land in the same catalog as everything else.
        led.tracer = self.tracer

    def attach_partitioned(self, router):
        """Switch serving to the partitioned (sharded-state) backend:
        a fresh un-mirrored DeviceLedger in attach mode over `router`,
        seeded from the current verified epoch base, plus a
        ReshardController for live migrations (driven by `reshard()`
        and the per-window tick). The write-through mirror does not
        exist in attach mode, so the epoch check's mirror audit is
        disabled; result parity and the sharded state digest remain.
        Create accounts BEFORE attaching (the epoch base seeds the
        sharded state). Returns the controller."""
        from .parallel.resharding import ReshardController

        # Fold the open log into the verified base first — the sharded
        # state is seeded from it, so anything still un-verified would
        # silently vanish from the new backend.
        self.verify_epoch()
        self.led.shutdown_staging()
        self.mirror_audit = "off"
        router.tracer = self.tracer
        router.flight = self.flight
        self.part_router = router
        led = DeviceLedger(self.a_cap, self.t_cap)
        led.attach_partitioned(router,
                               router.from_oracle(self.epoch_base))
        self._attach(led)
        self.resharder = ReshardController(router, tracer=self.tracer)
        return self.resharder

    def reshard(self, plan) -> None:
        """Begin a live migration (parallel/resharding.ReshardPlan).
        The snapshot is taken at a VERIFIED epoch — verify_epoch()
        quiesces, replays the log, and proves the digests first, so the
        frozen range is witness-backed and the epoch base can vouch for
        the copy (oracle digest leg + the range's ring rows). The
        migration then advances one copy chunk per submitted window
        (conflicting windows drain it), double-writes, and flips at a
        later window boundary; MigrationAborted propagates to the
        caller with ownership already reverted."""
        from .parallel.resharding import MigrationAborted

        assert self.part_router is not None, \
            "attach_partitioned() first"
        assert not self.resharder.active, "migration already in flight"
        self.verify_epoch()
        led = self.led
        try:
            led._part_state = self.resharder.begin(
                led.partitioned_state, plan, oracle=self.epoch_base)
        except MigrationAborted:
            # begin aborts before staging anything on device: the
            # artifact is frozen, ownership untouched, serving intact.
            raise

    def _reshard_tick(self, batches) -> None:
        """The per-window migration tick (both window paths call this
        BEFORE dispatching): quiesce the pipeline while a migration is
        active and advance it one step at this window boundary. An
        abort here is survivable by construction — ownership reverted,
        staged copy evicted — so serving continues on the pre-migration
        owner and the abort surfaces through the controller's records
        and the flight artifact rather than failing the window."""
        from .parallel.resharding import MigrationAborted

        ctl = self.resharder
        if ctl is None or not ctl.active:
            return
        self.drain_pipeline()
        self.led.resolve_windows()
        led = self.led
        try:
            led._part_state = ctl.on_window(led.partitioned_state,
                                            batches)
        except MigrationAborted as e:
            led._part_state = e.state

    # ------------------------------------------------------------ serving

    def create_accounts(self, accounts: list, timestamp: int):
        accounts = list(accounts)
        res = self._dispatch(
            lambda: self.led.create_accounts(accounts, timestamp),
            what="create_accounts")
        norm = [(r.timestamp, int(r.status)) for r in res]
        self.log.append(("accounts", accounts, timestamp))
        self.history.append(norm)
        return res

    def create_transfers_window(self, batches: list, timestamps: list,
                                trace_ctxs: list | None = None,
                                deadline_s: float | None = None):
        """Submit one commit window: `batches` is a list of Transfer
        object lists, `timestamps` the per-prepare commit timestamps.
        Returns the ledger's per-prepare (status u32[n], ts u64[n])
        pairs. Runs the epoch check when the interval elapses.

        `trace_ctxs` is the optional per-prepare TraceContext list
        (entries may be None): the window span joins the first traced
        request's causal tree and LINKS every constituent trace id —
        the fan-in edge assemble_traces() reads. A window that lands on
        the fallback route force-keeps its constituent traces (tail
        retention), as does any recovery that replays it."""
        from .ops.batch import transfers_to_arrays

        batches = [list(b) for b in batches]
        timestamps = list(timestamps)
        win = self.windows_total
        ctxs = [c for c in (trace_ctxs or ()) if c is not None]
        trace_ids = [fmt_trace_id(c.trace_id) for c in ctxs]
        self._epoch_trace_ids.extend(trace_ids)
        self._reshard_tick(batches)

        def thunk():
            evs = [transfers_to_arrays(b) for b in batches]
            return self.led.create_transfers_window(evs, timestamps)

        thunk = self._profiled(thunk)
        # window_commit wraps submit→resolve and is tagged late (the
        # ledger only knows which route it took after dispatch), so
        # each window lands in its route/tier latency class — the
        # per-class distributions the SLO objectives read.
        with self.tracer.span(Event.window_commit,
                              ctx=ctxs[0] if ctxs else None) as sp:
            for tid in trace_ids:
                sp.link(tid)
            out = self._dispatch(thunk, what="window", win=win,
                                 deadline_s=deadline_s)
            # The route the ledger actually took (chain is the default
            # whole-window scan dispatch) — counted into the trace
            # catalog so route regressions are visible next to
            # retry/recovery counters; retry/epoch-verify semantics are
            # route-independent.
            route = self.led.last_window_route
            if route:
                sp.tags["route"] = route
                tier = self.led.last_window_tier
                if tier:
                    sp.tags["tier"] = tier
                self.tracer.count(Event.dispatch_route, route=route)
        if route and "fallback" in route:
            for tid in trace_ids:
                self.tracer.keep_trace(tid, reason="fallback")
        self.flight.record(window=win, route=route or "unknown",
                           prepares=len(batches),
                           **({"trace_ids": trace_ids} if trace_ids
                              else {}))
        norm = [[(int(t), int(s)) for s, t in zip(st.tolist(), ts.tolist())]
                for st, ts in out]
        self.log.append(("window", batches, timestamps))
        self.history.append(norm)
        self.windows_total += 1
        self._windows_since_epoch += 1
        self._observatory_tick()
        if self._windows_since_epoch >= self.epoch_interval:
            self.verify_epoch()
        return out

    # ------------------------------------------------- overlapped serving

    def submit_transfers_window(self, batches: list, timestamps: list,
                                trace_ctxs: list | None = None,
                                deadline_s: float | None = None,
                                evs: list | None = None) -> int:
        """The overlapped serving hot loop's submit half: stage window
        k's stacked operands on the ledger's background stager FIRST,
        resolve the oldest in-flight window when the pipeline is at
        depth (the stage's pack+transfer overlaps that blocking resolve
        and the in-flight dispatch), then dispatch window k with zero
        host synchronization (DeviceLedger.submit_window — poison
        chaining unchanged). Returns the window's history index;
        results materialize at resolve_transfers_windows() /
        drain_pipeline(), or out of a recovery's oracle replay exactly
        like the synchronous path (the window is logged at dispatch, so
        bounded replay covers in-flight windows; a staged-but-
        undispatched pack dies with the quarantined ledger and is never
        committed). Windows the pipeline cannot take (flagged/imported/
        oversized) fall through to the synchronous window path inline.
        Runs the epoch check when the interval elapses — epoch verify
        drains the pipeline, as does recovery."""
        from .ops.batch import transfers_to_arrays

        batches = [list(b) for b in batches]
        timestamps = list(timestamps)
        win = self.windows_total
        ctxs = [c for c in (trace_ctxs or ()) if c is not None]
        trace_ids = [fmt_trace_id(c.trace_id) for c in ctxs]
        self._epoch_trace_ids.extend(trace_ids)
        self._reshard_tick(batches)
        # `evs` lets the admission plane pass the SAME array dicts it
        # already staged ahead (DeviceLedger.stage_window matches on
        # prepare-dict identity) — re-staging here would replace the
        # in-flight pack and forfeit the overlap.
        if evs is None:
            evs = [transfers_to_arrays(b) for b in batches]
        if not self.led.staged_matches(evs, timestamps):
            self.led.stage_window(evs, timestamps)
        if len(self._pending) >= self.pipeline_depth:
            self.resolve_transfers_windows(count=1)
        t0 = self.tracer.now_ns()
        ticket = self._dispatch(
            lambda: self.led.submit_window(evs, timestamps),
            what="window_submit", win=win, deadline_s=deadline_s)
        rec = {"hist_idx": len(self.history), "win": win,
               "ticket": ticket, "t0_ns": t0, "trace_ids": trace_ids,
               "route": self.led.last_window_route,
               "tier": self.led.last_window_tier, "results": None,
               "deadline_s": deadline_s}
        if ticket is None:
            # Ineligible for the pipeline: the synchronous window path
            # (which itself resolves everything in flight first, so
            # submit order is preserved).
            out = self._dispatch(
                lambda: self.led.create_transfers_window(evs,
                                                         timestamps),
                what="window", win=win, deadline_s=deadline_s)
            rec["route"] = self.led.last_window_route
            rec["tier"] = self.led.last_window_tier
            rec["results"] = [
                [(int(t), int(s))
                 for s, t in zip(st.tolist(), ts.tolist())]
                for st, ts in out]
        route = rec["route"]
        if route:
            self.tracer.count(Event.dispatch_route, route=route)
        if route and "fallback" in route:
            for tid in trace_ids:
                self.tracer.keep_trace(tid, reason="fallback")
        self.flight.record(window=win, route=route or "unknown",
                           prepares=len(batches),
                           **({"trace_ids": trace_ids} if trace_ids
                              else {}))
        self.log.append(("window", batches, timestamps))
        self.history.append(rec["results"])
        hist_idx = rec["hist_idx"]
        if rec["results"] is None:
            self._pending.append(rec)
        else:
            self._close_window_span(rec)
        self.windows_total += 1
        self._windows_since_epoch += 1
        self._observatory_tick()
        if self._windows_since_epoch >= self.epoch_interval:
            self.verify_epoch()
        return hist_idx

    def resolve_transfers_windows(self, count: int | None = None) -> list:
        """Resolve the oldest `count` pending pipelined windows (all of
        them when None), filling their history entries, and return
        their normalized per-prepare results ([(ts, status), ...] per
        prepare, the history/oracle shape). A mid-pipeline fallback or
        a recovery may resolve more than asked on the ledger side; the
        extra records simply materialize without blocking when their
        turn comes."""
        n = len(self._pending) if count is None \
            else min(count, len(self._pending))
        out = []
        for _ in range(n):
            rec = self._pending[0]
            tk = rec["ticket"]
            if rec["results"] is None and tk is not None \
                    and tk.results is None:
                self._dispatch(
                    lambda: self.led.resolve_windows(count=1),
                    what="window_resolve", win=rec["win"],
                    deadline_s=rec.get("deadline_s"))
                tk = rec["ticket"]  # a recovery replaces it with None
            self._pending.pop(0)
            if rec["results"] is None:
                _kind, pairs = tk.results
                rec["results"] = [
                    [(int(t), int(s))
                     for s, t in zip(st.tolist(), ts.tolist())]
                    for st, ts in pairs]
                self.history[rec["hist_idx"]] = rec["results"]
            self._close_window_span(rec)
            out.append(rec["results"])
        return out

    def drain_pipeline(self) -> list:
        """Resolve every pending pipelined window (epoch verify and
        recovery drain through here): history is fully materialized
        after this returns."""
        return self.resolve_transfers_windows()

    def _close_window_span(self, rec) -> None:
        """Emit the submit->resolve window_commit span for one
        pipelined window (explicit timing — its open/close sites are
        separate calls), tagged with the route/tier latency class the
        SLO engine partitions on."""
        t0 = rec["t0_ns"]
        tags = {}
        if rec["route"]:
            tags["route"] = rec["route"]
            if rec["tier"]:
                tags["tier"] = rec["tier"]
        self.tracer.record_span(Event.window_commit, t0,
                                self.tracer.now_ns() - t0, **tags)

    def expire_pending_transfers(self, timestamp: int) -> int:
        n = self._dispatch(
            lambda: self.led.expire_pending_transfers(timestamp),
            what="expire")
        self.log.append(("expire", None, timestamp))
        self.history.append(n)
        return n

    def _profiled(self, thunk):
        """Wrap one WINDOW dispatch thunk in the sampled profiler (when
        attached). Route/tier are resolved late — the ledger records
        them only after dispatching — via the profiler's callable-tag
        hook. Non-window dispatches stay unwrapped: the window routes
        (chain / partitioned_chain / per-batch) are the dispatch
        surface the roofline model attributes."""
        prof = self.profiler
        if prof is None:
            return thunk
        return lambda: prof.time(
            thunk,
            route=lambda: self.led.last_window_route or "unknown",
            tier=lambda: self.led.last_window_tier or "-")

    def _observatory_tick(self) -> None:
        """Advance the alert engine one committed window (it decimates
        internally); runs at every window close on both serving
        paths."""
        if self.alert_engine is not None:
            self.alert_engine.tick()

    def _dispatch(self, thunk, *, what: str = "", win: int | None = None,
                  deadline_s: float | None = None):
        hook = self.fault_hook
        idx = self.windows_total if win is None else win
        policy = self.retry.clamped(deadline_s)

        def run():
            if hook is not None:
                hook(idx, what)
            return thunk()

        try:
            with self.tracer.span(Event.serving_dispatch, what=what):
                return call_with_retries(run, policy, self.rng,
                                         self.counters, sleep=self._sleep,
                                         tracer=self.tracer)
        except RecoveryNeeded as e:
            self._recover(e.cause, detail=e.detail)
            # Fresh, verified state: one post-recovery re-dispatch of
            # the op itself (no fault hook — the injected fault was a
            # property of the quarantined attempt sequence).
            return thunk()

    # ------------------------------------------------------------- epochs

    def verify_epoch(self) -> bool:
        """Quiesce, replay the epoch's log through the oracle, and check
        results / state digest / mirror. Clean -> advance the verified
        base and return True; any divergence -> recover and return
        False. Calling with an empty log is a cheap no-op epoch."""
        with self.tracer.span(Event.serving_epoch_verify):
            return self._verify_epoch()

    def _verify_epoch(self) -> bool:
        from .ops import state_epoch

        # Quiesce the overlapped pipeline first: every pending window
        # resolves (filling its history entry) before the oracle replay
        # below compares against history. A recovery triggered inside
        # this drain clears the log and swaps the ledger — the checks
        # below then run against the freshly rebuilt state, trivially.
        self.drain_pipeline()
        led = self.led
        try:
            led.resolve_windows()
            led.drain_mirror()
        except MirrorDivergence as e:
            self._recover("mirror_divergence", detail=str(e))
            return False
        except _STRUCTURAL_FAULTS as e:
            self._recover("drain_fault", detail=repr(e))
            return False
        # An in-flight migration makes the whole-state digest
        # incomparable (staged copy rows bump the target's counts):
        # complete it — or let it abort cleanly — before judging the
        # epoch. Either way ownership is settled when the folds run.
        if self.resharder is not None and self.resharder.active:
            from .parallel.resharding import MigrationAborted
            try:
                led._part_state = self.resharder.drain(
                    led.partitioned_state)
            except MigrationAborted as e:
                led._part_state = e.state
        n_entries = len(self.log)
        replayed = self._replay_log_into_base()
        cause = None
        detail = ""
        # (a) result parity: device answers vs the oracle replay.
        start = len(self.history) - n_entries
        for i, want in enumerate(replayed):
            if self.history[start + i] != want:
                cause = "result_divergence"
                detail = f"op {start + i}"
                break
        # (b) state digest: device fold vs the replayed-oracle fold.
        # Partitioned backend: the sharded digest vs the oracle pack
        # placed by the CURRENT ownership table (overlay entries are
        # part of the epoch's identity — a flip moves rows between
        # shards and the pack must agree on where they landed).
        if cause is None:
            if self.part_router is not None:
                r = self.part_router
                got = state_epoch.partitioned_state_digest(
                    led.partitioned_state)
                want_d = state_epoch.partitioned_oracle_digest(
                    self.epoch_base, self.a_cap, r.n_shards,
                    overlay=r.ownership.entries)
            else:
                got = state_epoch.device_state_digest(led.state)
                want_d = state_epoch.oracle_state_digest(
                    self.epoch_base, self.a_cap)
            if got != want_d:
                self.counters["checksum_mismatches"] += 1
                cause = "state_digest"
                detail = ",".join(
                    state_epoch.diverging_components(got, want_d))
        # (c) mirror audit: write-through mirror vs the replayed oracle.
        if cause is None and self.mirror_audit != "off":
            bad = self._mirror_audit_fields(
                full=self.mirror_audit == "full")
            if bad:
                cause = "mirror_divergence"
                detail = ",".join(bad)
        if cause is None:
            self.counters["epochs_verified"] += 1
            self.flight.record(window=self.windows_total,
                               route="epoch_verified",
                               epoch_digest=got)
            self.log.clear()
            self._windows_since_epoch = 0
            self._epoch_trace_ids.clear()
            # Memory watermark at the quiesce point: the pipeline is
            # drained, so the measured components are the steady-state
            # residents (plus whatever pack the stager holds).
            if self.memwatch is not None:
                self.memwatch.observe(self.led)
            return True
        self._recover(cause, detail=detail, replayed=replayed)
        return False

    def _replay_log_into_base(self) -> list:
        """Apply the epoch log to the verified base oracle, returning
        normalized results per entry (the authoritative answers)."""
        base = self.epoch_base
        out = []
        for kind, payload, ts in self.log:
            if kind == "accounts":
                res = base.create_accounts(payload, ts)
                out.append([(r.timestamp, int(r.status)) for r in res])
            elif kind == "window":
                out.append([
                    [(r.timestamp, int(r.status))
                     for r in base.create_transfers(b, bts)]
                    for b, bts in zip(payload, ts)])
            else:
                assert kind == "expire", kind
                out.append(base.expire_pending_transfers(ts))
        return out

    def _mirror_audit_fields(self, full: bool) -> list[str]:
        """Object-level audit of the write-through mirror against the
        replayed oracle. full=True compares every container; spot mode
        compares sizes/scalars plus a seeded object sample."""
        sm = self.led.mirror
        base = self.epoch_base
        bad: list[str] = []
        if full:
            for field in ("accounts", "transfers", "pending_status",
                          "orphaned", "expiry"):
                if getattr(sm, field) != getattr(base, field):
                    bad.append(field)
            off = sm.events_base - base.events_base
            if not (0 <= off <= len(base.account_events)) or \
                    sm.account_events != base.account_events[off:]:
                bad.append("account_events")
            return bad
        if (len(sm.accounts) != len(base.accounts)
                or len(sm.transfers) != len(base.transfers)
                or sm.commit_timestamp != base.commit_timestamp):
            return ["sizes"]
        ids = list(base.transfers)
        for tid in (self.rng.sample(ids, min(4, len(ids))) if ids else ()):
            if sm.transfers.get(tid) != base.transfers.get(tid):
                bad.append(f"transfer:{tid}")
        return bad

    # ----------------------------------------------------------- recovery

    def _recover(self, cause: str, detail: str = "",
                 replayed: list | None = None) -> None:
        """Quarantine the device state and recover from the last
        verified epoch: oracle-replay the logged suffix (bounded),
        revise the authoritative history, rebuild mirror + device from
        the recovered oracle, resume serving.

        Recovery is THE flight-recorder dump point: freeze the
        last-N window records (+ epoch digests) as a JSON artifact
        tagged with the recovery cause before anything is rebuilt —
        covering retry exhaustion, deadline, divergence, and
        drain-fault causes alike."""
        # Tail retention: every request whose window sits in the
        # replayed suffix is force-kept regardless of head sampling,
        # and the flight artifact names the same trace ids so the
        # post-mortem can be cross-referenced with the causal traces.
        affected = list(dict.fromkeys(self._epoch_trace_ids))
        for tid in affected:
            self.tracer.keep_trace(tid, reason=cause)
        self.flight.record(window=self.windows_total, route="recovery",
                           cause=cause, detail=detail[:200],
                           **({"trace_ids": affected} if affected
                              else {}))
        self.flight.dump(cause)
        self.tracer.count(Event.serving_recoveries, cause=cause)
        with self.tracer.span(Event.serving_recovery_replay, cause=cause):
            self._recover_replay(cause, detail, replayed)

    def _recover_replay(self, cause: str, detail: str,
                        replayed: list | None) -> None:
        n_entries = len(self.log)
        n_windows = sum(1 for e in self.log if e[0] == "window")
        # Bounded-replay invariant: recovery never replays more windows
        # than fit between two epoch checks.
        assert n_windows <= self.epoch_interval, \
            (n_windows, self.epoch_interval)
        # The bounded-replay SLO (perf/slo.json) reads this
        # distribution: windows replayed per recovery, unit windows.
        self.tracer.observe(Event.serving_replay_windows, n_windows)
        if replayed is None:
            replayed = self._replay_log_into_base()
        start = len(self.history) - n_entries
        self.history[start:] = replayed
        # Pipelined windows still in flight at quarantine: every one of
        # them was LOGGED at dispatch, so the oracle replay above just
        # produced their authoritative results — adopt those and detach
        # the dead tickets. A staged-but-undispatched pack was never
        # logged: it dies with the quarantined ledger's stager
        # (shutdown_staging below) and is re-staged fresh if its window
        # is ever submitted again — drained cleanly, committed never.
        for rec in self._pending:
            rec["results"] = self.history[rec["hist_idx"]]
            rec["ticket"] = None
        self.counters["replayed_windows"] += n_windows
        recs = self.counters["recoveries"]
        recs[cause] = recs.get(cause, 0) + 1
        self.last_recovery = {"cause": cause, "detail": detail,
                              "replayed_entries": n_entries,
                              "replayed_windows": n_windows}
        # Fresh mirror from the recovered oracle (a deep copy: the
        # mirror evolves by write-through deltas, the base only by
        # replay) and a device rebuild through from_host — the same
        # path a restart/state-sync takes. The quarantined ledger's
        # stager drains first: its staged-but-undispatched window (if
        # any) is dropped, its worker joined.
        self.led.shutdown_staging()
        if self.part_router is not None:
            # Partitioned backend: an un-flipped migration reverts to
            # its pre-flip owner FIRST (the controller drops the
            # overlay entry and records the reshard_abort), then the
            # whole sharded state rebuilds from the verified base via
            # the router's resync — the pack places every range by the
            # reverted table, so staged copy rows simply never
            # reappear. A flipped migration keeps its MIGRATED entry
            # and the rebuild honors it.
            if self.resharder is not None:
                self.resharder.on_recovery()
            r = self.part_router
            state = r.resync(self.epoch_base)
            led = DeviceLedger(self.a_cap, self.t_cap)
            led.attach_partitioned(r, state)
            self._attach(led)
        else:
            new_mirror = copy.deepcopy(self.epoch_base)
            self._attach(DeviceLedger(self.a_cap, self.t_cap,
                                      write_through=new_mirror))
        self.log.clear()
        self._windows_since_epoch = 0
        self._epoch_trace_ids.clear()

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        out = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in self.counters.items()}
        out["windows_total"] = self.windows_total
        out["windows_since_epoch"] = self._windows_since_epoch
        out["pipeline"] = {"depth": self.pipeline_depth,
                           "pending": len(self._pending)}
        out["last_recovery"] = self.last_recovery
        if self.resharder is not None:
            out["resharding"] = {
                "stage": self.resharder.stage,
                "migrations": list(self.resharder.migrations),
                "aborts": list(self.resharder.aborts)}
        out["flight"] = {"windows_recorded": self.flight.seq,
                         "dumps": self.flight.dumps,
                         "last_dump": self.flight.last_dump_path}
        observatory = {}
        if self.profiler is not None:
            observatory["profiler"] = self.profiler.stats()
        if self.memwatch is not None:
            observatory["memwatch"] = self.memwatch.stats()
        if self.alert_engine is not None:
            observatory["alerts"] = self.alert_engine.stats()
        if observatory:
            out["observatory"] = observatory
        out["ledger"] = self.led.fallback_stats()
        return out
