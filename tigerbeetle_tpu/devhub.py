"""devhub: benchmark history + regression detection + dashboard.

reference: src/devhub/ + src/scripts/devhub.zig — nightly metrics
(benchmark tx/s, latency, sizes) recorded to a database and rendered on
a dashboard; the CFO fleet pushes failing fuzz seeds to the same place
(src/scripts/cfo.zig:1-41). Here: bench JSON lines append to a JSONL
history; `regressions` flags metrics that dropped against their
trailing median (the reference's nightly-regression purpose); `render`
emits a self-contained HTML dashboard — metric sparklines, regression
badges, parity series, and the latest CFO sweep's failing seeds with
their reproduction commands (no external assets, mirroring the
reference's static devhub page).
"""

from __future__ import annotations

import glob
import html
import json
import os
import time
from typing import Optional

NUMERIC_KEYS = (
    "value", "config1_2hot_tps", "config2_10k_tps", "config3_chains_tps",
    "config4_twophase_limits_tps", "config6_serving_tps",
)

# Nested metrics: (display key, path into the record).
NESTED_KEYS = (
    ("serving_sustained_tps", ("serving_batch_latency", "sustained_tps")),
    ("serving_p99_ms", ("serving_batch_latency", "p99_ms")),
    ("serving_p999_ms", ("serving_batch_latency", "p999_ms")),
    # Tracing-cost guard (bench ##trace): recording-vs-NullTracer wall
    # clock on the same commit loop; a creeping ratio is a tracing
    # regression like any other.
    ("trace_overhead_ratio", ("trace", "overhead_ratio")),
    # Causal-propagation cost guard (ISSUE 15): the same loop with
    # trace-context stamping at sampling 1.0 vs NullTracer; the
    # acceptance ceiling is 1.15x.
    ("trace_ctx_overhead_ratio", ("trace", "ctx_overhead_ratio")),
)

REGRESSION_WINDOW = 8  # trailing runs forming the baseline median
REGRESSION_TOLERANCE = 0.10  # flag drops beyond 10% of the median


def record(history_path: str, bench_json: dict,
           timestamp: Optional[int] = None) -> None:
    entry = dict(bench_json)
    entry["recorded_at"] = timestamp if timestamp is not None else int(time.time())
    with open(history_path, "a") as f:
        f.write(json.dumps(entry) + "\n")


def load(history_path: str) -> list[dict]:
    out = []
    try:
        with open(history_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn write: skip
    except FileNotFoundError:
        pass
    return out


def _series(entries: list[dict], key: str) -> list:
    for display, path in NESTED_KEYS:
        if key == display:
            out = []
            for e in entries:
                v = e
                for part in path:
                    v = v.get(part) if isinstance(v, dict) else None
                out.append(v)
            return out
    return [e.get(key) for e in entries]


def _median(values: list[float]) -> Optional[float]:
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2


# Metrics where a regression is an INCREASE (latency); everything else
# regresses by dropping (throughput).
_HIGHER_IS_WORSE = frozenset({"serving_p99_ms", "serving_p999_ms",
                              "trace_overhead_ratio",
                              "trace_ctx_overhead_ratio"})


def regressions(entries: list[dict]) -> dict:
    """metric -> {latest, baseline, ratio} for metrics whose newest
    value moved more than REGRESSION_TOLERANCE past the median of the
    preceding REGRESSION_WINDOW runs, in that metric's bad direction
    (reference: the devhub dashboard exists to catch exactly these
    overnight)."""
    out = {}
    keys = NUMERIC_KEYS + tuple(d for d, _ in NESTED_KEYS)
    for key in keys:
        series = [v for v in _series(entries, key) if v is not None]
        if len(series) < 2:
            continue
        latest = series[-1]
        baseline = _median(series[-1 - REGRESSION_WINDOW:-1])
        if not baseline:
            continue
        if key in _HIGHER_IS_WORSE:
            bad = latest > baseline * (1 + REGRESSION_TOLERANCE)
        else:
            bad = latest < baseline * (1 - REGRESSION_TOLERANCE)
        if bad:
            out[key] = {"latest": latest, "baseline": baseline,
                        "ratio": round(latest / baseline, 3)}
    return out


def load_cfo(cfo_dir: str) -> Optional[dict]:
    """Newest CFO sweep artifact (cfo/CFO_*.json), or None."""
    paths = sorted(glob.glob(os.path.join(cfo_dir, "CFO_*.json")))
    if not paths:
        return None
    try:
        with open(paths[-1]) as f:
            d = json.load(f)
        d["_path"] = paths[-1]
        return d
    except (OSError, ValueError):
        return None


def _sparkline(values: list[float], width: int = 320, height: int = 48) -> str:
    values = [v for v in values if v is not None]
    if not values:
        return "<svg/>"
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    step = width / max(1, len(values) - 1) if len(values) > 1 else width
    points = " ".join(
        f"{round(i * step, 1)},{round(height - 4 - (v - lo) / span * (height - 8), 1)}"
        for i, v in enumerate(values))
    return (f'<svg width="{width}" height="{height}">'
            f'<polyline fill="none" stroke="#2a6" stroke-width="2" '
            f'points="{points}"/></svg>')


def render(history_path: str, out_path: str,
           cfo_dir: Optional[str] = None,
           entries: Optional[list] = None,
           regress: Optional[dict] = None) -> int:
    """Render the dashboard; returns the number of history entries.
    `entries`/`regress` let a caller that already loaded the history
    (cmd_devhub's gate) avoid parsing and scanning it twice."""
    if entries is None:
        entries = load(history_path)
    if regress is None:
        regress = regressions(entries)
    rows = []
    for key in NUMERIC_KEYS + tuple(d for d, _ in NESTED_KEYS):
        series = _series(entries, key)
        latest = next((v for v in reversed(series) if v is not None), None)
        flag = ""
        if key in regress:
            r = regress[key]
            flag = (f'<span style="color:#c22;font-weight:600">'
                    f'REGRESSED {r["ratio"]:.2f}x of median '
                    f'{r["baseline"]:,.0f}</span>')
        rows.append(
            "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td></tr>".format(
                html.escape(key),
                "-" if latest is None else f"{latest:,.0f}",
                _sparkline(series), flag))
    # Oracle-parity series: every recorded run must say True.
    parity = [e.get("config5_oracle_parity") for e in entries
              if e.get("config5_oracle_parity") is not None]
    parity_html = (
        f"<p>oracle parity: {sum(1 for p in parity if p)}/{len(parity)} "
        f"runs clean"
        + ("" if all(parity) else
           ' — <b style="color:#c22">PARITY FAILURE RECORDED</b>')
        + "</p>") if parity else ""
    # Fallback observability: the newest run's per-config per-cause
    # host-fallback counters (bench fallback_diagnostics). "Zero host
    # fallbacks" is a measured invariant — a nonzero count is rendered
    # as loudly as a throughput regression.
    fb_html = ""
    fb = next((e.get("fallback_diagnostics") for e in reversed(entries)
               if isinstance(e.get("fallback_diagnostics"), dict)), None)
    if fb:
        rows_fb = []
        any_host_fb = False
        for cfg in sorted(fb):
            d = fb[cfg] or {}
            host = (d.get("host_fallbacks", 0) or 0) + \
                (d.get("window_fallbacks", 0) or 0)
            any_host_fb = any_host_fb or host > 0
            causes = d.get("causes") or {}
            cause_txt = ", ".join(
                f"{k}={v}" for k, v in sorted(causes.items())) or "-"
            rows_fb.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "</tr>".format(
                    html.escape(cfg), host,
                    d.get("escalations", 0) or 0, html.escape(cause_txt)))
        badge_fb = ("" if not any_host_fb else
                    '<p style="color:#c22;font-weight:700">HOST FALLBACKS '
                    'RECORDED — the fast path left the device</p>')
        fb_html = (
            "<h2>fallback diagnostics (latest run)</h2>" + badge_fb
            + "<table><tr><th>config</th><th>host fallbacks</th>"
              "<th>escalations</th><th>causes</th></tr>"
            + "".join(rows_fb) + "</table>")
    # Recovery panel (next to the fallback diagnostics): the newest
    # run's chaos/recovery counters — retries, backoff, replayed
    # windows, verified checksum epochs, recoveries by cause. A nonzero
    # recovery or checksum mismatch in a bench run means the serving
    # pipeline quarantined device state mid-run: rendered as loudly as
    # a host fallback.
    rec_html = ""
    rec = next((e.get("recovery_diagnostics")
                for e in reversed(entries)
                if isinstance(e.get("recovery_diagnostics"), dict)
                and e.get("recovery_diagnostics")), None)
    if rec is None:
        fbd = next((e.get("fallback_diagnostics")
                    for e in reversed(entries)
                    if isinstance(e.get("fallback_diagnostics"), dict)),
                   None) or {}
        rec = {cfg: d.get("recovery") for cfg, d in fbd.items()
               if isinstance(d, dict)
               and isinstance(d.get("recovery"), dict)}
    if rec:
        rows_rec = []
        any_rec = False
        for cfg in sorted(rec):
            d = rec[cfg] or {}
            causes = d.get("recoveries") or {}
            n_rec = sum(causes.values()) if causes else 0
            mism = d.get("checksum_mismatches", 0) or 0
            any_rec = any_rec or n_rec > 0 or mism > 0
            cause_txt = ", ".join(
                f"{k}={v}" for k, v in sorted(causes.items())) or "-"
            rows_rec.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "<td>{}</td><td>{}</td><td>{}</td></tr>".format(
                    html.escape(cfg), d.get("retries", 0) or 0,
                    d.get("backoff_s", 0) or 0,
                    d.get("replayed_windows", 0) or 0,
                    d.get("epochs_verified", 0) or 0, mism,
                    html.escape(cause_txt)))
        badge_rec = ("" if not any_rec else
                     '<p style="color:#c22;font-weight:700">RECOVERIES '
                     'RECORDED — device state was quarantined and '
                     'replayed</p>')
        rec_html = (
            "<h2>recovery / verified epochs (latest run)</h2>" + badge_rec
            + "<table><tr><th>config</th><th>retries</th>"
              "<th>backoff s</th><th>replayed windows</th>"
              "<th>epochs verified</th><th>checksum mismatches</th>"
              "<th>recoveries by cause</th></tr>"
            + "".join(rows_rec) + "</table>")
    # Dispatch-route panel: which kernel route each config's windows
    # took ("chain" = the default scan-form whole-window dispatch;
    # "partitioned_chain" / "partitioned_per_batch" = the sharded-state
    # routes, fused scan vs per-prepare) and the per-cause prepares
    # that fell out of chain windows — a shift away from a chain route
    # on a plain workload is a routing regression, rendered next to
    # the fallback diagnostics it would show up in.
    route_html = ""
    routes = next((e.get("dispatch_routes") for e in reversed(entries)
                   if isinstance(e.get("dispatch_routes"), dict)
                   and e.get("dispatch_routes")), None)
    if routes is None:
        fbd = next((e.get("fallback_diagnostics")
                    for e in reversed(entries)
                    if isinstance(e.get("fallback_diagnostics"), dict)),
                   None) or {}
        routes = {cfg: d.get("routes") for cfg, d in fbd.items()
                  if isinstance(d, dict)
                  and isinstance(d.get("routes"), dict)
                  and (d["routes"].get("windows")
                       or d["routes"].get("chain_batch_fallbacks"))}
    if routes:
        rows_rt = []
        for cfg in sorted(routes):
            d = routes[cfg] or {}
            wins = d.get("windows") or {}
            if not wins and d.get("route"):
                depths = ",".join(str(x) for x in
                                  d.get("window_depths") or []) or "-"
                wins_txt = f"{d['route']} (depths {depths})"
            else:
                wins_txt = ", ".join(
                    f"{k}={v}" for k, v in sorted(wins.items())) or "-"
            cbf = d.get("chain_batch_fallbacks") or {}
            cbf_txt = ", ".join(
                f"{k}={v}" for k, v in sorted(cbf.items())) or "-"
            rows_rt.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td></tr>".format(
                    html.escape(cfg), html.escape(wins_txt),
                    html.escape(cbf_txt)))
        route_html = (
            "<h2>dispatch routes (latest run)</h2>"
            "<table><tr><th>config</th><th>windows by route</th>"
            "<th>chain per-prepare fallbacks</th></tr>"
            + "".join(rows_rt) + "</table>")
    # Host-staging panel (ISSUE 16), next to the dispatch-routes table:
    # double-buffered window staging per config — how much host pack/
    # transfer work ran (work_ms), how much of it the dispatch path
    # actually waited on (stall_ms), windows staged ahead vs packed
    # inline, and the headline host_stall_fraction (1.0 = staging fully
    # synchronous; the overlap gate leg ceilings the same number on a
    # live run). A fraction near 1.0 WITH overlap enabled means the
    # double buffer stopped hiding the pack — a pipelining regression.
    stage_html = ""
    staging = next((e.get("host_staging") for e in reversed(entries)
                    if isinstance(e.get("host_staging"), dict)
                    and e.get("host_staging")), None)
    if staging is None:
        fbd = next((e.get("fallback_diagnostics")
                    for e in reversed(entries)
                    if isinstance(e.get("fallback_diagnostics"), dict)),
                   None) or {}
        staging = {cfg: d.get("staging") for cfg, d in fbd.items()
                   if isinstance(d, dict)
                   and isinstance(d.get("staging"), dict)
                   and d["staging"].get("windows")}
    if staging:
        rows_st = []
        any_sync = False
        for cfg in sorted(staging):
            d = staging[cfg] or {}
            frac = d.get("host_stall_fraction")
            overlap_on = bool(d.get("overlap", True))
            sync_flag = (overlap_on and frac is not None
                         and frac >= 0.9 and d.get("staged"))
            any_sync = any_sync or bool(sync_flag)
            frac_txt = "-" if frac is None else f"{frac:.4f}"
            if sync_flag:
                frac_txt = ('<span style="color:#c22;font-weight:600">'
                            f"{frac:.4f}</span>")
            rows_st.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "<td>{}</td><td>{}</td><td>{}</td><td>{}</td></tr>"
                .format(html.escape(cfg),
                        "on" if overlap_on else "off",
                        d.get("windows", 0) or 0,
                        d.get("staged", 0) or 0,
                        d.get("misses", 0) or 0,
                        d.get("work_ms", 0) or 0,
                        d.get("stall_ms", 0) or 0, frac_txt))
        badge_st = ("" if not any_sync else
                    '<p style="color:#c22;font-weight:700">HOST STALL '
                    'NEAR 1.0 WITH OVERLAP ON — window staging is no '
                    'longer hidden behind device execution</p>')
        stage_html = (
            "<h2>host staging / overlap (latest run)</h2>" + badge_st
            + "<table><tr><th>config</th><th>overlap</th>"
              "<th>windows</th><th>staged ahead</th><th>misses</th>"
              "<th>staging work ms</th><th>stall ms</th>"
              "<th>host stall fraction</th></tr>"
            + "".join(rows_st) + "</table>")
    # Admission panel (ISSUE 18): the latest run's ##admission record —
    # per-class admitted vs shed-by-reason under the sessionized
    # Zipfian overload, the shed line reached, queue occupancy, and
    # sustained ADMITTED events/s (the success metric under overload is
    # admitted throughput + per-class admitted p99 while lower classes
    # shed explicitly, not raw tps). RED badge when the top class shed
    # for shed_line/deadline (the priority ladder regressed) or
    # conservation broke (a silent drop — the one thing the plane
    # promises never happens).
    adm_html = ""
    adm = next((e.get("admission") for e in reversed(entries)
                if isinstance(e.get("admission"), dict)
                and e.get("admission")), None)
    if adm and isinstance(adm.get("classes"), dict):
        by_prio = sorted(adm["classes"].items(),
                         key=lambda kv: kv[1].get("priority", 0))
        top_name, top_d = by_prio[0]
        bad_top = sorted(r for r in (top_d.get("shed") or {})
                         if r in ("shed_line", "deadline"))
        cons = adm.get("conservation") or {}
        bad_cons = not cons.get("ok", True)
        rows_ad = []
        for name, d in by_prio:
            shed = d.get("shed") or {}
            shed_txt = ", ".join(f"{k}={v}"
                                 for k, v in sorted(shed.items())) or "-"
            wait = d.get("admit_wait_ms") or {}
            p99, slo = wait.get("p99"), d.get("slo_ms")
            p99_txt = "-" if p99 is None else f"{p99:.1f}"
            if p99 is not None and slo is not None and p99 > slo:
                p99_txt = ('<span style="color:#c22;font-weight:600">'
                           f"{p99:.1f}</span>")
            rows_ad.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "<td>{}</td><td>{}</td></tr>".format(
                    html.escape(name), d.get("submitted", 0) or 0,
                    d.get("admitted", 0) or 0, html.escape(shed_txt),
                    p99_txt, "-" if slo is None else slo))
        badge_ad = ""
        if bad_top or bad_cons:
            why = []
            if bad_top:
                why.append(f"top class '{top_name}' shed for {bad_top}")
            if bad_cons:
                why.append("conservation broke (silent drop)")
            badge_ad = ('<p style="color:#c22;font-weight:700">'
                        'ADMISSION RED: '
                        + html.escape("; ".join(why)) + "</p>")
        q = adm.get("queue") or {}
        adm_html = (
            "<h2>admission plane (latest run)</h2>" + badge_ad
            + "<p>shed level {} &middot; queue occupancy {} &middot; "
              "sustained {} admitted events/s virtual ({} wall) &middot; "
              "{} live sessions of a {} population</p>".format(
                  adm.get("shed_level", "-"), q.get("occupancy", "-"),
                  adm.get("sustained_admitted_eps_virtual", "-"),
                  adm.get("admitted_eps_wall", "-"),
                  adm.get("sessions", "-"),
                  adm.get("session_population", "-"))
            + "<table><tr><th>class</th><th>submitted</th>"
              "<th>admitted</th><th>shed by reason</th>"
              "<th>admitted p99 ms</th><th>slo ms</th></tr>"
            + "".join(rows_ad) + "</table>")
    # Op-budget table (next to the fallback diagnostics): the newest
    # run's heavy-op census per kernel tier vs the committed gate
    # ceilings (the NEWEST perf/opbudget_r*.json — resolved, not
    # hardcoded, so a new budget round shows up without a devhub edit)
    # — compile-footprint regressions are rendered as loudly as
    # throughput ones.
    ob_html = ""
    ob = next((e.get("opbudget") for e in reversed(entries)
               if isinstance(e.get("opbudget"), dict)
               and "error" not in e.get("opbudget", {})), None)
    if ob:
        budgets = {}
        try:
            from .jaxhound import newest_budget_path
            with open(newest_budget_path()) as f:
                budgets = json.load(f).get("budget", {})
        except (OSError, ValueError):
            pass
        rows_ob = []
        any_over = False
        for tier in sorted(ob):
            d = ob[tier] or {}
            total = d.get("heavy_total")
            limit = (budgets.get(tier) or {}).get("heavy_total")
            over = (total is not None and limit is not None
                    and total > limit)
            any_over = any_over or over
            classes = d.get("heavy") or {}
            cls_txt = " ".join(f"{k}={v}" for k, v in classes.items()
                               if v) or "-"
            flag = ('<span style="color:#c22;font-weight:600">OVER '
                    'BUDGET</span>' if over else "")
            rows_ob.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "<td>{}</td><td>{}</td></tr>".format(
                    html.escape(tier),
                    "-" if total is None else total,
                    "-" if limit is None else limit,
                    html.escape(cls_txt),
                    d.get("operand_mb", "-"), flag))
        badge_ob = ("" if not any_over else
                    '<p style="color:#c22;font-weight:700">OP BUDGET '
                    'EXCEEDED — scripts/gate.py would be RED</p>')
        ob_html = (
            "<h2>op budget (latest run vs committed ceilings)</h2>"
            + badge_ob
            + "<table><tr><th>kernel tier</th><th>heavy ops</th>"
              "<th>budget</th><th>by class</th><th>operand MB</th>"
              "<th></th></tr>"
            + "".join(rows_ob) + "</table>")
    # Static-analysis panel: the gate `static` leg's last verdict
    # (perf/static_status.json, written by testing/static_smoke.py) —
    # per-pass ok flags with finding samples and the negative-proof
    # verdicts — next to the committed retrace-budget head (the NEWEST
    # perf/tracebudget_r*.json, resolved newest_budget_path-style so a
    # new pinned round shows up without a devhub edit).
    st_html = ""
    st = None
    try:
        from .jaxhound.core import _DEFAULT_PERF_DIR
        with open(os.path.join(_DEFAULT_PERF_DIR,
                               "static_status.json")) as f:
            st = json.load(f)
    except (OSError, ValueError, ImportError):
        pass
    if isinstance(st, dict):
        rows_st = []
        any_red = False
        for name in sorted(st.get("passes") or {}):
            d = st["passes"][name] or {}
            ok = bool(d.get("ok"))
            any_red = any_red or not ok
            sample = "; ".join(d.get("findings") or [])[:200] or "-"
            flag = ("clean" if ok else
                    '<span style="color:#c22;font-weight:600">RED</span>')
            rows_st.append(
                "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                "</tr>".format(
                    html.escape(name), flag, d.get("n_findings", 0),
                    html.escape(sample)))
        negs = st.get("negatives") or {}
        n_ok = sum(1 for v in negs.values() if v)
        neg_red = n_ok < len(negs)
        any_red = any_red or neg_red
        neg_txt = (
            f"{n_ok}/{len(negs)} injected violations red as required"
            if negs else "-")
        if neg_red:
            neg_txt = ('<span style="color:#c22;font-weight:600">'
                       + html.escape(neg_txt) + "</span>")
        else:
            neg_txt = html.escape(neg_txt)
        tb_txt = "-"
        try:
            from .jaxhound import newest_tracebudget_path
            with open(newest_tracebudget_path()) as f:
                tb = json.load(f)
            tb_txt = "{}: {} entries pinned, depth matrix {}".format(
                html.escape(str(st.get("tracebudget") or "")),
                len(tb.get("entries") or {}),
                html.escape(str((tb.get("matrix") or {}).get(
                    "depths", "-"))))
        except (OSError, ValueError, ImportError):
            pass
        badge_st = ("" if not any_red else
                    '<p style="color:#c22;font-weight:700">STATIC '
                    'ANALYSIS RED — scripts/gate.py static leg would '
                    'fail</p>')
        st_html = (
            "<h2>static analysis (jaxhound passes, last gate leg)</h2>"
            + badge_st
            + "<p>{} registry entries; retrace budget {}; negative "
              "proofs: {}</p>".format(
                  st.get("n_entries", "-"), tb_txt, neg_txt)
            + "<table><tr><th>pass</th><th></th><th>findings</th>"
              "<th>sample</th></tr>"
            + "".join(rows_st) + "</table>")
    # Shard-balance panel (bench ##shard): the partitioned route's
    # events-per-shard spread, cross-shard fraction, and exchange
    # overflow count — a skewed ownership hash or an overflow-prone
    # exchange capacity shows up here before it shows up as fallbacks.
    sh_html = ""
    sh = next((e.get("shard_balance") for e in reversed(entries)
               if isinstance(e.get("shard_balance"), dict)
               and "error" not in e.get("shard_balance", {})), None)
    if sh:
        owned = [int(x) for x in (sh.get("events_per_shard") or [])]
        peak = max(owned) if owned else 1
        rows_sh = []
        for i, n in enumerate(owned):
            bar = '<div style="background:#62a;height:10px;width:{}px">' \
                  '</div>'.format(max(1, round(n / (peak or 1) * 240)))
            rows_sh.append(
                "<tr><td>shard {}</td><td>{}</td><td>{}</td></tr>".format(
                    i, n, bar))
        over = int(sh.get("exchange_overflows") or 0)
        warn = ("" if not over else
                '<p style="color:#c22;font-weight:700">EXCHANGE '
                'OVERFLOWS — per-shard capacity too small for this '
                'workload</p>')
        bytes_dev = sh.get("state_bytes_per_device")
        bytes_rep = sh.get("state_bytes_replicated_equiv")
        ratio = (f" ({bytes_dev / bytes_rep:.3f}x of replicated)"
                 if bytes_dev and bytes_rep else "")
        # Elastic-shards rows (##shard `migration` / `hot_range`): the
        # probe's live split migration — duration, rows moved, windows
        # served under double-write — and the degenerate-hot-account
        # verdict (unsplittable = the remedy is AT2 lane parallelism
        # within the account's commit lane, not placement).
        mig = sh.get("migration")
        mig_html = ""
        if isinstance(mig, dict):
            mig_html = (
                "<p>live migration: {} {}&rarr;{} — {} rows copied in "
                "{:.3f}s, {} double-write window(s), {} window(s) "
                "committed while in flight</p>".format(
                    mig.get("kind", "-"), mig.get("src", "-"),
                    mig.get("dst", "-"), mig.get("rows_copied", 0),
                    float(mig.get("duration_s") or 0.0),
                    mig.get("double_write_windows", 0),
                    mig.get("windows_live", 0)))
        hr = sh.get("hot_range")
        hr_html = ""
        if isinstance(hr, dict):
            style = (' style="color:#c60;font-weight:700"'
                     if hr.get("verdict") == "unsplittable" else "")
            hr_html = (
                "<p{}>hot-range detector: {} (shard {}, top-account "
                "fraction {:.0%}) — {}</p>".format(
                    style, hr.get("verdict", "-"), hr.get("shard", "-"),
                    float(hr.get("fraction") or 0.0),
                    hr.get("note", "")))
        sh_html = (
            "<h2>shard balance (partitioned route, latest run)</h2>"
            + warn
            + "<p>{} shards, cross-shard fraction {:.1%} "
              "({} transfers), {} exchange overflows, "
              "{} state bytes/device{}</p>".format(
                  sh.get("n_shards", "-"),
                  float(sh.get("cross_shard_fraction") or 0.0),
                  sh.get("cross_shard_transfers", 0), over,
                  "-" if bytes_dev is None else bytes_dev,
                  ratio)
            + mig_html + hr_html
            + "<table><tr><th>shard</th><th>events owned</th><th></th>"
              "</tr>" + "".join(rows_sh) + "</table>")
    # Device-telemetry panel: the fused route's on-device measurements
    # (bench ##shard record's `telemetry` sub-dict, decoded from the
    # harvested TEL_LAYOUT block) — exchange-headroom burn first (the
    # early warning BEFORE overflows become host fallbacks), then the
    # fixpoint-round distribution, decoded poison causes, and the
    # flight-recorder activity counters.
    dt_html = ""
    dt = (sh or {}).get("telemetry") if isinstance(sh, dict) else None
    if isinstance(dt, dict):
        occ_txt = "-"
        occ_warn = ""
        try:
            from .trace import Histogram
            oh = Histogram.from_dict(dt.get("exchange_occupancy") or {})
            if oh.count:
                p99 = oh.quantile(0.99)
                occ_txt = ("p50 {:.1f}% / p99 {:.1f}% of lane capacity "
                           "({} samples)").format(
                               oh.quantile(0.50), p99, oh.count)
                if p99 is not None and p99 > 85.0:
                    occ_warn = (
                        '<p style="color:#c22;font-weight:700">'
                        'EXCHANGE HEADROOM BURNING — p99 occupancy '
                        'past the 85% SLO threshold</p>')
        except (AssertionError, ValueError, TypeError):
            pass
        fr = dt.get("fixpoint_rounds") or {}
        fr_txt = ("-" if not fr.get("count") else
                  "p50 {} / p99 {} / max {} over {} prepares".format(
                      fr.get("p50", "-"), fr.get("p99", "-"),
                      fr.get("max", "-"), fr.get("count", 0)))
        causes = dt.get("device_poison_causes") or {}
        cause_txt = ", ".join(f"{k}={v}"
                              for k, v in sorted(causes.items())) or "none"
        dt_html = (
            "<h2>device telemetry (fused partitioned route, latest "
            "run)</h2>" + occ_warn
            + "<table>"
              "<tr><td>exchange occupancy</td><td>{}</td></tr>"
              "<tr><td>fixpoint rounds</td><td>{}</td></tr>"
              "<tr><td>poison causes (decoded)</td><td>{}</td></tr>"
              "<tr><td>write-back rows</td><td>{}</td></tr>"
              "<tr><td>shard capacity hits</td><td>{}</td></tr>"
              "<tr><td>flight recorder</td>"
              "<td>{} windows ringed, {} dumps</td></tr>"
              "</table>".format(
                  html.escape(occ_txt), html.escape(fr_txt),
                  html.escape(cause_txt),
                  dt.get("writeback_rows", 0),
                  dt.get("shard_capacity_hits", 0),
                  dt.get("flight_windows", 0),
                  dt.get("flight_dumps", 0)))
    # Commit-pipeline panel: the newest run's per-stage trace aggregates
    # (bench ##trace, recorded under a recording tracer) as time shares —
    # the operator-facing answer to "where does a commit go", next to the
    # tracing-cost guard (NullTracer vs recording wall clock).
    tr_html = ""
    tr = next((e.get("trace") for e in reversed(entries)
               if isinstance(e.get("trace"), dict)
               and isinstance(e.get("trace").get("commit_stages"), dict)),
              None)
    if tr:
        stages = tr["commit_stages"]
        total_us = sum(s.get("sum_us", 0) for s in stages.values()) or 1.0
        rows_tr = []
        for stage in ("commit_prefetch", "commit_execute",
                      "commit_compact", "commit_checkpoint"):
            s = stages.get(stage)
            if s is None:
                continue
            share = s.get("sum_us", 0) / total_us
            bar = '<div style="background:#2a6;height:10px;width:{}px">' \
                  '</div>'.format(max(1, round(share * 240)))
            rows_tr.append(
                "<tr><td>{}</td><td>{}</td><td>{:.1f}</td><td>{:.1%}</td>"
                "<td>{}</td></tr>".format(
                    html.escape(stage), s.get("count", 0),
                    s.get("sum_us", 0) / 1000.0, share, bar))
        guard = ""
        if tr.get("overhead_ratio") is not None:
            guard = ("<p>tracing cost guard: NullTracer {}s vs recording "
                     "{}s ({}x) over {} ops</p>").format(
                tr.get("null_s"), tr.get("recording_s"),
                tr.get("overhead_ratio"), tr.get("ops"))
        tr_html = (
            "<h2>commit pipeline (latest traced run)</h2>" + guard
            + "<table><tr><th>stage</th><th>spans</th><th>total ms</th>"
              "<th>share</th><th></th></tr>"
            + "".join(rows_tr) + "</table>")
    # SLO panel: every declared objective (perf/slo.json) evaluated
    # against the recorded runs — latest value vs threshold, burn rate
    # over the trailing burn window, breach badges. Rendered even when
    # all green: an invisible SLO is an unenforced one.
    slo_html = ""
    try:
        from .trace.slo import (burn_rates, evaluate_bench_record,
                                load_objectives)

        slo_cfg = load_objectives()
    except (OSError, ValueError, ImportError):
        slo_cfg = None
    if slo_cfg is not None and entries:
        per_run = [evaluate_bench_record(e, slo_cfg["objectives"])
                   for e in entries]
        burn = burn_rates(per_run, slo_cfg["burn_window_runs"],
                          slo_cfg["burn_budget"])
        rows_slo = []
        any_breach = False
        for o, latest in zip(slo_cfg["objectives"], per_run[-1]):
            b = burn.get(o.name, {})
            badge_cell = ""
            if latest["ok"] is False:
                badge_cell = ('<span style="color:#c22;font-weight:600">'
                              'BREACHED</span>')
            elif b.get("badge"):
                badge_cell = ('<span style="color:#c60;font-weight:600">'
                              'BURNING</span>')
            any_breach = any_breach or bool(badge_cell)
            val = latest["value"]
            rows_slo.append(
                "<tr><td>{}</td><td>p{:g} {} &le; {:g} {}</td>"
                "<td>{}</td><td>{:.0%} of {} runs</td><td>{}</td>"
                "</tr>".format(
                    html.escape(o.name), o.quantile * 100,
                    html.escape(o.event), o.threshold,
                    html.escape(o.unit),
                    "-" if val is None else f"{val:g} {o.unit}",
                    b.get("burn_rate", 0.0), b.get("evaluated", 0),
                    badge_cell))
        badge_slo = ("" if not any_breach else
                     '<p style="color:#c22;font-weight:700">SLO BREACH / '
                     'BURN — an objective is out of budget</p>')
        slo_html = (
            "<h2>SLOs (perf/slo.json vs recorded runs)</h2>" + badge_slo
            + "<table><tr><th>objective</th><th>declared</th>"
              "<th>latest</th><th>burn rate</th><th></th></tr>"
            + "".join(rows_slo) + "</table>")
    # Critical-path panel: stage-share attribution of the slowest-decile
    # windows from the newest traced run (trace/merge.py critical_path
    # over the bench probe's merged cluster trace) — the operator-facing
    # answer to "which stage owns p99".
    cp_html = ""
    cp = next((e.get("trace", {}).get("critical_path")
               for e in reversed(entries)
               if isinstance(e.get("trace"), dict)
               and isinstance(e.get("trace").get("critical_path"), dict)),
              None)
    if cp:
        rows_cp = []
        for stage, share in (cp.get("stage_share") or {}).items():
            bar = '<div style="background:#26c;height:10px;width:{}px">' \
                  '</div>'.format(max(1, round(share * 240)))
            rows_cp.append(
                "<tr><td>{}</td><td>{:.1%}</td><td>{}</td></tr>".format(
                    html.escape(stage), share, bar))
        cp_html = (
            "<h2>p99 critical path (latest traced run)</h2>"
            "<p>slowest {} of {} windows ({} units, threshold "
            "{} ms, p99 {} ms) — p99 owned by <b>{}</b></p>".format(
                cp.get("windows_analyzed", 0), cp.get("windows_total", 0),
                html.escape(str(cp.get("window_event", ""))),
                cp.get("threshold_ms", "-"), cp.get("p99_ms", "-"),
                html.escape(str(cp.get("p99_owner", "-"))))
            + "<table><tr><th>stage</th><th>share of slow-window time"
              "</th><th></th></tr>"
            + "".join(rows_cp) + "</table>")
    # Per-request waterfall panel (ISSUE 15): the newest traced run's
    # assembled request traces (bench ##trace `request_waterfall`, from
    # trace/merge.py assemble_traces) — one row per kept request, its
    # wall time broken into quorum wait / commit / device dispatch /
    # network+other, stacked as a waterfall bar. The causal-propagation
    # cost guard rides the same record as ctx_overhead_ratio.
    wf_html = ""
    wf = next((e.get("trace", {}).get("request_waterfall")
               for e in reversed(entries)
               if isinstance(e.get("trace"), dict)
               and e.get("trace").get("request_waterfall")),
              None)
    if wf:
        colors = {"quorum_wait_us": "#c62", "commit_us": "#2a6",
                  "device_dispatch_us": "#26c",
                  "network_other_us": "#aaa"}
        peak = max((r.get("total_us") or 1.0) for r in wf) or 1.0
        rows_wf = []
        for r in wf[:12]:
            stages = r.get("stages") or {}
            segs = "".join(
                '<div style="background:{};height:10px;width:{}px;'
                'display:inline-block"></div>'.format(
                    colors.get(k, "#888"),
                    max(0, round((stages.get(k, 0.0) or 0.0)
                                 / peak * 320)))
                for k in colors)
            rows_wf.append(
                "<tr><td><code>{}</code></td><td>{:.2f}</td><td>{}</td>"
                "<td>{}</td><td>{}</td></tr>".format(
                    html.escape(str(r.get("trace_id", ""))[:16]),
                    (r.get("total_us") or 0.0) / 1000.0,
                    html.escape(str(r.get("owner", "-"))),
                    html.escape(str(r.get("keep_reason", "-"))),
                    segs))
        legend = " ".join(
            '<span style="background:{};padding:0 .5em">&nbsp;</span> {}'
            .format(c, html.escape(k.replace("_us", "")))
            for k, c in colors.items())
        guard_ctx = ""
        tr_rec = next((e.get("trace") for e in reversed(entries)
                       if isinstance(e.get("trace"), dict)
                       and e.get("trace").get("ctx_overhead_ratio")
                       is not None), None)
        if tr_rec:
            guard_ctx = ("<p>causal-propagation cost guard: traced "
                         "(sampling 1.0) vs NullTracer {}x "
                         "(ceiling 1.15x)</p>").format(
                             tr_rec.get("ctx_overhead_ratio"))
        wf_html = (
            "<h2>per-request waterfall (latest traced run)</h2>"
            + guard_ctx + f"<p>{legend}</p>"
            + "<table><tr><th>trace id</th><th>total ms</th>"
              "<th>owner</th><th>kept</th><th>waterfall</th></tr>"
            + "".join(rows_wf) + "</table>")
    # Performance-observatory panels (ISSUE 20, bench ##profile):
    # achieved-vs-roofline fraction per dispatch tier, the sampled
    # dispatch_device_time series, the memory watermark vs the NEWEST
    # committed perf/membudget_r*.json (resolved, not hardcoded — a new
    # budget round shows up without a devhub edit), and the burn-rate
    # alert engine's rule catalog + firing state.
    obs_html = ""
    pf = next((e.get("profile") for e in reversed(entries)
               if isinstance(e.get("profile"), dict)
               and "error" not in e.get("profile", {})), None)
    if pf:
        # Roofline attribution per tier.
        rows_rf = []
        for tier in sorted(pf.get("roofline") or {}):
            d = pf["roofline"][tier] or {}
            frac = float(d.get("fraction") or 0.0)
            bar = '<div style="background:#a42;height:10px;width:{}px">' \
                  '</div>'.format(max(1, round(min(frac, 1.0) * 240)))
            rows_rf.append(
                "<tr><td>{}</td><td>{:.3f}</td><td>{:.3f}</td>"
                "<td>{:.1%}</td><td>{}</td></tr>".format(
                    html.escape(tier),
                    float(d.get("roofline_seconds") or 0.0) * 1e3,
                    float(d.get("measured_p50_s") or 0.0) * 1e3,
                    frac, bar))
        rows_dd = []
        for key in sorted(pf.get("dispatch_device_time") or {}):
            m = pf["dispatch_device_time"][key] or {}
            p50, p99 = m.get("p50_us"), m.get("p99_us")
            rows_dd.append(
                "<tr><td><code>{}</code></td><td>{}</td><td>{}</td>"
                "<td>{}</td></tr>".format(
                    html.escape(key), m.get("count", 0),
                    "-" if p50 is None else f"{p50 / 1e3:.3f}",
                    "-" if p99 is None else f"{p99 / 1e3:.3f}"))
        sampler = pf.get("sampler") or {}
        plat = (pf.get("cost_model") or {}).get("platform", "-")
        obs_html += (
            "<h2>performance observatory: dispatch roofline "
            "(latest run)</h2>"
            "<p>platform {} &middot; {} dispatches, {} sampled "
            "(1-in-{})</p>".format(
                html.escape(str(plat)), sampler.get("dispatches", "-"),
                sampler.get("samples", "-"),
                sampler.get("sample_every", "-"))
            + "<table><tr><th>tier</th><th>roofline ms</th>"
              "<th>measured p50 ms</th><th>of roofline</th><th></th>"
              "</tr>" + "".join(rows_rf) + "</table>"
            + "<table><tr><th>dispatch series</th><th>samples</th>"
              "<th>p50 ms</th><th>p99 ms</th></tr>"
            + "".join(rows_dd) + "</table>")
        # Memory watermark vs the committed membudget pins.
        mwr = (pf.get("memwatch") or {}).get("last") or {}
        reds = (pf.get("memwatch") or {}).get("reds") or []
        if mwr:
            pins = {}
            try:
                from .jaxhound import newest_membudget_path
                with open(newest_membudget_path()) as f:
                    pins = json.load(f).get("components", {})
            except (OSError, ValueError, ImportError):
                pass
            rows_mw = []
            comps = mwr.get("components") or {}
            for name in sorted(set(comps) | set(pins)):
                cur, pin = comps.get(name), pins.get(name)
                over = (cur is not None and pin is not None
                        and cur > pin)
                flag = ('<span style="color:#c22;font-weight:600">OVER '
                        'PIN</span>' if over else "")
                rows_mw.append(
                    "<tr><td>{}</td><td>{}</td><td>{}</td><td>{}</td>"
                    "</tr>".format(
                        html.escape(name),
                        "-" if cur is None else cur,
                        "-" if pin is None else pin, flag))
            badge_mw = ""
            if reds:
                badge_mw = ('<p style="color:#c22;font-weight:700">'
                            'MEMORY WATERMARK RED: '
                            + html.escape("; ".join(reds)[:300]) + "</p>")
            head = mwr.get("headroom_bytes")
            obs_html += (
                "<h2>memory watermark (vs committed membudget)</h2>"
                + badge_mw
                + "<p>{} resident bytes &middot; headroom {} &middot; "
                  "{} observation(s)</p>".format(
                      mwr.get("total_bytes", "-"),
                      "-" if head is None else head,
                      (pf.get("memwatch") or {}).get(
                          "observations", "-"))
                + "<table><tr><th>component</th><th>measured</th>"
                  "<th>budget pin</th><th></th></tr>"
                + "".join(rows_mw) + "</table>")
        # Burn-rate alerts: declared rules + the latest run's verdicts.
        al = pf.get("alerts") or {}
        rules_cfg = []
        try:
            from .trace.alerts import load_alert_rules
            rules_cfg = load_alert_rules()["rules"]
        except (OSError, ValueError, ImportError):
            pass
        if rules_cfg or al:
            active = set(al.get("active") or [])
            rows_al = []
            for r in rules_cfg:
                state = ("<span style='color:#c22;font-weight:600'>"
                         "FIRING</span>" if r.name in active else "ok")
                rows_al.append(
                    "<tr><td>{}</td><td>{}</td><td>{}</td>"
                    "<td>{}/{} ticks @ {:g}/{:g}</td>"
                    "<td><a href=\"{}\">runbook</a></td><td>{}</td>"
                    "</tr>".format(
                        html.escape(r.name), html.escape(r.objective),
                        html.escape(r.severity), r.fast_window,
                        r.slow_window, r.fast_burn, r.slow_burn,
                        html.escape(r.runbook), state))
            badge_al = ""
            if active:
                badge_al = ('<p style="color:#c22;font-weight:700">'
                            'ALERT FIRING: '
                            + html.escape(", ".join(sorted(active)))
                            + "</p>")
            obs_html += (
                "<h2>burn-rate alerts (perf/slo.json rules)</h2>"
                + badge_al
                + "<p>{} rule(s), {} tick(s) evaluated, {} fired "
                  "total</p>".format(
                      len(rules_cfg) or al.get("rules", "-"),
                      al.get("ticks", "-"), al.get("fired_total", "-"))
                + "<table><tr><th>rule</th><th>objective</th>"
                  "<th>severity</th><th>windows</th><th></th><th></th>"
                  "</tr>" + "".join(rows_al) + "</table>")
    # CFO: the failing-seed feed (reference: cfo.zig pushes failing
    # seeds to devhubdb; a green fleet is part of the dashboard).
    cfo_html = ""
    cfo = load_cfo(cfo_dir) if cfo_dir else None
    if cfo:
        failing = cfo.get("failing", [])
        cfo_html = (
            f"<h2>continuous fuzzing</h2>"
            f"<p>{html.escape(os.path.basename(cfo.get('_path', '')))}: "
            f"{html.escape(str(cfo.get('runs_clean', 0)))} clean, "
            f"{html.escape(str(cfo.get('runs_failing', 0)))} failing "
            f"({html.escape(str(cfo.get('elapsed_s', 0)))}s)</p>")
        if failing:
            items = "".join(
                "<li><code>{}</code> seed {} — <code>{}</code></li>".format(
                    html.escape(str(f.get("name"))),
                    html.escape(str(f.get("seed"))),
                    html.escape(str(f.get("reproduce", ""))))
                for f in failing[:50])
            cfo_html += f"<ol>{items}</ol>"
    badge = ("" if not regress else
             f'<p style="color:#c22;font-weight:700">'
             f'{len(regress)} metric(s) regressed vs trailing median</p>')
    doc = f"""<!doctype html>
<html><head><meta charset="utf-8"><title>tigerbeetle-tpu devhub</title>
<style>
body {{ font: 14px/1.5 system-ui, sans-serif; margin: 2rem; }}
table {{ border-collapse: collapse; }}
td {{ padding: .4rem 1rem; border-bottom: 1px solid #ddd; }}
</style></head><body>
<h1>tigerbeetle-tpu devhub</h1>
<p>{len(entries)} recorded runs; latest metric values with history
sparklines (reference: devhub.tigerbeetle.com).</p>
{badge}{parity_html}
<table><tr><th>metric</th><th>latest</th><th>history</th><th></th></tr>
{''.join(rows)}
</table>
{fb_html}
{rec_html}
{route_html}
{stage_html}
{adm_html}
{ob_html}
{st_html}
{sh_html}
{dt_html}
{tr_html}
{slo_html}
{cp_html}
{wf_html}
{obs_html}
{cfo_html}
</body></html>"""
    with open(out_path, "w") as f:
        f.write(doc)
    return len(entries)
