"""devhub: benchmark history + dashboard.

reference: src/devhub/ + src/scripts/devhub.zig — nightly metrics
(benchmark tx/s, latency, sizes) recorded to a database and rendered on a
dashboard. Here: bench JSON lines append to a JSONL history, and `render`
emits a self-contained HTML dashboard with inline SVG sparklines (no
external assets, mirroring the reference's static devhub page).
"""

from __future__ import annotations

import html
import json
import time
from typing import Optional

NUMERIC_KEYS = (
    "value", "config1_2hot_tps", "config2_10k_tps", "config3_chains_tps",
    "config4_twophase_limits_tps",
)


def record(history_path: str, bench_json: dict,
           timestamp: Optional[int] = None) -> None:
    entry = dict(bench_json)
    entry["recorded_at"] = timestamp if timestamp is not None else int(time.time())
    with open(history_path, "a") as f:
        f.write(json.dumps(entry) + "\n")


def load(history_path: str) -> list[dict]:
    out = []
    try:
        with open(history_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn write: skip
    except FileNotFoundError:
        pass
    return out


def _sparkline(values: list[float], width: int = 320, height: int = 48) -> str:
    values = [v for v in values if v is not None]
    if not values:
        return "<svg/>"
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    step = width / max(1, len(values) - 1) if len(values) > 1 else width
    points = " ".join(
        f"{round(i * step, 1)},{round(height - 4 - (v - lo) / span * (height - 8), 1)}"
        for i, v in enumerate(values))
    return (f'<svg width="{width}" height="{height}">'
            f'<polyline fill="none" stroke="#2a6" stroke-width="2" '
            f'points="{points}"/></svg>')


def render(history_path: str, out_path: str) -> int:
    """Render the dashboard; returns the number of history entries."""
    entries = load(history_path)
    rows = []
    for key in NUMERIC_KEYS:
        series = [e.get(key) for e in entries]
        latest = next((v for v in reversed(series) if v is not None), None)
        rows.append(
            "<tr><td>{}</td><td>{}</td><td>{}</td></tr>".format(
                html.escape(key),
                "-" if latest is None else f"{latest:,.0f}",
                _sparkline(series)))
    doc = f"""<!doctype html>
<html><head><meta charset="utf-8"><title>tigerbeetle-tpu devhub</title>
<style>
body {{ font: 14px/1.5 system-ui, sans-serif; margin: 2rem; }}
table {{ border-collapse: collapse; }}
td {{ padding: .4rem 1rem; border-bottom: 1px solid #ddd; }}
</style></head><body>
<h1>tigerbeetle-tpu devhub</h1>
<p>{len(entries)} recorded runs; latest metric values with history
sparklines (reference: devhub.tigerbeetle.com).</p>
<table><tr><th>metric</th><th>latest</th><th>history</th></tr>
{''.join(rows)}
</table></body></html>"""
    with open(out_path, "w") as f:
        f.write(doc)
    return len(entries)
